"""Standard op library registrations — the TPU equivalents of libnd4j's
declarable ops (``libnd4j/include/ops/declarable/generic/**``).

Convention: arrays are jnp arrays (tracing-friendly); attrs are python
scalars/tuples (static under jit). NHWC is the native conv layout on TPU
(the reference is NCHW-first; importers transpose at the boundary).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import register

# --------------------------------------------------------------- arithmetic
register("add", lambda a, b: a + b, aliases=["Add"])
register("sub", lambda a, b: a - b, aliases=["Sub", "subtract"])
register("mul", lambda a, b: a * b, aliases=["Mul", "multiply"])
register("div", lambda a, b: a / b, aliases=["Div", "RealDiv", "truediv"])
register("floordiv", lambda a, b: jnp.floor_divide(a, b), aliases=["FloorDiv"])
register("mod", lambda a, b: jnp.mod(a, b), aliases=["FloorMod", "Mod"])
register("pow", lambda a, b: jnp.power(a, b), aliases=["Pow"])
register("squaredsubtract", lambda a, b: jnp.square(a - b), aliases=["SquaredDifference"])
register("maximum", jnp.maximum, aliases=["Maximum"])
register("minimum", jnp.minimum, aliases=["Minimum"])
register("neg", jnp.negative, aliases=["Neg"])
register("reciprocal", jnp.reciprocal, aliases=["Reciprocal"])
# C-style truncating division (ONNX Div on ints truncates toward zero)
register("truncate_div", lax.div, aliases=["TruncateDiv"])

# --------------------------------------------------------------- elementwise
for _n, _f, _al in [
    ("abs", jnp.abs, ["Abs"]), ("exp", jnp.exp, ["Exp"]), ("log", jnp.log, ["Log"]),
    ("log1p", jnp.log1p, ["Log1p"]), ("sqrt", jnp.sqrt, ["Sqrt"]),
    ("rsqrt", lax.rsqrt, ["Rsqrt"]), ("square", jnp.square, ["Square"]),
    ("sin", jnp.sin, ["Sin"]), ("cos", jnp.cos, ["Cos"]), ("tan", jnp.tan, ["Tan"]),
    ("asin", jnp.arcsin, ["Asin"]), ("acos", jnp.arccos, ["Acos"]), ("atan", jnp.arctan, ["Atan"]),
    ("sinh", jnp.sinh, ["Sinh"]), ("cosh", jnp.cosh, ["Cosh"]), ("tanh", jnp.tanh, ["Tanh"]),
    ("asinh", jnp.arcsinh, []), ("acosh", jnp.arccosh, []), ("atanh", jnp.arctanh, []),
    ("erf", jax.scipy.special.erf, ["Erf"]), ("erfc", jax.scipy.special.erfc, ["Erfc"]),
    ("floor", jnp.floor, ["Floor"]), ("ceil", jnp.ceil, ["Ceil"]),
    ("round", jnp.round, ["Round"]), ("sign", jnp.sign, ["Sign"]),
    ("isnan", jnp.isnan, ["IsNan"]), ("isinf", jnp.isinf, ["IsInf"]),
    ("isfinite", jnp.isfinite, ["IsFinite"]),
]:
    register(_n, _f, aliases=_al)

register("clipbyvalue", lambda x, lo=None, hi=None, clip_value_min=None, clip_value_max=None:
         jnp.clip(x, lo if lo is not None else clip_value_min, hi if hi is not None else clip_value_max),
         aliases=["ClipByValue", "clip_by_value"])


@register("clipbynorm", aliases=["ClipByNorm"])
def _clipbynorm(x, clipnorm=1.0):
    n = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(n > clipnorm, x * (clipnorm / n), x)


# -------------------------------------------------------------- activations
register("sigmoid", jax.nn.sigmoid, aliases=["Sigmoid"])
register("relu", jax.nn.relu, aliases=["Relu"])
register("relu6", jax.nn.relu6, aliases=["Relu6"])
register("elu", jax.nn.elu, aliases=["Elu"])
register("selu", jax.nn.selu, aliases=["Selu"])
register("gelu", jax.nn.gelu, aliases=["Gelu"])
register("softplus", jax.nn.softplus, aliases=["Softplus"])
register("softsign", jax.nn.soft_sign, aliases=["Softsign"])
register("swish", jax.nn.silu, aliases=["silu"])
register("mish", jax.nn.mish)
# reference/Keras/ONNX-default semantics clip(0.2x+0.5, 0, 1) — NOT
# jax.nn.hard_sigmoid's relu6(x+3)/6
register("hard_sigmoid", lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
         aliases=["HardSigmoid"])
register("hard_tanh", lambda x: jnp.clip(x, -1.0, 1.0), aliases=["HardTanh"])
register("leakyrelu", lambda x, alpha=0.01: jax.nn.leaky_relu(x, negative_slope=alpha),
         aliases=["LeakyRelu", "leaky_relu"])
register("prelu", lambda x, alpha: jnp.where(x >= 0, x, alpha * x), aliases=["PRelu"])
register("cube", lambda x: x ** 3)
register("rationaltanh", lambda x: 1.7159 * jnp.tanh(2.0 * x / 3.0))
register("rectifiedtanh", lambda x: jnp.maximum(0.0, jnp.tanh(x)))
register("thresholdedrelu", lambda x, theta=1.0: jnp.where(x > theta, x, 0.0))
register("softmax", lambda x, axis=-1: jax.nn.softmax(x, axis=axis), aliases=["Softmax"])
register("log_softmax", lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis), aliases=["LogSoftmax"])


# --------------------------------------------------------------- reductions
def _red(fn):
    def op(x, axis=None, keepdims=False, keep_dims=None):
        kd = keepdims if keep_dims is None else keep_dims
        if isinstance(axis, (list,)):
            axis = tuple(axis)
        return fn(x, axis=axis, keepdims=kd)
    return op

register("reduce_sum", _red(jnp.sum), aliases=["Sum", "sum"])
register("reduce_mean", _red(jnp.mean), aliases=["Mean", "mean"])
register("reduce_max", _red(jnp.max), aliases=["Max"])
register("reduce_min", _red(jnp.min), aliases=["Min"])
register("reduce_prod", _red(jnp.prod), aliases=["Prod"])
register("reduce_norm1", _red(lambda x, axis=None, keepdims=False: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)))
register("reduce_norm2", _red(lambda x, axis=None, keepdims=False: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims))))
register("reduce_normmax", _red(lambda x, axis=None, keepdims=False: jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims)))
register("reduce_variance", lambda x, axis=None, keepdims=False, bias_corrected=False:
         jnp.var(x, axis=axis, ddof=1 if bias_corrected else 0, keepdims=keepdims))
register("reduce_stdev", lambda x, axis=None, keepdims=False, bias_corrected=False:
         jnp.std(x, axis=axis, ddof=1 if bias_corrected else 0, keepdims=keepdims))
register("reduce_logsumexp", lambda x, axis=None, keepdims=False:
         jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
register("argmax", lambda x, axis=None: jnp.argmax(x, axis=axis), aliases=["ArgMax"])
register("argmin", lambda x, axis=None: jnp.argmin(x, axis=axis), aliases=["ArgMin"])
register("cumsum", lambda x, axis=0, exclusive=False, reverse=False:
         _cum(jnp.cumsum, x, axis, exclusive, reverse, 0.0), aliases=["Cumsum"])
register("cumprod", lambda x, axis=0, exclusive=False, reverse=False:
         _cum(jnp.cumprod, x, axis, exclusive, reverse, 1.0), aliases=["Cumprod"])


def _cum(fn, x, axis, exclusive, reverse, init):
    if reverse:
        x = jnp.flip(x, axis)
    out = fn(x, axis=axis)
    if exclusive:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad, constant_values=init)
        out = lax.slice_in_dim(out, 0, x.shape[axis], axis=axis)
    if reverse:
        out = jnp.flip(out, axis)
    return out


# -------------------------------------------------------------------- shape
register("reshape", lambda x, shape: jnp.reshape(x, tuple(int(s) for s in shape)), aliases=["Reshape"])
register("transpose", lambda x, perm=None: jnp.transpose(x, perm), aliases=["Transpose", "permute"])
register("squeeze", lambda x, axis=None: jnp.squeeze(x, axis=tuple(axis) if isinstance(axis, list) else axis), aliases=["Squeeze"])
register("expand_dims", lambda x, axis=0: jnp.expand_dims(x, axis), aliases=["ExpandDims"])
register("concat", lambda *xs, axis=0: jnp.concatenate(xs, axis=axis), aliases=["Concat", "ConcatV2"])
register("stack", lambda *xs, axis=0: jnp.stack(xs, axis=axis), aliases=["Stack", "Pack"])
register("unstack", lambda x, axis=0, num=None: tuple(jnp.moveaxis(x, axis, 0)),
         num_outputs=-1, aliases=["Unstack", "Unpack"])
register("tile", lambda x, reps: jnp.tile(x, tuple(int(r) for r in reps)), aliases=["Tile"])
register("flip", lambda x, axis: jnp.flip(x, axis), aliases=["ReverseV2", "reverse"])
register("slice", lambda x, begin, size: lax.dynamic_slice(x, tuple(int(b) for b in begin),
                                                           tuple(x.shape[i] - int(begin[i]) if int(s) == -1 else int(s)
                                                                 for i, s in enumerate(size))),
         aliases=["Slice"])
def _strided_slice(x, begin, end, strides=None):
    # None entries mean "full extent in the stride's direction" (Python slice
    # semantics) — the TF importer maps begin_mask/end_mask to None so that
    # negative strides (x[::-1]) and end-of-axis shrinks (x[-1]) work
    strides = strides if strides is not None else [1] * len(begin)
    as_int = lambda v: None if v is None else int(v)
    return x[tuple(slice(as_int(b), as_int(e), as_int(s))
                   for b, e, s in zip(begin, end, strides))]


register("strided_slice", _strided_slice, aliases=["StridedSlice"])
register("gather", lambda x, indices, axis=0: jnp.take(x, indices, axis=axis), aliases=["Gather", "GatherV2"])
register("split", lambda x, num_split=2, axis=0: tuple(jnp.split(x, int(num_split), axis=axis)),
         num_outputs=-1, aliases=["Split"])
register("split_v", lambda x, size_splits, axis=0:
         tuple(jnp.split(x, list(np.cumsum([int(s) for s in size_splits[:-1]])), axis=axis)),
         num_outputs=-1, aliases=["SplitV"])
register("einsum", lambda *xs, equation: jnp.einsum(equation, *xs), aliases=["Einsum"])
register("gather_nd", lambda x, indices: x[tuple(jnp.moveaxis(indices, -1, 0))], aliases=["GatherNd"])


@register("scatter_update", aliases=["ScatterUpdate"])
def _scatter_update(ref, indices, updates):
    return ref.at[indices].set(updates)


@register("scatter_add", aliases=["ScatterAdd"])
def _scatter_add(ref, indices, updates):
    return ref.at[indices].add(updates)


register("pad", lambda x, paddings, mode="CONSTANT", constant_values=0:
         jnp.pad(x, tuple(tuple(int(v) for v in p) for p in paddings),
                 mode={"CONSTANT": "constant", "REFLECT": "reflect", "SYMMETRIC": "symmetric"}.get(str(mode).upper(), mode),
                 **({"constant_values": constant_values} if str(mode).upper() == "CONSTANT" else {})),
         aliases=["Pad", "PadV2"])
register("shape_of", lambda x: jnp.asarray(x.shape, dtype=jnp.int32), aliases=["Shape"])
register("size", lambda x: jnp.asarray(x.size, dtype=jnp.int32), aliases=["Size"])
register("rank", lambda x: jnp.asarray(x.ndim, dtype=jnp.int32), aliases=["Rank"])
register("cast", lambda x, dtype: x.astype(dtype), aliases=["Cast"])
register("identity", lambda x: x, aliases=["Identity"])
register("fill", lambda shape, value: jnp.full(tuple(int(s) for s in shape), value), aliases=["Fill"])
register("zeros_like", jnp.zeros_like, aliases=["ZerosLike"])
register("ones_like", jnp.ones_like, aliases=["OnesLike"])
register("linspace", lambda start, stop, num: jnp.linspace(start, stop, int(num)), aliases=["LinSpace"])
register("range", lambda start, limit, delta: jnp.arange(start, limit, delta), aliases=["Range"])
def _one_hot(indices, depth, on_value=1.0, off_value=0.0, axis=-1, dtype=None):
    out = jax.nn.one_hot(indices, int(depth), axis=axis) \
        * (on_value - off_value) + off_value
    return out.astype(dtype) if dtype is not None else out


register("one_hot", _one_hot, aliases=["OneHot", "onehot"])
register("where", lambda cond, x=None, y=None: jnp.where(cond, x, y) if x is not None
         else jnp.stack(jnp.nonzero(cond), axis=-1), aliases=["Where", "Select", "SelectV2"])
register("broadcast_to", lambda x, shape: jnp.broadcast_to(x, tuple(int(s) for s in shape)), aliases=["BroadcastTo"])
register("space_to_depth", lambda x, block_size=2: _space_to_depth(x, int(block_size)), aliases=["SpaceToDepth"])
register("depth_to_space", lambda x, block_size=2: _depth_to_space(x, int(block_size)), aliases=["DepthToSpace"])


def _space_to_depth(x, b):
    n, h, w, c = x.shape
    x = x.reshape(n, h // b, b, w // b, b, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b, c * b * b)


def _depth_to_space(x, b):
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, b, b, c // (b * b))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * b, w * b, c // (b * b))


# -------------------------------------------------------------- comparisons
register("equals", lambda a, b: a == b, aliases=["Equal", "eq"])
register("not_equals", lambda a, b: a != b, aliases=["NotEqual", "neq"])
register("greater", lambda a, b: a > b, aliases=["Greater", "gt"])
register("greater_equal", lambda a, b: a >= b, aliases=["GreaterEqual", "gte"])
register("less", lambda a, b: a < b, aliases=["Less", "lt"])
register("less_equal", lambda a, b: a <= b, aliases=["LessEqual", "lte"])
register("boolean_and", jnp.logical_and, aliases=["LogicalAnd"])
register("boolean_or", jnp.logical_or, aliases=["LogicalOr"])
register("boolean_not", jnp.logical_not, aliases=["LogicalNot"])
register("boolean_xor", jnp.logical_xor, aliases=["LogicalXor"])


# ------------------------------------------------------------------- linalg
@register("matmul", aliases=["MatMul", "mmul", "BatchMatMul", "BatchMatMulV2"])
def _matmul(a, b, transpose_a=False, transpose_b=False, transA=None, transB=None):
    ta = transpose_a if transA is None else bool(transA)
    tb = transpose_b if transB is None else bool(transB)
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    prefer = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else None
    return jnp.matmul(a, b, preferred_element_type=prefer)


register("tensordot", lambda a, b, axes: jnp.tensordot(a, b, axes=axes), aliases=["tensormmul"])
register("diag", jnp.diag, aliases=["Diag"])
register("diag_part", jnp.diagonal, aliases=["DiagPart"])
register("matrix_inverse", jnp.linalg.inv, aliases=["MatrixInverse"])
register("matrix_determinant", jnp.linalg.det, aliases=["MatrixDeterminant"])
register("cholesky", jnp.linalg.cholesky, aliases=["Cholesky"])
register("qr", jnp.linalg.qr, num_outputs=2, aliases=["Qr"])
register("svd", lambda x, full_matrices=False: jnp.linalg.svd(x, full_matrices=full_matrices),
         num_outputs=3, aliases=["Svd"])
register("trace", jnp.trace, aliases=["Trace"])
register("lstsq", lambda a, b: jnp.linalg.lstsq(a, b)[0])
register("triangular_solve", lambda a, b, lower=True: jax.scipy.linalg.solve_triangular(a, b, lower=lower))
register("solve", jnp.linalg.solve, aliases=["MatrixSolve"])
register("matrix_band_part", lambda x, lower, upper: _band_part(x, int(lower), int(upper)),
         aliases=["MatrixBandPart"])


def _band_part(x, lower, upper):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.logical_and(
        (i - j) <= (lower if lower >= 0 else m),
        (j - i) <= (upper if upper >= 0 else n))
    return jnp.where(keep, x, jnp.zeros_like(x))


# ------------------------------------------------------------- convolutions
# NHWC / NWC / NDHWC layouts — TPU-native. Weights: HWIO (spatial..., in, out).
def _conv_nd(x, w, strides, padding, dilation, dims, feature_group_count=1):
    num = {1: ("NWC", "WIO", "NWC"), 2: ("NHWC", "HWIO", "NHWC"), 3: ("NDHWC", "DHWIO", "NDHWC")}[dims]
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilation, dimension_numbers=num,
        feature_group_count=feature_group_count,
        # no preferred_element_type: the MXU accumulates f32 internally
        # regardless, and a f32-PET conv breaks the transpose (dW) rule
        # under grad with bf16 inputs (mixed-dtype conv). bf16-in ->
        # bf16-out matches the flax convention.
        )


def _pad_attr(padding, kernel, strides, dilation=None):
    """Map DL4J/TF padding attrs to lax padding."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        padding = (padding,) * len(kernel)
    if all(isinstance(p, (tuple, list)) for p in padding):
        return [(int(lo), int(hi)) for lo, hi in padding]
    return [(int(p), int(p)) for p in padding]


@register("conv1d", aliases=["Conv1D"])
def conv1d(x, w, b=None, stride=1, padding="SAME", dilation=1):
    out = _conv_nd(x, w, (int(stride),), _pad_attr(padding, (0,), None), (int(dilation),), 1)
    return out + b if b is not None else out


@register("conv2d", aliases=["Conv2D"])
def conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1), groups=1):
    strides = (strides, strides) if isinstance(strides, int) else tuple(strides)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    out = _conv_nd(x, w, strides, _pad_attr(padding, (0, 0), None), dilation, 2,
                   feature_group_count=int(groups))
    return out + b if b is not None else out


# NCHW variants for the ONNX import path (ONNX is NCHW/OIHW-native; XLA's
# layout assignment makes these TPU-efficient without host transposes)
@register("conv2d_nchw")
def conv2d_nchw(x, w, b=None, strides=(1, 1), padding=((0, 0), (0, 0)),
                dilation=(1, 1), groups=1):
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(strides), padding=_pad_attr(padding, (0, 0), None),
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(groups),
        # no preferred_element_type: the MXU accumulates f32 internally
        # regardless, and a f32-PET conv breaks the transpose (dW) rule
        # under grad with bf16 inputs (mixed-dtype conv). bf16-in ->
        # bf16-out matches the flax convention.
        )
    return out + b.reshape(1, -1, 1, 1) if b is not None else out


def _pool_nchw(x, reducer, init, kernel, strides, padding):
    return lax.reduce_window(
        x, init, reducer, window_dimensions=(1, 1) + tuple(kernel),
        window_strides=(1, 1) + tuple(strides),
        padding=((0, 0), (0, 0)) + tuple(tuple(p) for p in padding))


@register("maxpool2d_nchw")
def maxpool2d_nchw(x, kernel=(2, 2), strides=(2, 2), padding=((0, 0), (0, 0))):
    return _pool_nchw(x, lax.max, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                      else jnp.iinfo(x.dtype).min, kernel, strides, padding)


@register("avgpool2d_nchw")
def avgpool2d_nchw(x, kernel=(2, 2), strides=(2, 2), padding=((0, 0), (0, 0)),
                   count_include_pad=False):
    s = _pool_nchw(x, lax.add, 0.0, kernel, strides, padding)
    if count_include_pad:
        return s / float(np.prod(kernel))
    cnt = _pool_nchw(jnp.ones_like(x), lax.add, 0.0, kernel, strides, padding)
    return s / cnt


@register("global_avgpool_nchw")
def global_avgpool_nchw(x):
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@register("batchnorm_nchw")
def batchnorm_nchw(x, scale, offset, mean, var, epsilon=1e-5):
    # folded scale/shift in >=f32 (see `batchnorm`)
    shp = (1, -1) + (1,) * (x.ndim - 2)
    acc = jnp.promote_types(x.dtype, jnp.float32)
    s = lax.rsqrt(var.astype(acc) + epsilon) * scale.astype(acc)
    sh = mean.astype(acc) * s - offset.astype(acc)
    return (x.astype(acc) * s.reshape(shp) - sh.reshape(shp)).astype(x.dtype)


@register("conv3d", aliases=["Conv3D"])
def conv3d(x, w, b=None, strides=(1, 1, 1), padding="SAME", dilation=(1, 1, 1)):
    out = _conv_nd(x, w, tuple(strides), _pad_attr(padding, (0, 0, 0), None), tuple(dilation), 3)
    return out + b if b is not None else out


@register("depthwise_conv2d", aliases=["DepthwiseConv2dNative", "sconv2d_depthwise"])
def depthwise_conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1)):
    # w: (H, W, C, multiplier) → grouped conv with C groups
    h, ww, c, m = w.shape
    w2 = w.reshape(h, ww, 1, c * m)
    out = _conv_nd(x, w2, tuple(strides), _pad_attr(padding, (0, 0), None), tuple(dilation), 2,
                   feature_group_count=c)
    return out + b if b is not None else out


@register("deconv2d", aliases=["Conv2DTranspose", "Conv2DBackpropInput"])
def deconv2d(x, w, b=None, strides=(1, 1), padding="SAME",
             transpose_kernel=False):
    """``transpose_kernel=True`` applies the 180-degree spatial flip +
    in/out channel swap of a true conv GRADIENT (TF Conv2DBackpropInput
    semantics, filter layout (H, W, out, in)); False keeps the
    correlation form used by the Keras/ONNX ConvTranspose layers."""
    strides = (strides, strides) if isinstance(strides, int) else tuple(strides)
    pad = padding.upper() if isinstance(padding, str) else [(int(p), int(p)) for p in ((padding, padding) if isinstance(padding, int) else padding)]
    out = lax.conv_transpose(x, w, strides=strides, padding=pad,
                             dimension_numbers=("NHWC", "HWIO", "NHWC"),
                             transpose_kernel=bool(transpose_kernel))
    return out + b if b is not None else out


def _pool(x, kind, window, strides, padding, dims):
    init, fn = {"max": (-np.inf, lax.max), "sum": (0.0, lax.add)}[kind]
    window = (1,) + tuple(window) + (1,)
    strides = (1,) + tuple(strides) + (1,)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        if all(isinstance(p, (tuple, list)) for p in padding):
            spatial = tuple((int(lo), int(hi)) for lo, hi in padding)
        else:
            spatial = tuple((int(p), int(p)) for p in padding)
        pad = ((0, 0),) + spatial + ((0, 0),)
    # init must stay a concrete scalar: a traced/Array init routes
    # reduce_window onto the generic variadic primitive, which has no
    # reverse-mode rule under jit∘grad linearization.
    return lax.reduce_window(x, np.asarray(init, x.dtype), fn, window, strides, pad)


@register("maxpool2d", aliases=["MaxPool", "max_pool_2d", "MaxPoolV2"])
def maxpool2d(x, kernel=(2, 2), strides=None, padding="VALID"):
    kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    strides = kernel if strides is None else ((strides, strides) if isinstance(strides, int) else tuple(strides))
    return _pool(x, "max", kernel, strides, padding, 2)


@register("avgpool2d", aliases=["AvgPool", "avg_pool_2d"])
def avgpool2d(x, kernel=(2, 2), strides=None, padding="VALID"):
    kernel = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
    strides = kernel if strides is None else ((strides, strides) if isinstance(strides, int) else tuple(strides))
    s = _pool(x, "sum", kernel, strides, padding, 2)
    if isinstance(padding, str) and padding.upper() == "VALID":
        return s / (kernel[0] * kernel[1])
    ones = jnp.ones_like(x)
    counts = _pool(ones, "sum", kernel, strides, padding, 2)
    return s / counts


def _norm_pool_args(kernel, strides, dims):
    kernel = (kernel,) * dims if isinstance(kernel, int) else tuple(kernel)
    if strides is None:
        strides = kernel
    else:
        strides = (strides,) * dims if isinstance(strides, int) else tuple(strides)
    return kernel, strides


@register("pnormpool2d")
def pnormpool2d(x, kernel=(2, 2), strides=None, padding="VALID", pnorm=2):
    kernel, strides = _norm_pool_args(kernel, strides, 2)
    s = _pool(jnp.abs(x) ** pnorm, "sum", kernel, strides, padding, 2)
    return s ** (1.0 / pnorm)


@register("maxpool3d", aliases=["MaxPool3D"])
def maxpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID"):
    kernel, strides = _norm_pool_args(kernel, strides, 3)
    return _pool(x, "max", kernel, strides, padding, 3)


@register("avgpool3d", aliases=["AvgPool3D"])
def avgpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID"):
    kernel, strides = _norm_pool_args(kernel, strides, 3)
    s = _pool(x, "sum", kernel, strides, padding, 3)
    if isinstance(padding, str) and padding.upper() == "VALID":
        return s / (kernel[0] * kernel[1] * kernel[2])
    counts = _pool(jnp.ones_like(x), "sum", kernel, strides, padding, 3)
    return s / counts


@register("global_avgpool2d")
def global_avgpool2d(x):
    return jnp.mean(x, axis=(1, 2))


@register("upsampling2d", aliases=["ResizeNearestNeighbor"])
def upsampling2d(x, size=2):
    size = (size, size) if isinstance(size, int) else tuple(size)
    return jnp.repeat(jnp.repeat(x, size[0], axis=1), size[1], axis=2)


@register("resize_bilinear", aliases=["ResizeBilinear"])
def resize_bilinear(x, size):
    n, h, w, c = x.shape
    # antialias=False matches TF's kernel (no filtering on downscale)
    return jax.image.resize(x, (n, int(size[0]), int(size[1]), c),
                            method="bilinear", antialias=False)


@register("im2col")
def im2col(x, kernel, strides=(1, 1), padding="VALID"):
    """Patch extraction (ref: libnd4j im2col helper); NHWC → (N, OH, OW,
    C*KH*KW) — channel-major feature packing, the
    conv_general_dilated_patches layout; col2im consumes the same."""
    kh, kw = kernel
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides),
        padding.upper() if isinstance(padding, str) else [(p, p) for p in padding],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return patches


# ------------------------------------------------------------ normalization
@register("batchnorm", aliases=["FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"])
def batchnorm(x, mean, variance, gamma=None, beta=None, epsilon=1e-5, axis=-1):
    # Fold the per-channel algebra into ONE scale and ONE shift vector in
    # >=f32, then apply a single fused elementwise to the activation.
    # Casting mean/inv/gamma/beta down to x.dtype first (the old form) left
    # ~3 standalone [C]-vector convert kernels per BN in the compiled
    # ResNet-50 step (161 total vs flax's 2 — benchmarks/resnet_hlo_diff.py);
    # the f32 per-channel math is how flax/TF normalize half inputs too.
    shp = [1] * x.ndim
    shp[axis] = x.shape[axis]
    acc = jnp.promote_types(x.dtype, jnp.float32)   # ≥f32; keeps f64 exact
    scale = lax.rsqrt(variance.astype(acc) + epsilon)
    if gamma is not None:
        scale = scale * gamma.astype(acc)
    shift = mean.astype(acc) * scale
    if beta is not None:
        shift = shift - beta.astype(acc)
    out = x.astype(acc) * scale.reshape(shp) - shift.reshape(shp)
    return out.astype(x.dtype)


@register("layer_norm", aliases=["LayerNorm"])
def layer_norm(x, gamma=None, beta=None, axis=-1, epsilon=1e-5):
    from deeplearning4j_tpu.ops.moments import one_pass_moments
    mean, var = one_pass_moments(x, axis, keepdims=True)   # stats >= f32
    out = (x - mean) * lax.rsqrt(var + epsilon)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    return out.astype(x.dtype)


@register("lrn", aliases=["LRN"])
def lrn(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    sq = jnp.square(x)
    d = int(depth_radius)
    pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(d, d)])
    window = jnp.stack([pad[..., i:i + x.shape[-1]] for i in range(2 * d + 1)], axis=0).sum(axis=0)
    return x / jnp.power(bias + alpha * window, beta)


@register("standardize")
def standardize(x, axis=-1, epsilon=1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    std = jnp.std(x, axis=axis, keepdims=True)
    return (x - mean) / (std + epsilon)


@register("l2_normalize", aliases=["L2Normalize"])
def l2_normalize(x, axis=-1, epsilon=1e-12):
    return x * lax.rsqrt(jnp.maximum(jnp.sum(jnp.square(x), axis=axis, keepdims=True), epsilon))


# ------------------------------------------------------------------- losses
@register("softmax_cross_entropy", aliases=["SoftmaxCrossEntropyWithLogits"])
def softmax_cross_entropy(logits, labels, axis=-1):
    return -jnp.sum(labels * jax.nn.log_softmax(logits, axis=axis), axis=axis)


@register("sparse_softmax_cross_entropy", aliases=["SparseSoftmaxCrossEntropyWithLogits"])
def sparse_softmax_cross_entropy(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]


@register("sigmoid_cross_entropy")
def sigmoid_cross_entropy(logits, labels):
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))


# ---------------------------------------------------------------- recurrent
@register("lstm_cell", aliases=["LSTMBlockCell"])
def lstm_cell(x, h_prev, c_prev, w, b, forget_bias=1.0):
    """One fused LSTM step. w: (input+hidden, 4*hidden) gate order i,f,g,o —
    a single MXU matmul per step (ref: libnd4j lstmLayer/lstmBlockCell)."""
    z = jnp.concatenate([x, h_prev], axis=-1) @ w + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


@register("gru_cell", aliases=["GRUCell"])
def gru_cell(x, h_prev, w_rz, w_h, b_rz, b_h):
    """GRU step. w_rz: (input+hidden, 2*hidden); w_h: (input+hidden, hidden)."""
    xh = jnp.concatenate([x, h_prev], axis=-1)
    rz = jax.nn.sigmoid(xh @ w_rz + b_rz)
    r, z = jnp.split(rz, 2, axis=-1)
    h_tilde = jnp.tanh(jnp.concatenate([x, r * h_prev], axis=-1) @ w_h + b_h)
    return (1.0 - z) * h_tilde + z * h_prev


@register("sru_cell")
def sru_cell(x, c_prev, w, b):
    z = x @ w
    xt, f, r = jnp.split(z, 3, axis=-1)
    bf, br = jnp.split(b, 2, axis=-1)
    f = jax.nn.sigmoid(f + bf)
    r = jax.nn.sigmoid(r + br)
    c = f * c_prev + (1 - f) * xt
    h = r * jnp.tanh(c) + (1 - r) * x[..., :c.shape[-1]]
    return h, c


# ---------------------------------------------------------------- attention
@register("dot_product_attention", aliases=["MultiHeadDotProductAttention"])
def dot_product_attention(q, k, v, mask=None, scaled=True):
    """(..., heads, seq, d) attention; softmax accumulates in at least f32
    for bf16 stability (f64 inputs keep f64 — the gradcheck harness runs
    this layer in double precision)."""
    d = q.shape[-1]
    acc = jnp.promote_types(q.dtype, jnp.float32)
    scores = jnp.einsum("...qd,...kd->...qk", q, k,
                        preferred_element_type=acc)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, scores.dtype))
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("...qk,...kd->...qd", w, v)


# ------------------------------------------------------------------- random
@register("dropout")
def dropout(x, key, rate=0.5):
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


@register("random_normal", aliases=["RandomStandardNormal"])
def random_normal(key, shape, mean=0.0, stddev=1.0, dtype=jnp.float32):
    return mean + stddev * jax.random.normal(key, tuple(shape), dtype)


@register("random_uniform", aliases=["RandomUniform"])
def random_uniform(key, shape, minval=0.0, maxval=1.0, dtype=jnp.float32):
    return jax.random.uniform(key, tuple(shape), dtype, minval, maxval)


@register("random_bernoulli")
def random_bernoulli(key, shape, p=0.5):
    return jax.random.bernoulli(key, p, tuple(shape)).astype(jnp.float32)


@register("dropout_inverted")
def dropout_inverted(x, key, p=0.5):
    """DL4J dropout semantics: p = RETAIN probability (ref: Dropout layer docs)."""
    mask = jax.random.bernoulli(key, p, x.shape)
    return jnp.where(mask, x / p, jnp.zeros_like(x))


# -------------------------------------------------------------- image / misc
@register("non_max_suppression", aliases=["NonMaxSuppressionV3"])
def non_max_suppression(boxes, scores, max_output_size=10, iou_threshold=0.5, score_threshold=-jnp.inf):
    """Sequential greedy NMS as lax.scan over fixed max_output_size (static
    shapes — returns padded indices with -1; ref: generic/image ops)."""
    n = boxes.shape[0]
    ys1, xs1, ys2, xs2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    y1 = jnp.minimum(ys1, ys2); y2 = jnp.maximum(ys1, ys2)
    x1 = jnp.minimum(xs1, xs2); x2 = jnp.maximum(xs1, xs2)
    areas = (y2 - y1) * (x2 - x1)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j]); xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j]); xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(0.0, yy2 - yy1) * jnp.maximum(0.0, xx2 - xx1)
        return inter / (areas[i] + areas[j] - inter + 1e-9)

    def body(carry, _):
        valid, = carry
        masked = jnp.where(valid, scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = valid[best] & (masked[best] >= score_threshold)
        idx = jnp.where(ok, best, -1)
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(n))
        valid = valid & (ious <= iou_threshold) & ok
        return (valid,), idx

    (_,), out = lax.scan(body, (jnp.ones(n, bool),), None, length=int(max_output_size))
    return out


@register("confusion_matrix", aliases=["ConfusionMatrix"])
def confusion_matrix(labels, predictions, num_classes):
    idx = labels.astype(jnp.int32) * num_classes + predictions.astype(jnp.int32)
    counts = jnp.bincount(idx, length=num_classes * num_classes)
    return counts.reshape(num_classes, num_classes)


@register("top_k", aliases=["TopKV2", "TopK"], num_outputs=2)
def top_k(x, k=1, sorted=True):
    return lax.top_k(x, int(k))


@register("in_top_k", aliases=["InTopKV2"])
def in_top_k(predictions, targets, k=1):
    _, idx = lax.top_k(predictions, int(k))
    return jnp.any(idx == targets[:, None], axis=-1)


@register("segment_sum", aliases=["SegmentSum"])
def segment_sum(data, segment_ids, num_segments=None):
    n = int(num_segments) if num_segments is not None else int(segment_ids.max()) + 1
    return jax.ops.segment_sum(data, segment_ids, n)


@register("sequence_mask", aliases=["SequenceMask"])
def sequence_mask(lengths, maxlen=None):
    m = int(maxlen) if maxlen is not None else int(lengths.max())
    return jnp.arange(m)[None, :] < lengths[:, None]


@register("reverse_sequence", aliases=["ReverseSequence"])
def reverse_sequence(x, seq_lengths, seq_axis=1, batch_axis=0):
    x = jnp.moveaxis(x, (batch_axis, seq_axis), (0, 1))
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    rev = seq_lengths[:, None] - 1 - idx
    gather_idx = jnp.where(idx < seq_lengths[:, None], rev, idx)
    out = jnp.take_along_axis(x, gather_idx.reshape(gather_idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return jnp.moveaxis(out, (0, 1), (batch_axis, seq_axis))


# ----------------------------------------------- threshold codec (Strom 2015)
@register("encode_threshold")
def encode_threshold(grad, threshold=1e-3):
    """Sparse 1-bit gradient encoding: returns (flat sign int8, mask, residual).
    Ref: libnd4j encode_threshold / EncodedGradientsAccumulator (SURVEY N9/D7).
    On-TPU gradient sync uses dense allreduce instead; this codec exists for
    the DCN cross-slice path and API parity. Dense-mask representation —
    XLA-friendly static shapes (index lists are host-side concepts)."""
    flat = grad.ravel()
    over = jnp.abs(flat) >= threshold
    signs = jnp.where(over, jnp.sign(flat), 0.0).astype(jnp.int8)
    residual = jnp.where(over, flat - jnp.sign(flat) * threshold, flat)
    return signs, residual


@register("decode_threshold")
def decode_threshold(signs, threshold=1e-3, shape=None):
    out = signs.astype(jnp.float32) * threshold
    return out.reshape(tuple(shape)) if shape is not None else out
