"""Op library: registry + standard XLA lowerings + Pallas platform kernels."""
from deeplearning4j_tpu.ops import registry
from deeplearning4j_tpu.ops import standard  # noqa: F401 — populates registry
from deeplearning4j_tpu.ops import extended  # noqa: F401 — long-tail ops
from deeplearning4j_tpu.ops import longtail  # noqa: F401 — tranche 3
from deeplearning4j_tpu.ops import tranche4  # noqa: F401 — tranche 4
from deeplearning4j_tpu.ops import tranche5  # noqa: F401 — tranche 5
from deeplearning4j_tpu.ops import tranche6  # noqa: F401 — tranche 6
from deeplearning4j_tpu.ops import transforms

__all__ = ["registry", "standard", "extended", "longtail", "tranche4",
           "tranche5", "tranche6", "transforms"]
