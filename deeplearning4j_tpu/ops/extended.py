"""Extended op library — the libnd4j declarable-op long tail
(``libnd4j/include/ops/declarable/generic/**`` groups not covered by
``standard.py``: absolute-statistics reductions, segment/scatter families,
bitwise, image color/resize/patch ops, special functions, random
distributions, loss ops, sequence-layer RNN ops — SURVEY N3, VERDICT r1 LoC
diagnostic "op library ~145 vs ~500").

Same conventions as ``standard.py``: arrays traced, attrs static, NHWC.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import exec_op, register

# ----------------------------------------------------- elementwise long tail
for _n, _f, _al in [
    ("expm1", jnp.expm1, ["Expm1"]),
    ("log2", lambda x: jnp.log2(x), ["Log2"]),
    ("log10", lambda x: jnp.log10(x), ["Log10"]),
    ("rint", jnp.rint, ["Rint"]),
    ("trunc", jnp.trunc, ["Trunc"]),
    ("atan2", jnp.arctan2, ["Atan2", "tr_atan2"]),
    ("hypot", jnp.hypot, []),
    ("lgamma", jax.scipy.special.gammaln, ["Lgamma"]),
    ("digamma", jax.scipy.special.digamma, ["Digamma"]),
    ("erfinv", jax.scipy.special.erfinv, ["Erfinv"]),
    ("sigmoid_derivative",
     lambda x: jax.nn.sigmoid(x) * (1 - jax.nn.sigmoid(x)), []),
    ("tanh_derivative", lambda x: 1 - jnp.tanh(x) ** 2, []),
]:
    register(_n, _f, aliases=_al)

register("rsub", lambda a, b: b - a, aliases=["reversesubtract", "RSub"])
register("rdiv", lambda a, b: b / a, aliases=["reversedivide", "RDiv"])
register("divide_no_nan",
         lambda a, b: jnp.where(b == 0, jnp.zeros_like(a * b), a / b),
         aliases=["DivNoNan"])
register("igamma", jax.scipy.special.gammainc, aliases=["Igamma"])
register("igammac", jax.scipy.special.gammaincc, aliases=["Igammac"])
register("betainc", jax.scipy.special.betainc, aliases=["Betainc"])


@register("polygamma", aliases=["Polygamma"])
def _polygamma(n, x):
    return jax.scipy.special.polygamma(jnp.asarray(n, jnp.int32), x)


@register("isclose", aliases=["ApproxEquals"])
def _isclose(a, b, rtol=1e-5, atol=1e-8):
    return jnp.isclose(a, b, rtol=rtol, atol=atol)


register("is_non_decreasing",
         lambda x: jnp.all(jnp.ravel(x)[1:] >= jnp.ravel(x)[:-1]),
         aliases=["IsNonDecreasing"])
register("is_strictly_increasing",
         lambda x: jnp.all(jnp.ravel(x)[1:] > jnp.ravel(x)[:-1]),
         aliases=["IsStrictlyIncreasing"])


# ------------------------------------------------- absolute-value reductions
register("reduce_amax", lambda x, axis=None, keepdims=False:
         jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims), aliases=["amax"])
register("reduce_amin", lambda x, axis=None, keepdims=False:
         jnp.min(jnp.abs(x), axis=axis, keepdims=keepdims), aliases=["amin"])
register("reduce_amean", lambda x, axis=None, keepdims=False:
         jnp.mean(jnp.abs(x), axis=axis, keepdims=keepdims), aliases=["amean"])
register("reduce_asum", lambda x, axis=None, keepdims=False:
         jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims), aliases=["asum"])
register("count_nonzero", lambda x, axis=None, keepdims=False:
         jnp.count_nonzero(x, axis=axis, keepdims=keepdims),
         aliases=["CountNonZero"])
register("count_zero", lambda x, axis=None, keepdims=False:
         jnp.sum((x == 0), axis=axis, keepdims=keepdims),
         aliases=["CountZero"])
register("zero_fraction", lambda x: jnp.mean((x == 0).astype(jnp.float32)),
         aliases=["ZeroFraction"])
register("argamax", lambda x, axis=None: jnp.argmax(jnp.abs(x), axis=axis),
         aliases=["absargmax"])
register("argamin", lambda x, axis=None: jnp.argmin(jnp.abs(x), axis=axis),
         aliases=["absargmin"])


@register("entropy", aliases=["Entropy"])
def _entropy(p, axis=None):
    """−Σ p·log p (ref: reduce ops entropy)."""
    q = jnp.where(p > 0, p, 1.0)
    return -jnp.sum(p * jnp.log(q), axis=axis)


@register("log_entropy", aliases=["LogEntropy"])
def _log_entropy(p, axis=None):
    return jnp.log(_entropy(p, axis=axis))


@register("shannon_entropy", aliases=["ShannonEntropy", "shannonentropy"])
def _shannon_entropy(p, axis=None):
    q = jnp.where(p > 0, p, 1.0)
    return -jnp.sum(p * jnp.log2(q), axis=axis)


@register("moments", num_outputs=2, aliases=["Moments"])
def _moments(x, axes=None, keepdims=False):
    # tf.nn.moments computes half-precision stats in f32 then casts back —
    # but only for inexact inputs: integer x must keep FLOAT statistics
    # (casting the mean of [0, 1] back to int32 would yield 0)
    from deeplearning4j_tpu.ops.moments import one_pass_moments
    axes = tuple(axes) if axes is not None else None
    mean, var = one_pass_moments(x, axes, keepdims=keepdims)
    if jnp.issubdtype(x.dtype, jnp.inexact):
        return mean.astype(x.dtype), var.astype(x.dtype)
    return mean, var


@register("normalize_moments", num_outputs=2, aliases=["NormalizeMoments"])
def _normalize_moments(counts, mean_ss, var_ss, shift=0.0):
    mean = mean_ss / counts + shift
    var = var_ss / counts - jnp.square(mean_ss / counts)
    return mean, var


@register("reduce_dot", aliases=["dot"])
def _reduce_dot(a, b, axis=None, keepdims=False):
    return jnp.sum(a * b, axis=axis, keepdims=keepdims)


@register("cosine_similarity", aliases=["CosineSimilarity"])
def _cosine_similarity(a, b, axis=-1):
    num = jnp.sum(a * b, axis=axis)
    den = (jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis))
    return num / jnp.maximum(den, 1e-12)


register("cosine_distance", lambda a, b, axis=-1:
         1.0 - _cosine_similarity(a, b, axis=axis),
         aliases=["CosineDistance"])
register("euclidean_distance", lambda a, b, axis=-1:
         jnp.sqrt(jnp.sum(jnp.square(a - b), axis=axis)),
         aliases=["EuclideanDistance"])
register("manhattan_distance", lambda a, b, axis=-1:
         jnp.sum(jnp.abs(a - b), axis=axis), aliases=["ManhattanDistance"])
register("hamming_distance", lambda a, b, axis=None:
         jnp.sum((a != b), axis=axis), aliases=["HammingDistance"])
register("jaccard_distance", lambda a, b, axis=-1:
         1.0 - (jnp.sum(jnp.minimum(a, b), axis=axis)
                / jnp.maximum(jnp.sum(jnp.maximum(a, b), axis=axis), 1e-12)),
         aliases=["JaccardDistance"])


# ------------------------------------------------------------- shape / index
register("eye", lambda n, m=None, dtype=jnp.float32:
         jnp.eye(n, m if m is not None else n, dtype=dtype), aliases=["Eye"])
register("repeat", lambda x, repeats, axis=None:
         jnp.repeat(x, repeats, axis=axis), aliases=["Repeat"])
register("roll", lambda x, shift, axis=None: jnp.roll(x, shift, axis=axis),
         aliases=["Roll"])
register("rot90", lambda x, k=1, axes=(0, 1): jnp.rot90(x, k, axes=axes))
register("invert_permutation", lambda p: jnp.argsort(p),
         aliases=["InvertPermutation"])
register("meshgrid", lambda *xs, indexing="xy":
         jnp.meshgrid(*xs, indexing=indexing), aliases=["Meshgrid"])
register("size_at", lambda x, dim: x.shape[dim], aliases=["SizeAt"])
register("searchsorted", lambda sorted_seq, values, side="left":
         jnp.searchsorted(sorted_seq, values, side=side),
         aliases=["SearchSorted"])
register("bincount", lambda x, weights=None, minlength=0, length=None:
         jnp.bincount(jnp.ravel(x), weights=weights, minlength=minlength,
                      # static `length` makes it jit-traceable (TF
                      # Bincount/DenseBincount size attr)
                      length=length),
         aliases=["Bincount"])


@register("histogram_fixed_width", aliases=["HistogramFixedWidth"])
def _histogram_fixed_width(x, value_range, nbins=100):
    lo, hi = value_range[0], value_range[1]
    idx = jnp.clip(((x - lo) / (hi - lo) * nbins).astype(jnp.int32), 0,
                   nbins - 1)
    return jnp.zeros(nbins, jnp.int32).at[jnp.ravel(idx)].add(1)


@register("unique", num_outputs=2, aliases=["Unique"])
def _unique(x, size=None):
    """Values + inverse indices. ``size`` makes it jit-compatible (padded
    with the max value, reference semantics are host-eager anyway)."""
    if size is None:
        vals, inv = np.unique(np.asarray(x), return_inverse=True)
        return jnp.asarray(vals), jnp.asarray(inv.reshape(np.shape(x)))
    vals = jnp.unique(x, size=size, fill_value=jnp.max(x))
    inv = jnp.searchsorted(vals, jnp.ravel(x)).reshape(jnp.shape(x))
    return vals, inv


@register("unique_with_counts", num_outputs=3, aliases=["UniqueWithCounts"])
def _unique_with_counts(x):
    vals, inv, counts = np.unique(np.asarray(x), return_inverse=True,
                                  return_counts=True)
    return (jnp.asarray(vals), jnp.asarray(inv.reshape(np.shape(x))),
            jnp.asarray(counts))


@register("listdiff", num_outputs=2, aliases=["ListDiff", "setdiff1d"])
def _listdiff(x, y):
    x_np, y_np = np.asarray(x), np.asarray(y)
    mask = ~np.isin(x_np, y_np)
    return jnp.asarray(x_np[mask]), jnp.asarray(np.nonzero(mask)[0])


@register("dynamic_partition", aliases=["DynamicPartition"])
def _dynamic_partition(x, partitions, num_partitions):
    x_np, p_np = np.asarray(x), np.asarray(partitions)
    return [jnp.asarray(x_np[p_np == i]) for i in range(num_partitions)]


@register("dynamic_stitch", aliases=["DynamicStitch"])
def _dynamic_stitch(indices, values):
    n = int(max(np.max(np.asarray(i)) for i in indices)) + 1
    first = np.asarray(values[0])
    out = np.zeros((n,) + first.shape[1:], first.dtype)
    for idx, val in zip(indices, values):
        out[np.asarray(idx)] = np.asarray(val)
    return jnp.asarray(out)


# --------------------------------------------------------- segment / scatter
for _nm, _red in [("segment_max", "max"), ("segment_min", "min"),
                  ("segment_prod", "prod"), ("segment_mean", "mean")]:
    def _make(red):
        def f(data, segment_ids, num_segments=None):
            n = (int(num_segments) if num_segments is not None
                 else int(np.asarray(segment_ids).max()) + 1)
            if red == "mean":
                s = jax.ops.segment_sum(data, segment_ids, n)
                c = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, n)
                return s / jnp.maximum(c, 1)
            fn = {"max": jax.ops.segment_max, "min": jax.ops.segment_min,
                  "prod": jax.ops.segment_prod}[red]
            return fn(data, segment_ids, n)
        return f
    register(_nm, _make(_red),
             aliases=["Segment" + _red.capitalize(),
                      "unsorted_" + _nm, "Unsorted" + _nm.title().replace("_", "")])

register("unsorted_segment_sqrt_n",
         lambda data, segment_ids, num_segments:
         jax.ops.segment_sum(data, segment_ids, int(num_segments))
         / jnp.sqrt(jnp.maximum(jax.ops.segment_sum(
             jnp.ones_like(data), segment_ids, int(num_segments)), 1)),
         aliases=["UnsortedSegmentSqrtN"])

register("scatter_sub", lambda ref, idx, upd: ref.at[idx].add(-upd),
         aliases=["ScatterSub"])
register("scatter_mul", lambda ref, idx, upd: ref.at[idx].multiply(upd),
         aliases=["ScatterMul"])
register("scatter_div", lambda ref, idx, upd: ref.at[idx].divide(upd),
         aliases=["ScatterDiv"])
register("scatter_max", lambda ref, idx, upd: ref.at[idx].max(upd),
         aliases=["ScatterMax"])
register("scatter_min", lambda ref, idx, upd: ref.at[idx].min(upd),
         aliases=["ScatterMin"])


@register("scatter_nd", aliases=["ScatterNd"])
def _scatter_nd(indices, updates, shape):
    out = jnp.zeros(tuple(shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


register("scatter_nd_add",
         lambda ref, indices, upd:
         ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(upd),
         aliases=["ScatterNdAdd", "TensorScatterAdd"])
register("scatter_nd_update",
         lambda ref, indices, upd:
         ref.at[tuple(jnp.moveaxis(indices, -1, 0))].set(upd),
         aliases=["ScatterNdUpdate", "TensorScatterUpdate"])
register("scatter_nd_sub",
         lambda ref, indices, upd:
         ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(-upd),
         aliases=["ScatterNdSub", "TensorScatterSub"])


# ------------------------------------------------------------------- bitwise
register("bitwise_and", jnp.bitwise_and, aliases=["BitwiseAnd", "bitwise_and_"])
register("bitwise_or", jnp.bitwise_or, aliases=["BitwiseOr"])
register("bitwise_xor", jnp.bitwise_xor, aliases=["BitwiseXor"])
register("toggle_bits", jnp.bitwise_not, aliases=["ToggleBits", "bitwise_not"])
register("shift_bits", jnp.left_shift, aliases=["ShiftBits", "LeftShift"])
register("rshift_bits", jnp.right_shift, aliases=["RShiftBits", "RightShift"])


@register("cyclic_shift_bits", aliases=["CyclicShiftBits"])
def _cyclic_shift_bits(x, shift):
    nbits = x.dtype.itemsize * 8
    shift = shift % nbits
    ux = x.astype(jnp.uint32) if nbits == 32 else x
    out = (ux << shift) | (ux >> (nbits - shift))
    return out.astype(x.dtype)


@register("bits_hamming_distance", aliases=["BitsHammingDistance"])
def _bits_hamming_distance(a, b):
    return jnp.sum(jax.lax.population_count(jnp.bitwise_xor(a, b)))


register("bitcast", lambda x, dtype: lax.bitcast_convert_type(x, dtype),
         aliases=["Bitcast"])


# --------------------------------------------------------------------- image
def _resize(x, size, method):
    n, h, w, c = x.shape
    # antialias=False: TF's ResizeBilinear/Bicubic kernels do not
    # antialias on downscale (jax defaults to True)
    return jax.image.resize(x, (n, int(size[0]), int(size[1]), c), method,
                            antialias=False)


register("resize_nearest_neighbor",
         lambda x, size: _resize(x, size, "nearest"),
         aliases=["ResizeNearestNeighbor"])


def _tf_cubic_matrix(out_size: int, in_size: int) -> np.ndarray:
    """Sampling matrix (out, in) of ``tf.image.resize(method='bicubic',
    antialias=False)``: Keys cubic convolution (A = −0.5), half-pixel
    centers, and — the part jax.image's 'cubic' differs on — boundary
    taps falling OUTSIDE the image are dropped and the remaining weights
    renormalized, with the fractional offset quantized through TF's
    1024-entry coefficient lookup table (round(t·1024)/1024). Verified
    against TF's own weight matrix via an identity-basis probe: max
    deviation 9e-8. Static sizes → a trace-time numpy constant; the
    resize itself is two einsums XLA fuses."""
    A = -0.5
    scale = in_size / out_size
    coords = (np.arange(out_size, dtype=np.float64) + 0.5) * scale - 0.5
    base = np.floor(coords)
    t = np.round((coords - base) * 1024.0) / 1024.0

    def k(s):
        s = np.abs(s)
        return np.where(
            s <= 1.0, ((A + 2.0) * s - (A + 3.0)) * s * s + 1.0,
            np.where(s < 2.0, A * (((s - 5.0) * s + 8.0) * s - 4.0), 0.0))

    W = np.zeros((out_size, in_size), np.float64)
    rows = np.arange(out_size)
    for off in (-1, 0, 1, 2):
        idx = base.astype(np.int64) + off
        inside = (idx >= 0) & (idx < in_size)
        np.add.at(W, (rows[inside], idx[inside]),
                  (k(t - off))[inside])
    W /= W.sum(axis=1, keepdims=True)
    return W.astype(np.float32)


@register("resize_bicubic", aliases=["ResizeBicubic"])
def _resize_bicubic(x, size):
    n, h, w, c = x.shape
    oh, ow = int(size[0]), int(size[1])
    wy = jnp.asarray(_tf_cubic_matrix(oh, h))
    wx = jnp.asarray(_tf_cubic_matrix(ow, w))
    y = jnp.einsum("oy,nyxc->noxc", wy, x.astype(jnp.float32))
    return jnp.einsum("px,noxc->nopc", wx, y).astype(x.dtype)
register("resize_area", lambda x, size: _resize(x, size, "linear"),
         aliases=["ResizeArea"])   # XLA has no true area; linear is closest


@register("crop_and_resize", aliases=["CropAndResize"])
def _crop_and_resize(image, boxes, box_indices, crop_size):
    """Normalised-coordinate box crops resized to ``crop_size`` (ref/TF
    semantics, bilinear)."""
    ch, cw = int(crop_size[0]), int(crop_size[1])
    n, h, w, c = image.shape

    def one(box, bi):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        ys = y1 * (h - 1) + jnp.linspace(0.0, 1.0, ch) * (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.linspace(0.0, 1.0, cw) * (x2 - x1) * (w - 1)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        img = image[bi]
        tl = img[y0][:, x0]
        tr = img[y0][:, x1i]
        bl = img[y1i][:, x0]
        br = img[y1i][:, x1i]
        return (tl * (1 - wy) * (1 - wx) + tr * (1 - wy) * wx
                + bl * wy * (1 - wx) + br * wy * wx)

    return jax.vmap(one)(jnp.asarray(boxes, jnp.float32),
                         jnp.asarray(box_indices, jnp.int32))


@register("extract_image_patches", aliases=["ExtractImagePatches"])
def _extract_image_patches(x, ksizes, strides, rates=(1, 1), padding="VALID"):
    kh, kw = ksizes
    out = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides),
        padding.upper() if isinstance(padding, str) else padding,
        rhs_dilation=tuple(rates),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # lax returns channels-major patch layout (C, kh, kw); reference/TF wants
    # (kh, kw, C) — transpose the patch dim
    n, oh, ow, _ = out.shape
    c = x.shape[-1]
    out = out.reshape(n, oh, ow, c, kh * kw).transpose(0, 1, 2, 4, 3)
    return out.reshape(n, oh, ow, kh * kw * c)


register("rgb_to_grayscale",
         lambda x: jnp.sum(x * jnp.asarray([0.2989, 0.587, 0.114], x.dtype),
                           axis=-1, keepdims=True),
         aliases=["RgbToGrayscale", "rgb_to_grs"])


@register("rgb_to_hsv", aliases=["RgbToHsv"])
def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe_d = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        d == 0, 0.0,
        jnp.where(mx == r, jnp.mod((g - b) / safe_d, 6.0),
                  jnp.where(mx == g, (b - r) / safe_d + 2.0,
                            (r - g) / safe_d + 4.0))) / 6.0
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@register("hsv_to_rgb", aliases=["HsvToRgb"])
def _hsv_to_rgb(x):
    h, s, v = x[..., 0] * 6.0, x[..., 1], x[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


_YUV = np.array([[0.299, 0.587, 0.114],
                 [-0.14714119, -0.28886916, 0.43601035],
                 [0.61497538, -0.51496512, -0.10001026]], np.float32)
register("rgb_to_yuv", lambda x: jnp.einsum("...c,rc->...r", x,
                                            jnp.asarray(_YUV, x.dtype)),
         aliases=["RgbToYuv"])
register("yuv_to_rgb", lambda x: jnp.einsum("...c,rc->...r", x,
                                            jnp.asarray(np.linalg.inv(_YUV),
                                                        x.dtype)),
         aliases=["YuvToRgb"])
register("adjust_contrast",
         lambda x, factor: (x - jnp.mean(x, axis=(-3, -2), keepdims=True))
         * factor + jnp.mean(x, axis=(-3, -2), keepdims=True),
         aliases=["AdjustContrast", "AdjustContrastV2"])


@register("adjust_saturation", aliases=["AdjustSaturation"])
def _adjust_saturation(x, factor):
    hsv = _rgb_to_hsv(x)
    hsv = hsv.at[..., 1].set(jnp.clip(hsv[..., 1] * factor, 0.0, 1.0))
    return _hsv_to_rgb(hsv)


@register("adjust_hue", aliases=["AdjustHue"])
def _adjust_hue(x, delta):
    hsv = _rgb_to_hsv(x)
    hsv = hsv.at[..., 0].set(jnp.mod(hsv[..., 0] + delta, 1.0))
    return _hsv_to_rgb(hsv)


# ------------------------------------------------------------------- random
register("random_gamma", lambda key, alpha, shape=None, dtype=jnp.float32:
         jax.random.gamma(key, alpha,
                          shape=tuple(shape) if shape else None).astype(dtype),
         aliases=["RandomGamma"])
register("random_poisson", lambda key, lam, shape=None, dtype=jnp.float32:
         jax.random.poisson(key, lam,
                            shape=tuple(shape) if shape else None)
         .astype(dtype), aliases=["RandomPoisson", "RandomPoissonV2"])
register("random_exponential", lambda key, rate, shape, dtype=jnp.float32:
         (jax.random.exponential(key, tuple(shape)) / rate).astype(dtype),
         aliases=["RandomExponential"])
register("random_shuffle", lambda key, x: jax.random.permutation(key, x),
         aliases=["RandomShuffle"])
register("random_categorical",
         lambda key, logits, num_samples:
         jax.random.categorical(key, logits, shape=(logits.shape[0],
                                                    int(num_samples))),
         aliases=["Multinomial", "multinomial"])


# ------------------------------------------------------------------- linalg
register("matrix_diag", lambda d: jnp.apply_along_axis(jnp.diag, -1, d)
         if d.ndim > 1 else jnp.diag(d), aliases=["MatrixDiag"])
register("matrix_set_diag",
         lambda x, d: x.at[..., jnp.arange(d.shape[-1]),
                           jnp.arange(d.shape[-1])].set(d),
         aliases=["MatrixSetDiag"])
register("cross", jnp.cross, aliases=["Cross"])
register("logdet", lambda x: jnp.linalg.slogdet(x)[1], aliases=["Logdet"])
register("lu", lambda x: jax.scipy.linalg.lu(x), aliases=["Lu"])
register("self_adjoint_eig", lambda x: jnp.linalg.eigh(x),
         aliases=["SelfAdjointEigV2", "eigh"])
register("matrix_transpose", lambda x: jnp.swapaxes(x, -1, -2),
         aliases=["MatrixTranspose", "adjoint"])
register("batched_gemm", lambda a, b: jnp.matmul(a, b),
         aliases=["BatchedGemm", "batch_matmul", "BatchMatMul",
                  "BatchMatMulV2"])


# ------------------------------------------------------------------ loss ops
def _apply_weights_and_reduce(per, weights, reduction):
    if weights is not None:
        per = per * weights
    if reduction in ("mean", "MEAN_BY_WEIGHT", "weighted_mean"):
        den = (jnp.sum(jnp.broadcast_to(weights, per.shape))
               if weights is not None else per.size)
        return jnp.sum(per) / jnp.maximum(den, 1e-12)
    if reduction in ("sum", "SUM"):
        return jnp.sum(per)
    return per     # "none"


@register("huber_loss", aliases=["HuberLoss"])
def _huber_loss(labels, predictions, weights=None, delta=1.0,
                reduction="mean"):
    err = jnp.abs(predictions - labels)
    per = jnp.where(err <= delta, 0.5 * err * err,
                    delta * err - 0.5 * delta * delta)
    return _apply_weights_and_reduce(per, weights, reduction)


@register("log_loss", aliases=["LogLoss"])
def _log_loss(labels, predictions, weights=None, epsilon=1e-7,
              reduction="mean"):
    p = jnp.clip(predictions, epsilon, 1 - epsilon)
    per = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    return _apply_weights_and_reduce(per, weights, reduction)


@register("absolute_difference_loss", aliases=["AbsoluteDifference"])
def _absolute_difference_loss(labels, predictions, weights=None,
                              reduction="mean"):
    return _apply_weights_and_reduce(jnp.abs(predictions - labels), weights,
                                     reduction)


@register("mean_sqerr_loss", aliases=["MeanSqerrLoss"])
def _mean_sqerr_loss(labels, predictions, weights=None, reduction="mean"):
    return _apply_weights_and_reduce(jnp.square(predictions - labels),
                                     weights, reduction)


@register("hinge_loss", aliases=["HingeLoss"])
def _hinge_loss(labels, logits, weights=None, reduction="mean"):
    signed = 2.0 * labels - 1.0
    return _apply_weights_and_reduce(jnp.maximum(0.0, 1.0 - signed * logits),
                                     weights, reduction)


@register("cosine_distance_loss", aliases=["CosineDistanceLoss"])
def _cosine_distance_loss(labels, predictions, weights=None, axis=-1,
                          reduction="mean"):
    per = 1.0 - jnp.sum(labels * predictions, axis=axis, keepdims=True)
    return _apply_weights_and_reduce(per, weights, reduction)


@register("weighted_cross_entropy_with_logits",
          aliases=["WeightedCrossEntropyWithLogits"])
def _weighted_ce(labels, logits, pos_weight):
    log_w = 1 + (pos_weight - 1) * labels
    return (1 - labels) * logits + log_w * (
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
        + jnp.maximum(-logits, 0.0))


# ------------------------------------------------------------ nn extensions
register("bias_add", lambda x, b: x + b, aliases=["BiasAdd"])
register("xw_plus_b", lambda x, w, b: x @ w + b, aliases=["XwPlusB"])
register("relu_layer", lambda x, w, b: jax.nn.relu(x @ w + b),
         aliases=["ReluLayer"])
register("embedding_lookup", lambda params, ids: params[ids],
         aliases=["EmbeddingLookup"])


@register("lstm_layer", num_outputs=2, aliases=["LSTMLayer", "lstmLayer"])
def _lstm_layer(x, h0, c0, w, b, forget_bias=0.0):
    """Full-sequence LSTM over (N,T,C) via lax.scan of the fused cell (ref:
    declarable/recurrent/lstmLayer.cpp). Returns (outputs (N,T,H),
    (hN, cN))."""
    def step(carry, xt):
        h, c = carry
        h, c = exec_op("lstm_cell", xt, h, c, w, b, forget_bias=forget_bias)
        return (h, c), h

    (hN, cN), ys = lax.scan(step, (h0, c0), jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1), (hN, cN)


@register("gru_layer", num_outputs=2, aliases=["GRULayer", "gruLayer"])
def _gru_layer(x, h0, w_rz, w_h, b_rz, b_h):
    """Full-sequence GRU over (N,T,C) via lax.scan of the fused cell.
    Returns (outputs (N,T,H), hN)."""
    def step(h, xt):
        h = exec_op("gru_cell", xt, h, w_rz, w_h, b_rz, b_h)
        return h, h

    hN, ys = lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1), hN


# ----------------------------------------------------------------- sequence
@register("reverse", aliases=["Reverse", "ReverseV2"])
def _reverse(x, axis):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axis)


@register("trapz", aliases=[])
def _trapz(y, x=None, axis=-1):
    return jnp.trapezoid(y, x=x, axis=axis)


# boolean reductions (TF All/Any — assert chains in imported graphs)
register("reduce_all", lambda x, axis=None, keepdims=False:
         jnp.all(x, axis=axis, keepdims=keepdims), aliases=["All"])
register("reduce_any", lambda x, axis=None, keepdims=False:
         jnp.any(x, axis=axis, keepdims=keepdims), aliases=["Any"])


# ---------------------------------------------------------- spectral / signal
register("fft", lambda x, n=None, axis=-1: jnp.fft.fft(x, n=n, axis=axis),
         aliases=["FFT"])
register("ifft", lambda x, n=None, axis=-1: jnp.fft.ifft(x, n=n, axis=axis),
         aliases=["IFFT"])
register("rfft", lambda x, n=None, axis=-1: jnp.fft.rfft(x, n=n, axis=axis),
         aliases=["RFFT"])
register("irfft", lambda x, n=None, axis=-1: jnp.fft.irfft(x, n=n, axis=axis),
         aliases=["IRFFT"])
register("fft2", lambda x: jnp.fft.fft2(x), aliases=["FFT2D"])
register("ifft2", lambda x: jnp.fft.ifft2(x), aliases=["IFFT2D"])


# ----------------------------------------------------------------- ctc loss
@register("ctc_loss", aliases=["CTCLoss", "ctc_loss_v2"])
def _ctc_loss(log_probs, labels, logit_lengths, label_lengths, blank_id=0):
    """Connectionist temporal classification loss (ref: libnd4j ctc_loss
    declarable op). ``log_probs`` (B, T, C) log-softmax outputs; ``labels``
    (B, S) int32; per-example valid lengths. Uses optax's lattice
    implementation under the hood."""
    import optax

    T = log_probs.shape[1]
    S = labels.shape[1]
    logit_pad = (jnp.arange(T)[None, :]
                 >= jnp.asarray(logit_lengths)[:, None]).astype(jnp.float32)
    label_pad = (jnp.arange(S)[None, :]
                 >= jnp.asarray(label_lengths)[:, None]).astype(jnp.float32)
    return optax.ctc_loss(log_probs, logit_pad, labels, label_pad,
                          blank_id=blank_id)


# ------------------------------------------------------------ linalg tranche
register("pinv", jnp.linalg.pinv, aliases=["Pinv"])
register("kron", jnp.kron, aliases=["Kron"])
register("matrix_power", jnp.linalg.matrix_power, aliases=["MatrixPower"])
register("matrix_rank", lambda x: jnp.linalg.matrix_rank(x),
         aliases=["MatrixRank"])
register("norm", lambda x, ord=None, axis=None, keepdims=False:
         jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims),
         aliases=["Norm"])
register("outer", jnp.outer, aliases=["Outer"])
register("triu", lambda x, k=0: jnp.triu(x, k=k), aliases=["Triu"])
register("tril", lambda x, k=0: jnp.tril(x, k=k), aliases=["Tril"])


@register("trilu", aliases=["Trilu"])
def _trilu(x, k=0, upper=True):
    return jnp.triu(x, k=k) if upper else jnp.tril(x, k=k)
