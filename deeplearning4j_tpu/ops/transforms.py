"""Eager elementwise/transform ops, analog of
``org.nd4j.linalg.ops.transforms.Transforms`` plus the commonly used
``Nd4j.math`` surface. Bodies are XLA-lowered jnp calls — the reference's
hand-written loop families (libnd4j loops/cpu/transform_*.hpp) collapse into
the compiler (SURVEY.md N2).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import NDArray, _unwrap


def _u1(fn):
    def op(x, *args, **kwargs):
        return NDArray(fn(_unwrap(x), *[_unwrap(a) for a in args], **kwargs))
    return op


# --- strict transforms
exp = _u1(jnp.exp)
log = _u1(jnp.log)
log1p = _u1(jnp.log1p)
expm1 = _u1(jnp.expm1)
sqrt = _u1(jnp.sqrt)
cbrt = _u1(jnp.cbrt)
abs = _u1(jnp.abs)
sign = _u1(jnp.sign)
floor = _u1(jnp.floor)
ceil = _u1(jnp.ceil)
round = _u1(jnp.round)
sin = _u1(jnp.sin)
cos = _u1(jnp.cos)
tan = _u1(jnp.tan)
asin = _u1(jnp.arcsin)
acos = _u1(jnp.arccos)
atan = _u1(jnp.arctan)
sinh = _u1(jnp.sinh)
cosh = _u1(jnp.cosh)
tanh = _u1(jnp.tanh)
atanh = _u1(jnp.arctanh)
asinh = _u1(jnp.arcsinh)
acosh = _u1(jnp.arccosh)
reciprocal = _u1(jnp.reciprocal)
square = _u1(jnp.square)
erf = _u1(jax.scipy.special.erf)
erfc = _u1(jax.scipy.special.erfc)


def pow(x, p):
    return NDArray(jnp.power(_unwrap(x), _unwrap(p)))


def max(x, y):
    return NDArray(jnp.maximum(_unwrap(x), _unwrap(y)))


def min(x, y):
    return NDArray(jnp.minimum(_unwrap(x), _unwrap(y)))


def clip(x, lo, hi):
    return NDArray(jnp.clip(_unwrap(x), lo, hi))


def atan2(y, x):
    return NDArray(jnp.arctan2(_unwrap(y), _unwrap(x)))


def isNaN(x):
    return NDArray(jnp.isnan(_unwrap(x)))


def isInf(x):
    return NDArray(jnp.isinf(_unwrap(x)))


# --- neural activations (ref: Transforms + libnd4j generic/transforms)
sigmoid = _u1(jax.nn.sigmoid)
relu = _u1(jax.nn.relu)
relu6 = _u1(jax.nn.relu6)
elu = _u1(jax.nn.elu)
selu = _u1(jax.nn.selu)
gelu = _u1(jax.nn.gelu)
softplus = _u1(jax.nn.softplus)
softsign = _u1(jax.nn.soft_sign)
hardSigmoid = _u1(jax.nn.hard_sigmoid)
hardTanh = _u1(lambda x: jnp.clip(x, -1.0, 1.0))
swish = _u1(jax.nn.silu)
mish = _u1(jax.nn.mish)


def leakyRelu(x, alpha=0.01):
    return NDArray(jax.nn.leaky_relu(_unwrap(x), negative_slope=alpha))


def softmax(x, axis=-1):
    return NDArray(jax.nn.softmax(_unwrap(x), axis=axis))


def logSoftmax(x, axis=-1):
    return NDArray(jax.nn.log_softmax(_unwrap(x), axis=axis))


def logSumExp(x, axis=None):
    return NDArray(jax.scipy.special.logsumexp(_unwrap(x), axis=axis))


def step(x):
    return NDArray((_unwrap(x) > 0).astype(jnp.float32))


# --- distance / similarity (ref: Transforms#cosineSim etc.)
def cosineSim(a, b) -> float:
    a, b = _unwrap(a).ravel(), _unwrap(b).ravel()
    return float(jnp.vdot(a, b) / (jnp.linalg.norm(a) * jnp.linalg.norm(b)))


def cosineDistance(a, b) -> float:
    return 1.0 - cosineSim(a, b)


def euclideanDistance(a, b) -> float:
    return float(jnp.linalg.norm(_unwrap(a).ravel() - _unwrap(b).ravel()))


def manhattanDistance(a, b) -> float:
    return float(jnp.sum(jnp.abs(_unwrap(a).ravel() - _unwrap(b).ravel())))


def hammingDistance(a, b) -> float:
    return float(jnp.sum(_unwrap(a).ravel() != _unwrap(b).ravel()))


def jaccardDistance(a, b) -> float:
    a, b = _unwrap(a).ravel(), _unwrap(b).ravel()
    mn = jnp.sum(jnp.minimum(a, b))
    mx = jnp.sum(jnp.maximum(a, b))
    return float(1.0 - mn / mx)


# --- normalization
def unitVec(x):
    b = _unwrap(x)
    return NDArray(b / jnp.linalg.norm(b))


def normalizeZeroMeanAndUnitVariance(x):
    b = _unwrap(x)
    return NDArray((b - jnp.mean(b, axis=0)) / (jnp.std(b, axis=0) + 1e-12))


def stabilize(x, k=1.0):
    """ref: Transforms.stabilize — clamp to the numerically safe exp range."""
    b = _unwrap(x)
    lim = 80.0 / k
    return NDArray(jnp.clip(b, -lim, lim))


def andOp(a, b):
    return NDArray(jnp.asarray(_unwrap(a)).astype(bool)
                   & jnp.asarray(_unwrap(b)).astype(bool))


def orOp(a, b):
    return NDArray(jnp.asarray(_unwrap(a)).astype(bool)
                   | jnp.asarray(_unwrap(b)).astype(bool))


def xorOp(a, b):
    return NDArray(jnp.asarray(_unwrap(a)).astype(bool)
                   ^ jnp.asarray(_unwrap(b)).astype(bool))


def notOp(a):
    return NDArray(~jnp.asarray(_unwrap(a)).astype(bool))


def greaterThanOrEqual(a, b):
    return NDArray(jnp.greater_equal(_unwrap(a), _unwrap(b)))


def lessThanOrEqual(a, b):
    return NDArray(jnp.less_equal(_unwrap(a), _unwrap(b)))


def allEuclideanDistances(a, b, dim=1):
    """ref: Transforms.allEuclideanDistances — pairwise row distances."""
    A, B = _unwrap(a), _unwrap(b)
    if dim == 0:
        A, B = A.T, B.T
    d2 = (jnp.sum(A * A, 1)[:, None] - 2.0 * A @ B.T
          + jnp.sum(B * B, 1)[None, :])
    return NDArray(jnp.sqrt(jnp.maximum(d2, 0.0)))


def allManhattanDistances(a, b, dim=1):
    A, B = _unwrap(a), _unwrap(b)
    if dim == 0:
        A, B = A.T, B.T
    return NDArray(jnp.sum(jnp.abs(A[:, None, :] - B[None, :, :]), axis=-1))


def allCosineSimilarities(a, b, dim=1):
    A, B = _unwrap(a), _unwrap(b)
    if dim == 0:
        A, B = A.T, B.T
    An = A / (jnp.linalg.norm(A, axis=1, keepdims=True) + 1e-12)
    Bn = B / (jnp.linalg.norm(B, axis=1, keepdims=True) + 1e-12)
    return NDArray(An @ Bn.T)


def cross(a, b):
    return NDArray(jnp.cross(_unwrap(a), _unwrap(b)))


def dot(a, b):
    return NDArray(jnp.dot(_unwrap(a), _unwrap(b)))


def reverse(x, *dims):
    return NDArray(jnp.flip(_unwrap(x), axis=dims or None))


class Transforms:
    """Reference-spelled static facade (ref: org.nd4j.linalg.ops.transforms
    .Transforms). All module functions as statics, incl. python-keyword-safe
    names (``Transforms.and_`` for Java's ``Transforms.and``)."""
    pass


def _populate_transforms_facade():
    import sys
    mod = sys.modules[__name__]
    for name in dir(mod):
        if name.startswith("_") or name == "Transforms":
            continue
        obj = getattr(mod, name)
        if callable(obj) and getattr(obj, "__module__", "") == __name__:
            setattr(Transforms, name, staticmethod(obj))
    Transforms.and_ = staticmethod(andOp)
    Transforms.or_ = staticmethod(orOp)
    Transforms.xor_ = staticmethod(xorOp)
    Transforms.not_ = staticmethod(notOp)


_populate_transforms_facade()
