"""Declarable-op long tail, tranche 3 — spatial/batch reshuffles, merge ops,
unsorted segments, quantization, loss stragglers, RNN sequence runners, and
morphology (ref: libnd4j ``ops/declarable/generic/{transforms,parity_ops,
recurrent,quantization,loss}`` groups, SURVEY N3 — the ~500-op registry this
library mirrors).

Same conventions as ``standard.py``: arrays traced, attrs static, NHWC.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import exec_op, register

# ------------------------------------------------- spatial/batch reshuffles


@register("space_to_batch", aliases=["SpaceToBatch"])
def space_to_batch(x, block_size=2, paddings=((0, 0), (0, 0))):
    """NHWC (N,H,W,C) → (N·b², H/b, W/b, C) (ref: parity_ops space_to_batch;
    TF dilated-conv building block)."""
    return space_to_batch_nd(x, (int(block_size),) * 2, paddings)


@register("batch_to_space", aliases=["BatchToSpace"])
def batch_to_space(x, block_size=2, crops=((0, 0), (0, 0))):
    return batch_to_space_nd(x, (int(block_size),) * 2, crops)


@register("space_to_batch_nd", aliases=["SpaceToBatchND"])
def space_to_batch_nd(x, block_shape, paddings):
    block_shape = [int(b) for b in np.atleast_1d(block_shape)]
    m = len(block_shape)
    pads = [(0, 0)] + [tuple(int(v) for v in p) for p in paddings] \
        + [(0, 0)] * (x.ndim - 1 - m)
    x = jnp.pad(x, pads)
    n = x.shape[0]
    # (N, H/b1, b1, W/b2, b2, C...) → (b1, b2, N, H/b1, W/b2, C...)
    shape = [n]
    for i, b in enumerate(block_shape):
        shape += [x.shape[1 + i] // b, b]
    shape += list(x.shape[1 + m:])
    x = x.reshape(shape)
    perm = [2 * i + 2 for i in range(m)] + [0] \
        + [2 * i + 1 for i in range(m)] \
        + list(range(2 * m + 1, x.ndim))
    x = x.transpose(perm)
    out_shape = [n * int(np.prod(block_shape))] \
        + [x.shape[m + 1 + i] for i in range(m)] + list(x.shape[2 * m + 1:])
    return x.reshape(out_shape)


@register("batch_to_space_nd", aliases=["BatchToSpaceND"])
def batch_to_space_nd(x, block_shape, crops):
    block_shape = [int(b) for b in np.atleast_1d(block_shape)]
    m = len(block_shape)
    prod_b = int(np.prod(block_shape))
    n = x.shape[0] // prod_b
    x = x.reshape(block_shape + [n] + list(x.shape[1:]))
    perm = [m]
    for i in range(m):
        perm += [m + 1 + i, i]
    perm += list(range(2 * m + 1, x.ndim))
    x = x.transpose(perm)
    shape = [n] + [x.shape[1 + 2 * i] * block_shape[i] for i in range(m)] \
        + list(x.shape[2 * m + 1:])
    x = x.reshape(shape)
    idx = [slice(None)]
    for i, (lo, hi) in enumerate(tuple(tuple(int(v) for v in c)
                                       for c in crops)):
        idx.append(slice(lo, x.shape[1 + i] - hi))
    return x[tuple(idx)]


@register("mirror_pad", aliases=["MirrorPad"])
def mirror_pad(x, paddings, mode="REFLECT"):
    mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}[str(mode).upper()]
    pads = [tuple(int(v) for v in p) for p in np.asarray(paddings)]
    return jnp.pad(x, pads, mode=mode)


@register("col2im")
def col2im(cols, kernel, out_hw, strides=(1, 1), padding="VALID"):
    """Inverse of im2col: scatter-add (N,OH,OW,C·KH·KW) patches (channel-
    major feature packing, matching im2col) back to the (N,H,W,C) image
    (ref: libnd4j col2im helper — conv backward building block)."""
    kh, kw = (int(k) for k in kernel)
    sh, sw = (int(s) for s in strides)
    h, w = (int(v) for v in out_hw)
    n, oh, ow, _ = cols.shape
    c = cols.shape[-1] // (kh * kw)
    # im2col (conv_general_dilated_patches) packs features channel-major
    # (C, KH, KW); unpack the same way so col2im is its exact adjoint
    # (ordering bug caught by the conformance sweep's tape-adjoint twin —
    # the previous all-ones roundtrip test was permutation-blind)
    cols = cols.reshape(n, oh, ow, c, kh, kw).transpose(0, 1, 2, 4, 5, 3)
    if padding.upper() == "SAME":
        ph = max((oh - 1) * sh + kh - h, 0)
        pw = max((ow - 1) * sw + kw - w, 0)
        pt, pl = ph // 2, pw // 2
    else:
        pt = pl = 0
    pad_h = max((oh - 1) * sh + kh, h + pt)
    pad_w = max((ow - 1) * sw + kw, w + pl)
    # scatter-add every patch position in one batched index-add
    oy = jnp.arange(oh) * sh
    ox = jnp.arange(ow) * sw
    ky = jnp.arange(kh)
    kx = jnp.arange(kw)
    yy = (oy[:, None] + ky[None, :]).reshape(-1)          # (OH*KH,)
    xx = (ox[:, None] + kx[None, :]).reshape(-1)          # (OW*KW,)
    # flatten to linear indices over (H_pad, W_pad)
    cols_t = cols.transpose(0, 1, 3, 2, 4, 5).reshape(n, oh * kh, ow * kw, c)
    flat = jnp.zeros((n, pad_h * pad_w, c), cols.dtype)
    lin = (yy[:, None] * pad_w + xx[None, :]).reshape(-1)
    flat = flat.at[:, lin].add(cols_t.reshape(n, -1, c))
    img = flat.reshape(n, pad_h, pad_w, c)
    return img[:, pt:pt + h, pl:pl + w]


@register("dilation2d", aliases=["Dilation2D"])
def dilation2d(x, w, strides=(1, 1), rates=(1, 1), padding="SAME"):
    """Morphological dilation: out = max over window of (x + w) (ref:
    parity_ops dilation2d; TF kernel semantics)."""
    sh, sw = (int(s) for s in strides)
    rh, rw = (int(r) for r in rates)
    kh, kw, c = w.shape
    neg = jnp.asarray(-jnp.inf, x.dtype)
    pad = padding.upper()
    if pad == "SAME":
        eff_kh, eff_kw = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        oh = -(-x.shape[1] // sh)
        ow = -(-x.shape[2] // sw)
        ph = max((oh - 1) * sh + eff_kh - x.shape[1], 0)
        pw = max((ow - 1) * sw + eff_kw - x.shape[2], 0)
        x = jnp.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                        (pw // 2, pw - pw // 2), (0, 0)),
                    constant_values=neg)
    outs = []
    for i in range(kh):
        for j in range(kw):
            sl = x[:, i * rh: x.shape[1] - (kh - 1 - i) * rh or None: 1,
                   j * rw: x.shape[2] - (kw - 1 - j) * rw or None: 1]
            outs.append(sl[:, ::sh, ::sw] + w[i, j])
    oh = min(o.shape[1] for o in outs)
    ow = min(o.shape[2] for o in outs)
    return jnp.max(jnp.stack([o[:, :oh, :ow] for o in outs]), axis=0)


@register("maxpool_with_argmax", num_outputs=2, aliases=["MaxPoolWithArgmax"])
def maxpool_with_argmax(x, kernel=(2, 2), strides=None, padding="VALID"):
    """Returns (pooled, argmax indices) with TF's flat-index convention
    ``((y * W) + x) * C + c`` — ref: parity_ops max_pool_with_argmax /
    TF MaxPoolWithArgmax."""
    kh, kw = (int(k) for k in kernel)
    strides = strides or (kh, kw)
    sh, sw = (int(s) for s in strides)
    h, w, c = x.shape[1], x.shape[2], x.shape[-1]
    pt = pl_ = 0
    if padding.upper() == "SAME":
        # explicit -inf pad (extract_image_patches zero-pads, which would
        # beat genuine negative maxima) and index math in UNPADDED coords
        oh_s, ow_s = -(-h // sh), -(-w // sw)
        ph = max((oh_s - 1) * sh + kh - h, 0)
        pw = max((ow_s - 1) * sw + kw - w, 0)
        pt, pl_ = ph // 2, pw // 2
        neg = (jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating)
               else jnp.iinfo(x.dtype).min)
        x = jnp.pad(x, ((0, 0), (pt, ph - pt), (pl_, pw - pl_), (0, 0)),
                    constant_values=neg)
    patches = exec_op("extract_image_patches", x, ksizes=(kh, kw),
                      strides=strides, rates=(1, 1), padding="VALID")
    n, oh, ow, _ = patches.shape
    patches = patches.reshape(n, oh, ow, kh * kw, c)
    pooled = jnp.max(patches, axis=3)
    within = jnp.argmax(patches, axis=3)                  # (N,OH,OW,C)
    oy = jnp.arange(oh)[None, :, None, None] * sh - pt
    ox = jnp.arange(ow)[None, None, :, None] * sw - pl_
    ky, kx = within // kw, within % kw
    cc = jnp.arange(c)[None, None, None, :]
    flat = ((oy + ky) * w + (ox + kx)) * c + cc
    return pooled, flat.astype(jnp.int32)


@register("upsampling3d", aliases=["Upsampling3D"])
def upsampling3d(x, scale=2):
    """(N,D,H,W,C) nearest-neighbor ×scale (ref: convo/upsampling3d.cpp)."""
    s = int(scale)
    return jnp.repeat(jnp.repeat(jnp.repeat(x, s, axis=1), s, axis=2),
                      s, axis=3)


@register("deconv3d", aliases=["DeConv3D", "Conv3DTranspose"])
def deconv3d(x, w, b=None, strides=(1, 1, 1), padding="SAME"):
    """(N,D,H,W,C) transposed conv, weights (KD,KH,KW,Cin,Cout)."""
    pad = padding.upper()
    out = lax.conv_transpose(x, w, strides=tuple(int(s) for s in strides),
                             padding=pad,
                             dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    return out + b if b is not None else out


@register("sconv2d", aliases=["SeparableConv2D", "separable_conv2d"])
def sconv2d(x, depth_w, point_w=None, b=None, strides=(1, 1), padding="SAME"):
    """Separable conv: depthwise then optional 1×1 pointwise (ref:
    convo/sconv2d.cpp)."""
    out = exec_op("depthwise_conv2d", x, depth_w, strides=strides,
                  padding=padding)
    if point_w is not None:
        out = lax.conv_general_dilated(
            out, point_w, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + b if b is not None else out


@register("pointwise_conv2d", aliases=["PointwiseConv2D"])
def pointwise_conv2d(x, w, b=None):
    out = lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out + b if b is not None else out


# --------------------------------------------------------------- merge ops
register("mergeadd", lambda *xs: sum(xs[1:], xs[0]),
         aliases=["MergeAdd", "mergesum", "accumulate_n"])
register("mergeavg", lambda *xs: sum(xs[1:], xs[0]) / len(xs),
         aliases=["MergeAvg"])
register("mergemax", lambda *xs: jnp.max(jnp.stack(xs), axis=0),
         aliases=["MergeMax"])
register("mergemaxindex",
         lambda *xs: jnp.argmax(jnp.stack(xs), axis=0).astype(jnp.int32),
         aliases=["MergeMaxIndex"])


# ------------------------------------------------------- unsorted segments
def _unsorted(reducer, init):
    def op(data, segment_ids, num_segments=None):
        n = int(num_segments)
        ini = init(np.dtype(data.dtype)) if callable(init) else init
        out = jnp.full((n,) + data.shape[1:], ini, data.dtype)
        return reducer(out.at[segment_ids], data)
    return op


def _dtype_min(dt):
    # TF fills EMPTY segments of unsorted_segment_max with dtype.min
    # (finite -3.4e38 for f32), NOT -inf — verified divergence, r3 verdict
    return np.finfo(dt).min if np.issubdtype(dt, np.floating) \
        else np.iinfo(dt).min


def _dtype_max(dt):
    return np.finfo(dt).max if np.issubdtype(dt, np.floating) \
        else np.iinfo(dt).max


register("unsorted_segment_sum",
         lambda d, i, num_segments=None:
         jnp.zeros((int(num_segments),) + d.shape[1:], d.dtype)
         .at[i].add(d), aliases=["UnsortedSegmentSum"])
register("unsorted_segment_max",
         _unsorted(lambda at, d: at.max(d), _dtype_min),
         aliases=["UnsortedSegmentMax"])
register("unsorted_segment_min",
         _unsorted(lambda at, d: at.min(d), _dtype_max),
         aliases=["UnsortedSegmentMin"])
register("unsorted_segment_prod",
         _unsorted(lambda at, d: at.multiply(d), 1),
         aliases=["UnsortedSegmentProd"])


@register("unsorted_segment_mean", aliases=["UnsortedSegmentMean"])
def unsorted_segment_mean(data, segment_ids, num_segments):
    n = int(num_segments)
    tot = jnp.zeros((n,) + data.shape[1:], data.dtype).at[segment_ids].add(data)
    cnt = jnp.zeros((n,), data.dtype).at[segment_ids].add(1.0)
    cnt = jnp.maximum(cnt, 1).reshape((n,) + (1,) * (data.ndim - 1))
    return tot / cnt


# ------------------------------------------------------------ quantization
@register("fake_quant_with_min_max_args", aliases=["FakeQuantWithMinMaxArgs"])
def fake_quant_args(x, min=-6.0, max=6.0, num_bits=8, narrow_range=False):
    return _fake_quant(x, jnp.asarray(min, jnp.float32),
                       jnp.asarray(max, jnp.float32), int(num_bits),
                       bool(narrow_range))


@register("fake_quant_with_min_max_vars",
          aliases=["FakeQuantWithMinMaxVars",
                   "fake_quant_with_min_max_vars_per_channel",
                   "FakeQuantWithMinMaxVarsPerChannel"])
def fake_quant_vars(x, minv, maxv, num_bits=8, narrow_range=False):
    return _fake_quant(x, minv, maxv, int(num_bits), bool(narrow_range))


def _fake_quant(x, minv, maxv, num_bits, narrow):
    """TF fake-quant nudging semantics (ref: quantization group)."""
    qmin = 1.0 if narrow else 0.0
    qmax = float(2 ** num_bits - 1)
    scale = (maxv - minv) / (qmax - qmin)
    zp_f = qmin - minv / scale
    nudged_zp = jnp.clip(jnp.round(zp_f), qmin, qmax)
    nmin = (qmin - nudged_zp) * scale
    nmax = (qmax - nudged_zp) * scale
    xc = jnp.clip(x, nmin, nmax)
    return jnp.round((xc - nmin) / scale) * scale + nmin


@register("compare_and_bitpack", aliases=["CompareAndBitpack"])
def compare_and_bitpack(x, threshold):
    """Pack (…, 8k) boolean comparisons into uint8 bytes, MSB-first."""
    bits = (x > threshold).astype(jnp.uint8)
    b = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(b * weights, axis=-1).astype(jnp.uint8)


# ------------------------------------------------------------------ losses
register("l2_loss", lambda x: 0.5 * jnp.sum(jnp.square(x)),
         aliases=["L2Loss"])


@register("log_poisson_loss", aliases=["LogPoissonLoss"])
def log_poisson_loss(log_input, targets, full=False):
    loss = jnp.exp(log_input) - targets * log_input
    if full:
        # Stirling approximation term for the full loss
        t = targets
        stirling = t * jnp.log(jnp.maximum(t, 1e-12)) - t \
            + 0.5 * jnp.log(jnp.maximum(2 * jnp.pi * t, 1e-12))
        loss = loss + jnp.where(t > 1, stirling, jnp.zeros_like(t))
    return loss


@register("mean_pairwssqerr_loss", aliases=["MeanPairwsSqErrLoss"])
def mean_pairwssqerr_loss(predictions, labels):
    """Pairwise squared-error (ref: loss/meanPairWsSqErr.cpp — TF
    mean_pairwise_squared_error). Matches TF's implementation-defined
    scalar-weight behavior: per-sample term1−term2 with the denominator N
    being the TOTAL present element count (a `_num_present` quirk), then a
    batch SUM."""
    d = (predictions - labels).reshape(predictions.shape[0], -1)
    n_total = d.size
    sum_d = jnp.sum(d, axis=-1)
    sum_d2 = jnp.sum(d * d, axis=-1)
    term1 = 2.0 * sum_d2 / max(n_total - 1, 1)
    term2 = 2.0 * sum_d ** 2 / max(n_total * (n_total - 1), 1)
    return jnp.sum(term1 - term2)


# ------------------------------------------------------------- misc math
register("log_sigmoid", jax.nn.log_sigmoid, aliases=["LogSigmoid"])
register("crelu", lambda x, axis=-1: jax.nn.relu(
    jnp.concatenate([x, -x], axis=axis)), aliases=["CRelu"])
register("axpy", lambda x, y, a=1.0: a * x + y, aliases=["Axpy"])
register("assign", lambda x, y: jnp.broadcast_to(y, x.shape).astype(x.dtype),
         aliases=["Assign"])


@register("zeta", aliases=["Zeta"])
def zeta(x, q):
    """Hurwitz zeta via Euler–Maclaurin (ref: parity_ops zeta.cpp)."""
    return jax.scipy.special.zeta(x, q)


@register("percentile", aliases=["Percentile"])
def percentile(x, q=50.0, axis=None, interpolation="linear"):
    return jnp.percentile(x, q, axis=axis, method=str(interpolation))


@register("nth_element", aliases=["NthElement"])
def nth_element(x, n, reverse=False):
    """n-th order statistic along the last axis (ref: parity_ops
    nth_element.cpp)."""
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., int(n)]


@register("clip_by_global_norm", aliases=["ClipByGlobalNorm"])
def clip_by_global_norm(*tensors, clip_norm=1.0):
    g = jnp.sqrt(sum(jnp.sum(jnp.square(t)) for t in tensors))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(g, 1e-12))
    out = tuple(t * scale for t in tensors)
    return out if len(out) > 1 else out[0]


@register("clip_by_avg_norm", aliases=["ClipByAvgNorm"])
def clip_by_avg_norm(x, clip_norm=1.0):
    avg = jnp.linalg.norm(x.ravel()) / x.size
    return x * jnp.minimum(1.0, clip_norm / jnp.maximum(avg, 1e-12))


@register("choose", num_outputs=2, aliases=["Choose"])
def choose(x, scalar=0.0, mode=0):
    """Filter x by comparison against scalar; returns (matching values
    compacted to the front with zero fill, count) — ref: parity_ops
    choose.cpp  modes 0..5 = lt/gt/eq/ne/le/ge."""
    cmps = [x < scalar, x > scalar, x == scalar, x != scalar,
            x <= scalar, x >= scalar]
    m = cmps[int(mode)].ravel()
    flat = x.ravel()
    order = jnp.argsort(~m, stable=True)
    vals = jnp.where(jnp.sort(~m, stable=True) == 0, flat[order], 0)
    return vals.reshape(x.shape), jnp.sum(m).astype(jnp.int32)


# ------------------------------------------------------------------ color
_YIQ = np.array([[0.299, 0.587, 0.114],
                 [0.5959, -0.2746, -0.3213],
                 [0.2115, -0.5227, 0.3112]], np.float32)


register("rgb_to_yiq", lambda x: x @ jnp.asarray(_YIQ.T, x.dtype),
         aliases=["RgbToYiq"])
register("yiq_to_rgb",
         lambda x: x @ jnp.asarray(np.linalg.inv(_YIQ).T, x.dtype),
         aliases=["YiqToRgb"])


# ------------------------------------------------------------------ image
@register("draw_bounding_boxes", aliases=["DrawBoundingBoxes"])
def draw_bounding_boxes(images, boxes, colors=None):
    """Paint 1-px box outlines; boxes (N,B,4) normalized [y1,x1,y2,x2]
    (ref: parity_ops draw_bounding_boxes.cpp). Vectorized mask build —
    no per-pixel host loop."""
    n, h, w, c = images.shape
    nb = boxes.shape[1]
    if colors is None:
        colors = jnp.ones((nb, c), images.dtype)
    ys = jnp.arange(h)[None, None, :]                      # (1,1,H)
    xs = jnp.arange(w)[None, None, :]
    y1 = jnp.round(boxes[..., 0] * (h - 1))[..., None]     # (N,B,1)
    x1 = jnp.round(boxes[..., 1] * (w - 1))[..., None]
    y2 = jnp.round(boxes[..., 2] * (h - 1))[..., None]
    x2 = jnp.round(boxes[..., 3] * (w - 1))[..., None]
    in_y = (ys >= y1) & (ys <= y2)                         # (N,B,H)
    in_x = (xs >= x1) & (xs <= x2)                         # (N,B,W)
    edge_y = (ys == y1) | (ys == y2)
    edge_x = (xs == x1) | (xs == x2)
    mask = (edge_y[:, :, :, None] & in_x[:, :, None, :]) \
        | (in_y[:, :, :, None] & edge_x[:, :, None, :])    # (N,B,H,W)
    out = images
    for b in range(nb):
        mb = mask[:, b, :, :, None]
        out = jnp.where(mb, colors[b].reshape(1, 1, 1, c).astype(out.dtype),
                        out)
    return out


@register("non_max_suppression_overlaps",
          aliases=["NonMaxSuppressionWithOverlaps"])
def nms_overlaps(overlaps, scores, max_output_size, overlap_threshold=0.5,
                 score_threshold=-jnp.inf):
    """NMS on a precomputed pairwise overlap matrix (ref: image ops
    non_max_suppression_overlaps)."""
    k = int(max_output_size)
    overlaps = jnp.asarray(overlaps)
    scores = jnp.asarray(scores)
    n = scores.shape[0]
    order = jnp.argsort(-scores)
    valid0 = scores[order] > score_threshold

    def body(i, state):
        keep, sup = state
        cand = order[i]
        ok = valid0[i] & ~sup[i]
        keep = keep.at[i].set(jnp.where(ok, cand, -1))
        row = overlaps[cand][order] > overlap_threshold
        sup = jnp.where(ok, sup | row, sup)
        sup = sup.at[i].set(sup[i] | ~ok)
        return keep, sup

    keep, _ = lax.fori_loop(0, n, body,
                            (jnp.full((n,), -1, jnp.int32),
                             jnp.zeros((n,), bool)))
    # keep is already score-descending (it follows `order`); compact the
    # surviving entries to the front, preserving that order (TF returns the
    # top-k survivors by score, not by box index)
    alive = keep >= 0
    pos = jnp.argsort(~alive, stable=True)
    sel = jnp.where(jnp.sort(~alive, stable=True) == 0, keep[pos], -1)
    return sel[:k].astype(jnp.int32)


@register("random_crop", aliases=["RandomCrop"])
def random_crop(x, size, seed=0):
    key = jax.random.key(int(seed))
    size = tuple(int(s) for s in size)
    starts = []
    for i, s in enumerate(size):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, x.shape[i] - s + 1))
    return lax.dynamic_slice(x, starts, size)


# -------------------------------------------------------- RNN runners
@register("static_rnn", num_outputs=2,
          aliases=["StaticRNN", "dynamic_rnn", "DynamicRNN"])
def static_rnn(x, h0, c0, w, b, cell="lstm", forget_bias=0.0):
    """Run a cell over (N,T,C) via lax.scan (ref: recurrent static_rnn.cpp /
    dynamic_rnn.cpp — identical math on TPU; 'dynamic' time-major handling
    is a transpose at the call site). Returns (outputs, final state).

    For ``cell="gru"``, ``w``/``b`` pack the two GRU weight groups:
    ``w = (w_rz, w_h)`` and ``b = (b_rz, b_h)`` (gru_cell's signature)."""
    def step(carry, xt):
        if cell == "lstm":
            h, c = carry
            h, c = exec_op("lstm_cell", xt, h, c, w, b,
                           forget_bias=forget_bias)
            return (h, c), h
        w_rz, w_h = w
        b_rz, b_h = b
        h = exec_op("gru_cell", xt, carry[0], w_rz, w_h, b_rz, b_h)
        return (h, carry[1]), h

    (hN, cN), ys = lax.scan(step, (h0, c0), x.transpose(1, 0, 2))
    return ys.transpose(1, 0, 2), (hN, cN)


@register("static_bidirectional_rnn", num_outputs=2,
          aliases=["StaticBidirectionalRNN", "dynamic_bidirectional_rnn",
                   "DynamicBidirectionalRNN"])
def static_bidirectional_rnn(x, h0f, c0f, wf, bf, h0b, c0b, wb, bb,
                             cell="lstm", forget_bias=0.0):
    """Forward + time-reversed backward pass, concat on features."""
    yf, sf = static_rnn(x, h0f, c0f, wf, bf, cell=cell,
                        forget_bias=forget_bias)
    yb, sb = static_rnn(jnp.flip(x, axis=1), h0b, c0b, wb, bb, cell=cell,
                        forget_bias=forget_bias)
    return jnp.concatenate([yf, jnp.flip(yb, axis=1)], axis=-1), (sf, sb)


@register("lstm_block", num_outputs=2, aliases=["LSTMBlock"])
def lstm_block(x, h0, c0, w, b, forget_bias=1.0):
    """Whole-sequence fused LSTM (ref: recurrent/lstmBlock.cpp) — same scan
    as lstm_layer but with TF-style forget-bias default."""
    return exec_op("lstm_layer", x, h0, c0, w, b, forget_bias=forget_bias)


@register("sru", num_outputs=2, aliases=["SRU"])
def sru(x, c0, w, b):
    """Simple Recurrent Unit over (N,T,C) (ref: recurrent/sru.cpp). The
    matmuls batch over the whole sequence (MXU-friendly); only the light
    elementwise recurrence runs in the scan."""
    n, t, d = x.shape
    proj = x @ w                                           # (N,T,3D)
    xt_, f_, r_ = jnp.split(proj, 3, axis=-1)
    bf, br = jnp.split(b, 2)
    f = jax.nn.sigmoid(f_ + bf)
    r = jax.nn.sigmoid(r_ + br)

    def step(c, inp):
        xt, ft, rt, xraw = inp
        c = ft * c + (1 - ft) * xt
        h = rt * jnp.tanh(c) + (1 - rt) * xraw
        return c, h

    cN, hs = lax.scan(step, c0, (xt_.transpose(1, 0, 2),
                                 f.transpose(1, 0, 2),
                                 r.transpose(1, 0, 2),
                                 x.transpose(1, 0, 2)))
    return hs.transpose(1, 0, 2), cN


@register("sru_bi", num_outputs=2, aliases=["SRUBi"])
def sru_bi(x, c0f, wf, bf, c0b, wb, bb):
    hf, cf = sru(x, c0f, wf, bf)
    hb, cb = sru(jnp.flip(x, axis=1), c0b, wb, bb)
    return jnp.concatenate([hf, jnp.flip(hb, axis=1)], axis=-1), (cf, cb)


# ------------------------------------------------------- fused NLP steps
@register("skipgram", aliases=["SkipGram", "sg"])
def skipgram(syn0, syn1neg, center, context, neg, lr=0.025):
    """Fused skip-gram negative-sampling update (ref: libnd4j sg/cbow
    natives — the word2vec hot loop, SURVEY D15). Returns updated
    (syn0, syn1neg). Pure-functional twin of nlp/word2vec's jitted batch
    step, exposed as a registry op for parity."""
    v_in = syn0[center]                                    # (B,D)
    tgt = jnp.concatenate([context[:, None], neg], axis=1)  # (B,1+K)
    lbl = jnp.concatenate([jnp.ones_like(context[:, None]),
                           jnp.zeros_like(neg)], axis=1).astype(syn0.dtype)
    v_out = syn1neg[tgt]                                   # (B,1+K,D)
    logits = jnp.einsum("bd,bkd->bk", v_in, v_out)
    g = (lbl - jax.nn.sigmoid(logits)) * lr                # (B,1+K)
    d_in = jnp.einsum("bk,bkd->bd", g, v_out)
    d_out = jnp.einsum("bk,bd->bkd", g, v_in)
    syn0 = syn0.at[center].add(d_in)
    syn1neg = syn1neg.at[tgt.reshape(-1)].add(
        d_out.reshape(-1, d_out.shape[-1]))
    return syn0, syn1neg


@register("cbow", aliases=["CBOW"])
def cbow(syn0, syn1neg, context_words, target, neg, lr=0.025):
    """Fused CBOW negative-sampling update; context (B,W) averaged."""
    v_in = jnp.mean(syn0[context_words], axis=1)           # (B,D)
    tgt = jnp.concatenate([target[:, None], neg], axis=1)
    lbl = jnp.concatenate([jnp.ones_like(target[:, None]),
                           jnp.zeros_like(neg)], axis=1).astype(syn0.dtype)
    v_out = syn1neg[tgt]
    logits = jnp.einsum("bd,bkd->bk", v_in, v_out)
    g = (lbl - jax.nn.sigmoid(logits)) * lr
    d_in = jnp.einsum("bk,bkd->bd", g, v_out) / context_words.shape[1]
    d_out = jnp.einsum("bk,bd->bkd", g, v_in)
    syn0 = syn0.at[context_words.reshape(-1)].add(
        jnp.repeat(d_in, context_words.shape[1], axis=0))
    syn1neg = syn1neg.at[tgt.reshape(-1)].add(
        d_out.reshape(-1, d_out.shape[-1]))
    return syn0, syn1neg


# ----------------------------------------------------- fused attention op
@register("multi_head_dot_product_attention", num_outputs=1,
          aliases=["MultiHeadDotProductAttentionOp"])
def mh_attention(q, k, v, wq, wk, wv, wo, mask=None, causal=False):
    """Projected multi-head attention as ONE registry op (ref: SameDiff
    MultiHeadDotProductAttention, SURVEY 5.7). Inputs (N,T,D); heads from
    wq (D, H, Dh)."""
    def proj(x, w):
        return jnp.einsum("ntd,dhk->nhtk", x, w)

    qh, kh, vh = proj(q, wq), proj(k, wk), proj(v, wv)
    s = jnp.einsum("nhqk,nhmk->nhqm", qh, kh) / np.sqrt(qh.shape[-1])
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        cm = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(cm, s, -1e30)
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("nhqm,nhmk->nhqk", p, vh)
    return jnp.einsum("nhtk,hkd->ntd", o, wo)


# ---------------------------------------------------- tranche-4 stragglers
@register("maxout", aliases=["Maxout"])
def maxout(x, channels=2):
    """Maxout activation: max over groups of `channels` features (ref:
    generic/nn/activations maxout.cpp)."""
    c = int(channels)
    shp = x.shape[:-1] + (x.shape[-1] // c, c)
    return jnp.max(x.reshape(shp), axis=-1)


register("stop_gradient", lax.stop_gradient,
         aliases=["StopGradient", "stopgradient"])
register("tri", lambda rows, cols=None, diag=0: jnp.tri(
    int(rows), int(cols) if cols is not None else None, int(diag)),
    aliases=["Tri"])


@register("sufficient_statistics", num_outputs=3,
          aliases=["SufficientStatistics"])
def sufficient_statistics(x, axes):
    """(count, mean_ss=Σx, var_ss=Σx²) over axes (ref: parity_ops
    sufficient_statistics.cpp / tf.nn.sufficient_statistics)."""
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    count = float(np.prod([x.shape[a] for a in axes]))
    return (jnp.asarray(count, x.dtype), jnp.sum(x, axis=axes),
            jnp.sum(jnp.square(x), axis=axes))


# NOTE: no TF aliases here — standard.py's `batchnorm` owns the
# FusedBatchNorm/V2/V3 alias family with the (x, mean, var, gamma, beta)
# inference signature; this is the TRAINING-mode (scale, offset) form.
@register("fused_batch_norm", num_outputs=3)
def fused_batch_norm(x, scale, offset, mean=None, variance=None,
                     epsilon=1e-3, is_training=True):
    """TF training-mode FusedBatchNorm semantics: returns (y, batch_mean,
    batch_var); NHWC. y normalizes with the BIASED batch variance, while
    the returned batch_var is Bessel-corrected (N/(N-1)) — what TF feeds
    the moving-variance update.

    Batch statistics are computed in f32 (one_pass_moments) but returned in
    the MOVING-VARIABLE dtype — the dtype of the incoming moving mean/var,
    falling back to scale's. The imported graph's moving-average update
    site (assign_sub on the stored variables) consumes these outputs
    directly; returning f32 there would silently promote a bf16 imported
    model's stored statistics to f32."""
    stat_dtype = getattr(mean if mean is not None else scale, "dtype", None)
    if is_training or mean is None:
        from deeplearning4j_tpu.ops.moments import one_pass_moments
        n = float(np.prod([x.shape[i] for i in (0, 1, 2)]))
        mean, variance = one_pass_moments(x, (0, 1, 2))
        var_out = variance * (n / max(n - 1.0, 1.0))
    else:
        var_out = variance
    inv = lax.rsqrt(variance + epsilon)
    y = (x - mean) * inv * scale + offset
    if stat_dtype is not None:
        mean = jnp.asarray(mean).astype(stat_dtype)
        var_out = jnp.asarray(var_out).astype(stat_dtype)
    return y.astype(x.dtype), mean, var_out


@register("histogram", aliases=["Histogram"])
def histogram(x, num_bins=10):
    """Equal-width histogram over [min, max] (ref: parity_ops
    histogram.cpp)."""
    n = int(num_bins)
    lo, hi = jnp.min(x), jnp.max(x)
    width = jnp.maximum(hi - lo, 1e-12)
    idx = jnp.clip(((x - lo) / width * n).astype(jnp.int32), 0, n - 1)
    return jnp.zeros((n,), jnp.int32).at[idx.ravel()].add(1)


@register("boolean_mask", num_outputs=2, aliases=["BooleanMask"])
def boolean_mask(x, mask):
    """Compact rows where mask is True to the front, zero-filled tail
    (static-shape variant of tf.boolean_mask — XLA needs fixed shapes;
    pair with the returned count). Returns (values, count)."""
    m = jnp.ravel(mask).astype(bool)
    flat = x.reshape((m.shape[0],) + x.shape[mask.ndim:])
    order = jnp.argsort(~m, stable=True)
    vals = jnp.where((jnp.sort(~m, stable=True) == 0)
                     .reshape((-1,) + (1,) * (flat.ndim - 1)),
                     flat[order], 0)
    return vals, jnp.sum(m).astype(jnp.int32)


@register("sparse_to_dense", aliases=["SparseToDense"])
def sparse_to_dense(indices, values, dense_shape=None, default_value=0):
    """COO scatter (ref: parity_ops sparse_to_dense.cpp). indices (N, R);
    ``dense_shape`` is a static attr (XLA shapes are static)."""
    shape = tuple(int(s) for s in np.atleast_1d(dense_shape))
    out = jnp.full(shape, default_value,
                   values.dtype if hasattr(values, "dtype") else jnp.float32)
    idx = tuple(jnp.asarray(indices)[:, i] for i in range(len(shape)))
    return out.at[idx].set(values)


@register("sparse_dense_matmul", aliases=["SparseTensorDenseMatMul"])
def sparse_dense_matmul(indices, values, dense_shape, b):
    """(sparse A in COO) @ (dense B) via scatter-free segment sum — the
    rows of B gathered by A's column indices, scaled and summed per A-row.
    TPU-friendly: one gather + one segment-sum, no host loop."""
    a_rows = int(np.atleast_1d(dense_shape)[0])
    idx = jnp.asarray(indices)
    rows, cols = idx[:, 0], idx[:, 1]
    contrib = values[:, None] * b[cols]                  # (nnz, N)
    return jnp.zeros((a_rows, b.shape[1]), contrib.dtype) \
        .at[rows].add(contrib)


@register("log_matrix_determinant", num_outputs=2,
          aliases=["LogMatrixDeterminant"])
def log_matrix_determinant(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


register("reduce_sqnorm", lambda x, axis=None, keepdims=False:
         jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims),
         aliases=["SquaredNorm"])


@register("matrix_diag_part", aliases=["MatrixDiagPartV3Op"])
def matrix_diag_part(x):
    """Main diagonal of the LAST two axes (TF batched semantics — plain
    diag_part reduces axes 0,1 which is wrong for (B, M, N))."""
    return jnp.diagonal(x, axis1=-2, axis2=-1)


# ----------------------------------------------- ONNX-layout recurrent ops
def _onnx_dirs(x, num_dirs, direction, run_dir):
    """Shared forward/reverse/bidirectional dispatch: ``run_dir(di, xd)``
    returns (y (T,B,H), *finals); outputs stack to (T,D,B,H)/(D,B,H)."""
    dirs = ["forward"] if num_dirs == 1 else ["forward", "reverse"]
    if direction == "reverse":
        dirs = ["reverse"]
    outs, finals = [], None
    for di, kind in enumerate(dirs):
        xd = jnp.flip(x, 0) if kind == "reverse" else x
        res = run_dir(di, xd)
        y, rest = res[0], res[1:]
        if kind == "reverse":
            y = jnp.flip(y, 0)
        outs.append(y)
        if finals is None:
            finals = [[] for _ in rest]
        for slot, v in zip(finals, rest):
            slot.append(v)
    return (jnp.stack(outs, 1),
            *[jnp.stack(slot, 0) for slot in finals])


def _onnx_lstm_dir(x, w, r, wb, rb, h0, c0):
    """One direction. x (T,B,I); w (4H,I); r (4H,H); gate order iofc."""
    hsz = r.shape[1]

    def step(carry, xt):
        h, c = carry
        z = xt @ w.T + h @ r.T + wb + rb
        i, o, f, g = (z[:, :hsz], z[:, hsz:2 * hsz],
                      z[:, 2 * hsz:3 * hsz], z[:, 3 * hsz:])
        i, o, f = (jax.nn.sigmoid(v) for v in (i, o, f))
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return (h, c), h

    (hN, cN), ys = lax.scan(step, (h0, c0), x)
    return ys, hN, cN


@register("onnx_lstm", num_outputs=3, aliases=["OnnxLSTM"])
def onnx_lstm(x, w, r, b=None, h0=None, c0=None, direction="forward"):
    """ONNX LSTM semantics (ref: samediff-import-onnx LSTM mapping): x
    (T,B,I), W (D,4H,I), R (D,4H,H), B (D,8H); gate order i,o,f,c; default
    activations. Returns (Y (T,D,B,H), Y_h (D,B,H), Y_c)."""
    t, bsz, _ = x.shape
    d, four_h, hsz = w.shape[0], w.shape[1], r.shape[2]
    if b is None:
        b = jnp.zeros((d, 2 * four_h), x.dtype)
    if h0 is None:
        h0 = jnp.zeros((d, bsz, hsz), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((d, bsz, hsz), x.dtype)
    return _onnx_dirs(x, d, direction, lambda di, xd: _onnx_lstm_dir(
        xd, w[di], r[di], b[di, :four_h], b[di, four_h:], h0[di], c0[di]))


@register("onnx_gru", num_outputs=2, aliases=["OnnxGRU"])
def onnx_gru(x, w, r, b=None, h0=None, direction="forward",
             linear_before_reset=0):
    """ONNX GRU: gate order z,r,h; torch exports linear_before_reset=1."""
    t, bsz, _ = x.shape
    d, three_h, hsz = w.shape[0], w.shape[1], r.shape[2]
    if b is None:
        b = jnp.zeros((d, 2 * three_h), x.dtype)
    if h0 is None:
        h0 = jnp.zeros((d, bsz, hsz), x.dtype)

    def run_dir(xd, wd, rd, wbd, rbd, h0d):
        def step(h, xt):
            xz = xt @ wd.T + wbd
            hz = h @ rd.T + rbd
            z = jax.nn.sigmoid(xz[:, :hsz] + hz[:, :hsz])
            rr = jax.nn.sigmoid(xz[:, hsz:2 * hsz] + hz[:, hsz:2 * hsz])
            if linear_before_reset:
                ht = jnp.tanh(xz[:, 2 * hsz:] + rr * hz[:, 2 * hsz:])
            else:
                ht = jnp.tanh(xz[:, 2 * hsz:]
                              + (rr * h) @ rd[2 * hsz:].T + rbd[2 * hsz:])
            h = (1 - z) * ht + z * h
            return h, h
        return lax.scan(step, h0d, xd)

    def one(di, xd):
        hN, y = run_dir(xd, w[di], r[di], b[di, :three_h],
                        b[di, three_h:], h0[di])
        return y, hN

    return _onnx_dirs(x, d, direction, one)


@register("onnx_rnn", num_outputs=2, aliases=["OnnxRNN"])
def onnx_rnn(x, w, r, b=None, h0=None, direction="forward"):
    """ONNX vanilla RNN (tanh)."""
    t, bsz, _ = x.shape
    d, hsz = w.shape[0], r.shape[2]
    if b is None:
        b = jnp.zeros((d, 2 * hsz), x.dtype)
    if h0 is None:
        h0 = jnp.zeros((d, bsz, hsz), x.dtype)
    def one(di, xd):
        def step(h, xt, _w=w[di], _r=r[di], _wb=b[di, :hsz],
                 _rb=b[di, hsz:]):
            h = jnp.tanh(xt @ _w.T + h @ _r.T + _wb + _rb)
            return h, h

        hN, y = lax.scan(step, h0[di], xd)
        return y, hN

    return _onnx_dirs(x, d, direction, one)


@register("deconv2d_nchw", aliases=["ConvTransposeNCHW"])
def deconv2d_nchw(x, w, b=None, strides=(1, 1), padding=((0, 0), (0, 0))):
    """NCHW transposed conv with ONNX/torch weight layout (Cin, Cout, kH,
    kW). lax's IOHW rhs spec matches that layout directly."""
    pad = [(int(lo), int(hi)) for lo, hi in padding]
    # lax.conv_transpose padding refers to the FORWARD conv's padding
    # semantics via transpose; ONNX pads shrink the output:
    # out = (in-1)*s + k - pad_lo - pad_hi
    kh, kw = w.shape[2], w.shape[3]
    sh, sw = (int(s) for s in strides)
    # torch/ONNX weight (Cin, Cout, kH, kW) = the FORWARD conv's (O, I)
    # once transposed, so the rhs spec under transpose_kernel=True is OIHW
    full = lax.conv_transpose(
        x, w, (sh, sw), [(kh - 1, kh - 1), (kw - 1, kw - 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"), transpose_kernel=True)
    lo_h, hi_h = pad[0]
    lo_w, hi_w = pad[1]
    out = full[:, :, lo_h: full.shape[2] - hi_h or None,
               lo_w: full.shape[3] - hi_w or None]
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


@register("scatter_elements", aliases=["ScatterElements"])
def scatter_elements(x, indices, updates, axis=0, reduction="none"):
    """ONNX ScatterElements / torch scatter: per-element writes along one
    axis."""
    x = jnp.asarray(x)
    indices = jnp.asarray(indices)
    axis = int(axis) % x.ndim
    grids = list(jnp.meshgrid(*[jnp.arange(s) for s in indices.shape],
                              indexing="ij"))
    grids[axis] = indices
    at = x.at[tuple(grids)]
    if reduction == "add":
        return at.add(updates)
    if reduction == "mul":
        return at.multiply(updates)
    if reduction == "min":
        return at.min(updates)
    if reduction == "max":
        return at.max(updates)
    if reduction not in ("none", "", None):
        raise ValueError(f"scatter_elements: unknown reduction "
                         f"{reduction!r}")
    return at.set(updates)


register("trilu", lambda x, k=0, upper=True:
         (jnp.triu(x, k) if upper else jnp.tril(x, k)), aliases=["Trilu"])
register("hardmax", lambda x, axis=-1: jax.nn.one_hot(
    jnp.argmax(x, axis=axis), x.shape[axis], axis=axis, dtype=x.dtype),
    aliases=["Hardmax"])
register("global_maxpool_nchw", lambda x: jnp.max(x, axis=(2, 3),
                                                  keepdims=True),
         aliases=["GlobalMaxPoolNCHW"])
register("shrink", lambda x, bias=0.0, lambd=0.5: jnp.where(
    x < -lambd, x + bias, jnp.where(x > lambd, x - bias,
                                    jnp.zeros_like(x))), aliases=["Shrink"])
register("celu", lambda x, alpha=1.0: jnp.maximum(x, 0)
         + jnp.minimum(0, alpha * jnp.expm1(x / alpha)), aliases=["Celu"])


@register("group_norm", aliases=["GroupNormalization", "group_normalization"])
def group_norm(x, scale, bias, num_groups, epsilon=1e-5):
    """NCHW group normalization (ONNX GroupNormalization)."""
    n, c = x.shape[0], x.shape[1]
    g = int(num_groups)
    xg = x.reshape(n, g, c // g, *x.shape[2:])
    from deeplearning4j_tpu.ops.moments import one_pass_moments
    axes = tuple(range(2, xg.ndim))
    mu, var = one_pass_moments(xg, axes, keepdims=True)   # stats >= f32
    xn = ((xg - mu) * lax.rsqrt(var + epsilon)).reshape(x.shape).astype(x.dtype)
    shape = (1, c) + (1,) * (x.ndim - 2)
    return xn * scale.reshape(shape) + bias.reshape(shape)


register("reduce_logsumexp_axes",
         lambda x, axis=None, keepdims=False:
         jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims),
         aliases=["ReduceLogSumExpOp"])


register("truncatemod", lambda a, b: jnp.fmod(a, b),
         aliases=["TruncateMod", "fmod_op"])
