"""Op registry: named ops with jax-traceable bodies + shape inference.

Reference: libnd4j's declarable-op registry (``DeclarableOp`` +
``ops/declarable/headers/*.h`` registrations, ~500 ops) and the Java mirror
op classes (``DynamicCustomOp``). TPU-first redesign: an op is a named,
jax-traceable callable; "shape function" is ``jax.eval_shape`` over the body
(the compiler computes what the reference hand-wrote per op); execution is
whatever jit context the caller is tracing in — ops never dispatch one by one
across a runtime boundary.

The registry is the shared vocabulary for the SameDiff-style graph engine
(autodiff/), the TF/ONNX importers, and Pallas platform overrides (the
analog of libnd4j's PlatformHelper cuDNN/oneDNN swap-in, SURVEY.md N4).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence

import jax


@dataclasses.dataclass
class OpDef:
    name: str
    fn: Callable                     # (*arrays, **attrs) -> array | tuple of arrays
    num_outputs: int = 1
    aliases: tuple = ()
    # platform override (e.g. a Pallas kernel). When set and enabled, used
    # instead of `fn` — the PlatformHelper analog.
    platform_fn: Optional[Callable] = None

    def __call__(self, *args, **attrs):
        fn = self.platform_fn if (self.platform_fn is not None and _platform_overrides_enabled) else self.fn
        return fn(*args, **attrs)


_REGISTRY: Dict[str, OpDef] = {}
_platform_overrides_enabled = True


def register(name: str, fn: Callable = None, *, num_outputs: int = 1, aliases: Sequence[str] = ()):
    """Register an op. Usable as decorator or direct call."""
    def do_register(f):
        op = OpDef(name=name, fn=f, num_outputs=num_outputs, aliases=tuple(aliases))
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return f
    if fn is not None:
        return do_register(fn)
    return do_register


def alias(existing: str, *spellings: str):
    """Bind alternate spellings to an existing OpDef. Raises on collision
    with a DIFFERENT op — silent clobbering is how alias bugs start."""
    op = get(existing)
    for s in spellings:
        bound = _REGISTRY.get(s)
        if bound is not None and bound is not op:
            raise ValueError(
                f"alias {s!r} already bound to op {bound.name!r}; "
                f"refusing to rebind to {op.name!r}")
        _REGISTRY[s] = op


def register_platform(name: str, fn: Callable):
    """Attach an accelerated override (Pallas kernel) to an existing op."""
    _REGISTRY[name].platform_fn = fn


def set_platform_overrides(enabled: bool):
    """Global toggle, used by crosscheck tests (Pallas vs XLA-builtin)."""
    global _platform_overrides_enabled
    _platform_overrides_enabled = enabled


def get(name: str) -> OpDef:
    if name not in _REGISTRY:
        raise KeyError(f"Unknown op: {name!r}. {len(names())} ops registered.")
    return _REGISTRY[name]


def has(name: str) -> bool:
    return name in _REGISTRY


def names() -> list:
    return sorted({op.name for op in _REGISTRY.values()})


def exec_op(name: str, *args, **attrs):
    return get(name)(*args, **attrs)


def infer_shape(name: str, *args, **attrs):
    """Shape inference without execution (ref: DeclarableOp#calculateOutputShape)."""
    return jax.eval_shape(lambda *a: get(name)(*a, **attrs), *args)
