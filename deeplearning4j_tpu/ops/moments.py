"""One-pass batch/layer-norm moments (TPU fusion-friendly).

``jnp.var`` computes ``mean((x - mean)**2)`` — the second reduction depends
on the first, so XLA must make two HBM passes over the activation. The
one-pass form ``E[x^2] - E[x]^2`` reads ``x`` twice *independently*, which
XLA fuses into a single multi-output reduction (one pass). Measured on the
ResNet-50 TPU bench (benchmarks/resnet_profile.py, 2026-08-02): switching
BatchNormalization to this form took the train step from 12.80 to
11.92 ms/step (0.895x -> 0.961x flax).

The price of the one-pass form is catastrophic cancellation when
``|mean| >> std`` in f32 — the subtraction can even go negative, and a
negative variance turns ``rsqrt(var + eps)`` into NaN. The clamp to zero
restores ``jnp.var``'s non-negativity guarantee (gradients are unaffected
wherever the clamp is inactive, i.e. everywhere the statistics are usable).
Short of the clamp, relative accuracy degrades as (mean/std)^2 * 2^-23 —
e.g. mean~1e3, std~1 loses ~12% of the variance. The clamp regime — where
cancellation is total and the returned variance collapses to exactly 0 —
begins where that relative error reaches ~1, i.e. (mean/std)^2 ≳ 2^23, or
|mean|/std ≳ 2^11.5 ≈ 2.9e3 in f32 (in bf16's 8-bit mantissa the same
threshold is |mean|/std ≳ 2^4 = 16, which is why the accumulation below is
forced to >= f32). This is the SAME tradeoff
flax.linen.normalization makes (its ``_compute_stats`` uses the identical
one-pass form), i.e. parity with the ecosystem twin, and normalization-layer
inputs in practice sit near zero mean; callers with pathological offsets
should normalize their data (data/normalizers) first.

Reference analog: the fused mean+variance accumulation of the batchnorm
kernels (SURVEY N3 `declarable ops batchnorm`); here the fusion is XLA's,
the formulation just has to permit it.
"""
from __future__ import annotations

import jax.numpy as jnp


def one_pass_variance(x, mean, axes, keepdims: bool = False):
    """``max(E[x^2] - mean^2, 0)`` given an already-computed ``mean`` over
    the same reduction — the single home of the clamp-against-cancellation
    decision (also used by the emission peephole in autodiff/passes).

    Accumulates in >= f32 regardless of input dtype and returns the
    accumulation dtype: in bf16 the squares cancel totally at modest
    offsets (mean 30/std 0.5 -> variance exactly 0 after the clamp, vs
    0.25 true), and TF itself computes half-precision norm statistics in
    f32. Callers that need the input dtype back cast at their boundary.
    """
    acc = jnp.promote_types(x.dtype, jnp.float32)
    ex2 = jnp.mean(jnp.square(x.astype(acc)), axis=axes, keepdims=keepdims)
    return jnp.maximum(ex2 - jnp.square(mean.astype(acc)), 0)


def one_pass_moments(xf, axes, keepdims: bool = False):
    """Return ``(mean, var)`` over ``axes`` in the >=f32 accumulation
    dtype (see ``one_pass_variance``). ``var`` is clamped to ``>= 0``."""
    acc = jnp.promote_types(xf.dtype, jnp.float32)
    xf = xf.astype(acc)
    mean = jnp.mean(xf, axis=axes, keepdims=keepdims)
    return mean, one_pass_variance(xf, mean, axes, keepdims)
