"""Op-registry tranche 6 — image-sampling / integer-conv / unpool ops.

Added for ONNX importer parity (reference: samediff-import-onnx mapping
registry, SURVEY J8; libnd4j has no GridSample/ConvInteger — these are
net-new TPU-first lowerings):

- ``grid_sample``: ONNX GridSample / torch ``F.grid_sample`` semantics —
  bilinear or nearest sampling of an NCHW input at normalized grid
  coordinates, zeros or border padding, align_corners both ways. Pure
  gather+lerp: vectorized, MXU-free but VPU-friendly, fully jittable.
- ``max_unpool``: ONNX MaxUnpool — scatter pooled values back to their
  argmax flat indices (the dual of ``maxpool_with_argmax``).
- ``conv_integer``: ONNX ConvInteger — int8/uint8 conv with zero-point
  subtraction, exact int32 accumulation (XLA integer conv).
- ``lp_pool2d_nchw``: ONNX LpPool — (sum |x|^p over window)^(1/p); built
  on the average-pool window machinery so padding semantics match.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import exec_op, register


def _unnormalize(coord, size, align_corners):
    # ONNX/torch: align_corners=True maps [-1,1] -> [0, size-1];
    # False maps [-1,1] -> [-0.5, size-0.5]
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


@register("grid_sample")
def grid_sample(x, grid, mode: str = "bilinear",
                padding_mode: str = "zeros", align_corners: bool = False):
    """x: (N, C, H, W); grid: (N, Ho, Wo, 2) with (x, y) in [-1, 1].
    Returns (N, C, Ho, Wo)."""
    x = jnp.asarray(x)
    grid = jnp.asarray(grid)
    n, c, h, w = x.shape
    gx = _unnormalize(grid[..., 0].astype(jnp.float32), w, align_corners)
    gy = _unnormalize(grid[..., 1].astype(jnp.float32), h, align_corners)

    def sample_at(ix, iy):
        """Gather x[n, :, iy, ix] with out-of-bounds handling."""
        if padding_mode == "border":
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            valid = jnp.ones_like(ix, jnp.bool_)
        else:                               # zeros
            valid = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
        flat = x.reshape(n, c, h * w)
        idx = (iyc * w + ixc).reshape(n, -1)            # (N, Ho*Wo)
        g = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        g = g.reshape(n, c, *ix.shape[1:])
        return jnp.where(valid[:, None], g, jnp.zeros_like(g))

    if mode == "nearest":
        # torch rounds half away from0? — it uses round-half-to-even via
        # float rounding; jnp.round (banker's) matches torch here
        out = sample_at(jnp.round(gx).astype(jnp.int32),
                        jnp.round(gy).astype(jnp.int32))
        return out.astype(x.dtype)
    if mode != "bilinear":
        raise NotImplementedError(f"grid_sample mode {mode!r}")
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = (gx - x0)[:, None]
    wy = (gy - y0)[:, None]
    x0i = x0.astype(jnp.int32)
    y0i = y0.astype(jnp.int32)
    v00 = sample_at(x0i, y0i)
    v01 = sample_at(x0i + 1, y0i)
    v10 = sample_at(x0i, y0i + 1)
    v11 = sample_at(x0i + 1, y0i + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return (top * (1 - wy) + bot * wy).astype(x.dtype)


@register("max_unpool")
def max_unpool(pooled, indices, output_shape):
    """ONNX MaxUnpool: scatter ``pooled`` values to flat position
    ``indices`` (per N,C slice — the maxpool_with_argmax convention) in a
    zeros tensor of ``output_shape`` (N, C, H, W)."""
    pooled = jnp.asarray(pooled)
    indices = jnp.asarray(indices).astype(jnp.int32)
    n, c = int(output_shape[0]), int(output_shape[1])
    spatial = int(np.prod(output_shape[2:]))
    flat_idx = indices.reshape(n, c, -1)
    flat_val = pooled.reshape(n, c, -1)
    zeros = jnp.zeros((n, c, spatial), pooled.dtype)
    out = jax_vmap_scatter(zeros, flat_idx, flat_val)
    return out.reshape(tuple(int(s) for s in output_shape))


def jax_vmap_scatter(zeros, idx, val):
    import jax

    def one(z, i, v):
        return z.at[i].set(v)

    return jax.vmap(jax.vmap(one))(zeros, idx, val)


@register("conv_integer")
def conv_integer(x, w, x_zero_point=0, w_zero_point=0,
                 strides=(1, 1), padding=((0, 0), (0, 0)),
                 dilations=(1, 1)):
    """ONNX ConvInteger: (x - x_zp) * (w - w_zp) convolution with exact
    int32 accumulation. x: (N, C, H, W) int8/uint8; w: (M, C, kH, kW)."""
    xi = jnp.asarray(x).astype(jnp.int32) - jnp.asarray(
        x_zero_point).astype(jnp.int32)
    wi = jnp.asarray(w).astype(jnp.int32) - jnp.asarray(
        w_zero_point).astype(jnp.int32)
    return lax.conv_general_dilated(
        xi, wi, tuple(strides), tuple(tuple(p) for p in padding),
        rhs_dilation=tuple(dilations),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


@register("lp_pool2d_nchw")
def lp_pool2d_nchw(x, kernel, strides=None, padding=((0, 0), (0, 0)),
                   p: float = 2.0):
    """ONNX LpPool (NCHW): (sum_window |x|^p)^(1/p). Sum (not average) per
    the ONNX spec; padded positions contribute zero."""
    x = jnp.asarray(x)
    strides = tuple(strides) if strides else tuple(kernel)
    powed = jnp.abs(x.astype(jnp.float32)) ** p
    summed = lax.reduce_window(
        powed, 0.0, lax.add, (1, 1) + tuple(kernel), (1, 1) + strides,
        ((0, 0), (0, 0)) + tuple(tuple(pp) for pp in padding))
    return (summed ** (1.0 / p)).astype(x.dtype)


@register("random_normal_gen")
def random_normal_gen(shape, mean=0.0, scale=1.0, dtype=jnp.float32,
                      seed=None):
    """ONNX RandomNormal(Like) generator — attr-shaped, optionally seeded
    (the key convention of bernoulli_sample)."""
    import jax
    from deeplearning4j_tpu.ndarray import random as _rng
    key = jax.random.key(int(seed)) if seed is not None else _rng.next_key()
    shape = tuple(int(s) for s in shape)
    return mean + scale * jax.random.normal(key, shape, dtype)


@register("random_uniform_gen")
def random_uniform_gen(shape, low=0.0, high=1.0, dtype=jnp.float32,
                       seed=None):
    """ONNX RandomUniform(Like) generator."""
    import jax
    from deeplearning4j_tpu.ndarray import random as _rng
    key = jax.random.key(int(seed)) if seed is not None else _rng.next_key()
    shape = tuple(int(s) for s in shape)
    return jax.random.uniform(key, shape, dtype, low, high)
