"""Pipeline parallelism — GPipe micro-batch schedule over the ``stage`` mesh
axis (SURVEY P5: ABSENT in the reference; net-new TPU capability).

Design (TPU-idiomatic, no per-stage processes): the layer stack is split
into S stages; each device along ``stage`` holds ONE stage's params
(leading-axis sharded pytree). A ``shard_map`` program runs the classic
GPipe schedule: at tick t, stage s processes micro-batch (t − s); between
ticks activations hop one stage to the right via ``lax.ppermute`` over ICI.
The whole schedule — M + S − 1 ticks — is one ``lax.fori_loop`` inside one
jitted program, and it is DIFFERENTIABLE: jax reverse-mode through the
ppermute ring gives the backward pipeline automatically (the hand-built
1F1B machinery of torch-style PP collapses into autodiff).

Bubble fraction is the standard (S−1)/(M+S−1) — callers pick M >> S.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import STAGE_AXIS, axis_size


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage axis
    (shardable over ``stage``)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stage_params(stacked, mesh: Mesh):
    """Place the stacked tree so each stage device holds its own slice."""
    spec = jax.tree.map(
        lambda a: NamedSharding(mesh, P(STAGE_AXIS)), stacked)
    return jax.device_put(stacked, spec)


def gpipe(stage_fn: Callable, mesh: Mesh, num_stages: int = None):
    """Build a pipelined forward: ``fn(stacked_params, x_micro) -> y_micro``.

    ``stage_fn(stage_params, h) -> h`` is the per-stage computation (same
    activation shape in/out — transformer-block-stack shaped, which is what
    pipelining is for). ``x_micro``: (M, micro_batch, ...) micro-batches.
    Returns (M, micro_batch, ...) outputs after all S stages.
    """
    S = num_stages or axis_size(mesh, STAGE_AXIS)

    def local(params_slice, x):          # runs per stage device
        # params_slice: (1, ...) leading stage slice; x: (M, mb, ...) full
        # micro-batch queue, replicated — stage 0 reads it, others ignore
        p = jax.tree.map(lambda a: a[0], params_slice)
        stage_id = lax.axis_index(STAGE_AXIS)
        M = x.shape[0]
        n_ticks = M + S - 1
        mb_shape = x.shape[1:]
        out = jnp.zeros_like(x)

        def tick(t, carry):
            h, out = carry
            # stage 0 ingests micro-batch t (if any); others use the
            # activation handed over from the left neighbour
            feed = x[jnp.clip(t, 0, M - 1)]
            h_in = jnp.where(stage_id == 0, feed, h)
            mb_idx = t - stage_id                 # micro-batch at this stage
            active = (mb_idx >= 0) & (mb_idx < M)
            h_out = stage_fn(p, h_in)
            h_out = jnp.where(active, h_out, h_in)
            # the LAST stage's finished micro-batch lands in the output slot
            out = lax.cond(
                active & (stage_id == S - 1),
                lambda o: o.at[jnp.clip(mb_idx, 0, M - 1)].set(h_out),
                lambda o: o, out)
            # hop right: stage s → s+1 (ring; the wraparound edge is ignored
            # because stage 0 always re-ingests from x)
            h_next = lax.ppermute(h_out, STAGE_AXIS,
                                  [(i, (i + 1) % S) for i in range(S)])
            return h_next, out

        h0 = jnp.zeros(mb_shape, x.dtype)
        _, out = lax.fori_loop(0, n_ticks, tick, (h0, out))
        # only the last stage wrote outputs; psum broadcasts them to all
        return lax.psum(out, STAGE_AXIS)

    def run(stacked_params, x_micro):
        specs = jax.tree.map(lambda _: P(STAGE_AXIS), stacked_params)
        f = shard_map(local, mesh=mesh, in_specs=(specs, P()),
                      out_specs=P(), check_vma=False)
        return f(stacked_params, x_micro)

    return run
