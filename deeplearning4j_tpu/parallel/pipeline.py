"""Pipeline parallelism — GPipe micro-batch schedule over the ``stage`` mesh
axis (SURVEY P5: ABSENT in the reference; net-new TPU capability).

Design (TPU-idiomatic, no per-stage processes): the layer stack is split
into S stages; each device along ``stage`` holds ONE stage's params
(leading-axis sharded pytree). A ``shard_map`` program runs the classic
GPipe schedule: at tick t, stage s processes micro-batch (t − s); between
ticks activations hop one stage to the right via ``lax.ppermute`` over ICI.
The whole schedule — one tick per micro-batch plus the (S−1)-tick bubble —
is nested ``lax.fori_loop``s inside one jitted program, and it is
DIFFERENTIABLE: jax reverse-mode through the ppermute ring gives the
backward pipeline (and with it micro-batch gradient accumulation) for free —
the hand-built 1F1B machinery of torch-style PP collapses into autodiff.

Memory is O(M/S) micro-batches per device (M = micro-batch count), not the
round-2 O(M)-replicated queue:

- **input**: the queue is block-sharded over ``stage`` — stage s holds
  micro-batches [s·Q, (s+1)·Q) where Q = M/S. Stage 0 consumes its resident
  slab one micro-batch per tick; every Q ticks the slabs rotate one stage
  down (s → s−1), so the block stage 0 needs next is always arriving.
  Amortized rotation traffic: one micro-batch per tick — the same order as
  the activation hop itself.
- **output**: finished micro-batches ride a systolic channel DOWN the ring
  (stage S−1 → 0, opposite to activations): every tick each stage forwards
  its channel slot and the last stage inserts the micro-batch it just
  finished; each stage copies out the passing micro-batches it owns
  (block-layout home: stage s keeps finished [s·Q, (s+1)·Q)). The last
  arrival lands exactly on the final tick — no extra ticks needed.

Bubble fraction stays the standard (S−1)/(M+S−1) — callers pick M >> S.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
try:
    from jax import shard_map
except ImportError:         # pre-0.6 jax: experimental home, same signature
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import inspect as _inspect

# "skip the replication/varying-type check" kwarg was renamed across jax
# versions (check_rep -> check_vma); resolve the spelling once
_NO_CHECK = ({"check_vma": False}
             if "check_vma" in _inspect.signature(shard_map).parameters
             else {"check_rep": False})

from deeplearning4j_tpu.parallel.mesh import STAGE_AXIS, axis_size


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with a leading stage axis
    (shardable over ``stage``)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stage_params(stacked, mesh: Mesh):
    """Place the stacked tree so each stage device holds its own slice."""
    spec = jax.tree.map(
        lambda a: NamedSharding(mesh, P(STAGE_AXIS)), stacked)
    return jax.device_put(stacked, spec)


def gpipe(stage_fn: Callable, mesh: Mesh, num_stages: Optional[int] = None,
          batch_axis: Optional[str] = None):
    """Build a pipelined forward: ``fn(stacked_params, x_micro) -> y_micro``.

    ``stage_fn(stage_params, h) -> h`` is the per-stage computation (same
    activation shape in/out — transformer-block-stack shaped, which is what
    pipelining is for). ``x_micro``: (M, micro_batch, ...) micro-batches.
    Returns (M, micro_batch, ...) outputs after all S stages.

    ``batch_axis``: optionally shard the micro-batch dim of activations over
    a second mesh axis (PP × DP composition); params stay replicated over it.

    ``stage_fn`` may also accept a third argument — the (traced) micro-batch
    index — e.g. to derive per-micro-batch dropout keys.
    """
    S = num_stages or axis_size(mesh, STAGE_AXIS)
    import inspect
    takes_mb = len(inspect.signature(stage_fn).parameters) >= 3

    def local(params_slice, x_slab):     # runs per stage device
        # params_slice: (1, ...) leading stage slice; x_slab: (Q, mb, ...) —
        # this stage's block of the micro-batch queue (NOT the full queue)
        p = jax.tree.map(lambda a: a[0], params_slice)
        stage_id = lax.axis_index(STAGE_AXIS)
        Q = x_slab.shape[0]
        M = Q * S                        # padded micro-batch count
        mb_shape = x_slab.shape[1:]
        n_phases = S + int(np.ceil((S - 1) / Q))   # covers M + S - 1 ticks

        down = [(i, (i - 1) % S) for i in range(S)]
        up = [(i, (i + 1) % S) for i in range(S)]

        def tick(t, carry):
            slab, h, chan, out = carry
            # stage 0 ingests micro-batch t from its resident slab; others
            # use the activation handed over from the left neighbour
            feed = lax.dynamic_index_in_dim(slab, jnp.mod(t, Q), 0,
                                            keepdims=False)
            h_in = jnp.where(stage_id == 0, feed, h)
            mb_idx = t - stage_id                 # micro-batch at this stage
            active = (mb_idx >= 0) & (mb_idx < M)
            h_out = (stage_fn(p, h_in, jnp.clip(mb_idx, 0)) if takes_mb
                     else stage_fn(p, h_in))
            h_out = jnp.where(active, h_out, h_in)
            # ---- output channel: shift down, last stage inserts its result
            chan = lax.ppermute(chan, STAGE_AXIS, down)
            chan = jnp.where(stage_id == S - 1, h_out, chan)
            # the micro-batch in this stage's channel slot right now
            m = t - 2 * (S - 1) + stage_id
            own = (m >= 0) & (m < M) & (m // Q == stage_id)
            idx = jnp.mod(jnp.clip(m, 0), Q)
            out = jnp.where(own, out.at[idx].set(chan), out)
            # ---- activation hop right (the pipeline edge itself)
            h = lax.ppermute(h_out, STAGE_AXIS, up)
            return slab, h, chan, out

        def phase(ph, carry):
            def inner(i, c):
                return tick(ph * Q + i, c)
            slab, h, chan, out = lax.fori_loop(0, Q, inner, carry)
            # stage 0 finished block ph; bring the next block down one stage
            slab = lax.ppermute(slab, STAGE_AXIS, down)
            return slab, h, chan, out

        h0 = jnp.zeros(mb_shape, x_slab.dtype)
        chan0 = jnp.zeros(mb_shape, x_slab.dtype)
        out0 = jnp.zeros_like(x_slab)
        _, _, _, out = lax.fori_loop(0, n_phases, phase,
                                     (x_slab, h0, chan0, out0))
        return out

    def run(stacked_params, x_micro):
        M = x_micro.shape[0]
        Q = -(-M // S)                   # ceil: pad the queue to S·Q
        pad = S * Q - M
        if pad:
            x_micro = jnp.concatenate(
                [x_micro, jnp.zeros((pad,) + x_micro.shape[1:],
                                    x_micro.dtype)], axis=0)
        pspecs = jax.tree.map(lambda _: P(STAGE_AXIS), stacked_params)
        act_spec = P(*([STAGE_AXIS, batch_axis]
                       + [None] * (x_micro.ndim - 2))) \
            if batch_axis else P(STAGE_AXIS)
        f = shard_map(local, mesh=mesh, in_specs=(pspecs, act_spec),
                      out_specs=act_spec, **_NO_CHECK)
        out = f(stacked_params, x_micro)
        return out[:M] if pad else out

    return run


def pipeline_trunk_1f1b(stage_fn: Callable, mesh: Mesh,
                        num_stages: Optional[int] = None,
                        batch_axis: Optional[str] = None):
    """A differentiable pipelined trunk with a **1F1B backward**: forward
    is the GPipe schedule (`gpipe`), but reverse-mode runs the 1F1B
    wavefront (explicit per-tick vjp, cotangents ppermuted down, ring-
    buffer remat) instead of autodiff-through-the-schedule — so the
    backward's live activations are bounded by the schedule depth, not
    the micro-batch count, while the result composes with surrounding
    autodiff (embedding below, head/loss above) like any jax function.

    ``stage_fn(stage_params, h[, mb_idx])`` as in ``gpipe``. Returns
    ``fn(stacked_params, x_micro) -> y_micro`` usable under jax.grad."""
    S = num_stages or axis_size(mesh, STAGE_AXIS)
    import inspect
    takes_mb = len(inspect.signature(stage_fn).parameters) >= 3
    fwd_run = gpipe(stage_fn, mesh, S, batch_axis=batch_axis)

    def bwd_local(params_slice, x_all, dy_all):
        p = jax.tree.map(lambda a: a[0], params_slice)
        stage_id = lax.axis_index(STAGE_AXIS)
        M = x_all.shape[0]
        mb_shape = x_all.shape[1:]
        R = 2 * S - 1
        T = M + 2 * (S - 1)
        down = [(i, (i - 1) % S) for i in range(S)]
        up = [(i, (i + 1) % S) for i in range(S)]

        def call(pp, hh, m):
            return stage_fn(pp, hh, jnp.clip(m, 0)) if takes_mb \
                else stage_fn(pp, hh)

        def tick(t, carry):
            h_chan, g_chan, buf, dp, dx = carry
            mf = t - stage_id
            f_active = (mf >= 0) & (mf < M)
            feed = lax.dynamic_index_in_dim(x_all, jnp.clip(mf, 0, M - 1),
                                            0, keepdims=False)
            h_in = jnp.where(stage_id == 0, feed, h_chan)
            h_out = jnp.where(f_active, call(p, h_in, mf), h_in)
            buf = jnp.where(
                f_active,
                lax.dynamic_update_index_in_dim(
                    buf, h_in, jnp.mod(jnp.clip(mf, 0), R), 0),
                buf)
            mb_ = t - 2 * (S - 1) + stage_id
            b_active = (mb_ >= 0) & (mb_ < M)
            h_saved = lax.dynamic_index_in_dim(
                buf, jnp.mod(jnp.clip(mb_, 0), R), 0, keepdims=False)
            _, vjp = jax.vjp(lambda pp, hh: call(pp, hh, mb_), p, h_saved)
            dy_m = lax.dynamic_index_in_dim(
                dy_all, jnp.clip(mb_, 0, M - 1), 0, keepdims=False)
            g_seed = jnp.where(stage_id == S - 1, dy_m, g_chan)
            dp_m, dh_m = vjp(g_seed.astype(h_saved.dtype))
            dp = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_active, g, 0.0), dp, dp_m)
            # stage 0's input cotangent IS dL/dx for this micro-batch
            dx = jnp.where(
                b_active & (stage_id == 0),
                lax.dynamic_update_index_in_dim(
                    dx, dh_m, jnp.clip(mb_, 0, M - 1), 0),
                dx)
            g_chan = lax.ppermute(
                jnp.where(b_active, dh_m, jnp.zeros_like(dh_m)),
                STAGE_AXIS, down)
            h_chan = lax.ppermute(h_out, STAGE_AXIS, up)
            return h_chan, g_chan, buf, dp, dx

        z = jnp.zeros(mb_shape, x_all.dtype)
        dp0 = jax.tree.map(jnp.zeros_like, p)
        buf0 = jnp.zeros((R,) + mb_shape, x_all.dtype)
        dx0 = jnp.zeros_like(x_all)
        _, _, _, dp, dx = lax.fori_loop(0, T, tick, (z, z, buf0, dp0, dx0))
        # dx is populated only on stage 0; psum makes it uniform so the
        # replicated out-spec is valid
        dx = lax.psum(dx, STAGE_AXIS)
        if batch_axis is not None:
            # params replicate over the data axis, so each data shard's
            # dp is a PARTIAL sum over its mb slice — reduce explicitly
            # (autodiff-of-shard_map would have inserted this psum; a
            # custom_vjp must do it by hand)
            dp = jax.tree.map(lambda g: lax.psum(g, batch_axis), dp)
        return jax.tree.map(lambda a: a[None], dp), dx

    @jax.custom_vjp
    def trunk(stacked_params, x_micro):
        return fwd_run(stacked_params, x_micro)

    def trunk_fwd(stacked_params, x_micro):
        return fwd_run(stacked_params, x_micro), (stacked_params, x_micro)

    def trunk_bwd(res, dy):
        stacked_params, x_micro = res
        pspecs = jax.tree.map(lambda _: P(STAGE_AXIS), stacked_params)
        # activations replicate over stage; the mb dim may shard over a
        # data axis (PP x DP) — the schedule is elementwise across mb
        aspec = P(*([None, batch_axis] + [None] * (x_micro.ndim - 2))) \
            if batch_axis else P()
        f = shard_map(bwd_local, mesh=mesh,
                      in_specs=(pspecs, aspec, aspec),
                      out_specs=(pspecs, aspec), **_NO_CHECK)
        return f(stacked_params, x_micro, dy)

    trunk.defvjp(trunk_fwd, trunk_bwd)
    return trunk


def one_f_one_b(stage_fn: Callable, loss_fn: Callable, mesh: Mesh,
                num_stages: Optional[int] = None):
    """1F1B pipeline TRAINING step (SURVEY P5; VERDICT r4 #9):
    ``run(stacked_params, x_micro, tgt_micro) -> (loss, grads)`` with the
    backward of each micro-batch starting the moment its forward leaves
    the last stage — per-stage live activations bounded by the schedule
    depth, not the micro-batch count.

    Implemented as ``value_and_grad`` over :func:`pipeline_trunk_1f1b`
    (ONE copy of the 1F1B tick machinery lives there): the trunk's
    custom_vjp routes reverse-mode through the explicit 1F1B wavefront,
    and the per-micro-batch ``loss_fn(h, tgt) -> scalar`` (summed over
    micro-batches) differentiates on top like any jax function."""
    trunk = pipeline_trunk_1f1b(stage_fn, mesh, num_stages)

    def run(stacked_params, x_micro, tgt_micro):
        def total_loss(sp):
            y = trunk(sp, x_micro)
            return jnp.sum(jax.vmap(loss_fn)(y, tgt_micro))
        return jax.value_and_grad(total_loss)(stacked_params)

    return run
