"""Distributed training over TPU device meshes (SURVEY §2.4/§5.8).

The reference's transport stack (Spark control plane + Aeron UDP gradient
mesh + threshold codec) is replaced wholesale by XLA collectives over
ICI/DCN emitted from sharding annotations — see mesh.py for the axis map,
trainer.py for the engine, master.py for the reference-parity facades, and
ring.py for sequence parallelism (net-new vs reference).
"""
from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS, STAGE_AXIS, MeshSpec)
from deeplearning4j_tpu.parallel.trainer import (  # noqa: F401
    ParallelWrapper, ShardedTrainer)
from deeplearning4j_tpu.parallel.inference import (  # noqa: F401
    InferenceMode, ParallelInference)
from deeplearning4j_tpu.parallel.master import (  # noqa: F401
    DistributedConfig, ParameterAveragingTrainingMaster, SharedTrainingMaster,
    SparkComputationGraph, SparkDl4jMultiLayer, TrainingMaster)
from deeplearning4j_tpu.parallel.ring import ring_attention  # noqa: F401
from deeplearning4j_tpu.parallel.compression import (  # noqa: F401
    AdaptiveThresholdAlgorithm, FixedThresholdAlgorithm, ThresholdAlgorithm)
# NOTE: parallel.generation is intentionally NOT imported here — the
# flight recorder and test teardown check sys.modules to decide whether
# the generation stack is in play, and every non-generating process
# would otherwise pay its import at startup. Import it explicitly:
# `from deeplearning4j_tpu.parallel.generation import GenerationPipeline`.
