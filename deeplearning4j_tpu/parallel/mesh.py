"""Device-mesh construction — the communication-topology layer.

Replaces the reference's transport stack (Aeron UDP mesh in
``org.nd4j.parameterserver.distributed.v2.transport.impl.AeronUdpTransport`` +
``util.MeshOrganizer`` spanning tree, SURVEY J13/P9): on TPU the "mesh" is
the physical ICI torus exposed through ``jax.sharding.Mesh``, and collectives
are emitted by the compiler — there is no user-level transport to organize.

Axis conventions (used by sharding rules framework-wide):
- ``data``  — data parallelism (batch sharding, gradient allreduce)
- ``model`` — tensor parallelism (intra-layer weight sharding)
- ``seq``   — sequence/context parallelism (ring attention)
- ``stage`` — pipeline parallelism
- ``expert``— expert parallelism (MoE)
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"
EXPERT_AXIS = "expert"


@dataclasses.dataclass
class MeshSpec:
    """Declarative mesh: ordered {axis_name: size}; one size may be -1
    ("take the rest"), mirroring the reference's implicit worker count
    (``SharedTrainingMaster.Builder#workersPerNode``)."""
    axes: Dict[str, int]

    @staticmethod
    def data_parallel(n: int = -1) -> "MeshSpec":
        return MeshSpec({DATA_AXIS: n})

    @staticmethod
    def dp_tp(data: int = -1, model: int = 1) -> "MeshSpec":
        return MeshSpec({DATA_AXIS: data, MODEL_AXIS: model})

    @staticmethod
    def dp_tp_sp(data: int = -1, model: int = 1, seq: int = 1) -> "MeshSpec":
        return MeshSpec({DATA_AXIS: data, MODEL_AXIS: model, SEQ_AXIS: seq})

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dict(self.axes)
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        wild = [k for k, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        if int(np.prod(list(sizes.values()))) != n_devices:
            raise ValueError(f"Mesh {sizes} != {n_devices} devices")
        return sizes

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        arr = np.asarray(devices).reshape(*sizes.values())
        return Mesh(arr, tuple(sizes.keys()))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dim sharded over every data-like axis present."""
    axes = [a for a in (DATA_AXIS,) if a in mesh.axis_names]
    return NamedSharding(mesh, P(tuple(axes) if axes else None))


def axis_size(mesh: Mesh, name: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)
