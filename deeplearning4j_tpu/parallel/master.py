"""TrainingMaster facades — reference-parity distributed entry points.

Reference: ``org.deeplearning4j.spark.api.TrainingMaster`` with impls
``ParameterAveragingTrainingMaster`` (SURVEY P2) and
``SharedTrainingMaster`` (P3, the flagship: threshold-encoded async gradient
sharing over an Aeron UDP mesh) driven through ``SparkDl4jMultiLayer`` /
``SparkComputationGraph``.

TPU-native redesign (SURVEY §5.8 north star): the TrainingMaster API shape
survives as a thin facade that (a) builds the device mesh, (b) shards the
input pipeline over the ``data`` axis, and (c) runs the whole step as one
GSPMD program whose gradient allreduce rides ICI within a slice and DCN
across slices. Spark, Aeron, and the UDP transport are deleted — there is
no transport code to configure. The threshold codec + accumulator SURVIVE
as the opt-in compressed gradient exchange (parallel/compression.py):
``SharedTrainingMaster(threshold_algorithm=...)`` routes the trainer
through error-feedback threshold collectives instead of the dense
allreduce. Multi-host bootstrap is ``jax.distributed.initialize`` (the
``VoidConfiguration`` analog is ``DistributedConfig`` below).

Semantics divergence (documented, BASELINE.md): updates are synchronous and
dense; ``ParameterAveragingTrainingMaster(averaging_frequency=N)`` degrades
to sync-every-step, which strictly dominates it in convergence per step.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

import jax

from deeplearning4j_tpu.parallel.mesh import MeshSpec
from deeplearning4j_tpu.parallel.trainer import ShardedTrainer

# one probe per process: the answer cannot change while jaxlib doesn't
_MULTIPROC_PROBE: Optional[Tuple[bool, str]] = None

_PROBE_SCRIPT = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address="127.0.0.1:" + sys.argv[2],
                           num_processes=2, process_id=int(sys.argv[1]))
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.asarray(jax.devices()), ("data",))
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), np.ones((1,), np.float32))
s = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)
print("PSUM_OK", float(s), flush=True)
"""


def multiprocess_cpu_collectives_supported(
        timeout_s: float = 120.0) -> Tuple[bool, str]:
    """Runtime capability probe: can THIS jax/jaxlib run a cross-process
    collective on the CPU backend? Some builds (this container's among
    them) bootstrap ``jax.distributed`` fine and then fail the first
    multi-process computation with ``Multiprocess computations aren't
    implemented on the CPU backend`` — so the probe must run a REAL
    cross-process reduction, not just the handshake.

    Two throwaway subprocesses form a 2-process loopback mesh and psum
    one scalar. Cached per process (one ~5 s probe, then free); the
    ``DL4J_TPU_MULTIHOST_PROBE`` knob overrides it (``1`` = assume
    supported, ``0`` = assume not) for CI that already knows its
    platform. Returns ``(supported, reason)``.
    """
    global _MULTIPROC_PROBE
    override = os.environ.get("DL4J_TPU_MULTIHOST_PROBE", "")
    if override == "1":
        return True, "forced by DL4J_TPU_MULTIHOST_PROBE=1"
    if override == "0":
        return False, "forced by DL4J_TPU_MULTIHOST_PROBE=0"
    if _MULTIPROC_PROBE is not None:
        return _MULTIPROC_PROBE
    import socket
    import subprocess
    import sys
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)       # the probe pins its own platform
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SCRIPT, str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    ok = True
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out = (out or "") + "\n<probe timeout>"
                ok = False
            outs.append(out or "")
            ok = ok and p.returncode == 0 and "PSUM_OK" in outs[-1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    if ok:
        _MULTIPROC_PROBE = (True, "2-process loopback psum succeeded")
    else:
        # surface the decisive line (the XlaRuntimeError message) so a
        # skip names WHY, not just "probe failed"
        reason = "2-process loopback psum failed"
        for out in outs:
            for line in reversed(out.strip().splitlines()):
                if "Error" in line or "error" in line or "<probe" in line:
                    reason = line.strip()[:200]
                    break
            else:
                continue
            break
        _MULTIPROC_PROBE = (False, reason)
    return _MULTIPROC_PROBE


@dataclasses.dataclass
class DistributedConfig:
    """Multi-host bootstrap knobs (ref: VoidConfiguration — ports/mask/
    controller address → coordinator address/process ids)."""
    coordinator_address: Optional[str] = None   # "host:port" of process 0
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    def initialize(self):
        """ref: the Spark/Aeron bootstrap; here jax.distributed (PJRT DCN)."""
        if self.coordinator_address is not None:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id)


class TrainingMaster:
    """Base facade: owns MeshSpec + batch policy.

    ``tensor_parallel`` may be True (model axis of 2) or an int (the model
    axis size); the remaining devices form the ``data`` axis.
    """

    def __init__(self, batch_size_per_worker: int = 32, workers: Optional[int] = None,
                 tensor_parallel=False):
        self.batch_size_per_worker = batch_size_per_worker
        self.workers = workers
        self.tensor_parallel = tensor_parallel

    def mesh_spec(self) -> MeshSpec:
        if self.tensor_parallel:
            model = (int(self.tensor_parallel)
                     if not isinstance(self.tensor_parallel, bool) else 2)
            return MeshSpec.dp_tp(data=self.workers or -1, model=model)
        return MeshSpec.data_parallel(self.workers or -1)

    def make_trainer(self, net) -> ShardedTrainer:
        return ShardedTrainer(net, self.mesh_spec(),
                              tensor_parallel=bool(self.tensor_parallel))


class SharedTrainingMaster(TrainingMaster):
    """ref: org.deeplearning4j.spark.parameterserver.training.SharedTrainingMaster.

    ``threshold_algorithm`` is HONORED: passing one (a
    ``parallel.compression.ThresholdAlgorithm`` — Fixed/Adaptive, or a
    spec string) routes the built trainer through the compressed
    error-feedback gradient exchange (the EncodedGradientsAccumulator
    analog; see parallel/compression.py). With no algorithm the exchange
    stays the dense GSPMD allreduce, and the ``DL4J_TPU_GRAD_COMPRESS``
    env knob still applies (``0`` = kill switch either way)."""

    def __init__(self, batch_size_per_worker: int = 32, workers: Optional[int] = None,
                 threshold: Optional[float] = None, threshold_algorithm=None,
                 workers_per_node: Optional[int] = None, **_ignored):
        super().__init__(batch_size_per_worker, workers or workers_per_node)
        # an EXPLICIT threshold without an algorithm implies fixed:t (the
        # reference's threshold always configured the codec) — both
        # spellings, constructor and Builder, behave identically; leaving
        # both unset keeps the dense exchange
        if threshold is not None and threshold_algorithm is None:
            threshold_algorithm = "fixed:%g" % float(threshold)
        self.threshold = 1e-3 if threshold is None else threshold
        self.threshold_algorithm = threshold_algorithm

    def make_trainer(self, net) -> ShardedTrainer:
        return ShardedTrainer(net, self.mesh_spec(),
                              tensor_parallel=bool(self.tensor_parallel),
                              grad_compression=self.threshold_algorithm)

    class Builder:
        def __init__(self, *args):
            self._kw = {}

        def batch_size_per_worker(self, n):
            self._kw["batch_size_per_worker"] = n
            return self

        batchSizePerWorker = batch_size_per_worker

        def workers_per_node(self, n):
            self._kw["workers"] = n
            return self

        workersPerNode = workers_per_node

        def threshold_algorithm(self, a):
            self._kw["threshold_algorithm"] = a
            return self

        thresholdAlgorithm = threshold_algorithm

        def threshold(self, t):
            """ref: Builder#threshold — shorthand for a fixed algorithm
            at ``t`` (the constructor derives ``fixed:t`` when no explicit
            threshold_algorithm is set)."""
            self._kw["threshold"] = t
            return self

        def build(self):
            return SharedTrainingMaster(**self._kw)


class ParameterAveragingTrainingMaster(TrainingMaster):
    """ref: org.deeplearning4j.spark.impl.paramavg.ParameterAveragingTrainingMaster.
    Sync dense allreduce every step subsumes periodic averaging."""

    def __init__(self, batch_size_per_worker: int = 32, workers: Optional[int] = None,
                 averaging_frequency: int = 1, **_ignored):
        super().__init__(batch_size_per_worker, workers)
        self.averaging_frequency = averaging_frequency

    class Builder:
        def __init__(self, *args):
            self._kw = {}

        def batch_size_per_worker(self, n):
            self._kw["batch_size_per_worker"] = n
            return self

        def averaging_frequency(self, n):
            self._kw["averaging_frequency"] = n
            return self

        averagingFrequency = averaging_frequency

        def build(self):
            return ParameterAveragingTrainingMaster(**self._kw)


def _rebatch(data, target: int):
    """Re-chunk a stream of DataSets to ``target`` examples per step
    (the batch_size_per_worker × data-axis-size policy). Tuple-valued
    (MultiDataSet) batches pass through unchanged."""
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet

    buf_x, buf_y, n = [], [], 0
    has_labels = None
    for ds in data:
        x, y = ds.features, ds.labels
        if (isinstance(x, (tuple, list)) or ds.features_mask is not None
                or getattr(ds, "labels_mask", None) is not None):
            yield ds  # masks/multi-input: don't re-split, preserve alignment
            continue
        if has_labels is None:
            has_labels = y is not None
        elif has_labels != (y is not None):
            raise ValueError(
                "mixed labeled/unlabeled DataSets in one stream cannot be "
                "re-batched without misaligning features and labels")
        buf_x.append(np.asarray(x))
        if has_labels:
            buf_y.append(np.asarray(y))
        n += buf_x[-1].shape[0]
        while n >= target:
            X = np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0]
            Y = (np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0]) \
                if has_labels else None
            yield DataSet(X[:target], Y[:target] if has_labels else None)
            buf_x = [X[target:]] if X.shape[0] > target else []
            buf_y = ([Y[target:]] if Y.shape[0] > target else []) if has_labels else []
            n -= target
    if n:
        yield DataSet(np.concatenate(buf_x) if len(buf_x) > 1 else buf_x[0],
                      (np.concatenate(buf_y) if len(buf_y) > 1 else buf_y[0])
                      if has_labels else None)


class SparkDl4jMultiLayer:
    """ref: org.deeplearning4j.spark.impl.multilayer.SparkDl4jMultiLayer.
    The SparkContext slot is accepted for parity and unused (no Spark in the
    TPU path; data distribution is the input pipeline's job)."""

    _net_cls = None  # set per subclass

    def _wrap_conf(self, net_or_conf):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork(net_or_conf)

    def __init__(self, sc, net_or_conf, training_master: TrainingMaster):
        if not hasattr(net_or_conf, "fit"):
            net_or_conf = self._wrap_conf(net_or_conf)
        self.network = net_or_conf
        self.training_master = training_master
        self._trainer = training_master.make_trainer(self.network)

    def fit(self, data, epochs: int = 1):
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, axis_size
        tm = self.training_master
        if hasattr(data, "__iter__") and not hasattr(data, "shape"):
            target = tm.batch_size_per_worker * axis_size(self._trainer.mesh,
                                                          DATA_AXIS)
            for _ in range(epochs):
                if hasattr(data, "reset"):
                    data.reset()
                self._trainer.fit(list(_rebatch(data, target)), epochs=1)
        else:
            self._trainer.fit(data, epochs=epochs)
        return self.network

    def get_network(self):
        return self.network

    getNetwork = get_network


class SparkComputationGraph(SparkDl4jMultiLayer):
    """ref: org.deeplearning4j.spark.impl.graph.SparkComputationGraph."""

    def _wrap_conf(self, net_or_conf):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph(net_or_conf)
