"""GenerationPipeline: continuous batching for autoregressive decode.

The serving half of the generative decode path (the model half is
``models/generation.py``). ``ParallelInference``'s batcher coalesces
*one-shot* requests into padded windows; generation is different — a
request occupies device batch space for its whole multi-step lifetime,
and windowed batching makes every member wait on the window's LONGEST
member before any slot frees. Continuous batching fixes exactly that:

- the decode batch is a fixed set of ``slots`` (one compiled
  ``decode_step`` executable over all of them, occupied or not);
- a finished/shed request frees its slot **at the step boundary**, and a
  queued request joins in the freed slot immediately — its prefill runs
  and its k/v land in that slot's cache pages
  (``DecodeEngine.insert_slot``) while every other slot keeps decoding
  on the next step;
- steady-state decode triggers **zero** new XLA traces (fixed shapes
  throughout; pinned via ``compile_watch`` counters in tests).

The PR-5 policies apply unchanged: per-request deadlines (shed at
admission, at the step boundary, and by the caller's walk-away),
bounded-queue shedding (``reject_newest``/``reject_oldest``), a circuit
breaker on the decode device path, transient-fault retries under a
budget, and exactly-once resolution through the shared
``_Request.claim()``. Chaos point ``generation.step`` fires once per
step boundary. Trace phases per request: ``slot_wait`` (enqueue → slot
granted), ``prefill``, and a batch-level ``decode_step`` span per step.

Metrics (``dl4j_decode_*``): generated tokens, slot occupancy,
prefill/decode latency split, cache bytes, sheds, queue depth — on
``/metrics``, with decode/prefill MFU entries on ``/debug/perf`` via the
cost model, and in flight-recorder bundles (``generation.json``).

Multi-tenant QoS (kill switch ``DL4J_TPU_QOS=0``, see
``resilience/qos.py``): the slot-wait queue becomes a per-tenant DWRR
``FairQueue`` (cost = one slot per request), full-queue shedding evicts
the most over-share tenant's newest request, a higher-priority tenant
may PREEMPT a lower-tier slot at a step boundary (the victim resolves
with the typed ``PreemptedError``), and each request's tenant is charged
its emitted tokens plus prefill + per-slot decode-step FLOPs shares.
"""
from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.models.generation import (DECODE_FN, PREFILL_FN,
                                                  DecodeEngine)
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.observability.tracing import (current_context,
                                                      now_us, record_span)
from deeplearning4j_tpu.parallel.inference import _Request
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import qos as _qos
from deeplearning4j_tpu.resilience.policy import (TYPED_OUTCOMES,
                                                  CircuitBreaker,
                                                  CircuitOpenError, Deadline,
                                                  DeadlineExceeded,
                                                  RetryPolicy, ShedError,
                                                  ShutdownError,
                                                  default_deadline_ms)

_TYPED_OUTCOMES = TYPED_OUTCOMES


class StreamCancelled(ShedError):
    """The streaming consumer walked away (its ``on_token`` callback
    returned ``False`` or raised): the request stops decoding and its
    slot frees at the next step boundary. A typed lifecycle outcome
    (``ShedError`` subclass), never an error-rate event — a client
    closing its SSE connection is load behavior, not a model failure."""


class _GenMetrics:
    """Label-bound decode instruments (shared across instances, same
    rationale as ``_ServingMetrics``)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        self.tokens = reg.counter(
            "dl4j_decode_tokens_total",
            "tokens emitted by the continuous-batching decode loop "
            "(rate = serving tokens/s)")
        self.steps = reg.counter(
            "dl4j_decode_steps_total",
            "decode step boundaries executed (each runs every occupied "
            "slot one token forward)")
        self.requests = reg.counter(
            "dl4j_decode_requests_total",
            "generation requests resolved (success, typed shed, or error)")
        self.errors = reg.counter(
            "dl4j_decode_errors_total",
            "generation requests that raised a non-typed error")
        shed = reg.counter(
            "dl4j_decode_shed_total",
            "generation requests shed by admission control or deadlines",
            label_names=("reason",))
        self.shed = {r: shed.labels(reason=r)
                     for r in ("queue_full", "deadline", "circuit_open",
                               "client_gone", "preempted")}
        self.occupancy = reg.histogram(
            "dl4j_decode_slot_occupancy_ratio",
            "occupied slots / total slots per decode step (1.0 = the "
            "device batch is full — continuous batching's win condition)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self.prefill_latency = reg.histogram(
            "dl4j_decode_prefill_seconds",
            "prompt prefill wall time (trunk forward + cache insert), "
            "per joining request")
        self.step_latency = reg.histogram(
            "dl4j_decode_step_seconds",
            "one decode step boundary's wall time (single-query "
            "attention over every occupied slot + sampling)")
        self.latency = reg.histogram(
            "dl4j_decode_latency_seconds",
            "end-to-end GenerationPipeline.generate latency (queue wait "
            "+ prefill + all decode steps)")
        self.cache_bytes = reg.gauge(
            "dl4j_decode_cache_bytes",
            "preallocated KV-cache footprint of live pipelines "
            "(slots x max_len x layers x heads)")
        self.slots_in_use = reg.gauge(
            "dl4j_decode_slots_in_use",
            "slots occupied by in-flight generations (sampled per step "
            "boundary)")
        self.queue_depth = reg.gauge(
            "dl4j_decode_queue_depth",
            "generation requests waiting for a free slot")

    @classmethod
    def get(cls) -> "_GenMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_gen_metrics():
    _GenMetrics._instance = None


class _GenRequest(_Request):
    """One generation request riding the shared exactly-once machinery
    (``claim()``): ``x`` is the 1-D int32 prompt, ``out`` accumulates
    emitted tokens while the request owns a slot. ``on_token`` (when
    set) streams each token out at the step boundary that produced it."""

    __slots__ = ("max_new_tokens", "eos_id", "out", "t_slot_us",
                 "on_token", "cost_flops")

    def __init__(self, x, max_new_tokens: int, eos_id: Optional[int],
                 on_token=None):
        super().__init__(x)
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        self.out: List[int] = []
        self.t_slot_us = 0.0
        self.on_token = on_token
        # accounted device work attributed to this request (prefill +
        # per-slot decode-step shares) — charged to its tenant at
        # resolution under the QoS posture
        self.cost_flops = 0.0


class GenerationPipeline:
    """Slot-based continuous batching over one :class:`DecodeEngine`.

    Owns a decode-loop thread; call :meth:`shutdown` (or use as a
    context manager) when done. :meth:`shutdown_all` stops every live
    instance (test-harness teardown, like ``ParallelInference``)."""

    _live: "weakref.WeakSet[GenerationPipeline]" = weakref.WeakSet()

    def __init__(self, engine: DecodeEngine, slots: int = 4,
                 queue_limit: int = 64,
                 max_new_tokens: int = 32, eos_id: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.engine = engine
        self.slots = int(slots)
        if self.slots < 1:
            # a zero-slot pipeline would warm, go live, and then park
            # every request forever — refuse at construction
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.default_max_new_tokens = int(max_new_tokens)
        self.default_eos_id = eos_id
        self._resilience = _faults.resilience_enabled()
        if shed_policy is not None and shed_policy not in (
                "reject_newest", "reject_oldest"):
            raise ValueError("shed_policy must be 'reject_newest' or "
                             f"'reject_oldest', got {shed_policy!r}")
        if max_queue_depth is not None and self._resilience:
            queue_limit = max(1, int(max_queue_depth))
            shed_policy = shed_policy or "reject_newest"
        self._shed_policy = shed_policy if self._resilience else None
        self.default_deadline_ms = (deadline_ms if deadline_ms is not None
                                    else default_deadline_ms())
        self._breaker = None
        if self._resilience:
            self._breaker = breaker if breaker is not None else \
                CircuitBreaker("generation.step")
            self._retry = RetryPolicy(max_retries=2,
                                      base_delay_seconds=0.01)
        # QoS posture: per-tenant DWRR queue (cost = 1 slot per
        # request), same kill-switch discipline as ParallelInference
        self._qos = self._resilience and _qos.qos_enabled()
        if self._qos:
            self._queue = _qos.FairQueue(queue_limit,
                                         _qos.global_tenants())
        else:
            self._queue: "queue.Queue[_GenRequest]" = queue.Queue(
                maxsize=queue_limit)
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._stop = threading.Event()
        # slot state, owned exclusively by the decode thread
        self._slot_req: List[Optional[_GenRequest]] = [None] * self.slots
        self._tokens = np.zeros((self.slots,), np.int32)
        self._positions = np.zeros((self.slots,), np.int32)
        self._cache = engine.new_cache(self.slots)
        self._step = 0
        self._thread = threading.Thread(target=self._decode_loop,
                                        daemon=True, name="dl4j-gen-decode")
        self._thread.start()
        GenerationPipeline._live.add(self)
        self._publish_cache_bytes()

    @classmethod
    def _publish_cache_bytes(cls):
        """The gauge is documented as the footprint of LIVE pipelines —
        sum across them (a second deploy must not mask the first, and a
        retired pipeline's bytes must leave the gauge)."""
        total = 0
        for gp in list(cls._live):
            if gp._stop.is_set():
                continue
            total += gp._safe_cache_bytes() or 0
        _GenMetrics.get().cache_bytes.set(total)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    @classmethod
    def shutdown_all(cls):
        for gp in list(cls._live):
            gp.shutdown()

    # ------------------------------------------------------------- API
    def _resolve_deadline(self, deadline_ms) -> Optional[Deadline]:
        if not self._resilience:
            return None
        ms = (deadline_ms if deadline_ms is not None
              else self.default_deadline_ms)
        return Deadline.after_ms(ms) if ms and ms > 0 else None

    def _shed(self, reason: str, tenant=None):
        _GenMetrics.get().shed[reason].inc()
        if tenant is not None:
            _qos.global_tenants().count_shed(tenant, reason)
        _faults.record_event("shed", op="generation", reason=reason)

    def _check_admission(self, tenant=None):
        if self._breaker is not None and not self._breaker.allow():
            self._shed("circuit_open", tenant=tenant)
            raise CircuitOpenError(
                "generation circuit open (consecutive decode-step "
                "failures); retry after the reset timeout")

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 on_token=None, tenant=None) -> np.ndarray:
        """Generate up to ``max_new_tokens`` continuation tokens for a
        1-D int32 ``prompt``. Blocks until the request resolves; raises
        the typed resilience outcomes (shed/deadline/circuit/shutdown)
        or the device error that killed it. Returns the emitted tokens
        (1-D int32, possibly shorter on ``eos_id``).

        ``on_token(token, index)`` (optional) streams each emitted token
        at the step boundary that produced it — the SSE per-token wire
        surface rides this. It is called from the decode-loop thread, so
        it must be fast and non-blocking (hand off to a queue, never
        write a socket inline). Returning ``False`` or raising cancels
        the request: it resolves with the typed :class:`StreamCancelled`
        and its slot frees at the boundary — the disconnect-mid-stream
        path can never leak a slot. The streamed sequence is exactly the
        returned array: same tokens, same order, nothing elided."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must contain at least one token")
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.default_max_new_tokens)
        if n_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # fail fast on prompts that can never decode (bucket overflow):
        # a programming error, not a load condition — never typed
        self.engine.prefill_bucket(prompt.size)
        if prompt.size + 1 > self.engine.max_len:
            raise ValueError(
                f"prompt ({prompt.size} tokens) leaves no room to "
                f"decode in a {self.engine.max_len}-token cache")
        obs = _GenMetrics.get()
        t0 = time.perf_counter()
        req = _GenRequest(prompt, n_new,
                          eos_id if eos_id is not None
                          else self.default_eos_id, on_token=on_token)
        req.deadline = self._resolve_deadline(deadline_ms)
        req.tenant = (_qos.global_tenants().resolve(tenant)
                      if self._qos else None)
        with _flight().arm("generation_request"), \
                _span("generation_request", prompt_tokens=int(prompt.size),
                      max_new_tokens=n_new):
            req.ctx = current_context()
            req.t_enqueue_us = now_us()

            def _account(err: Optional[BaseException]):
                obs.latency.observe(time.perf_counter() - t0)
                obs.requests.inc()
                if err is not None and not isinstance(err, _TYPED_OUTCOMES):
                    obs.errors.inc()
                if req.tenant is not None:
                    reg = _qos.global_tenants()
                    reg.observe_request(req.tenant,
                                        time.perf_counter() - t0, err)
                    if req.out:
                        reg.account_tokens(req.tenant, len(req.out))
                    if req.cost_flops:
                        reg.account_cost(req.tenant, req.cost_flops)

            try:
                self._check_admission(tenant=req.tenant)
                self._enqueue(req, obs)
            except Exception as e:
                _account(e)
                raise
            self._await(req)
            if req.error is not None:
                _account(req.error)
                raise req.error
        _account(None)
        return req.result

    def _enqueue(self, req: _GenRequest, obs: "_GenMetrics"):
        """Bounded enqueue with the PI condition/shed semantics."""
        with self._not_full:
            while True:
                if self._stop.is_set():
                    raise ShutdownError(
                        "GenerationPipeline has been shut down")
                if req.deadline is not None and req.deadline.expired():
                    self._shed("deadline", tenant=req.tenant)
                    raise DeadlineExceeded(
                        "request expired while waiting to enqueue")
                try:
                    self._queue.put_nowait(req)
                    obs.queue_depth.set(self._queue.qsize())
                    return
                except queue.Full:
                    if self._qos and self._shed_policy is not None:
                        # tenant-aware: evict the most over-share
                        # tenant's newest request; None = the arriving
                        # tenant is itself the most over-share (under
                        # reject_oldest its OWN stale head gives way —
                        # the pre-QoS policy meaning, tenant-scoped)
                        victim = self._queue.pick_victim(req)
                        if (victim is None
                                and self._shed_policy == "reject_oldest"):
                            victim = (self._queue.pop_oldest_of(
                                req.tenant)
                                or self._queue.pop_global_oldest())
                        if victim is None:
                            self._shed("queue_full", tenant=req.tenant)
                            raise ShedError(
                                f"generation queue full "
                                f"({self._queue.maxsize} requests); "
                                "request rejected (tenant over its "
                                "fair share)")
                        self._shed_request(victim, "queue_full",
                                           ShedError(
                                               "shed from a full "
                                               "generation queue (most "
                                               "over-share tenant)"))
                        continue
                    if self._shed_policy == "reject_newest":
                        self._shed("queue_full", tenant=req.tenant)
                        raise ShedError(
                            f"generation queue full "
                            f"({self._queue.maxsize} requests); request "
                            "rejected (reject_newest)")
                    if self._shed_policy == "reject_oldest":
                        try:
                            old = self._queue.get_nowait()
                        except queue.Empty:
                            continue
                        self._shed_request(old, "queue_full", ShedError(
                            "shed from a full generation queue by a "
                            "newer request (reject_oldest)"))
                        continue
                    self._not_full.wait(timeout=0.1)

    def _await(self, req: _GenRequest):
        """Deadline-aware wait with the walk-away claim (a wedged decode
        step must not hang a deadline'd caller)."""
        if req.deadline is None:
            req.event.wait()
            return
        while not req.event.is_set():
            rem = req.deadline.remaining()
            if rem <= 0:
                break
            req.event.wait(timeout=rem)
        if not req.event.is_set():
            if req.claim():
                req.error = DeadlineExceeded(
                    "request expired while decoding")
                req.event.set()
                self._shed("deadline", tenant=req.tenant)
            else:
                req.event.wait(timeout=5.0)
                if req.error is None and req.result is None:
                    req.error = DeadlineExceeded(
                        "request expired while decoding "
                        "(resolution stalled)")

    # --------------------------------------------------- decode thread
    def _shed_request(self, req: _GenRequest, reason: str,
                      error: BaseException):
        if not req.claim():
            return
        self._shed(reason, tenant=req.tenant)
        if req.ctx is not None:
            record_span("shed", now_us(), ctx=req.ctx, reason=reason)
        req.error = error
        req.event.set()

    def _resolve(self, req: _GenRequest):
        """Successful completion (slot already freed by the caller)."""
        if not req.claim():
            return
        req.result = np.asarray(req.out, np.int32)
        req.event.set()

    @staticmethod
    def _emit_token(req: _GenRequest, tok: int) -> bool:
        """Deliver one just-appended token to the request's streaming
        callback (decode-thread context). Returns False when the
        consumer cancelled — returned False or raised — and the caller
        must shed the request (``client_gone``)."""
        cb = req.on_token
        if cb is None:
            return True
        try:
            return cb(tok, len(req.out) - 1) is not False
        except Exception:
            # a broken consumer must never kill the decode loop the
            # other slots are riding — treat exactly like a walk-away
            return False

    def _fail_request(self, req: _GenRequest, error: BaseException):
        if not req.claim():
            return
        req.error = error
        req.event.set()

    def _n_active(self) -> int:
        return sum(1 for r in self._slot_req if r is not None)

    def _take_request(self, timeout: float) -> Optional[_GenRequest]:
        """Pop one queued request (shedding already-expired ones), waking
        any producer parked on the full queue."""
        wait_until = time.monotonic() + timeout
        while True:
            try:
                req = self._queue.get(
                    timeout=max(0.0, wait_until - time.monotonic()))
            except queue.Empty:
                return None
            with self._not_full:
                self._not_full.notify()
            if (self._resilience and req.deadline is not None
                    and req.deadline.expired()):
                self._shed_request(req, "deadline", DeadlineExceeded(
                    "request expired waiting for a slot"))
                continue
            return req

    def _start_request(self, req: _GenRequest, slot: int) -> bool:
        """Prefill ``req`` into ``slot``'s cache pages. Returns True when
        the slot is now occupied (False: resolved without occupying)."""
        obs = _GenMetrics.get()
        if req._claimed:
            return False          # caller already walked away — no work
        req.t_slot_us = now_us()
        if req.ctx is not None:
            # the join-latency phase continuous batching exists to shrink
            record_span("slot_wait", req.t_enqueue_us, req.t_slot_us,
                        ctx=req.ctx, slot=slot)
        t0 = time.perf_counter()
        t_us = now_us()
        try:
            with _span("prefill", slot=slot,
                       prompt_tokens=int(req.x.size)):
                first, _logits, kv, t = self.engine.prefill(
                    req.x[None], step=self._step)
        except Exception as e:
            # prefill failed BEFORE the insert donated anything — the
            # live cache is intact, only the joiner dies
            if self._breaker is not None:
                self._breaker.record_failure()
            self._fail_request(req, e)
            return False
        try:
            with _span("prefill", slot=slot, phase="insert"):
                self._cache = self.engine.insert_slot(self._cache, kv, slot)
                first_tok = int(np.asarray(first)[0])
            dt = time.perf_counter() - t0
            if req.ctx is not None:
                record_span("prefill", t_us, now_us(), ctx=req.ctx,
                            slot=slot, prompt_tokens=int(req.x.size))
            obs.prefill_latency.observe(dt)
            _cost.global_cost_model().observe_time(PREFILL_FN, dt)
            if req.tenant is not None:
                req.cost_flops += _cost.global_cost_model().flops_for(
                    PREFILL_FN)
            if self._breaker is not None:
                self._breaker.record_success()
        except Exception as e:
            # insert_slot DONATED the live cache before dying — its
            # pages are gone, so every active generation is dead too:
            # fail them all with the real insert error (not the
            # deleted-buffer error one step later) and rebuild
            if self._breaker is not None:
                self._breaker.record_failure()
            self._fail_request(req, e)
            for s, other in enumerate(self._slot_req):
                if other is not None:
                    self._fail_request(other, e)
                    self._slot_req[s] = None
            self._cache = self.engine.new_cache(self.slots)
            return False
        req.out.append(first_tok)
        # the generation budget may be clipped by the cache length —
        # never write a position past the preallocated pages
        cap = min(req.max_new_tokens, self.engine.max_len - t)
        req.max_new_tokens = cap
        done = (len(req.out) >= cap
                or (req.eos_id is not None and first_tok == req.eos_id))
        obs.tokens.inc()
        if not self._emit_token(req, first_tok):
            if done:
                self._resolve(req)       # complete anyway: result is whole
            else:
                self._shed_request(req, "client_gone", StreamCancelled(
                    "streaming consumer cancelled during prefill"))
            return False
        if done:
            self._resolve(req)
            return False
        self._slot_req[slot] = req
        self._tokens[slot] = first_tok
        self._positions[slot] = t
        return True

    def _maybe_preempt(self) -> bool:
        """Priority preemption at a step boundary (QoS posture, slots
        full): when the highest queued tier strictly exceeds some active
        slot's tier, that slot's request is shed typed
        (:class:`~deeplearning4j_tpu.resilience.qos.PreemptedError`) and
        the slot freed. The victim: among lower-tier active slots, the
        most over-share tenant's longest-running request (slots frees
        and joins already happen exactly here — the preempted caller
        resolves typed, never hangs). Default tiers (0 everywhere)
        never preempt."""
        pri = self._queue.peek_priority()
        if pri is None:
            return False
        reg = _qos.global_tenants()
        active = [(slot, r) for slot, r in enumerate(self._slot_req)
                  if r is not None]
        cands = [(slot, r) for slot, r in active
                 if reg.priority(r.tenant) < pri]
        if not cands:
            return False
        counts: dict = {}
        for _, r in active:
            counts[r.tenant] = counts.get(r.tenant, 0) + 1
        wsum = sum(reg.weight(t) for t in counts) or 1.0

        def over_share(t):
            return counts[t] / max(1e-9,
                                   len(active) * reg.weight(t) / wsum)

        victim_slot, victim = max(
            cands, key=lambda sr: (over_share(sr[1].tenant),
                                   -sr[1].t_slot_us))
        self._shed_request(victim, "preempted", _qos.PreemptedError(
            f"generation slot {victim_slot} preempted by a higher-"
            f"priority tenant at a decode step boundary"))
        self._slot_req[victim_slot] = None
        return True

    def _admit(self):
        """Join queued requests into free slots at this step boundary
        (blocking briefly only when the whole pipeline is idle)."""
        while not self._stop.is_set():
            free = [i for i, r in enumerate(self._slot_req) if r is None]
            if not free:
                if self._qos and self._maybe_preempt():
                    continue       # a slot was freed — re-scan and join
                return
            idle = len(free) == self.slots
            req = self._take_request(timeout=0.05 if idle else 0.0)
            if req is None:
                return
            _GenMetrics.get().queue_depth.set(self._queue.qsize())
            self._start_request(req, free[0])

    def _sweep_finished(self, stepped: List[int]):
        """Post-step bookkeeping for every active slot: append the new
        token, then resolve/free finished or expired requests."""
        obs = _GenMetrics.get()
        # each occupied slot owns 1/slots of the decode step's accounted
        # FLOPs (the whole slot batch runs whether occupied or not —
        # charging per OCCUPIED slot would make a lonely tenant look
        # cheap while it monopolizes the executable)
        step_share = (_cost.global_cost_model().flops_for(DECODE_FN)
                      / max(1, self.slots)) if self._qos else 0.0
        for slot in stepped:
            req = self._slot_req[slot]
            if req is None:
                continue
            if req._claimed:
                # another path already resolved it (the caller's
                # deadline walk-away) — stop spending device steps on a
                # request nobody will read (racy read is safe: worst
                # case is one extra step before the slot frees)
                self._slot_req[slot] = None
                continue
            tok = int(self._tokens[slot])
            req.out.append(tok)
            self._positions[slot] += 1
            obs.tokens.inc()
            if req.tenant is not None:
                req.cost_flops += step_share
            expired = (self._resilience and req.deadline is not None
                       and req.deadline.expired())
            done = (len(req.out) >= req.max_new_tokens
                    or (req.eos_id is not None and tok == req.eos_id))
            if not self._emit_token(req, tok) and not done:
                # consumer gone mid-stream: free the slot NOW — other
                # slots keep decoding, nothing leaks
                self._shed_request(req, "client_gone", StreamCancelled(
                    "streaming consumer cancelled mid-stream"))
                self._slot_req[slot] = None
                continue
            if expired and not done:
                self._shed_request(req, "deadline", DeadlineExceeded(
                    "request expired at a decode step boundary"))
                self._slot_req[slot] = None
            elif done:
                self._resolve(req)
                self._slot_req[slot] = None

    def _decode_loop(self):
        while not self._stop.is_set():
            # re-fetch per iteration: a registry reset mid-flight drops
            # and re-binds the singleton (on_registry_reset) — a cached
            # handle would keep writing to detached instruments
            obs = _GenMetrics.get()
            self._admit()
            active = [i for i, r in enumerate(self._slot_req)
                      if r is not None]
            obs.slots_in_use.set(len(active))
            if not active:
                continue
            try:
                if self._resilience:
                    self._retry.call(
                        lambda: _faults.check("generation.step"),
                        op="generation.step")
                t0 = time.perf_counter()
                with _span("decode_step", active=len(active),
                           slots=self.slots):
                    tokens, _logits, self._cache = self.engine.decode(
                        self._cache, self._tokens, self._positions,
                        self._step)
                    toks = np.asarray(tokens)    # device→host sync point
                dt = time.perf_counter() - t0
                obs.step_latency.observe(dt)
                obs.steps.inc()
                obs.occupancy.observe(len(active) / max(1, self.slots))
                _cost.global_cost_model().observe_time(DECODE_FN, dt)
                if self._fresh_decode_compile():
                    self.engine.account_decode(
                        self._cache, self._tokens, self._positions,
                        self._step)
                if self._breaker is not None:
                    self._breaker.record_success()
                _flight().progress("generation_step")
            except Exception as e:
                if (self._breaker is not None
                        and not isinstance(e, _TYPED_OUTCOMES)):
                    self._breaker.record_failure()
                # the step died mid-donation: the cache buffers are no
                # longer trustworthy — fail every in-flight request and
                # rebuild the pages (queued requests are untouched)
                for slot, req in enumerate(self._slot_req):
                    if req is not None:
                        self._fail_request(req, e)
                        self._slot_req[slot] = None
                self._cache = self.engine.new_cache(self.slots)
                self._step += 1
                continue
            self._step += 1
            self._tokens[active] = toks[active]
            self._sweep_finished(active)
        # shutdown: resolve whatever still occupies a slot
        for slot, req in enumerate(self._slot_req):
            if req is not None:
                self._fail_request(req, ShutdownError(
                    "GenerationPipeline shut down"))
                self._slot_req[slot] = None

    def _fresh_decode_compile(self) -> bool:
        """True when compile_watch counted a decode trace the cost model
        has not analyzed yet (kept cheap: one counter compare)."""
        try:
            return _cost.global_cost_model().needs_account(DECODE_FN,
                                                           DECODE_FN)
        except Exception:
            return False

    # -------------------------------------------------------- lifecycle
    def shutdown(self):
        self._stop.set()
        with self._not_full:
            self._not_full.notify_all()
        self._thread.join(timeout=5.0)
        if self._breaker is not None:
            self._breaker.retire()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            self._fail_request(req, ShutdownError(
                "GenerationPipeline shut down"))
        _GenMetrics.get().queue_depth.set(self._queue.qsize())
        self._publish_cache_bytes()

    def snapshot(self) -> dict:
        """Live pipeline state (``/debug/generation`` + the
        flight-recorder ``generation.json`` payload)."""
        slots = []
        tenants: dict = {}
        for i, req in enumerate(self._slot_req):
            if req is None:
                slots.append({"slot": i, "state": "free"})
            else:
                slots.append({
                    "slot": i, "state": "decoding",
                    "position": int(self._positions[i]),
                    "generated": len(req.out),
                    "max_new_tokens": req.max_new_tokens,
                    "tenant": req.tenant,
                    "trace_id": (req.ctx.trace_id
                                 if req.ctx is not None else None)})
                if req.tenant is not None:
                    t = tenants.setdefault(req.tenant,
                                           {"active_slots": 0,
                                            "queued": 0})
                    t["active_slots"] += 1
        if self._qos:
            for t, n in self._queue.tenant_sizes().items():
                tenants.setdefault(t, {"active_slots": 0,
                                       "queued": 0})["queued"] = n
        return {
            "qos": self._qos,
            "tenants": tenants,
            "slots": self.slots,
            "active": self._n_active(),
            "queue_depth": self._queue.qsize(),
            "step": self._step,
            "max_len": self.engine.max_len,
            "prefill_buckets": list(self.engine.prefill_buckets),
            "sampler": {"kind": self.engine.sampler.kind,
                        "top_k": self.engine.sampler.top_k,
                        "temperature": self.engine.sampler.temperature},
            "cache_bytes": self._safe_cache_bytes(),
            "slot_table": slots,
        }

    def _safe_cache_bytes(self):
        """The decode thread may be mid-step (old cache donated away)
        when a /debug or bundle snapshot races this read — answer None
        for that instant rather than raising into the dump."""
        try:
            return DecodeEngine.cache_bytes(self._cache)
        except Exception:
            return None

    @classmethod
    def live_snapshots(cls) -> list:
        return [gp.snapshot() for gp in list(cls._live)
                if not gp._stop.is_set()]
