"""GenerationPipeline: continuous batching for autoregressive decode.

The serving half of the generative decode path (the model half is
``models/generation.py``). ``ParallelInference``'s batcher coalesces
*one-shot* requests into padded windows; generation is different — a
request occupies device batch space for its whole multi-step lifetime,
and windowed batching makes every member wait on the window's LONGEST
member before any slot frees. Continuous batching fixes exactly that:

- the decode batch is a fixed set of ``slots`` (one compiled
  ``decode_step`` executable over all of them, occupied or not);
- a finished/shed request frees its slot **at the step boundary**, and a
  queued request joins in the freed slot immediately — its prefill runs
  and its k/v land in that slot's cache pages
  (``DecodeEngine.insert_slot``) while every other slot keeps decoding
  on the next step;
- steady-state decode triggers **zero** new XLA traces (fixed shapes
  throughout; pinned via ``compile_watch`` counters in tests).

The PR-5 policies apply unchanged: per-request deadlines (shed at
admission, at the step boundary, and by the caller's walk-away),
bounded-queue shedding (``reject_newest``/``reject_oldest``), a circuit
breaker on the decode device path, transient-fault retries under a
budget, and exactly-once resolution through the shared
``_Request.claim()``. Chaos point ``generation.step`` fires once per
step boundary. Trace phases per request: ``slot_wait`` (enqueue → slot
granted), ``prefill``, and a batch-level ``decode_step`` span per step.

Metrics (``dl4j_decode_*``): generated tokens, slot occupancy,
prefill/decode latency split, cache bytes, sheds, queue depth — on
``/metrics``, with decode/prefill MFU entries on ``/debug/perf`` via the
cost model, and in flight-recorder bundles (``generation.json``).

Multi-tenant QoS (kill switch ``DL4J_TPU_QOS=0``, see
``resilience/qos.py``): the slot-wait queue becomes a per-tenant DWRR
``FairQueue`` (cost = one slot per request), full-queue shedding evicts
the most over-share tenant's newest request, a higher-priority tenant
may PREEMPT a lower-tier slot at a step boundary (the victim resolves
with the typed ``PreemptedError``), and each request's tenant is charged
its emitted tokens plus prefill + per-slot decode-step FLOPs shares.

Paged admission (PR 13): with the paged engine (default), FREE PAGES —
not free slots — are the admission unit. ``cache_pages=`` bounds the
pool below the dense worst case; ``_admit`` parks a joiner the pool
cannot back yet and retries it at every step boundary, and
``_reclaim_pages`` sheds the youngest active generation with the typed
``CachePagesExhausted`` when mid-decode growth exhausts the pool
(pages return, admission resumes). Speculative engines emit 1..spec_k
tokens per step boundary; ``_sweep_finished`` consumes per-slot token
LISTS so eos/budget/deadline/stream-cancel semantics are per token,
exactly as the one-token path behaved.

Durable sessions (PR 20, kill switch ``DL4J_TPU_SESSIONS=0``, see
``serving/session.py``): every admitted generation carries a journaled
session record; the decode loop's only added cost is a list append per
token and an ``Event.set`` per step boundary. A device-level fault now
RESUMES journaled sessions in place (re-prefill of prompt + emitted —
deterministic because sampling is in-graph seeded) instead of failing
every slot; ``resume(record)`` re-enters an adopted session from
another worker's journal through the ordinary admission path; page
reclamation prefers shedding unjournaled (new) sessions over journaled
ones; and a fence-stolen session sheds typed (``session_lost``) at the
next boundary so a stalled worker can never double-decode.
"""
from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.models.generation import (DECODE_FN, PREFILL_FN,
                                                  PROPOSE_FN, VERIFY_FN,
                                                  DecodeEngine)
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.observability.tracing import (current_context,
                                                      now_us, record_span)
from deeplearning4j_tpu.parallel.inference import _Request
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import qos as _qos
from deeplearning4j_tpu.resilience.policy import (TYPED_OUTCOMES,
                                                  CachePagesExhausted,
                                                  CircuitBreaker,
                                                  CircuitOpenError, Deadline,
                                                  DeadlineExceeded,
                                                  RetryPolicy, ShedError,
                                                  ShutdownError,
                                                  default_deadline_ms)

_TYPED_OUTCOMES = TYPED_OUTCOMES


def _session_mod():
    """Lazy ``serving.session`` import: ``parallel`` must not import the
    ``serving`` package at module load (the registry there imports the
    parallel modules back) — by the time a pipeline is constructed both
    packages are fully loaded and the import is safe."""
    from deeplearning4j_tpu.serving import session
    return session


class StreamCancelled(ShedError):
    """The streaming consumer walked away (its ``on_token`` callback
    returned ``False`` or raised): the request stops decoding and its
    slot frees at the next step boundary. A typed lifecycle outcome
    (``ShedError`` subclass), never an error-rate event — a client
    closing its SSE connection is load behavior, not a model failure."""


class _GenMetrics:
    """Label-bound decode instruments (shared across instances, same
    rationale as ``_ServingMetrics``)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        self.tokens = reg.counter(
            "dl4j_decode_tokens_total",
            "tokens emitted by the continuous-batching decode loop "
            "(rate = serving tokens/s)")
        self.steps = reg.counter(
            "dl4j_decode_steps_total",
            "decode step boundaries executed (each runs every occupied "
            "slot one token forward)")
        self.requests = reg.counter(
            "dl4j_decode_requests_total",
            "generation requests resolved (success, typed shed, or error)")
        self.errors = reg.counter(
            "dl4j_decode_errors_total",
            "generation requests that raised a non-typed error")
        shed = reg.counter(
            "dl4j_decode_shed_total",
            "generation requests shed by admission control or deadlines",
            label_names=("reason",))
        self.shed = {r: shed.labels(reason=r)
                     for r in ("queue_full", "deadline", "circuit_open",
                               "client_gone", "preempted",
                               "pages_exhausted", "session_lost")}
        self.occupancy = reg.histogram(
            "dl4j_decode_slot_occupancy_ratio",
            "occupied slots / total slots per decode step (1.0 = the "
            "device batch is full — continuous batching's win condition)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self.prefill_latency = reg.histogram(
            "dl4j_decode_prefill_seconds",
            "prompt prefill wall time (trunk forward + cache insert), "
            "per joining request")
        self.step_latency = reg.histogram(
            "dl4j_decode_step_seconds",
            "one decode step boundary's wall time (single-query "
            "attention over every occupied slot + sampling)")
        self.latency = reg.histogram(
            "dl4j_decode_latency_seconds",
            "end-to-end GenerationPipeline.generate latency (queue wait "
            "+ prefill + all decode steps)")
        self.cache_bytes = reg.gauge(
            "dl4j_decode_cache_bytes",
            "ACTUAL resident KV-cache bytes of live pipelines: paged = "
            "pages in use x page bytes (post-quantization), dense = the "
            "full preallocation")
        self.slots_in_use = reg.gauge(
            "dl4j_decode_slots_in_use",
            "slots occupied by in-flight generations (sampled per step "
            "boundary)")
        self.queue_depth = reg.gauge(
            "dl4j_decode_queue_depth",
            "generation requests waiting for a free slot")
        self.pages_in_use = reg.gauge(
            "dl4j_decode_pages_in_use",
            "KV-cache pages allocated to live generations across paged "
            "pipelines (the admission unit)")
        self.pages_total = reg.gauge(
            "dl4j_decode_pages_capacity",
            "KV-cache page pool capacity across live paged pipelines "
            "(gauge: _total is counter-reserved by the metric lint)")
        self.spec_accept = reg.gauge(
            "dl4j_spec_accept_ratio",
            "cumulative speculative-decode acceptance: accepted draft "
            "tokens / proposed (per live spec engines; 1.0 = every "
            "proposal verified)")

    @classmethod
    def get(cls) -> "_GenMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_gen_metrics():
    _GenMetrics._instance = None


class _GenRequest(_Request):
    """One generation request riding the shared exactly-once machinery
    (``claim()``): ``x`` is the 1-D int32 prompt, ``out`` accumulates
    emitted tokens while the request owns a slot. ``on_token`` (when
    set) streams each token out at the step boundary that produced it."""

    __slots__ = ("max_new_tokens", "eos_id", "out", "t_slot_us",
                 "on_token", "cost_flops", "session", "resumes")

    def __init__(self, x, max_new_tokens: int, eos_id: Optional[int],
                 on_token=None, session=None, out=None):
        super().__init__(x)
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        # non-empty ``out`` = a RESUMED session: these tokens were
        # already emitted (by this worker before a fault, or by a dead
        # one — they came back from the journal) and prefill re-enters
        # at prompt + out
        self.out: List[int] = list(out) if out else []
        self.t_slot_us = 0.0
        self.on_token = on_token
        # accounted device work attributed to this request (prefill +
        # per-slot decode-step shares) — charged to its tenant at
        # resolution under the QoS posture
        self.cost_flops = 0.0
        # the durable session record riding this request (None with
        # DL4J_TPU_SESSIONS=0); ``resumes`` bounds the in-place
        # fault-resume budget so a poisoned cache can't loop forever
        self.session = session
        self.resumes = 0


class GenerationPipeline:
    """Slot-based continuous batching over one :class:`DecodeEngine`.

    Owns a decode-loop thread; call :meth:`shutdown` (or use as a
    context manager) when done. :meth:`shutdown_all` stops every live
    instance (test-harness teardown, like ``ParallelInference``)."""

    _live: "weakref.WeakSet[GenerationPipeline]" = weakref.WeakSet()

    def __init__(self, engine: DecodeEngine, slots: int = 4,
                 queue_limit: int = 64,
                 max_new_tokens: int = 32, eos_id: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 cache_pages: Optional[int] = None):
        self.engine = engine
        self.slots = int(slots)
        if self.slots < 1:
            # a zero-slot pipeline would warm, go live, and then park
            # every request forever — refuse at construction
            raise ValueError(f"slots must be >= 1, got {slots}")
        # paged admission pool: None = the dense worst case (every slot
        # can hold max_len tokens); pass FEWER pages to run more slots
        # against a fixed HBM budget and admit by ACTUAL cached tokens
        self._cache_pages = cache_pages
        if cache_pages is not None and engine.paged:
            if int(cache_pages) < engine.pages_per_slot:
                raise ValueError(
                    f"cache_pages {cache_pages} cannot back even one "
                    f"full-length slot ({engine.pages_per_slot} pages)")
        self.default_max_new_tokens = int(max_new_tokens)
        self.default_eos_id = eos_id
        self._resilience = _faults.resilience_enabled()
        # durable-session posture (kill switch DL4J_TPU_SESSIONS=0):
        # resolved once at construction, same discipline as _resilience
        self._sessions = _session_mod().sessions_enabled()
        if shed_policy is not None and shed_policy not in (
                "reject_newest", "reject_oldest"):
            raise ValueError("shed_policy must be 'reject_newest' or "
                             f"'reject_oldest', got {shed_policy!r}")
        if max_queue_depth is not None and self._resilience:
            queue_limit = max(1, int(max_queue_depth))
            shed_policy = shed_policy or "reject_newest"
        self._shed_policy = shed_policy if self._resilience else None
        self.default_deadline_ms = (deadline_ms if deadline_ms is not None
                                    else default_deadline_ms())
        self._breaker = None
        if self._resilience:
            self._breaker = breaker if breaker is not None else \
                CircuitBreaker("generation.step")
            self._retry = RetryPolicy(max_retries=2,
                                      base_delay_seconds=0.01)
        # QoS posture: per-tenant DWRR queue (cost = 1 slot per
        # request), same kill-switch discipline as ParallelInference
        self._qos = self._resilience and _qos.qos_enabled()
        if self._qos:
            self._queue = _qos.FairQueue(queue_limit,
                                         _qos.global_tenants())
        else:
            self._queue: "queue.Queue[_GenRequest]" = queue.Queue(
                maxsize=queue_limit)
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._stop = threading.Event()
        # slot state, owned exclusively by the decode thread
        self._slot_req: List[Optional[_GenRequest]] = [None] * self.slots
        self._tokens = np.zeros((self.slots,), np.int32)
        self._positions = np.zeros((self.slots,), np.int32)
        self._cache = engine.new_state(self.slots, pages=cache_pages)
        # a popped request the pool couldn't back yet — retried at every
        # step boundary (pages free there) before the queue is touched
        self._waiting: Optional[_GenRequest] = None
        self._step = 0
        self._thread = threading.Thread(target=self._decode_loop,
                                        daemon=True, name="dl4j-gen-decode")
        self._thread.start()
        GenerationPipeline._live.add(self)
        self._publish_cache_bytes()

    @classmethod
    def _publish_cache_bytes(cls):
        """The gauge is documented as the ACTUAL resident footprint of
        LIVE pipelines — sum across them (a second deploy must not mask
        the first, and a retired pipeline's bytes must leave the
        gauge). Paged pipelines contribute pages-in-use x page-bytes
        (post-quantization), dense ones their full preallocation."""
        obs = _GenMetrics.get()
        total = in_use = pages = 0
        accepted = proposed = 0
        for gp in list(cls._live):
            if gp._stop.is_set():
                continue
            total += gp._safe_cache_bytes() or 0
            st = gp._cache
            if st is not None and st.alloc is not None:
                in_use += st.alloc.in_use
                pages += st.alloc.total
            if gp.engine.spec:
                accepted += gp.engine.spec_stats["accepted"]
                proposed += gp.engine.spec_stats["proposed"]
        obs.cache_bytes.set(total)
        obs.pages_in_use.set(in_use)
        obs.pages_total.set(pages)
        # 0 when no live spec engine has proposed anything — a retired
        # spec deploy's final ratio must not outlive it on dashboards
        obs.spec_accept.set(accepted / proposed if proposed else 0.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    @classmethod
    def shutdown_all(cls):
        for gp in list(cls._live):
            gp.shutdown()

    # ------------------------------------------------------------- API
    def _resolve_deadline(self, deadline_ms) -> Optional[Deadline]:
        if not self._resilience:
            return None
        ms = (deadline_ms if deadline_ms is not None
              else self.default_deadline_ms)
        return Deadline.after_ms(ms) if ms and ms > 0 else None

    def _shed(self, reason: str, tenant=None):
        _GenMetrics.get().shed[reason].inc()
        if tenant is not None:
            _qos.global_tenants().count_shed(tenant, reason)
        _faults.record_event("shed", op="generation", reason=reason)

    def _check_admission(self, tenant=None):
        if self._breaker is not None and not self._breaker.allow():
            self._shed("circuit_open", tenant=tenant)
            raise CircuitOpenError(
                "generation circuit open (consecutive decode-step "
                "failures); retry after the reset timeout")

    def _begin_session(self, prompt: np.ndarray, n_new: int, eos_id,
                       tenant, session_version, session_id):
        """Mint the durable session record for an admitted generation
        (None under ``DL4J_TPU_SESSIONS=0``)."""
        if not self._sessions:
            return None
        smod = _session_mod()
        samp = self.engine.sampler
        return smod.global_sessions().begin(
            prompt.tolist(),
            {"kind": samp.kind, "top_k": samp.top_k,
             "temperature": samp.temperature},
            getattr(self.engine, "_seed", None), n_new, eos_id,
            tenant=tenant, version=session_version, sid=session_id)

    @staticmethod
    def _session_append(req: "_GenRequest", tok: int):
        if req.session is not None:
            req.session.append(tok)

    def _run_request(self, req: "_GenRequest", obs: "_GenMetrics",
                     t0: float, span_name: str, **span_kw) -> np.ndarray:
        """Submit → await → account, shared by :meth:`generate` and
        :meth:`resume` (identical lifecycle, different admission
        preludes)."""
        # span names stay literal (bounded trace-index cardinality);
        # the two lifecycles are the only callers
        span_cm = (_span("generation_resume", **span_kw)
                   if span_name == "generation_resume"
                   else _span("generation_request", **span_kw))
        with _flight().arm(span_name), span_cm:
            req.ctx = current_context()
            req.t_enqueue_us = now_us()

            def _account(err: Optional[BaseException]):
                obs.latency.observe(time.perf_counter() - t0)
                obs.requests.inc()
                if err is not None and not isinstance(err, _TYPED_OUTCOMES):
                    obs.errors.inc()
                if req.tenant is not None:
                    reg = _qos.global_tenants()
                    reg.observe_request(req.tenant,
                                        time.perf_counter() - t0, err)
                    if req.out:
                        reg.account_tokens(req.tenant, len(req.out))
                    if req.cost_flops:
                        reg.account_cost(req.tenant, req.cost_flops)

            try:
                self._check_admission(tenant=req.tenant)
                self._enqueue(req, obs)
            except Exception as e:
                if req.session is not None:
                    req.session.finish(
                        "cancelled" if isinstance(e, _TYPED_OUTCOMES)
                        else "failed")
                _account(e)
                raise
            self._await(req)
            if req.error is not None:
                # the resolver paths (_resolve/_fail/_shed) run on the
                # decode thread; the caller's walk-away resolves HERE —
                # the session terminal status is stamped once, centrally
                if req.session is not None:
                    req.session.finish(
                        "cancelled" if isinstance(req.error,
                                                  _TYPED_OUTCOMES)
                        else "failed")
                _account(req.error)
                raise req.error
            if req.session is not None:
                req.session.finish("done")
        _account(None)
        return req.result

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 on_token=None, tenant=None,
                 session_id: Optional[str] = None,
                 session=None,
                 session_version: Optional[str] = None) -> np.ndarray:
        """Generate up to ``max_new_tokens`` continuation tokens for a
        1-D int32 ``prompt``. Blocks until the request resolves; raises
        the typed resilience outcomes (shed/deadline/circuit/shutdown)
        or the device error that killed it. Returns the emitted tokens
        (1-D int32, possibly shorter on ``eos_id``).

        ``on_token(token, index)`` (optional) streams each emitted token
        at the step boundary that produced it — the SSE per-token wire
        surface rides this. It is called from the decode-loop thread, so
        it must be fast and non-blocking (hand off to a queue, never
        write a socket inline). Returning ``False`` or raising cancels
        the request: it resolves with the typed :class:`StreamCancelled`
        and its slot frees at the boundary — the disconnect-mid-stream
        path can never leak a slot. The streamed sequence is exactly the
        returned array: same tokens, same order, nothing elided.

        Under the durable-session posture every admitted generation
        also gets a :mod:`~deeplearning4j_tpu.serving.session` record
        (``session_id`` pins its id, ``session`` supplies a pre-built
        record — the adoption path — and ``session_version`` stamps the
        serving deploy it ran under); ``DL4J_TPU_SESSIONS=0`` makes all
        three inert."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must contain at least one token")
        n_new = int(max_new_tokens if max_new_tokens is not None
                    else self.default_max_new_tokens)
        if n_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # fail fast on prompts that can never decode (bucket overflow):
        # a programming error, not a load condition — never typed
        self.engine.prefill_bucket(prompt.size)
        if prompt.size + 1 > self.engine.max_len:
            raise ValueError(
                f"prompt ({prompt.size} tokens) leaves no room to "
                f"decode in a {self.engine.max_len}-token cache")
        if (self.engine.paged and self.engine.min_pages_for_prompt(
                prompt.size) > self._cache.alloc.total):
            # capacity misconfiguration, not load: this prompt could
            # never admit even into an EMPTY pool
            raise ValueError(
                f"prompt ({prompt.size} tokens) needs "
                f"{self.engine.min_pages_for_prompt(prompt.size)} pages "
                f"but the pool holds {self._cache.alloc.total}")
        obs = _GenMetrics.get()
        t0 = time.perf_counter()
        real_eos = eos_id if eos_id is not None else self.default_eos_id
        sess = session
        if self._sessions and sess is None:
            sess = self._begin_session(prompt, n_new, real_eos, tenant,
                                       session_version, session_id)
        req = _GenRequest(prompt, n_new, real_eos, on_token=on_token,
                          session=sess)
        req.deadline = self._resolve_deadline(deadline_ms)
        req.tenant = (_qos.global_tenants().resolve(tenant)
                      if self._qos else None)
        return self._run_request(req, obs, t0, "generation_request",
                                 prompt_tokens=int(prompt.size),
                                 max_new_tokens=n_new)

    def resume(self, record: dict, on_token=None,
               deadline_ms: Optional[float] = None,
               tenant=None, session=None) -> np.ndarray:
        """Re-enter a journaled session (tentpole 2/3): replay the
        journaled token log through ``on_token`` (indices ``0..k-1`` —
        the caller's ``Last-Event-ID`` window dedups what its client
        already received), then re-prefill ``prompt + emitted`` into a
        free slot and continue the stream. Sampling is in-graph seeded,
        so under greedy the continued stream is byte-identical to the
        one the dead worker would have produced. Live slots are never
        disturbed — a resume is an ordinary admission into a freed slot
        (page pressure parks it exactly like any joiner).

        ``record`` is the journal/store form (``prompt``, ``tokens``,
        ``max_new_tokens``, ``eos_id``, ...); ``session`` (optional) is
        the local :class:`~deeplearning4j_tpu.serving.session.Session`
        mirror the continued tokens journal into — pass the
        ``adopt_local`` result on the adoption path."""
        prompt = np.asarray(record.get("prompt") or [],
                            np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("session record has no prompt to resume")
        emitted = [int(t) for t in (record.get("tokens") or [])]
        n_new = int(record.get("max_new_tokens")
                    or self.default_max_new_tokens)
        eos = record.get("eos_id")
        eos = int(eos) if eos is not None else None

        def _replay() -> bool:
            """Push the already-emitted log through the stream; False =
            the consumer walked away."""
            if on_token is None:
                return True
            for i, t in enumerate(emitted):
                if on_token(int(t), i) is False:
                    return False
            return True

        complete = (len(emitted) >= n_new
                    or (eos is not None and emitted
                        and emitted[-1] == eos))
        if complete:
            # nothing left to decode — the record IS the result (the
            # done-status adoption / replay-only path)
            _replay()
            return np.asarray(emitted, np.int32)
        total = prompt.size + len(emitted)
        self.engine.prefill_bucket(total)
        if total + 1 > self.engine.max_len:
            raise ValueError(
                f"resumed session ({total} cached tokens) leaves no "
                f"room to decode in a {self.engine.max_len}-token cache")
        if (self.engine.paged and self.engine.min_pages_for_prompt(total)
                > self._cache.alloc.total):
            raise ValueError(
                f"resumed session ({total} tokens) needs "
                f"{self.engine.min_pages_for_prompt(total)} pages but "
                f"the pool holds {self._cache.alloc.total}")
        obs = _GenMetrics.get()
        t0 = time.perf_counter()
        if not _replay():
            # client gone before the resume even admitted — same typed
            # outcome the mid-stream walk-away gets
            raise StreamCancelled(
                "streaming consumer cancelled during session replay")
        req = _GenRequest(prompt, n_new, eos, on_token=on_token,
                          session=session, out=emitted)
        req.deadline = self._resolve_deadline(deadline_ms)
        req.tenant = (_qos.global_tenants().resolve(
            tenant if tenant is not None else record.get("tenant"))
            if self._qos else None)
        if self._sessions:
            _session_mod().session_metrics().resumes.inc()
            _faults.record_event("session_resume",
                                 sid=record.get("sid"),
                                 emitted=len(emitted))
        return self._run_request(req, obs, t0, "generation_resume",
                                 prompt_tokens=int(prompt.size),
                                 replayed_tokens=len(emitted),
                                 max_new_tokens=n_new)

    def _enqueue(self, req: _GenRequest, obs: "_GenMetrics"):
        """Bounded enqueue with the PI condition/shed semantics."""
        with self._not_full:
            while True:
                if self._stop.is_set():
                    raise ShutdownError(
                        "GenerationPipeline has been shut down")
                if req.deadline is not None and req.deadline.expired():
                    self._shed("deadline", tenant=req.tenant)
                    raise DeadlineExceeded(
                        "request expired while waiting to enqueue")
                try:
                    self._queue.put_nowait(req)
                    obs.queue_depth.set(self._queue.qsize())
                    return
                except queue.Full:
                    if self._qos and self._shed_policy is not None:
                        # tenant-aware: evict the most over-share
                        # tenant's newest request; None = the arriving
                        # tenant is itself the most over-share (under
                        # reject_oldest its OWN stale head gives way —
                        # the pre-QoS policy meaning, tenant-scoped)
                        victim = self._queue.pick_victim(req)
                        if (victim is None
                                and self._shed_policy == "reject_oldest"):
                            victim = (self._queue.pop_oldest_of(
                                req.tenant)
                                or self._queue.pop_global_oldest())
                        if victim is None:
                            self._shed("queue_full", tenant=req.tenant)
                            raise ShedError(
                                f"generation queue full "
                                f"({self._queue.maxsize} requests); "
                                "request rejected (tenant over its "
                                "fair share)")
                        self._shed_request(victim, "queue_full",
                                           ShedError(
                                               "shed from a full "
                                               "generation queue (most "
                                               "over-share tenant)"))
                        continue
                    if self._shed_policy == "reject_newest":
                        self._shed("queue_full", tenant=req.tenant)
                        raise ShedError(
                            f"generation queue full "
                            f"({self._queue.maxsize} requests); request "
                            "rejected (reject_newest)")
                    if self._shed_policy == "reject_oldest":
                        try:
                            old = self._queue.get_nowait()
                        except queue.Empty:
                            continue
                        self._shed_request(old, "queue_full", ShedError(
                            "shed from a full generation queue by a "
                            "newer request (reject_oldest)"))
                        continue
                    self._not_full.wait(timeout=0.1)

    def _await(self, req: _GenRequest):
        """Deadline-aware wait with the walk-away claim (a wedged decode
        step must not hang a deadline'd caller)."""
        if req.deadline is None:
            req.event.wait()
            return
        while not req.event.is_set():
            rem = req.deadline.remaining()
            if rem <= 0:
                break
            req.event.wait(timeout=rem)
        if not req.event.is_set():
            if req.claim():
                req.error = DeadlineExceeded(
                    "request expired while decoding")
                req.event.set()
                self._shed("deadline", tenant=req.tenant)
            else:
                req.event.wait(timeout=5.0)
                if req.error is None and req.result is None:
                    req.error = DeadlineExceeded(
                        "request expired while decoding "
                        "(resolution stalled)")

    # --------------------------------------------------- decode thread
    def _shed_request(self, req: _GenRequest, reason: str,
                      error: BaseException):
        if not req.claim():
            return
        self._shed(reason, tenant=req.tenant)
        if req.ctx is not None:
            record_span("shed", now_us(), ctx=req.ctx, reason=reason)
        req.error = error
        req.event.set()

    def _resolve(self, req: _GenRequest):
        """Successful completion (slot already freed by the caller)."""
        if not req.claim():
            return
        req.result = np.asarray(req.out, np.int32)
        req.event.set()

    @staticmethod
    def _emit_token(req: _GenRequest, tok: int) -> bool:
        """Deliver one just-appended token to the request's streaming
        callback (decode-thread context). Returns False when the
        consumer cancelled — returned False or raised — and the caller
        must shed the request (``client_gone``)."""
        cb = req.on_token
        if cb is None:
            return True
        try:
            return cb(tok, len(req.out) - 1) is not False
        # graftlint: disable=typed-errors — a broken consumer callback is
        # resolved as a client_gone shed by the caller, not swallowed
        except Exception:
            # a broken consumer must never kill the decode loop the
            # other slots are riding — treat exactly like a walk-away
            return False

    def _fail_request(self, req: _GenRequest, error: BaseException):
        if not req.claim():
            return
        req.error = error
        req.event.set()

    def _n_active(self) -> int:
        return sum(1 for r in self._slot_req if r is not None)

    def _take_request(self, timeout: float) -> Optional[_GenRequest]:
        """Pop one queued request (shedding already-expired ones), waking
        any producer parked on the full queue."""
        wait_until = time.monotonic() + timeout
        while True:
            try:
                req = self._queue.get(
                    timeout=max(0.0, wait_until - time.monotonic()))
            except queue.Empty:
                return None
            with self._not_full:
                self._not_full.notify()
            if (self._resilience and req.deadline is not None
                    and req.deadline.expired()):
                self._shed_request(req, "deadline", DeadlineExceeded(
                    "request expired waiting for a slot"))
                continue
            return req

    def _free_slot(self, slot: int):
        """Release ``slot``: request pointer, its cache pages (paged),
        and the position/token books — every slot-freeing path must go
        through here or pages leak."""
        self._slot_req[slot] = None
        self.engine.free_slot(self._cache, slot)
        self._positions[slot] = 0
        self._tokens[slot] = 0

    def _rebuild_after_fault(self, error: BaseException):
        """A device-level fault poisoned the cache: fail every in-flight
        request EXCEPT the ones a durable session can deterministically
        resume (tentpole 2 — only genuinely unjournaled work is lost,
        bounded by the journal cadence), zero the slot books, and
        rebuild the page pool. Returns the resumable survivors for
        :meth:`_replace_survivors`. With sessions off every slot fails,
        byte-identical to the pre-session behavior."""
        survivors: List[_GenRequest] = []
        for slot, req in enumerate(self._slot_req):
            if req is not None:
                if (self._sessions and req.session is not None
                        and not req.session.stolen and not req._claimed
                        and req.resumes < 3):
                    req.resumes += 1
                    survivors.append(req)
                else:
                    self._fail_request(req, error)
            self._slot_req[slot] = None
        self._tokens[:] = 0
        self._positions[:] = 0
        self._cache = self.engine.new_state(self.slots,
                                            pages=self._cache_pages)
        return survivors

    def _replace_survivors(self, survivors: List[_GenRequest],
                           error: BaseException):
        """Re-prefill fault survivors into the rebuilt cache (all slots
        are free when this runs). A survivor that cannot re-place —
        pool too small for its grown context, or its re-prefill fails
        again — resolves with the original fault."""
        if not survivors:
            return
        _session_mod().session_metrics().resumes.inc(len(survivors))
        _faults.record_event("session_resume_inplace",
                             count=len(survivors))
        slot_i = 0
        for req in survivors:
            if slot_i >= self.slots:
                self._fail_request(req, error)
                continue
            if self._start_request(req, slot_i):
                slot_i += 1

    def _start_request(self, req: _GenRequest, slot: int) -> bool:
        """Prefill ``req`` into ``slot``'s cache pages. Returns True when
        the slot is now occupied (False: resolved without occupying)."""
        obs = _GenMetrics.get()
        if req._claimed:
            return False          # caller already walked away — no work
        req.t_slot_us = now_us()
        if req.ctx is not None:
            # the join-latency phase continuous batching exists to shrink
            record_span("slot_wait", req.t_enqueue_us, req.t_slot_us,
                        ctx=req.ctx, slot=slot)
        t0 = time.perf_counter()
        t_us = now_us()
        # a resumed request re-prefills prompt + already-emitted tokens:
        # the cache rebuilds to exactly the state the lost slot held, and
        # the in-graph seeded sampler continues the identical stream
        # (byte-identical under greedy)
        k_resumed = len(req.out)
        x_in = (np.concatenate([req.x, np.asarray(req.out, np.int32)])
                if k_resumed else req.x)
        try:
            with _span("prefill", slot=slot,
                       prompt_tokens=int(x_in.size)):
                first, _logits, kv, t = self.engine.prefill(
                    x_in[None], step=self._step)
        except Exception as e:
            # prefill failed BEFORE the insert donated anything — the
            # live cache is intact, only the joiner dies
            if self._breaker is not None:
                self._breaker.record_failure()
            self._fail_request(req, e)
            return False
        try:
            with _span("prefill", slot=slot, phase="insert"):
                self._cache = self.engine.insert_slot(self._cache, kv, slot)
                if self.engine.spec:
                    # the draft tracks the same prompt in its own dense
                    # cache — a failure here cannot touch the target
                    # pool (handled below)
                    self.engine.insert_draft_slot(self._cache, slot,
                                                  x_in[None],
                                                  step=self._step)
                first_tok = int(np.asarray(first)[0])
            dt = time.perf_counter() - t0
            if req.ctx is not None:
                record_span("prefill", t_us, now_us(), ctx=req.ctx,
                            slot=slot, prompt_tokens=int(req.x.size))
            obs.prefill_latency.observe(dt)
            _cost.global_cost_model().observe_time(PREFILL_FN, dt)
            if req.tenant is not None:
                req.cost_flops += _cost.global_cost_model().flops_for(
                    PREFILL_FN)
            if self._breaker is not None:
                self._breaker.record_success()
        except CachePagesExhausted as e:
            # raised BEFORE any device write (the paged insert checks
            # the free list first): the live cache is intact, only the
            # joiner sheds typed — _admit normally parks it first, so
            # this is the belt-and-braces path
            self._shed_request(req, "pages_exhausted", e)
            return False
        except Exception as e:
            if self._breaker is not None:
                self._breaker.record_failure()
            self._fail_request(req, e)
            if isinstance(e, (ValueError, TypeError)):
                # a POISONED REQUEST (bad shapes/dtypes/values raised by
                # validation before any device write): the live cache is
                # intact — one bad joiner must never take down every
                # in-flight stream (blast-radius fix, pinned by a test)
                return False
            # device-level: insert DONATED live cache arrays before
            # dying — its pages are gone, so every active generation
            # lost its cache: rebuild the pages, resume the journaled
            # sessions in place, and fail the rest with the real insert
            # error (not the deleted-buffer error one step later)
            survivors = self._rebuild_after_fault(e)
            self._replace_survivors(survivors, e)
            return False
        req.out.append(first_tok)
        self._session_append(req, first_tok)
        # the generation budget may be clipped by the cache length —
        # never write a position past the preallocated pages. On resume
        # (len(out)-1 == k pre-existing tokens) the budget already spent
        # k of its allowance; the cache-room clip applies to the REST
        cap = min(req.max_new_tokens,
                  (len(req.out) - 1) + self.engine.max_len - t)
        req.max_new_tokens = cap
        done = (len(req.out) >= cap
                or (req.eos_id is not None and first_tok == req.eos_id))
        obs.tokens.inc()
        if not self._emit_token(req, first_tok):
            if done:
                self._resolve(req)       # complete anyway: result is whole
            else:
                self._shed_request(req, "client_gone", StreamCancelled(
                    "streaming consumer cancelled during prefill"))
            self.engine.free_slot(self._cache, slot)
            return False
        if done:
            self._resolve(req)
            self.engine.free_slot(self._cache, slot)
            return False
        self._slot_req[slot] = req
        self._tokens[slot] = first_tok
        self._positions[slot] = t
        return True

    def _maybe_preempt(self, pri: Optional[float] = None) -> bool:
        """Priority preemption at a step boundary (QoS posture): when
        the contending tier — the highest QUEUED tier by default, or an
        explicit ``pri`` for a page-starved parked joiner — strictly
        exceeds some active slot's tier, that slot's request is shed
        typed
        (:class:`~deeplearning4j_tpu.resilience.qos.PreemptedError`) and
        the slot freed (its cache pages with it: under the paged engine
        the bottleneck is usually PAGES, not slots, and preemption must
        fire there too or the PR-12 priority guarantee silently dies in
        the default mode). The victim: among lower-tier active slots,
        the most over-share tenant's longest-running request (slot
        frees and joins already happen exactly here — the preempted
        caller resolves typed, never hangs). Default tiers (0
        everywhere) never preempt."""
        if pri is None:
            pri = self._queue.peek_priority()
        if pri is None:
            return False
        reg = _qos.global_tenants()
        active = [(slot, r) for slot, r in enumerate(self._slot_req)
                  if r is not None]
        cands = [(slot, r) for slot, r in active
                 if reg.priority(r.tenant) < pri]
        if not cands:
            return False
        counts: dict = {}
        for _, r in active:
            counts[r.tenant] = counts.get(r.tenant, 0) + 1
        wsum = sum(reg.weight(t) for t in counts) or 1.0

        def over_share(t):
            return counts[t] / max(1e-9,
                                   len(active) * reg.weight(t) / wsum)

        victim_slot, victim = max(
            cands, key=lambda sr: (over_share(sr[1].tenant),
                                   -sr[1].t_slot_us))
        self._shed_request(victim, "preempted", _qos.PreemptedError(
            f"generation slot {victim_slot} preempted by a higher-"
            f"priority tenant at a decode step boundary"))
        self._free_slot(victim_slot)
        return True

    def _admit(self):
        """Join queued requests into free slots at this step boundary.
        Paged mode admits on FREE PAGES, not free slots: a popped
        request whose prompt the pool cannot back yet is parked in
        ``_waiting`` and retried at every boundary (pages free exactly
        there) before the queue is touched — admission resumes the
        moment reclamation or completions return enough pages.
        (Blocking briefly only when the whole pipeline is idle.)"""
        while not self._stop.is_set():
            free = [i for i, r in enumerate(self._slot_req) if r is None]
            if not free:
                if self._qos and self._maybe_preempt():
                    continue       # a slot was freed — re-scan and join
                return
            req, self._waiting = self._waiting, None
            if req is not None:
                if req._claimed:
                    continue        # parked caller already walked away
                if (self._resilience and req.deadline is not None
                        and req.deadline.expired()):
                    self._shed_request(req, "deadline", DeadlineExceeded(
                        "request expired waiting for cache pages"))
                    continue
            else:
                idle = len(free) == self.slots
                req = self._take_request(timeout=0.05 if idle else 0.0)
            if req is None:
                return
            if (self.engine.paged
                    and self.engine.min_pages_for_prompt(
                        req.x.size + len(req.out))
                    > self._cache.alloc.free_count):
                # can't back the prompt yet; active slots still hold
                # pages (generate() pre-checked the empty-pool fit, so
                # an idle pipeline always admits). A higher-tier
                # tenant's joiner may PREEMPT a lower-tier slot for its
                # pages — the paged twin of the slots-full preemption
                # above (page pressure is the common overload state
                # under a bounded pool)
                if self._qos and self._maybe_preempt(
                        pri=_qos.global_tenants().priority(req.tenant)):
                    self._waiting = req
                    continue       # pages came back — retry this joiner
                self._waiting = req
                return
            _GenMetrics.get().queue_depth.set(self._queue.qsize())
            self._start_request(req, free[0])

    def _reclaim_victim_key(self, slot: int):
        """Reclamation victim ordering (max wins): shed sessions with
        NOTHING journaled before sessions the journal already made
        durable, youngest first within each class — under page pressure
        a worker sheds NEW sessions before evicting journaled ones
        (tentpole 4). With sessions off every slot is "unjournaled" and
        the key degenerates to the pre-session pure youngest-first."""
        req = self._slot_req[slot]
        unjournaled = True
        if self._sessions and req.session is not None:
            unjournaled = req.session.journaled == 0
        return (unjournaled, req.t_slot_us)

    def _reclaim_pages(self, active: List[int]) -> List[int]:
        """Step-boundary reclamation: grow every active slot's pages for
        this step's writes (spec windows reach ``spec_k`` further); on
        pool exhaustion the YOUNGEST active request is shed typed
        (:class:`CachePagesExhausted`) and its pages return to the
        pool, until the survivors fit. Returns the surviving active
        list — deterministic, oldest generations win."""
        if not self.engine.paged:
            return active
        reach = self.engine.spec_k if self.engine.spec else 0
        for slot in sorted(active,
                           key=lambda s: self._slot_req[s].t_slot_us):
            req = self._slot_req[slot]
            if req is None:
                continue            # already shed as a victim below
            last = min(int(self._positions[slot]) + reach,
                       self.engine.max_len - 1)
            while not self.engine.ensure_slot_pages(self._cache, slot,
                                                    last):
                # victim = the youngest ACTIVE request, whether or not
                # it is the one needing the page — oldest generations
                # win unconditionally (shedding an elder because a
                # newcomer grew would invert the policy)
                cands = [s for s in active
                         if self._slot_req[s] is not None]
                victim = max(cands, key=self._reclaim_victim_key)
                self._shed_request(
                    self._slot_req[victim], "pages_exhausted",
                    CachePagesExhausted(
                        "KV page pool exhausted at a decode step "
                        "boundary; request shed to reclaim pages"))
                self._free_slot(victim)
                if victim == slot:
                    break
        return [s for s in active if self._slot_req[s] is not None]

    def _sweep_finished(self, emitted: Dict[int, List[int]]):
        """Post-step bookkeeping for every stepped slot: append its
        emitted tokens IN ORDER (one for a plain decode step, up to
        ``spec_k`` for a speculative round), then resolve/free finished,
        cancelled, or expired requests. A request finishing mid-window
        simply ignores the window's tail — same semantics as plain
        decode stopping at its boundary."""
        obs = _GenMetrics.get()
        # each occupied slot owns 1/slots of the step boundary's
        # accounted FLOPs (the whole slot batch runs whether occupied or
        # not — charging per OCCUPIED slot would make a lonely tenant
        # look cheap while it monopolizes the executable). A spec round
        # ran propose + verify, never the one-token decode executable —
        # charge what actually executed.
        step_share = 0.0
        if self._qos:
            cm = _cost.global_cost_model()
            flops = ((cm.flops_for(VERIFY_FN) + cm.flops_for(PROPOSE_FN))
                     if self.engine.spec else cm.flops_for(DECODE_FN))
            step_share = flops / max(1, self.slots)
        for slot, toks_l in emitted.items():
            req = self._slot_req[slot]
            if req is None:
                continue
            if req._claimed:
                # another path already resolved it (the caller's
                # deadline walk-away) — stop spending device steps on a
                # request nobody will read (racy read is safe: worst
                # case is one extra step before the slot frees)
                self._free_slot(slot)
                continue
            if req.session is not None and req.session.stolen:
                # another worker fence-bumped this session away (it
                # adopted the stream mid-failover while we were merely
                # stalled): stop decoding NOW — continuing would
                # double-decode, and our journal writes are already
                # fenced off
                self._shed_request(req, "session_lost",
                                   _session_mod().SessionLost(
                                       "session adopted by another "
                                       "worker (lease fenced)"))
                self._free_slot(slot)
                continue
            if req.tenant is not None:
                req.cost_flops += step_share
            done = cancelled = False
            for tok in toks_l:
                req.out.append(int(tok))
                self._session_append(req, tok)
                obs.tokens.inc()
                done = (len(req.out) >= req.max_new_tokens
                        or (req.eos_id is not None
                            and int(tok) == req.eos_id))
                if not self._emit_token(req, int(tok)) and not done:
                    cancelled = True
                    break
                if done:
                    break
            expired = (self._resilience and req.deadline is not None
                       and req.deadline.expired())
            if cancelled:
                # consumer gone mid-stream: free the slot NOW — other
                # slots keep decoding, nothing leaks
                self._shed_request(req, "client_gone", StreamCancelled(
                    "streaming consumer cancelled mid-stream"))
                self._free_slot(slot)
            elif expired and not done:
                self._shed_request(req, "deadline", DeadlineExceeded(
                    "request expired at a decode step boundary"))
                self._free_slot(slot)
            elif done:
                self._resolve(req)
                self._free_slot(slot)

    def _decode_loop(self):
        while not self._stop.is_set():
            # re-fetch per iteration: a registry reset mid-flight drops
            # and re-binds the singleton (on_registry_reset) — a cached
            # handle would keep writing to detached instruments
            obs = _GenMetrics.get()
            self._admit()
            active = [i for i, r in enumerate(self._slot_req)
                      if r is not None]
            obs.slots_in_use.set(len(active))
            if not active:
                continue
            try:
                if self._resilience:
                    self._retry.call(
                        lambda: _faults.check("generation.step"),
                        op="generation.step")
                active = self._reclaim_pages(active)
                if not active:
                    self._step += 1
                    self._publish_cache_bytes()
                    continue
                t0 = time.perf_counter()
                if self.engine.spec:
                    with _span("decode_step", active=len(active),
                               slots=self.slots, spec=True):
                        emitted = self.engine.spec_step(
                            self._cache, self._tokens, self._positions,
                            self._step, active)
                    for slot, toks_l in emitted.items():
                        # the last emitted token is the next carry; the
                        # cache advanced one row per emitted token
                        self._tokens[slot] = toks_l[-1]
                        self._positions[slot] += len(toks_l)
                else:
                    with _span("decode_step", active=len(active),
                               slots=self.slots):
                        tokens, _logits, self._cache = self.engine.decode(
                            self._cache, self._tokens, self._positions,
                            self._step)
                        toks = np.asarray(tokens)  # device→host sync
                    self._tokens[active] = toks[active]
                    self._positions[active] += 1
                    emitted = {s: [int(toks[s])] for s in active}
                dt = time.perf_counter() - t0
                obs.step_latency.observe(dt)
                obs.steps.inc()
                obs.occupancy.observe(len(active) / max(1, self.slots))
                if self.engine.spec:
                    # the round's wall time covers the fused propose +
                    # the windowed verify — book it against the verify
                    # entry (the dominant executable), NEVER the
                    # one-token decode step that did not run
                    _cost.global_cost_model().observe_time(VERIFY_FN, dt)
                    if self._fresh_spec_compile():
                        self.engine.account_spec(
                            self._cache, self._tokens, self._positions,
                            self._step)
                else:
                    _cost.global_cost_model().observe_time(DECODE_FN, dt)
                    if self._fresh_decode_compile():
                        self.engine.account_decode(
                            self._cache, self._tokens, self._positions,
                            self._step)
                if self._breaker is not None:
                    self._breaker.record_success()
                _flight().progress("generation_step")
            # graftlint: disable=typed-errors — the catch must be broad
            # (any step fault poisons the donated cache); the taxonomy
            # is resolved per-request via _fail_request/_shed_request
            except Exception as e:
                if (self._breaker is not None
                        and not isinstance(e, _TYPED_OUTCOMES)):
                    self._breaker.record_failure()
                # the step died mid-donation: the cache buffers are no
                # longer trustworthy — rebuild the pages, resume the
                # journaled sessions in place (tentpole 2; the in-graph
                # seed makes the continued stream deterministic), and
                # fail the rest (queued requests are untouched; the
                # fresh state resets the page allocator and, in spec
                # mode, the draft cache with it)
                survivors = self._rebuild_after_fault(e)
                self._step += 1
                self._replace_survivors(survivors, e)
                self._notify_journal()
                self._publish_cache_bytes()
                continue
            self._step += 1
            self._sweep_finished(emitted)
            self._notify_journal()
            self._publish_cache_bytes()
        # shutdown: resolve whatever still occupies a slot (and the
        # parked joiner the pool never backed)
        for slot, req in enumerate(self._slot_req):
            if req is not None:
                self._fail_request(req, ShutdownError(
                    "GenerationPipeline shut down"))
                self._slot_req[slot] = None
        if self._waiting is not None:
            self._fail_request(self._waiting, ShutdownError(
                "GenerationPipeline shut down"))
            self._waiting = None

    def _notify_journal(self):
        """Step-boundary poke for the session journal writer — an
        ``Event.set``, the only hot-path cost journaling adds to the
        decode loop (the batched store write happens on the journal's
        own thread)."""
        if self._sessions:
            _session_mod().global_journal().notify()

    def _fresh_decode_compile(self) -> bool:
        """True when compile_watch counted a decode trace the cost model
        has not analyzed yet (kept cheap: one counter compare)."""
        try:
            return _cost.global_cost_model().needs_account(DECODE_FN,
                                                           DECODE_FN)
        except Exception:  # graftlint: disable=typed-errors — best-effort
            return False   # cost-telemetry probe; no request outcome here

    def _fresh_spec_compile(self) -> bool:
        """The spec twin: a fresh propose OR verify trace pending cost
        analysis."""
        try:
            cm = _cost.global_cost_model()
            return (cm.needs_account(VERIFY_FN, VERIFY_FN)
                    or cm.needs_account(PROPOSE_FN, PROPOSE_FN))
        except Exception:  # graftlint: disable=typed-errors — best-effort
            return False   # cost-telemetry probe; no request outcome here

    # -------------------------------------------------------- lifecycle
    def shutdown(self):
        self._stop.set()
        with self._not_full:
            self._not_full.notify_all()
        self._thread.join(timeout=5.0)
        if self._breaker is not None:
            self._breaker.retire()
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            self._fail_request(req, ShutdownError(
                "GenerationPipeline shut down"))
        _GenMetrics.get().queue_depth.set(self._queue.qsize())
        self._publish_cache_bytes()

    def snapshot(self) -> dict:
        """Live pipeline state (``/debug/generation`` + the
        flight-recorder ``generation.json`` payload)."""
        slots = []
        tenants: dict = {}
        for i, req in enumerate(self._slot_req):
            if req is None:
                slots.append({"slot": i, "state": "free"})
            else:
                slots.append({
                    "slot": i, "state": "decoding",
                    "position": int(self._positions[i]),
                    "generated": len(req.out),
                    "max_new_tokens": req.max_new_tokens,
                    "tenant": req.tenant,
                    "session": (req.session.sid
                                if req.session is not None else None),
                    "resumes": req.resumes,
                    "trace_id": (req.ctx.trace_id
                                 if req.ctx is not None else None)})
                if req.tenant is not None:
                    t = tenants.setdefault(req.tenant,
                                           {"active_slots": 0,
                                            "queued": 0})
                    t["active_slots"] += 1
        if self._qos:
            for t, n in self._queue.tenant_sizes().items():
                tenants.setdefault(t, {"active_slots": 0,
                                       "queued": 0})["queued"] = n
        eng = self.engine
        pages = None
        st = self._cache
        if eng.paged and st is not None and st.alloc is not None:
            pages = {
                "page_tokens": eng.page_tokens,
                "pages_per_slot": eng.pages_per_slot,
                "in_use": st.alloc.in_use,
                "total": st.alloc.total,
                "page_bytes": eng.page_bytes(),
                "quant": bool(eng.kv_quant),
                "quant_gate": eng.quant_gate,
                "waiting_for_pages": self._waiting is not None,
                "slot_pages": [len(p) for p in st.slot_pages],
            }
        spec = None
        if eng.draft is not None:
            ratio = eng.spec_accept_ratio()
            spec = {
                "enabled": eng.spec,
                "spec_k": eng.spec_k,
                "rounds": eng.spec_stats["rounds"],
                "proposed": eng.spec_stats["proposed"],
                "accepted": eng.spec_stats["accepted"],
                "accept_ratio": (round(ratio, 4)
                                 if ratio is not None else None),
            }
        return {
            "qos": self._qos,
            "sessions": self._sessions,
            "tenants": tenants,
            "slots": self.slots,
            "active": self._n_active(),
            "queue_depth": self._queue.qsize(),
            "step": self._step,
            "max_len": self.engine.max_len,
            "prefill_buckets": list(self.engine.prefill_buckets),
            "sampler": {"kind": self.engine.sampler.kind,
                        "top_k": self.engine.sampler.top_k,
                        "temperature": self.engine.sampler.temperature},
            "cache_bytes": self._safe_cache_bytes(),
            "pool_bytes": self._safe_pool_bytes(),
            "pages": pages,
            "spec": spec,
            "slot_table": slots,
        }

    def _safe_cache_bytes(self):
        """The decode thread may be mid-step (old cache donated away)
        when a /debug or bundle snapshot races this read — answer None
        for that instant rather than raising into the dump. Reports
        ACTUAL resident bytes (paged: pages in use x page bytes)."""
        try:
            return self.engine.resident_cache_bytes(self._cache)
        except Exception:  # graftlint: disable=typed-errors — snapshot
            return None    # reader racing the decode thread; answers None

    def _safe_pool_bytes(self):
        """Worst-case device footprint (the whole pool + draft cache) —
        the snapshot reports it next to the resident number."""
        try:
            return DecodeEngine.cache_bytes(self._cache)
        except Exception:  # graftlint: disable=typed-errors — snapshot
            return None    # reader racing the decode thread; answers None

    @classmethod
    def live_snapshots(cls) -> list:
        return [gp.snapshot() for gp in list(cls._live)
                if not gp._stop.is_set()]
