"""Compressed gradient exchange — error-feedback threshold collectives.

Reference: ``EncodedGradientsAccumulator`` + ``ThresholdAlgorithm`` (Strom
2015; SURVEY P3/D7 — the reference's flagship distributed path, where each
worker ships only the gradient entries whose magnitude clears a threshold
and keeps the remainder as a local *residual* that re-enters the next
step's accumulator). This module is the TPU-native redesign of that stack:

- **ThresholdAlgorithm family** (`FixedThresholdAlgorithm`,
  `AdaptiveThresholdAlgorithm` — mirroring
  ``org.deeplearning4j.optimize.solvers.accumulation.encoding``): the
  threshold is carried as first-class training state and, for the adaptive
  variant, adjusted *in-graph* toward a target encoded fraction.
- **Bucketed flattening**: the gradient pytree is flattened into
  dtype-homogeneous 1-D buckets, so the exchange is one collective per
  bucket (not per leaf) and threshold capacity is global across the whole
  tree rather than per-leaf.
- **Dense sign-mask wire form**: XLA needs static shapes, so the payload
  that crosses the ``data`` axis is the codec's dense form (ops/standard's
  ``encode_threshold`` sign mask, int8) plus a per-bucket scale — 1 byte
  per element vs 4 for the dense f32 allreduce. The sparse ±(idx+1) wire
  format (kernels/threshold.py) and the native host op remain the
  DCN/host-boundary forms; ``sparse_from_dense``/``dense_from_sparse``
  convert between them (parity-tested).
- **Error feedback**: each replica keeps ``residual = acc − sent`` where
  ``acc = grad + residual_prev``; the residual rides the model checkpoint
  (``gradCompression.npz``) so restore-resume replays byte-equal.

The actual train-step wiring lives in ``parallel/trainer.py``
(:class:`ShardedTrainer`); this module owns the algorithm/state/codec
pieces so they are testable without a mesh.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: env knob: ``0`` = kill switch (dense path, byte-identical), ``1`` /
#: ``adaptive[:init[:min:max]]`` / ``fixed[:threshold]`` = enable
ENV_KNOB = "DL4J_TPU_GRAD_COMPRESS"


# ---------------------------------------------------------------- algorithms
class ThresholdAlgorithm:
    """Base threshold policy (ref: ``encoding.ThresholdAlgorithm``).

    ``initial_threshold`` seeds the carried per-bucket threshold state;
    :meth:`update` runs *inside the jitted step* on the globally averaged
    encoded fraction, so every replica computes the identical next
    threshold (decode correctness requires a replica-uniform threshold).
    """

    initial_threshold: float = 1e-3

    def update(self, threshold: jnp.ndarray,
               encoded_fraction: jnp.ndarray) -> jnp.ndarray:
        return threshold

    def describe(self) -> dict:
        return {"algorithm": type(self).__name__,
                "initial_threshold": float(self.initial_threshold)}


@dataclasses.dataclass(frozen=True)
class FixedThresholdAlgorithm(ThresholdAlgorithm):
    """ref: ``FixedThresholdAlgorithm`` — constant threshold."""
    initial_threshold: float = 1e-3


@dataclasses.dataclass(frozen=True)
class AdaptiveThresholdAlgorithm(ThresholdAlgorithm):
    """ref: ``AdaptiveThresholdAlgorithm`` — drive the threshold so the
    encoded fraction (the reference's "sparsity ratio") stays inside
    [min_target, max_target]: too few entries encoded ⇒ decay the
    threshold (encode more); too many ⇒ grow it. The decay factor matches
    the reference default (0.95 per step in violation)."""
    initial_threshold: float = 1e-3
    min_target_fraction: float = 1e-4
    max_target_fraction: float = 1e-2
    decay_rate: float = 0.95

    def update(self, threshold, encoded_fraction):
        t = jnp.where(encoded_fraction < self.min_target_fraction,
                      threshold * self.decay_rate, threshold)
        t = jnp.where(encoded_fraction > self.max_target_fraction,
                      t / self.decay_rate, t)
        return jnp.clip(t, 1e-10, 1e6)

    def describe(self) -> dict:
        d = ThresholdAlgorithm.describe(self)
        d.update(min_target_fraction=self.min_target_fraction,
                 max_target_fraction=self.max_target_fraction,
                 decay_rate=self.decay_rate)
        return d


def algorithm_from_spec(spec) -> Optional[ThresholdAlgorithm]:
    """Resolve a builder arg / env value into a ThresholdAlgorithm.

    Accepted: a ThresholdAlgorithm instance (pass-through), ``True`` /
    ``"1"`` (adaptive defaults), ``"adaptive[:init[:min:max]]"``,
    ``"fixed[:threshold]"``. ``None`` / ``False`` / ``"0"`` / ``""`` →
    None (compression off)."""
    if spec is None or spec is False:
        return None
    if isinstance(spec, ThresholdAlgorithm):
        return spec
    if spec is True:
        return AdaptiveThresholdAlgorithm()
    s = str(spec).strip()
    if s in ("", "0"):
        return None
    if s == "1":
        return AdaptiveThresholdAlgorithm()
    parts = s.split(":")
    kind, args = parts[0].lower(), parts[1:]
    try:
        if kind == "fixed":
            if len(args) > 1:
                raise ValueError(
                    f"bad {ENV_KNOB} spec {s!r}: fixed takes at most one "
                    "argument (the threshold)")
            return FixedThresholdAlgorithm(
                initial_threshold=float(args[0]) if args else 1e-3)
        if kind == "adaptive":
            # grammar: adaptive[:init[:min:max]] — 0, 1, or 3 args; any
            # other arity is a mis-config that must raise, not silently
            # fall back to the default target band
            if len(args) not in (0, 1, 3):
                raise ValueError(
                    f"bad {ENV_KNOB} spec {s!r}: adaptive takes 0, 1 "
                    "(init) or 3 (init:min:max) arguments, got "
                    f"{len(args)}")
            kw = {}
            if args:
                kw["initial_threshold"] = float(args[0])
            if len(args) == 3:
                kw["min_target_fraction"] = float(args[1])
                kw["max_target_fraction"] = float(args[2])
            return AdaptiveThresholdAlgorithm(**kw)
    except ValueError as e:
        raise ValueError(f"bad {ENV_KNOB} spec {s!r}: {e}") from None
    raise ValueError(
        f"bad {ENV_KNOB} spec {s!r} (want 0 | 1 | fixed[:thr] | "
        f"adaptive[:init[:min:max]])")


def resolve_compression(arg=None) -> Optional[ThresholdAlgorithm]:
    """Builder arg + env knob → active algorithm (None = dense path).

    The env knob ``0`` is the KILL SWITCH: it forces the dense path even
    when a builder arg / SharedTrainingMaster algorithm asked for
    compression (byte-identical-rollback contract, like the other
    DL4J_TPU_* masters). Otherwise an explicit arg wins; with no arg the
    env spec decides. Read live (at placement time) so tests can flip it.
    """
    env = os.environ.get(ENV_KNOB, "").strip()
    if env == "0":
        return None
    if arg is not None:
        return algorithm_from_spec(arg)
    return algorithm_from_spec(env) if env else None


# ----------------------------------------------------------------- buckets
@dataclasses.dataclass(frozen=True)
class _LeafSlot:
    bucket: int          # bucket index
    offset: int          # start offset in the bucket's 1-D buffer
    size: int
    shape: Tuple[int, ...]
    dtype: str


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Flattening plan: gradient pytree ↔ dtype-homogeneous 1-D buckets.

    Leaves are grouped by canonical dtype in tree-flatten order, so the
    collective count collapses from one-per-leaf to one-per-dtype and the
    threshold applies over the WHOLE tree's mass (global capacity), not
    per-leaf. The layout is built once per placement from the param tree
    (grads share its structure) and is static thereafter.
    """
    treedef: object
    slots: Tuple[_LeafSlot, ...]
    bucket_dtypes: Tuple[str, ...]
    bucket_sizes: Tuple[int, ...]

    @property
    def n_buckets(self) -> int:
        return len(self.bucket_sizes)

    def total_elements(self) -> int:
        return sum(self.bucket_sizes)


def build_layout(tree) -> BucketLayout:
    leaves, treedef = jax.tree.flatten(tree)
    by_dtype: Dict[str, int] = {}
    offsets: List[int] = []
    slots = []
    sizes: List[int] = []
    dtypes: List[str] = []
    for leaf in leaves:
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            raise ValueError(
                f"gradient leaf with non-float dtype {leaf.dtype} cannot "
                "join a compressed bucket")
        dt = jnp.dtype(leaf.dtype).name
        if dt not in by_dtype:
            by_dtype[dt] = len(sizes)
            sizes.append(0)
            dtypes.append(dt)
            offsets.append(0)
        b = by_dtype[dt]
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        slots.append(_LeafSlot(b, offsets[b], size, tuple(leaf.shape), dt))
        offsets[b] += size
        sizes[b] += size
    return BucketLayout(treedef, tuple(slots), tuple(dtypes), tuple(sizes))


def flatten_buckets(tree, layout: BucketLayout) -> List[jnp.ndarray]:
    """Pytree → per-dtype 1-D buckets (f32 compression workspace)."""
    leaves = jax.tree.leaves(tree)
    parts: List[List[jnp.ndarray]] = [[] for _ in layout.bucket_sizes]
    for leaf, slot in zip(leaves, layout.slots):
        parts[slot.bucket].append(
            jnp.ravel(leaf).astype(jnp.float32))
    return [jnp.concatenate(p) if len(p) > 1 else p[0] for p in parts]


def unflatten_buckets(buckets: Sequence[jnp.ndarray],
                      layout: BucketLayout):
    """Per-dtype 1-D buckets → pytree (leaves restored to their original
    dtype/shape)."""
    leaves = []
    for slot in layout.slots:
        seg = jax.lax.dynamic_slice_in_dim(
            buckets[slot.bucket], slot.offset, slot.size)
        leaves.append(seg.reshape(slot.shape).astype(jnp.dtype(slot.dtype)))
    return jax.tree.unflatten(layout.treedef, leaves)


# -------------------------------------------------------------------- codec
def wire_dtype(n_replicas: int):
    """Sign-sum wire dtype: the psum of ±1 entries is bounded by the
    replica count, so int8 carries meshes up to 127 wide; wider meshes
    fall back to int16 (accounting follows the itemsize)."""
    return jnp.int8 if n_replicas <= 127 else jnp.int16


def encode_dense(acc: jnp.ndarray, threshold) -> jnp.ndarray:
    """Dense sign-mask encode (the in-graph form of ops/standard.py's
    ``encode_threshold``): int8 in {-1, 0, +1}, static shape."""
    return jnp.where(jnp.abs(acc) >= threshold,
                     jnp.sign(acc), 0.0).astype(jnp.int8)


def exchange_bucket(acc: jnp.ndarray, threshold, axis_name: str,
                    n_replicas: int):
    """One bucket's threshold exchange, inside a ``shard_map`` body over
    ``axis_name`` — THE single spelling of the encode/scale/psum/decode
    pipeline (ShardedTrainer's compressed step and the allreduce A/B
    benchmark both call this, so the benchmark cannot drift from what
    training actually runs).

    Returns ``(decoded, sent, fired, frac)``: the replica-mean decoded
    gradient, this replica's shipped mass (``residual' = acc − sent``),
    the fired {0,1} mask, and the replica-mean encoded fraction.

    The per-bucket decode SCALE is the mean |magnitude| of the entries
    that cleared the threshold, psum-averaged over the replicas that
    fired anything. Decoding at ±scale instead of the reference's flat
    ±threshold keeps the encoded mass magnitude-faithful (scaled-sign
    with error feedback), which downstream adaptive optimizers need —
    flat ±threshold decode starves Adam's moments."""
    signs = encode_dense(acc, threshold)
    fired = jnp.abs(signs).astype(jnp.float32)
    k = jnp.sum(fired)
    scale_local = jnp.sum(jnp.abs(acc) * fired) / jnp.maximum(k, 1.0)
    has = (k > 0).astype(jnp.float32)
    scale = jax.lax.psum(scale_local * has, axis_name) \
        / jnp.maximum(jax.lax.psum(has, axis_name), 1.0)
    sent = signs.astype(jnp.float32) * scale
    # the compact payload that crosses the wire: the sign entries
    # (psum'd — bounded by the replica count) + one f32 scale scalar
    wire = jax.lax.psum(signs.astype(wire_dtype(n_replicas)), axis_name)
    decoded = wire.astype(jnp.float32) * (scale / n_replicas)
    frac = jax.lax.pmean(jnp.mean(fired), axis_name)
    return decoded, sent, fired, frac


def payload_bytes(layout: BucketLayout, n_replicas: int) -> int:
    """Analytic per-step wire payload of the compressed exchange: one
    sign entry per element plus one f32 scale + one f32 encoded-fraction
    scalar per bucket."""
    itemsize = jnp.dtype(wire_dtype(n_replicas)).itemsize
    return layout.total_elements() * itemsize + 8 * layout.n_buckets


def dense_bytes(layout: BucketLayout) -> int:
    """What the dense allreduce would move: the full f32/bf16 leaf bytes."""
    return sum(size * jnp.dtype(dt).itemsize
               for size, dt in zip(layout.bucket_sizes,
                                   layout.bucket_dtypes))


# ------------------------------------------------------- state + checkpoint
def init_state(layout: BucketLayout, algorithm: ThresholdAlgorithm,
               n_replicas: int) -> dict:
    """Fresh compression state: per-replica residual buckets (leading
    replica axis — sharded over ``data`` at placement) + per-bucket
    threshold scalars (replicated)."""
    return {
        "residual": [jnp.zeros((n_replicas, size), jnp.float32)
                     for size in layout.bucket_sizes],
        "threshold": [jnp.float32(algorithm.initial_threshold)
                      for _ in layout.bucket_sizes],
    }


def state_matches(state: Optional[dict], layout: BucketLayout,
                  n_replicas: int) -> bool:
    """Does a (restored) state fit this layout + mesh? A topology or
    architecture change re-seeds the residual at zero instead of crashing
    (warned by the caller)."""
    if not isinstance(state, dict):
        return False
    res = state.get("residual")
    thr = state.get("threshold")
    if res is None or thr is None or len(res) != layout.n_buckets \
            or len(thr) != layout.n_buckets:
        return False
    return all(tuple(np.shape(r)) == (n_replicas, size)
               for r, size in zip(res, layout.bucket_sizes))


def reshape_state(state: Optional[dict], layout: BucketLayout,
                  n_replicas: int):
    """Carry a restored compression state across a TOPOLOGY change
    (checkpoint written on an M-replica mesh, restoring onto N replicas).

    Returns ``(state, mode)``:

    - ``("match")``      — same replica count, state reused as-is;
    - ``("rebucketed")`` — residual rows re-bucketed onto the new replica
      count: shrink (M % N == 0) group-MEANS consecutive rows, expand
      (N % M == 0) tiles each row — both preserve the replica-mean
      deferred mass the next step's error feedback contributes (the
      decode is a replica mean, so mean-preserving maps keep the
      effective update trajectory; byte-exact replay is impossible
      across a reshape and the caller warns);
    - ``("reseeded")``   — indivisible replica counts: residuals restart
      at zero;
    - ``(None, "layout_mismatch")`` — the bucket layout itself differs
      (architecture change): nothing is salvageable, caller re-inits.

    In every non-None case the THRESHOLD state is kept: thresholds are
    layout-keyed (one scalar per dtype bucket), not replica-keyed, and
    the adaptive algorithm's learned operating point survives reshaping.
    """
    if not isinstance(state, dict):
        return None, "layout_mismatch"
    res = state.get("residual")
    thr = state.get("threshold")
    if res is None or thr is None or len(res) != layout.n_buckets \
            or len(thr) != layout.n_buckets \
            or any(np.ndim(r) != 2 or np.shape(r)[1] != size
                   for r, size in zip(res, layout.bucket_sizes)):
        return None, "layout_mismatch"
    old_n = int(np.shape(res[0])[0])
    if any(int(np.shape(r)[0]) != old_n for r in res):
        return None, "layout_mismatch"
    thresholds = [jnp.asarray(t, jnp.float32) for t in thr]
    if old_n == n_replicas:
        return {"residual": [jnp.asarray(r, jnp.float32) for r in res],
                "threshold": thresholds}, "match"
    if old_n % n_replicas == 0:
        g = old_n // n_replicas
        new_res = [jnp.mean(jnp.asarray(r, jnp.float32).reshape(
            n_replicas, g, -1), axis=1) for r in res]
        return {"residual": new_res, "threshold": thresholds}, "rebucketed"
    if n_replicas % old_n == 0:
        g = n_replicas // old_n
        new_res = [jnp.repeat(jnp.asarray(r, jnp.float32), g, axis=0)
                   for r in res]
        return {"residual": new_res, "threshold": thresholds}, "rebucketed"
    new_res = [jnp.zeros((n_replicas, size), jnp.float32)
               for size in layout.bucket_sizes]
    return {"residual": new_res, "threshold": thresholds}, "reseeded"


def state_to_arrays(state: dict) -> Dict[str, np.ndarray]:
    """Checkpoint form (``gradCompression.npz`` entries): residuals are
    fetched as the GLOBAL (n_replicas, size) array — the gather across
    the mesh — so a restore is byte-exact per replica."""
    out = {}
    for i, r in enumerate(state["residual"]):
        out[f"residual_{i}"] = np.asarray(r)
    for i, t in enumerate(state["threshold"]):
        out[f"threshold_{i}"] = np.asarray(t)
    return out


def state_from_arrays(arrays: Dict[str, np.ndarray]) -> Optional[dict]:
    n = sum(1 for k in arrays if k.startswith("residual_"))
    if n == 0:
        return None
    try:
        return {
            "residual": [jnp.asarray(arrays[f"residual_{i}"])
                         for i in range(n)],
            "threshold": [jnp.asarray(arrays[f"threshold_{i}"])
                          for i in range(n)],
        }
    except KeyError:
        return None
