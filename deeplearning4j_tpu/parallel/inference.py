"""ParallelInference: batched serving facade.

Reference: ``org.deeplearning4j.parallelism.ParallelInference`` (SURVEY P8) —
per-device model replicas with INSTANT / BATCHED modes. TPU-first collapse:
there is ONE compiled program; "replicas" are the mesh's data-axis shards,
and XLA already pipelines concurrent calls. What survives is the *dynamic
batching* queue: BATCHED mode coalesces concurrent small requests into one
device call (padding to the configured batch size so the executable is
reused), which is where serving throughput on an accelerator comes from.
"""
from __future__ import annotations

import queue
import threading
import time
import weakref
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span


class InferenceMode:
    INSTANT = "INSTANT"
    BATCHED = "BATCHED"


class _ServingMetrics:
    """Label-bound serving instruments (shared across instances — the
    registry aggregates; per-instance series would leak one label value
    per short-lived ParallelInference in tests)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        lat = reg.histogram(
            "dl4j_inference_latency_seconds",
            "end-to-end ParallelInference.output latency (enqueue + batch "
            "window + device forward)", label_names=("mode",))
        self.latency = {m: lat.labels(mode=m)
                        for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        req = reg.counter("dl4j_inference_requests_total",
                          "ParallelInference requests served",
                          label_names=("mode",))
        self.requests = {m: req.labels(mode=m)
                         for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        self.errors = reg.counter("dl4j_inference_errors_total",
                                  "ParallelInference requests that raised")
        self.queue_depth = reg.gauge(
            "dl4j_inference_queue_depth",
            "requests waiting in the batching queue (sampled per transition)")
        self.batch_occupancy = reg.histogram(
            "dl4j_inference_batch_occupancy",
            "coalesced examples / batch_limit per device call (1.0 = full "
            "batch, the padded-executable reuse sweet spot)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self.batches = reg.counter("dl4j_inference_batches_total",
                                   "device calls issued by the serve loop")

    @classmethod
    def get(cls) -> "_ServingMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_serving_metrics():
    _ServingMetrics._instance = None


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None


class ParallelInference:
    """ref API: ParallelInference.Builder(model).inferenceMode(...)
    .batchLimit(n).queueLimit(n).build(); output(x).

    Instances own a serve thread (BATCHED mode); call :meth:`shutdown` (or
    use as a context manager) when done. :meth:`shutdown_all` stops every
    live instance — the test harness's safety net against leaked serve
    threads keeping the process's jit caches and buffers alive."""

    _live = weakref.WeakSet()

    def __init__(self, model, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 5.0, workers: Optional[int] = None):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        # workers: shard the forward over the first N devices (the
        # reference's per-device replicas become one data-parallel SPMD
        # program); None = single-program forward on the default device
        self._trainer = None
        if workers is not None:
            import jax
            from deeplearning4j_tpu.parallel.mesh import MeshSpec
            from deeplearning4j_tpu.parallel.trainer import ShardedTrainer
            n = workers or len(jax.devices())
            self._trainer = ShardedTrainer(model, MeshSpec.data_parallel(n),
                                           devices=jax.devices()[:n])
            self._n_dev = n
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # serializes enqueue vs shutdown-drain so a request can never be
        # enqueued after the drain and hang forever
        self._lock = threading.Lock()
        if self.mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._serve_loop,
                                            daemon=True)
            self._worker.start()
        ParallelInference._live.add(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    @classmethod
    def shutdown_all(cls):
        """Stop every live instance's serve thread (test-harness teardown)."""
        for pi in list(cls._live):
            pi.shutdown()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inference_mode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n):
            self._kw["batch_limit"] = n
            return self

        batchLimit = batch_limit

        def queue_limit(self, n):
            self._kw["queue_limit"] = n
            return self

        queueLimit = queue_limit

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # ----------------------------------------------------------------- api
    def _forward(self, x: np.ndarray) -> np.ndarray:
        if self._trainer is None:
            return np.asarray(self.model.output(x))
        # pad ragged batches up to the device count so the sharded program
        # always sees a divisible leading axis
        pad = (-x.shape[0]) % self._n_dev
        if pad:
            xp = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            return np.asarray(self._trainer.output(xp))[: x.shape[0]]
        return np.asarray(self._trainer.output(x))

    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        obs = _ServingMetrics.get()
        t0 = time.perf_counter()
        if self.mode == InferenceMode.INSTANT:
            try:
                out = self._forward(x)
            except Exception:
                obs.errors.inc()
                raise
            obs.latency[InferenceMode.INSTANT].observe(
                time.perf_counter() - t0)
            obs.requests[InferenceMode.INSTANT].inc()
            return out
        req = _Request(x)
        while True:
            # non-blocking put under the lock: a blocking put here would
            # hold the lock while the queue is full and deadlock shutdown()
            with self._lock:
                if self._stop.is_set():
                    raise RuntimeError("ParallelInference has been shut down")
                try:
                    self._queue.put_nowait(req)
                    obs.queue_depth.set(self._queue.qsize())
                    break
                except queue.Full:
                    pass
            time.sleep(0.001)
        req.event.wait()
        obs.latency[InferenceMode.BATCHED].observe(time.perf_counter() - t0)
        obs.requests[InferenceMode.BATCHED].inc()
        if req.error is not None:
            obs.errors.inc()
            raise req.error
        return req.result

    def shutdown(self):
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=2.0)
        # fail any requests that were still queued so callers never hang
        with self._lock:
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.error = RuntimeError("ParallelInference shut down")
                req.event.set()

    # ---------------------------------------------------------- batch loop
    def _serve_loop(self):
        import time as _time

        obs = _ServingMetrics.get()
        held: Optional[_Request] = None  # overflow from the previous window
        while not self._stop.is_set():
            if held is not None:
                first, held = held, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            obs.queue_depth.set(self._queue.qsize())
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            # coalesce within ONE wait window, never exceeding batch_limit
            # (exceeding it would skip the fixed-shape padding and trigger
            # an XLA recompile per distinct total)
            deadline = _time.monotonic() + self.max_wait_ms / 1e3
            while total < self.batch_limit:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if total + nxt.x.shape[0] > self.batch_limit:
                    # too big for this batch: hold it locally to seed the
                    # next one — putting it back on a bounded queue that
                    # producers may have refilled would deadlock the sole
                    # consumer (and break FIFO order)
                    held = nxt
                    break
                batch.append(nxt)
                total += nxt.x.shape[0]
            try:
                X = np.concatenate([r.x for r in batch], axis=0)
                n = X.shape[0]
                # pad to batch_limit so the compiled executable is reused
                if n < self.batch_limit:
                    pad = np.zeros((self.batch_limit - n,) + X.shape[1:],
                                   X.dtype)
                    X = np.concatenate([X, pad], axis=0)
                obs.batch_occupancy.observe(n / max(self.batch_limit, 1))
                obs.batches.inc()
                with _span("inference_batch", requests=len(batch),
                           examples=n):
                    out = self._forward(X)[:n]
                off = 0
                for r in batch:
                    k = r.x.shape[0]
                    r.result = out[off:off + k]
                    off += k
                    r.event.set()
            except Exception as e:             # surface errors to callers
                for r in batch:
                    r.error = e
                    r.event.set()
        if held is not None:                   # don't strand the overflow
            held.error = RuntimeError("ParallelInference shut down")
            held.event.set()
