"""ParallelInference: batched serving facade.

Reference: ``org.deeplearning4j.parallelism.ParallelInference`` (SURVEY P8) —
per-device model replicas with INSTANT / BATCHED modes. TPU-first collapse:
there is ONE compiled program; "replicas" are the mesh's data-axis shards,
and XLA already pipelines concurrent calls. What survives is the *dynamic
batching* queue: BATCHED mode coalesces concurrent small requests into one
device call (padding so the executable is reused), which is where serving
throughput on an accelerator comes from.

Async pipeline (default; kill switch ``DL4J_TPU_ASYNC=0``): the serve loop
is split into three stages so several device batches stay in flight —

    producers → request queue → **batcher** (coalesce + pad to a
    power-of-two shape bucket) → **dispatcher** (non-blocking device
    dispatch, up to ``inflight_limit`` batches queued on the device) →
    **completer** (blocks on the device→host transfer, distributes
    per-request slices)

Batch *k+1* dispatches while batch *k*'s results transfer back. Padding
goes to the next power-of-two bucket ≤ ``batch_limit`` instead of always
``batch_limit``: a small bounded set of compiled executables
(log2(limit)+1) in exchange for far less padded compute at partial
occupancy. Under ``DL4J_TPU_ASYNC=0`` the original single-threaded loop
runs: one batch in flight, pad-to-``batch_limit``, byte-identical
synchronous behavior.

Resilience (kill switch ``DL4J_TPU_RESILIENCE=0``): requests may carry a
deadline (``output(x, deadline_ms=...)``, ``Builder.deadline_ms`` or the
``DL4J_TPU_DEADLINE_MS`` default) — the batcher sheds already-expired
requests before padding/dispatch, the completer fails expired ones with
``DeadlineExceeded``, and a window that expired whole is dropped before it
occupies an in-flight slot. ``Builder.max_queue_depth``/``shed_policy``
turn the parked-producer full-queue behavior into bounded-queue load
shedding (``reject_newest`` refuses the arriving request with
``ShedError``; ``reject_oldest`` evicts the head of the queue instead).
A per-instance ``CircuitBreaker`` watches device execution: consecutive
failures open it and callers fail fast with ``CircuitOpenError`` instead
of queueing behind a dead device; timed half-open probe batches close it.
Sheds are counted in ``dl4j_inference_shed_total{reason}``, breaker state
is ``dl4j_circuit_state{op}``, and transient injected dispatch faults are
retried under a budgeted ``RetryPolicy``. Shutdown failures now raise the
typed ``ShutdownError`` (a ``RuntimeError``) so callers and error-rate
SLOs can tell a drained instance from a dying device.

Multi-tenant QoS (kill switch ``DL4J_TPU_QOS=0``, see
``resilience/qos.py``): requests may carry a tenant label
(``output(x, tenant=...)``) — the single-FIFO queue becomes a
deficit-weighted round-robin :class:`~deeplearning4j_tpu.resilience.qos.
FairQueue` over per-tenant queues (service converges to the configured
weight ratio while backlogged), full-queue shedding evicts from the most
over-share tenant (never an under-share one), and every resolved request
is accounted per tenant: requests/latency, usage tokens (examples), and
the cost model's FLOPs share of the executed bucket.
"""
from __future__ import annotations

import bisect
import queue
import threading
import time
import weakref
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import async_runtime as _async
from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.observability import device_memory as _devmem
from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.observability.straggler import StragglerDetector
from deeplearning4j_tpu.observability.tracing import (current_context,
                                                      now_us, record_span)
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import qos as _qos
from deeplearning4j_tpu.resilience.policy import (TYPED_OUTCOMES,
                                                  CircuitBreaker,
                                                  CircuitOpenError, Deadline,
                                                  DeadlineExceeded,
                                                  RetryPolicy, ShedError,
                                                  ShutdownError,
                                                  default_deadline_ms)


class InferenceMode:
    INSTANT = "INSTANT"
    BATCHED = "BATCHED"


#: excluded from dl4j_inference_errors_total and from the circuit
#: breaker's failure accounting (see policy.TYPED_OUTCOMES — shared with
#: the serving router so the two error-rate surfaces cannot diverge)
_TYPED_OUTCOMES = TYPED_OUTCOMES


class _ServingMetrics:
    """Label-bound serving instruments (shared across instances — the
    registry aggregates; per-instance series would leak one label value
    per short-lived ParallelInference in tests)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        lat = reg.histogram(
            "dl4j_inference_latency_seconds",
            "end-to-end ParallelInference.output latency (enqueue + batch "
            "window + device forward)", label_names=("mode",))
        self.latency = {m: lat.labels(mode=m)
                        for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        req = reg.counter("dl4j_inference_requests_total",
                          "ParallelInference requests served",
                          label_names=("mode",))
        self.requests = {m: req.labels(mode=m)
                         for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        self.errors = reg.counter("dl4j_inference_errors_total",
                                  "ParallelInference requests that raised")
        self.queue_depth = reg.gauge(
            "dl4j_inference_queue_depth",
            "requests waiting in the batching queue (sampled per transition)")
        self.batch_occupancy = reg.histogram(
            "dl4j_inference_batch_occupancy",
            "coalesced examples / batch_limit per device call (1.0 = full "
            "batch, the padded-executable reuse sweet spot)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self.batches = reg.counter("dl4j_inference_batches_total",
                                   "device calls issued by the serve loop")
        self.inflight = reg.gauge(
            "dl4j_inference_inflight_batches",
            "device batches dispatched but not yet completed (serving "
            "pipeline depth; bounded by inflight_limit)")
        bucket = reg.counter(
            "dl4j_inference_bucket_total",
            "shape-bucket outcomes per device call: hit = padded shape "
            "already compiled for this instance, miss = first use",
            label_names=("outcome",))
        self.bucket_hits = bucket.labels(outcome="hit")
        self.bucket_misses = bucket.labels(outcome="miss")
        self.bucket_fill = reg.histogram(
            "dl4j_inference_bucket_fill",
            "coalesced examples / padded bucket size per device call "
            "(1.0 = zero padded compute waste)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        shed = reg.counter(
            "dl4j_inference_shed_total",
            "requests shed by admission control: queue_full (bounded-queue "
            "reject), deadline (expired before completion), circuit_open "
            "(failed fast on an open breaker)", label_names=("reason",))
        self.shed = {r: shed.labels(reason=r)
                     for r in ("queue_full", "deadline", "circuit_open")}
        # serving-side straggler flag (the detector previously watched
        # train steps only): per-device-batch dispatch→complete wall time
        # against its rolling median, so one slow padded-shape compile or
        # a wedged transfer shows up in a scrape without a trace
        self.straggler = StragglerDetector(phase="inference_batch",
                                           registry=reg)

    @classmethod
    def get(cls) -> "_ServingMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_serving_metrics():
    _ServingMetrics._instance = None


class _Request:
    __slots__ = ("x", "event", "result", "error", "ctx", "t_enqueue_us",
                 "deadline", "tenant", "_claim_lock", "_claimed")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        # QoS tenant label (None when QoS is off — the request behaves
        # exactly as pre-tenant requests did)
        self.tenant = None
        # causal trace context captured at enqueue: the serve threads stamp
        # this request's queue_wait/bucket_pad/dispatch/device/complete
        # phases into ITS trace, so one trace_id follows the request across
        # the batcher→dispatcher→completer pipeline
        self.ctx = None
        self.t_enqueue_us = 0.0
        # optional Deadline: checked by the batcher before padding, the
        # dispatcher before an in-flight slot is taken, and the completer
        # before handing the slice back
        self.deadline = None
        # exactly-once resolution: every path that would set
        # result/error (completer, _fail, shed, the caller's deadline
        # walk-away) must win claim() first — two racing resolvers can
        # never both count a shed or overwrite each other's outcome
        self._claim_lock = threading.Lock()
        self._claimed = False

    def claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True


class ParallelInference:
    """ref API: ParallelInference.Builder(model).inferenceMode(...)
    .batchLimit(n).queueLimit(n).build(); output(x).

    Instances own serve threads (BATCHED mode); call :meth:`shutdown` (or
    use as a context manager) when done. :meth:`shutdown_all` stops every
    live instance — the test harness's safety net against leaked serve
    threads keeping the process's jit caches and buffers alive."""

    _live = weakref.WeakSet()

    def __init__(self, model, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 5.0, workers: Optional[int] = None,
                 inflight_limit: Optional[int] = None,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 max_queue_depth: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        # resilience posture, resolved at construction so a running
        # instance is stable even if the env knobs change mid-flight.
        # DL4J_TPU_RESILIENCE=0 ⇒ all of it inert (byte-identical paths).
        self._resilience = _faults.resilience_enabled()
        if shed_policy is not None and shed_policy not in (
                "reject_newest", "reject_oldest"):
            raise ValueError("shed_policy must be 'reject_newest' or "
                             f"'reject_oldest', got {shed_policy!r}")
        if max_queue_depth is not None and self._resilience:
            # under the kill switch the bounded queue must NOT apply
            # either: pre-resilience behavior is the default-depth queue
            # with producer parking, not a shrunk queue without shedding
            queue_limit = max(1, int(max_queue_depth))
            shed_policy = shed_policy or "reject_newest"
        self._shed_policy = shed_policy if self._resilience else None
        self.default_deadline_ms = (deadline_ms if deadline_ms is not None
                                    else default_deadline_ms())
        self._breaker = None
        if self._resilience:
            self._breaker = breaker if breaker is not None else \
                CircuitBreaker("inference.device_execute")
            self._retry = RetryPolicy(max_retries=2,
                                      base_delay_seconds=0.01)
        # pipeline depth + padding buckets (async serving; see module doc).
        # Both resolved here so a running instance has stable behavior even
        # if the env knobs change mid-flight.
        self.inflight_limit = max(1, inflight_limit if inflight_limit
                                  is not None else _async.inflight_limit())
        if bucket_sizes:
            buckets = tuple(sorted({int(b) for b in bucket_sizes
                                    if 0 < int(b) <= batch_limit}))
            if not buckets:
                # refuse loudly: silently swapping in the defaults would
                # hand the caller six compiled shapes they never asked for
                raise ValueError(
                    f"bucket_sizes {tuple(bucket_sizes)} has no entry in "
                    f"(0, batch_limit={batch_limit}]")
        else:
            buckets = _async.default_buckets(batch_limit)
        self.bucket_sizes = buckets + ((batch_limit,)
                                       if buckets[-1] != batch_limit else ())
        self._async = _async.async_enabled()
        self._seen_buckets: set = set()
        # workers: shard the forward over the first N devices (the
        # reference's per-device replicas become one data-parallel SPMD
        # program); None = single-program forward on the default device
        self._trainer = None
        if workers is not None:
            import jax
            from deeplearning4j_tpu.parallel.mesh import MeshSpec
            from deeplearning4j_tpu.parallel.trainer import ShardedTrainer
            n = workers or len(jax.devices())
            self._trainer = ShardedTrainer(model, MeshSpec.data_parallel(n),
                                           devices=jax.devices()[:n])
            self._n_dev = n
        # multi-tenant QoS posture (resolved at construction like the
        # rest): the single FIFO becomes a deficit-weighted round-robin
        # FairQueue over per-tenant queues. DL4J_TPU_QOS=0 (or the
        # resilience kill switch) keeps the original queue.Queue —
        # byte-identical pre-QoS behavior.
        self._qos = self._resilience and _qos.qos_enabled()
        if self._qos:
            self._queue = _qos.FairQueue(
                queue_limit, _qos.global_tenants(),
                cost_fn=lambda r: int(r.x.shape[0]))
        else:
            self._queue: "queue.Queue[_Request]" = queue.Queue(
                maxsize=queue_limit)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # serializes enqueue vs shutdown-drain so a request can never be
        # enqueued after the drain and hang forever; the condition wakes
        # producers blocked on a full queue the instant the batcher drains
        # it (no busy-wait poll)
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._held: Optional[_Request] = None  # window overflow carry
        if self.mode == InferenceMode.BATCHED:
            if self._async:
                self._dispatch_q: queue.Queue = queue.Queue(maxsize=2)
                self._complete_q: queue.Queue = queue.Queue(
                    maxsize=self.inflight_limit)
                targets = (self._batch_loop, self._dispatch_loop,
                           self._complete_loop)
            else:
                targets = (self._serve_loop,)
            for tgt in targets:
                t = threading.Thread(target=tgt, daemon=True,
                                     name=f"dl4j-serve-{tgt.__name__}")
                t.start()
                self._threads.append(t)
        ParallelInference._live.add(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    @classmethod
    def shutdown_all(cls):
        """Stop every live instance's serve threads (test-harness teardown)."""
        for pi in list(cls._live):
            pi.shutdown()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inference_mode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n):
            self._kw["batch_limit"] = n
            return self

        batchLimit = batch_limit

        def queue_limit(self, n):
            self._kw["queue_limit"] = n
            return self

        queueLimit = queue_limit

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def inflight_limit(self, n):
            self._kw["inflight_limit"] = n
            return self

        inflightLimit = inflight_limit

        def bucket_sizes(self, sizes):
            self._kw["bucket_sizes"] = tuple(sizes)
            return self

        bucketSizes = bucket_sizes

        def max_queue_depth(self, n):
            """Bound the request queue at ``n`` and shed instead of
            parking producers (admission control)."""
            self._kw["max_queue_depth"] = n
            return self

        maxQueueDepth = max_queue_depth

        def shed_policy(self, policy):
            """``reject_newest`` (refuse the arriving request) or
            ``reject_oldest`` (evict the head of the queue)."""
            self._kw["shed_policy"] = policy
            return self

        shedPolicy = shed_policy

        def deadline_ms(self, ms):
            """Default per-request deadline (overrides
            ``DL4J_TPU_DEADLINE_MS``); 0 disables."""
            self._kw["deadline_ms"] = ms
            return self

        deadlineMs = deadline_ms

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # ----------------------------------------------------------------- api
    def _forward(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._forward_async(x))

    def _forward_async(self, x: np.ndarray):
        """Dispatch the forward and return the DEVICE result without
        blocking (JAX async dispatch) — the completer stage materializes
        it. ``np.asarray`` on the return value is the device→host sync."""
        if self._trainer is None:
            out = self.model.output(x)
            return out.buf() if hasattr(out, "buf") else out
        # pad ragged batches up to the device count so the sharded program
        # always sees a divisible leading axis
        pad = (-x.shape[0]) % self._n_dev
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        out = self._trainer.output(x)
        return out.buf() if hasattr(out, "buf") else out

    @staticmethod
    def _exemplar(ctx):
        """Histogram exemplar linking a latency observation to its trace
        (a `/metrics` tail bucket then names the trace_id to pull from
        `/train/trace`)."""
        return {"trace_id": ctx.trace_id} if ctx is not None else None

    def _resolve_deadline(self, deadline_ms) -> Optional[Deadline]:
        if not self._resilience:
            return None
        ms = deadline_ms if deadline_ms is not None else self.default_deadline_ms
        return Deadline.after_ms(ms) if ms and ms > 0 else None

    def _shed(self, reason: str, tenant=None):
        _ServingMetrics.get().shed[reason].inc()
        if tenant is not None:
            _qos.global_tenants().count_shed(tenant, reason)
        _faults.record_event("shed", op="inference", reason=reason)

    def _check_admission(self, tenant=None):
        """Fail fast on an open circuit — a dead device must reject at the
        door, not after a queue+batch+dispatch round trip."""
        if self._breaker is not None and not self._breaker.allow():
            self._shed("circuit_open", tenant=tenant)
            raise CircuitOpenError(
                "inference circuit open (consecutive device-execution "
                "failures); retry after the reset timeout")

    def output(self, x, deadline_ms: Optional[float] = None,
               tenant=None) -> np.ndarray:
        x = np.asarray(x)
        obs = _ServingMetrics.get()
        t0 = time.perf_counter()
        dl = self._resolve_deadline(deadline_ms)
        # tenant identity rides the request only under the QoS posture;
        # otherwise the kwarg is inert (byte-identical pre-QoS paths)
        tn = _qos.global_tenants().resolve(tenant) if self._qos else None

        def _tenant_account(err=None):
            if tn is not None:
                _qos.global_tenants().observe_request(
                    tn, time.perf_counter() - t0, err)
        if self.mode == InferenceMode.INSTANT:
            with _span("inference_request", mode=InferenceMode.INSTANT,
                       examples=int(x.shape[0])):
                ctx = current_context()
                try:
                    self._check_admission(tenant=tn)
                    if dl is not None and dl.expired():
                        self._shed("deadline", tenant=tn)
                        raise DeadlineExceeded(
                            "request expired before dispatch")
                    if self._resilience:
                        # same retry parity as the serve loops: transient
                        # dispatch faults are absorbed under the budget
                        self._retry.call(
                            lambda: _faults.check("inference.dispatch"),
                            op="inference.dispatch")
                        _faults.check("inference.device_execute")
                    out = self._forward(x)[: x.shape[0]]
                    if self._breaker is not None:
                        self._breaker.record_success()
                    if dl is not None and dl.expired():
                        # the device answered, but late — a late answer is
                        # wrong by the same policy _distribute applies in
                        # BATCHED mode (the breaker still saw a success:
                        # the device itself is healthy)
                        self._shed("deadline", tenant=tn)
                        raise DeadlineExceeded(
                            "request expired during device execution")
                except Exception as e:
                    # failed requests still count in the requests_total
                    # denominator (same as the BATCHED path) — otherwise
                    # ErrorRateRule's min_requests gate would read a 100%
                    # INSTANT outage as "no traffic, ok"
                    if (self._breaker is not None
                            and not isinstance(e, _TYPED_OUTCOMES)):
                        self._breaker.record_failure()
                    obs.latency[InferenceMode.INSTANT].observe(
                        time.perf_counter() - t0,
                        exemplar=self._exemplar(ctx))
                    obs.requests[InferenceMode.INSTANT].inc()
                    _tenant_account(e)
                    if not isinstance(e, _TYPED_OUTCOMES):
                        obs.errors.inc()
                    raise
            obs.latency[InferenceMode.INSTANT].observe(
                time.perf_counter() - t0, exemplar=self._exemplar(ctx))
            obs.requests[InferenceMode.INSTANT].inc()
            _tenant_account()
            if tn is not None:
                _qos.global_tenants().account_tokens(tn, int(x.shape[0]))
            return out
        req = _Request(x)
        req.deadline = dl
        req.tenant = tn
        # the per-request END-TO-END span: everything the serve threads do
        # for this request parents under it (they stamp phase records with
        # req.ctx), and the flight recorder treats the outstanding request
        # as in-flight work whose completion must keep making progress
        with _flight().arm("inference_request"), \
                _span("inference_request", mode=InferenceMode.BATCHED,
                      examples=int(x.shape[0])):
            req.ctx = current_context()
            req.t_enqueue_us = now_us()
            try:
                self._check_admission(tenant=tn)
            except CircuitOpenError as e:
                # fail-fast rejections are still traffic: without the
                # requests_total increment a 100% circuit-open outage
                # would read as "no traffic, ok" to ErrorRateRule's
                # min_requests gate (INSTANT mode already counts these)
                obs.latency[InferenceMode.BATCHED].observe(
                    time.perf_counter() - t0,
                    exemplar=self._exemplar(req.ctx))
                obs.requests[InferenceMode.BATCHED].inc()
                _tenant_account(e)
                raise
            # condition-based enqueue: a producer facing a full queue
            # sleeps on the condition and is woken by the batcher the
            # moment it drains a request — no 1 ms busy-wait poll, no
            # burned CPU. The timeout is belt-and-braces against a lost
            # wakeup racing shutdown.
            try:
                with self._not_full:
                    while True:
                        if self._stop.is_set():
                            raise ShutdownError(
                                "ParallelInference has been shut down")
                        if (req.deadline is not None
                                and req.deadline.expired()):
                            self._shed("deadline", tenant=tn)
                            raise DeadlineExceeded(
                                "request expired while waiting to enqueue")
                        try:
                            self._queue.put_nowait(req)
                            obs.queue_depth.set(self._queue.qsize())
                            break
                        except queue.Full:
                            if (self._qos
                                    and self._shed_policy is not None):
                                # tenant-aware shedding: evict from the
                                # most over-share tenant; None means the
                                # ARRIVING tenant is the most over-share
                                # (or nobody is over) — never evict an
                                # under-share tenant's work. In that
                                # case reject_oldest keeps its pre-QoS
                                # meaning WITHIN the tenant: the
                                # arrival's own stale head gives way
                                victim = self._queue.pick_victim(req)
                                if (victim is None and
                                        self._shed_policy
                                        == "reject_oldest"):
                                    victim = (self._queue.pop_oldest_of(
                                        tn)
                                        or self._queue
                                        .pop_global_oldest())
                                if victim is None:
                                    self._shed("queue_full", tenant=tn)
                                    raise ShedError(
                                        "inference queue full "
                                        f"({self._queue.maxsize} "
                                        "requests); request rejected "
                                        "(tenant over its fair share)")
                                self._shed_request(
                                    victim, "queue_full", ShedError(
                                        "shed from a full inference "
                                        "queue (most over-share "
                                        "tenant)"))
                                continue
                            if self._shed_policy == "reject_newest":
                                self._shed("queue_full", tenant=tn)
                                raise ShedError(
                                    "inference queue full "
                                    f"({self._queue.maxsize} requests); "
                                    "request rejected (reject_newest)")
                            if self._shed_policy == "reject_oldest":
                                try:
                                    old = self._queue.get_nowait()
                                except queue.Empty:
                                    continue  # batcher drained it — retry
                                self._shed_request(
                                    old, "queue_full", ShedError(
                                        "shed from a full inference queue "
                                        "by a newer request (reject_oldest)"))
                                continue
                            self._not_full.wait(timeout=0.1)
            except (ShedError, DeadlineExceeded, ShutdownError) as e:
                # pre-enqueue rejections count as requests too — same
                # denominator invariant as the error path below
                obs.latency[InferenceMode.BATCHED].observe(
                    time.perf_counter() - t0,
                    exemplar=self._exemplar(req.ctx))
                obs.requests[InferenceMode.BATCHED].inc()
                _tenant_account(e)
                raise
            # deadline-aware wait: the batcher/dispatcher/completer checks
            # cover the queue and the pad/dispatch boundaries, but a
            # WEDGED device batch resolves nothing — the caller must be
            # able to walk away at its deadline instead of hanging
            if req.deadline is None:
                req.event.wait()
            else:
                while not req.event.is_set():
                    rem = req.deadline.remaining()
                    if rem <= 0:
                        break
                    req.event.wait(timeout=rem)
                if not req.event.is_set():
                    # walk away: atomically CLAIM the request so pipeline
                    # stages skip it (no second shed count when the
                    # wedged batch finally resolves). Losing the claim
                    # race means another path is resolving RIGHT NOW —
                    # wait for its outcome instead of inventing one.
                    if req.claim():
                        req.error = DeadlineExceeded(
                            "request expired while awaiting device results")
                        req.event.set()
                        self._shed("deadline", tenant=tn)
                    else:
                        req.event.wait(timeout=5.0)
                        if req.error is None and req.result is None:
                            # the claim winner stalled past the grace
                            # window too — resolve locally rather than
                            # fall through to a None "result" (nobody
                            # reads the winner's late outcome)
                            req.error = DeadlineExceeded(
                                "request expired while awaiting device "
                                "results (resolution stalled)")
                # falls through to the common error accounting below
            if req.error is not None:
                # raise INSIDE the request span so the trace and
                # dl4j_span_errors_total agree with
                # dl4j_inference_errors_total about this request failing.
                # Typed resilience outcomes (shed/deadline/shutdown) are
                # lifecycle results, not device errors — they count as
                # requests but must not move the error-rate SLO.
                obs.latency[InferenceMode.BATCHED].observe(
                    time.perf_counter() - t0,
                    exemplar=self._exemplar(req.ctx))
                obs.requests[InferenceMode.BATCHED].inc()
                _tenant_account(req.error)
                if not isinstance(req.error, _TYPED_OUTCOMES):
                    obs.errors.inc()
                raise req.error
        obs.latency[InferenceMode.BATCHED].observe(
            time.perf_counter() - t0, exemplar=self._exemplar(req.ctx))
        obs.requests[InferenceMode.BATCHED].inc()
        _tenant_account()
        return req.result

    def shutdown(self):
        self._stop.set()
        # wake producers parked on the not-full condition so they observe
        # the stop flag instead of waiting out their timeout
        with self._not_full:
            self._not_full.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        if self._breaker is not None:
            # a dead instance's open circuit must not pin /health failing
            self._breaker.retire()
        # fail any requests that were still queued so callers never hang
        with self._lock:
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                if not req.claim():
                    continue
                req.error = ShutdownError("ParallelInference shut down")
                req.event.set()
        # the queue-depth gauge must not freeze at the pre-shutdown burst
        # level — the SLO rule reads it live, and a stale >threshold value
        # would pin /health degraded/failing on a drained instance
        _ServingMetrics.get().queue_depth.set(self._queue.qsize())
        # stage-queue sweep: a batcher put can race the dispatcher's exit
        # (fail those — never dispatched), and if a join above timed out a
        # completed-but-unclaimed batch may remain (finish those)
        if getattr(self, "_dispatch_q", None) is not None:
            obs = _ServingMetrics.get()
            while True:
                try:
                    _, batch, _ = self._dispatch_q.get_nowait()
                except queue.Empty:
                    break
                self._fail(batch, ShutdownError("ParallelInference shut down"))
            while True:
                try:
                    item = self._complete_q.get_nowait()
                except queue.Empty:
                    break
                if item is self._DONE:
                    # re-deliver: a completer whose join timed out is still
                    # parked on get() and exits only on this marker —
                    # swallowing it would strand that thread forever. The
                    # marker is always last in FIFO order, so stop here.
                    self._complete_q.put(item)
                    break
                self._complete_one(obs, *item)

    # ------------------------------------------------------- batching stage
    def _shed_request(self, req: _Request, reason: str,
                      error: BaseException):
        """Fail one request with a typed shed outcome (never dispatched).
        A request another path already resolved (claimed) is skipped —
        it was shed/completed once; counting it again would lie."""
        if not req.claim():
            return
        self._shed(reason, tenant=req.tenant)
        if req.ctx is not None:
            record_span("shed", now_us(), ctx=req.ctx, reason=reason)
        req.error = error
        req.event.set()

    def _take_request(self, timeout: float) -> Optional[_Request]:
        """Pop one request (or the held window overflow), waking any
        producer blocked on the full queue. Requests whose deadline
        already expired are shed here — before any padding or dispatch
        work is spent on them."""
        wait_until = time.monotonic() + timeout
        while True:
            if self._held is not None:
                req, self._held = self._held, None
            else:
                try:
                    req = self._queue.get(
                        timeout=max(0.0, wait_until - time.monotonic()))
                except queue.Empty:
                    return None
                with self._not_full:
                    self._not_full.notify()
                # the request's queue_wait phase ends the moment the
                # batcher owns it; start was stamped by the producer thread
                # at enqueue (a held overflow request re-enters through
                # self._held above and is not double-counted)
                if req.ctx is not None:
                    record_span("queue_wait", req.t_enqueue_us, ctx=req.ctx,
                                examples=int(req.x.shape[0]))
            if (self._resilience and req.deadline is not None
                    and req.deadline.expired()):
                self._shed_request(req, "deadline", DeadlineExceeded(
                    "request expired in the batching queue"))
                continue
            return req

    def _next_window(self) -> Optional[List[_Request]]:
        """Coalesce one batch window, never exceeding batch_limit (the
        shared heart of both the sync loop and the async batcher)."""
        first = self._take_request(timeout=0.1)
        if first is None:
            return None
        obs = _ServingMetrics.get()
        obs.queue_depth.set(self._queue.qsize())
        batch: List[_Request] = [first]
        total = first.x.shape[0]
        deadline = time.monotonic() + self.max_wait_ms / 1e3
        while total < self.batch_limit:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            nxt = self._take_request(timeout=remaining)
            if nxt is None:
                break
            if total + nxt.x.shape[0] > self.batch_limit:
                # too big for this batch: hold it locally to seed the
                # next one — putting it back on a bounded queue that
                # producers may have refilled would deadlock the sole
                # consumer (and break FIFO order)
                self._held = nxt
                break
            batch.append(nxt)
            total += nxt.x.shape[0]
        return batch

    def _bucket_for(self, n: int) -> int:
        """Smallest configured bucket that fits ``n`` examples."""
        i = bisect.bisect_left(self.bucket_sizes, n)
        return self.bucket_sizes[min(i, len(self.bucket_sizes) - 1)]

    def _pad_concat(self, batch: List[_Request], target: int):
        """Concatenate a window and zero-pad the leading axis to ``target``
        so the compiled executable for that shape is reused."""
        X = np.concatenate([r.x for r in batch], axis=0)
        n = X.shape[0]
        if n < target:
            pad = np.zeros((target - n,) + X.shape[1:], X.dtype)
            X = np.concatenate([X, pad], axis=0)
        return X, n

    @staticmethod
    def _fail(batch: List[_Request], error: BaseException):
        for r in batch:
            if not r.claim():
                continue               # caller already walked away
            r.error = error
            r.event.set()

    def _distribute(self, batch: List[_Request], out: np.ndarray):
        off = 0
        for r in batch:
            k = r.x.shape[0]
            if (self._resilience and r.deadline is not None
                    and r.deadline.expired()):
                # the work is done but the caller's deadline has passed —
                # a late answer is a wrong answer to a deadline'd caller
                off += k
                self._shed_request(r, "deadline", DeadlineExceeded(
                    "request expired before results were distributed"))
                continue
            if not r.claim():
                off += k               # caller already walked away
                continue
            r.result = out[off:off + k]
            off += k
            r.event.set()

    def _drop_if_window_expired(self, batch: List[_Request]) -> bool:
        """True when EVERY member of the window has expired — the window
        is shed whole and must not occupy an in-flight slot. A partially
        expired window still dispatches (the padded buffer is positional;
        the completer sheds the expired members at distribute time)."""
        if not self._resilience or not batch:
            return False
        if all(r.deadline is not None and r.deadline.expired()
               for r in batch):
            for r in batch:
                self._shed_request(r, "deadline", DeadlineExceeded(
                    "request expired before dispatch"))
            return True
        return False

    @staticmethod
    def _record_phase(name: str, batch: List[_Request], start_us: float,
                      end_us: float, **attrs):
        """Stamp one pipeline phase into EVERY member request's trace —
        the per-request decomposition the batch-level spans can't give
        (a batch mixes requests from different traces)."""
        for r in batch:
            if r.ctx is not None:
                # graftlint: disable=span-names — forwarder: every
                # _record_phase caller passes a literal phase name
                record_span(name, start_us, end_us, ctx=r.ctx, **attrs)

    def _observe_batch(self, obs: "_ServingMetrics", n: int, target: int):
        obs.batch_occupancy.observe(n / max(self.batch_limit, 1))
        obs.bucket_fill.observe(n / max(target, 1))
        key = (target,)
        if key in self._seen_buckets:
            obs.bucket_hits.inc()
        else:
            self._seen_buckets.add(key)
            obs.bucket_misses.inc()
            # first use of this padded shape — the trace/compile it
            # provokes in the model's _output_jit claims this cause, so
            # /debug/compiles names the bucket behind the compile. The
            # cause is noted per MODEL, not per instance: the jit cache
            # lives on the model, so a second ParallelInference over the
            # same net records a (per-instance) miss that compiles
            # nothing — a pending cause there would mislabel the next
            # unrelated compile within the claim window
            model_seen = self.model.__dict__.setdefault(
                "_cw_seen_buckets", set())
            if key not in model_seen:
                model_seen.add(key)
                _cw.note_cause("bucket_miss", bucket=target)
        obs.batches.inc()

    def _charge_tenants(self, batch: List[_Request], target: int):
        """Per-tenant usage + cost for one executed device batch: each
        member is charged its examples as usage tokens and its share of
        the bucket executable's accounted FLOPs (k/target of the padded
        program — executed work is charged even when the caller already
        walked away, because the device ran it)."""
        if not self._qos:
            return
        flops = _cost.global_cost_model().flops_for(
            _cost.bucket_fn(self.model, target))
        reg = _qos.global_tenants()
        for r in batch:
            if r.tenant is None:
                continue
            k = int(r.x.shape[0])
            reg.account_tokens(r.tenant, k)
            if flops:
                reg.account_cost(r.tenant, flops * k / max(1, target))

    # ------------------------------------------------- sync loop (ASYNC=0)
    def _serve_loop(self):
        """Single-threaded synchronous serve loop: one batch in flight,
        pad to batch_limit — the DL4J_TPU_ASYNC=0 behavior."""
        obs = _ServingMetrics.get()
        while not self._stop.is_set():
            batch = self._next_window()
            if batch is None:
                continue
            if self._drop_if_window_expired(batch):
                continue
            try:
                t_pad = now_us()
                X, n = self._pad_concat(batch, self.batch_limit)
                self._record_phase("bucket_pad", batch, t_pad, now_us(),
                                   bucket=self.batch_limit)
                self._observe_batch(obs, n, self.batch_limit)
                t0 = time.perf_counter()
                t_dev = now_us()
                with _span("inference_batch", requests=len(batch),
                           examples=n):
                    # sync loop: dispatch + device + transfer are one
                    # blocking call, so the whole thing is the request's
                    # "device" phase (both serving fault points fire here).
                    # Parity with the async dispatcher: transient DISPATCH
                    # faults retry under the budget; device-execution
                    # faults surface (breaker food)
                    if self._resilience:
                        self._retry.call(
                            lambda: _faults.check("inference.dispatch"),
                            op="inference.dispatch")
                        _faults.check("inference.device_execute")
                    out = self._forward(X)[:n]
                t_done = now_us()
                self._record_phase("device", batch, t_dev, t_done,
                                   examples=n)
                dt = time.perf_counter() - t0
                obs.straggler.observe(dt)
                # cost observatory: the sync loop's sole executable (pad
                # to batch_limit) — account once (the dispatch above
                # already compiled it; the lowering is a cache hit), then
                # feed every batch's device wall time into its MFU
                _cost.maybe_account_bucket(self.model, self.batch_limit, X)
                _cost.observe_bucket_time(self.model, self.batch_limit, dt)
                self._charge_tenants(batch, self.batch_limit)
                if self._breaker is not None:
                    self._breaker.record_success()
                self._distribute(batch, out)
                self._record_phase("complete", batch, t_done, now_us())
                _flight().progress("inference_batch")
                _devmem.sample()
            except Exception as e:             # surface errors to callers
                if self._breaker is not None:
                    self._breaker.record_failure()
                self._fail(batch, e)
        if self._held is not None:             # don't strand the overflow
            if self._held.claim():
                self._held.error = ShutdownError(
                    "ParallelInference shut down")
                self._held.event.set()
            self._held = None

    # ------------------------------------------- async pipeline (default)
    def _put_stage(self, q: queue.Queue, item) -> bool:
        """Stop-aware bounded put between pipeline stages (backpressure:
        a full downstream queue throttles this stage)."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _batch_loop(self):
        """Stage 1 — coalesce request windows, pad to the shape bucket."""
        obs = _ServingMetrics.get()
        while not self._stop.is_set():
            batch = self._next_window()
            if batch is None:
                continue
            try:
                total = sum(r.x.shape[0] for r in batch)
                target = self._bucket_for(total)
                t_pad = now_us()
                X, n = self._pad_concat(batch, target)
                self._record_phase("bucket_pad", batch, t_pad, now_us(),
                                   bucket=target)
                self._observe_batch(obs, n, target)
            except Exception as e:
                self._fail(batch, e)
                continue
            if not self._put_stage(self._dispatch_q, (X, batch, n)):
                self._fail(batch,
                           ShutdownError("ParallelInference shut down"))
        if self._held is not None:             # don't strand the overflow
            if self._held.claim():
                self._held.error = ShutdownError(
                    "ParallelInference shut down")
                self._held.event.set()
            self._held = None

    _DONE = object()    # dispatcher→completer end-of-stream marker

    def _dispatch_loop(self):
        """Stage 2 — non-blocking device dispatch; up to inflight_limit
        batches queued on the device while earlier results transfer back."""
        obs = _ServingMetrics.get()
        try:
            while True:
                try:
                    X, batch, n = self._dispatch_q.get(timeout=0.05)
                except queue.Empty:
                    if self._stop.is_set():
                        break
                    continue
                if self._drop_if_window_expired(batch):
                    continue   # expired whole: never takes an in-flight slot
                t_disp = time.perf_counter()
                try:
                    t_us = now_us()
                    with _span("inference_dispatch", requests=len(batch),
                               examples=n):
                        if self._resilience:
                            # transient injected dispatch faults are
                            # retried under the budgeted policy; real
                            # errors surface immediately
                            def _dispatch(X=X):
                                _faults.check("inference.dispatch")
                                return self._forward_async(X)
                            dev = self._retry.call(
                                _dispatch, op="inference.dispatch")
                        else:
                            dev = self._forward_async(X)
                    self._record_phase("dispatch", batch, t_us, now_us(),
                                       examples=n)
                except Exception as e:         # trace/compile-time errors
                    if self._breaker is not None:
                        self._breaker.record_failure()
                    self._fail(batch, e)
                    continue
                # first dispatch of a padded shape just compiled the
                # bucket executable — account its cost now (AOT lowering
                # at the same signature = cache hit, no second compile)
                _cost.maybe_account_bucket(self.model, X.shape[0], X)
                if self._put_stage(self._complete_q,
                                   (dev, batch, n, t_disp, X.shape[0])):
                    obs.inflight.set(self._complete_q.qsize())
                else:
                    # shutdown raced the handoff: materialize inline so
                    # the callers still get their (valid) results
                    self._complete_one(obs, dev, batch, n, t_disp,
                                       X.shape[0])
        finally:
            # end-of-stream marker: a plain blocking put is safe because
            # the completer consumes until it sees the marker (it cannot
            # exit first), and it happens-after every real put from this
            # thread — so no dispatched batch is stranded behind the
            # completer's exit check (that race existed with a
            # stop-flag-only exit)
            self._complete_q.put(self._DONE)

    def _complete_one(self, obs, dev, batch, n, t_dispatch=None,
                      target=None):
        try:
            t_dev = now_us()
            with _span("inference_complete", requests=len(batch),
                       examples=n):
                if self._resilience:
                    _faults.check("inference.device_execute")
                out = np.asarray(dev)[:n]      # device→host sync point
            t_done = now_us()
            # "device" = dispatch→materialize (execution + transfer tail);
            # "complete" = slicing the host buffer out to callers
            self._record_phase("device", batch, t_dev, t_done, examples=n)
            if target is not None:
                self._charge_tenants(batch, target)
            self._distribute(batch, out)
            self._record_phase("complete", batch, t_done, now_us())
            if t_dispatch is not None:
                # straggler check over the batch's dispatch→complete wall
                # time — the serving analog of a slow train step
                dt = time.perf_counter() - t_dispatch
                obs.straggler.observe(dt)
                if target is not None:
                    # bucket MFU from the same duration (includes pipeline
                    # queueing under multi-in-flight — a lower bound)
                    _cost.observe_bucket_time(self.model, target, dt)
            _flight().progress("inference_batch")
            if self._breaker is not None:
                self._breaker.record_success()
            # batch boundary: sample device memory (throttled; no-op on
            # stat-less CPU backends)
            _devmem.sample()
        except Exception as e:                 # execution-time errors
            if self._breaker is not None:
                self._breaker.record_failure()
            self._fail(batch, e)

    def _complete_loop(self):
        """Stage 3 — block on the device→host transfer, hand out slices.
        Exits only on the dispatcher's end-of-stream marker, which follows
        every real item in queue order — in-flight batches always land."""
        obs = _ServingMetrics.get()
        while True:
            item = self._complete_q.get()
            if item is self._DONE:
                break
            self._complete_one(obs, *item)
            obs.inflight.set(self._complete_q.qsize())
