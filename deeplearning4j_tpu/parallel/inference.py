"""ParallelInference: batched serving facade.

Reference: ``org.deeplearning4j.parallelism.ParallelInference`` (SURVEY P8) —
per-device model replicas with INSTANT / BATCHED modes. TPU-first collapse:
there is ONE compiled program; "replicas" are the mesh's data-axis shards,
and XLA already pipelines concurrent calls. What survives is the *dynamic
batching* queue: BATCHED mode coalesces concurrent small requests into one
device call (padding so the executable is reused), which is where serving
throughput on an accelerator comes from.

Async pipeline (default; kill switch ``DL4J_TPU_ASYNC=0``): the serve loop
is split into three stages so several device batches stay in flight —

    producers → request queue → **batcher** (coalesce + pad to a
    power-of-two shape bucket) → **dispatcher** (non-blocking device
    dispatch, up to ``inflight_limit`` batches queued on the device) →
    **completer** (blocks on the device→host transfer, distributes
    per-request slices)

Batch *k+1* dispatches while batch *k*'s results transfer back. Padding
goes to the next power-of-two bucket ≤ ``batch_limit`` instead of always
``batch_limit``: a small bounded set of compiled executables
(log2(limit)+1) in exchange for far less padded compute at partial
occupancy. Under ``DL4J_TPU_ASYNC=0`` the original single-threaded loop
runs: one batch in flight, pad-to-``batch_limit``, byte-identical
synchronous behavior.
"""
from __future__ import annotations

import bisect
import queue
import threading
import time
import weakref
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import async_runtime as _async
from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability import device_memory as _devmem
from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.observability.straggler import StragglerDetector
from deeplearning4j_tpu.observability.tracing import (current_context,
                                                      now_us, record_span)


class InferenceMode:
    INSTANT = "INSTANT"
    BATCHED = "BATCHED"


class _ServingMetrics:
    """Label-bound serving instruments (shared across instances — the
    registry aggregates; per-instance series would leak one label value
    per short-lived ParallelInference in tests)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        lat = reg.histogram(
            "dl4j_inference_latency_seconds",
            "end-to-end ParallelInference.output latency (enqueue + batch "
            "window + device forward)", label_names=("mode",))
        self.latency = {m: lat.labels(mode=m)
                        for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        req = reg.counter("dl4j_inference_requests_total",
                          "ParallelInference requests served",
                          label_names=("mode",))
        self.requests = {m: req.labels(mode=m)
                         for m in (InferenceMode.INSTANT, InferenceMode.BATCHED)}
        self.errors = reg.counter("dl4j_inference_errors_total",
                                  "ParallelInference requests that raised")
        self.queue_depth = reg.gauge(
            "dl4j_inference_queue_depth",
            "requests waiting in the batching queue (sampled per transition)")
        self.batch_occupancy = reg.histogram(
            "dl4j_inference_batch_occupancy",
            "coalesced examples / batch_limit per device call (1.0 = full "
            "batch, the padded-executable reuse sweet spot)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self.batches = reg.counter("dl4j_inference_batches_total",
                                   "device calls issued by the serve loop")
        self.inflight = reg.gauge(
            "dl4j_inference_inflight_batches",
            "device batches dispatched but not yet completed (serving "
            "pipeline depth; bounded by inflight_limit)")
        bucket = reg.counter(
            "dl4j_inference_bucket_total",
            "shape-bucket outcomes per device call: hit = padded shape "
            "already compiled for this instance, miss = first use",
            label_names=("outcome",))
        self.bucket_hits = bucket.labels(outcome="hit")
        self.bucket_misses = bucket.labels(outcome="miss")
        self.bucket_fill = reg.histogram(
            "dl4j_inference_bucket_fill",
            "coalesced examples / padded bucket size per device call "
            "(1.0 = zero padded compute waste)",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        # serving-side straggler flag (the detector previously watched
        # train steps only): per-device-batch dispatch→complete wall time
        # against its rolling median, so one slow padded-shape compile or
        # a wedged transfer shows up in a scrape without a trace
        self.straggler = StragglerDetector(phase="inference_batch",
                                           registry=reg)

    @classmethod
    def get(cls) -> "_ServingMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_serving_metrics():
    _ServingMetrics._instance = None


class _Request:
    __slots__ = ("x", "event", "result", "error", "ctx", "t_enqueue_us")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        # causal trace context captured at enqueue: the serve threads stamp
        # this request's queue_wait/bucket_pad/dispatch/device/complete
        # phases into ITS trace, so one trace_id follows the request across
        # the batcher→dispatcher→completer pipeline
        self.ctx = None
        self.t_enqueue_us = 0.0


class ParallelInference:
    """ref API: ParallelInference.Builder(model).inferenceMode(...)
    .batchLimit(n).queueLimit(n).build(); output(x).

    Instances own serve threads (BATCHED mode); call :meth:`shutdown` (or
    use as a context manager) when done. :meth:`shutdown_all` stops every
    live instance — the test harness's safety net against leaked serve
    threads keeping the process's jit caches and buffers alive."""

    _live = weakref.WeakSet()

    def __init__(self, model, inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 max_wait_ms: float = 5.0, workers: Optional[int] = None,
                 inflight_limit: Optional[int] = None,
                 bucket_sizes: Optional[Sequence[int]] = None):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = batch_limit
        self.max_wait_ms = max_wait_ms
        # pipeline depth + padding buckets (async serving; see module doc).
        # Both resolved here so a running instance has stable behavior even
        # if the env knobs change mid-flight.
        self.inflight_limit = max(1, inflight_limit if inflight_limit
                                  is not None else _async.inflight_limit())
        if bucket_sizes:
            buckets = tuple(sorted({int(b) for b in bucket_sizes
                                    if 0 < int(b) <= batch_limit}))
            if not buckets:
                # refuse loudly: silently swapping in the defaults would
                # hand the caller six compiled shapes they never asked for
                raise ValueError(
                    f"bucket_sizes {tuple(bucket_sizes)} has no entry in "
                    f"(0, batch_limit={batch_limit}]")
        else:
            buckets = _async.default_buckets(batch_limit)
        self.bucket_sizes = buckets + ((batch_limit,)
                                       if buckets[-1] != batch_limit else ())
        self._async = _async.async_enabled()
        self._seen_buckets: set = set()
        # workers: shard the forward over the first N devices (the
        # reference's per-device replicas become one data-parallel SPMD
        # program); None = single-program forward on the default device
        self._trainer = None
        if workers is not None:
            import jax
            from deeplearning4j_tpu.parallel.mesh import MeshSpec
            from deeplearning4j_tpu.parallel.trainer import ShardedTrainer
            n = workers or len(jax.devices())
            self._trainer = ShardedTrainer(model, MeshSpec.data_parallel(n),
                                           devices=jax.devices()[:n])
            self._n_dev = n
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # serializes enqueue vs shutdown-drain so a request can never be
        # enqueued after the drain and hang forever; the condition wakes
        # producers blocked on a full queue the instant the batcher drains
        # it (no busy-wait poll)
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._held: Optional[_Request] = None  # window overflow carry
        if self.mode == InferenceMode.BATCHED:
            if self._async:
                self._dispatch_q: queue.Queue = queue.Queue(maxsize=2)
                self._complete_q: queue.Queue = queue.Queue(
                    maxsize=self.inflight_limit)
                targets = (self._batch_loop, self._dispatch_loop,
                           self._complete_loop)
            else:
                targets = (self._serve_loop,)
            for tgt in targets:
                t = threading.Thread(target=tgt, daemon=True,
                                     name=f"dl4j-serve-{tgt.__name__}")
                t.start()
                self._threads.append(t)
        ParallelInference._live.add(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    @classmethod
    def shutdown_all(cls):
        """Stop every live instance's serve threads (test-harness teardown)."""
        for pi in list(cls._live):
            pi.shutdown()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inference_mode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        inferenceMode = inference_mode

        def batch_limit(self, n):
            self._kw["batch_limit"] = n
            return self

        batchLimit = batch_limit

        def queue_limit(self, n):
            self._kw["queue_limit"] = n
            return self

        queueLimit = queue_limit

        def workers(self, n):
            self._kw["workers"] = n
            return self

        def inflight_limit(self, n):
            self._kw["inflight_limit"] = n
            return self

        inflightLimit = inflight_limit

        def bucket_sizes(self, sizes):
            self._kw["bucket_sizes"] = tuple(sizes)
            return self

        bucketSizes = bucket_sizes

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # ----------------------------------------------------------------- api
    def _forward(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._forward_async(x))

    def _forward_async(self, x: np.ndarray):
        """Dispatch the forward and return the DEVICE result without
        blocking (JAX async dispatch) — the completer stage materializes
        it. ``np.asarray`` on the return value is the device→host sync."""
        if self._trainer is None:
            out = self.model.output(x)
            return out.buf() if hasattr(out, "buf") else out
        # pad ragged batches up to the device count so the sharded program
        # always sees a divisible leading axis
        pad = (-x.shape[0]) % self._n_dev
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        out = self._trainer.output(x)
        return out.buf() if hasattr(out, "buf") else out

    @staticmethod
    def _exemplar(ctx):
        """Histogram exemplar linking a latency observation to its trace
        (a `/metrics` tail bucket then names the trace_id to pull from
        `/train/trace`)."""
        return {"trace_id": ctx.trace_id} if ctx is not None else None

    def output(self, x) -> np.ndarray:
        x = np.asarray(x)
        obs = _ServingMetrics.get()
        t0 = time.perf_counter()
        if self.mode == InferenceMode.INSTANT:
            with _span("inference_request", mode=InferenceMode.INSTANT,
                       examples=int(x.shape[0])):
                ctx = current_context()
                try:
                    out = self._forward(x)[: x.shape[0]]
                except Exception:
                    # failed requests still count in the requests_total
                    # denominator (same as the BATCHED path) — otherwise
                    # ErrorRateRule's min_requests gate would read a 100%
                    # INSTANT outage as "no traffic, ok"
                    obs.latency[InferenceMode.INSTANT].observe(
                        time.perf_counter() - t0,
                        exemplar=self._exemplar(ctx))
                    obs.requests[InferenceMode.INSTANT].inc()
                    obs.errors.inc()
                    raise
            obs.latency[InferenceMode.INSTANT].observe(
                time.perf_counter() - t0, exemplar=self._exemplar(ctx))
            obs.requests[InferenceMode.INSTANT].inc()
            return out
        req = _Request(x)
        # the per-request END-TO-END span: everything the serve threads do
        # for this request parents under it (they stamp phase records with
        # req.ctx), and the flight recorder treats the outstanding request
        # as in-flight work whose completion must keep making progress
        with _flight().arm("inference_request"), \
                _span("inference_request", mode=InferenceMode.BATCHED,
                      examples=int(x.shape[0])):
            req.ctx = current_context()
            req.t_enqueue_us = now_us()
            # condition-based enqueue: a producer facing a full queue
            # sleeps on the condition and is woken by the batcher the
            # moment it drains a request — no 1 ms busy-wait poll, no
            # burned CPU. The timeout is belt-and-braces against a lost
            # wakeup racing shutdown.
            with self._not_full:
                while True:
                    if self._stop.is_set():
                        raise RuntimeError(
                            "ParallelInference has been shut down")
                    try:
                        self._queue.put_nowait(req)
                        obs.queue_depth.set(self._queue.qsize())
                        break
                    except queue.Full:
                        self._not_full.wait(timeout=0.1)
            req.event.wait()
            if req.error is not None:
                # raise INSIDE the request span so the trace and
                # dl4j_span_errors_total agree with
                # dl4j_inference_errors_total about this request failing
                obs.latency[InferenceMode.BATCHED].observe(
                    time.perf_counter() - t0,
                    exemplar=self._exemplar(req.ctx))
                obs.requests[InferenceMode.BATCHED].inc()
                obs.errors.inc()
                raise req.error
        obs.latency[InferenceMode.BATCHED].observe(
            time.perf_counter() - t0, exemplar=self._exemplar(req.ctx))
        obs.requests[InferenceMode.BATCHED].inc()
        return req.result

    def shutdown(self):
        self._stop.set()
        # wake producers parked on the not-full condition so they observe
        # the stop flag instead of waiting out their timeout
        with self._not_full:
            self._not_full.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        # fail any requests that were still queued so callers never hang
        with self._lock:
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                req.error = RuntimeError("ParallelInference shut down")
                req.event.set()
        # the queue-depth gauge must not freeze at the pre-shutdown burst
        # level — the SLO rule reads it live, and a stale >threshold value
        # would pin /health degraded/failing on a drained instance
        _ServingMetrics.get().queue_depth.set(self._queue.qsize())
        # stage-queue sweep: a batcher put can race the dispatcher's exit
        # (fail those — never dispatched), and if a join above timed out a
        # completed-but-unclaimed batch may remain (finish those)
        if getattr(self, "_dispatch_q", None) is not None:
            obs = _ServingMetrics.get()
            while True:
                try:
                    _, batch, _ = self._dispatch_q.get_nowait()
                except queue.Empty:
                    break
                self._fail(batch, RuntimeError("ParallelInference shut down"))
            while True:
                try:
                    item = self._complete_q.get_nowait()
                except queue.Empty:
                    break
                if item is self._DONE:
                    # re-deliver: a completer whose join timed out is still
                    # parked on get() and exits only on this marker —
                    # swallowing it would strand that thread forever. The
                    # marker is always last in FIFO order, so stop here.
                    self._complete_q.put(item)
                    break
                self._complete_one(obs, *item)

    # ------------------------------------------------------- batching stage
    def _take_request(self, timeout: float) -> Optional[_Request]:
        """Pop one request (or the held window overflow), waking any
        producer blocked on the full queue."""
        if self._held is not None:
            req, self._held = self._held, None
            return req
        try:
            req = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        with self._not_full:
            self._not_full.notify()
        # the request's queue_wait phase ends the moment the batcher owns
        # it; start was stamped by the producer thread at enqueue (a held
        # overflow request re-enters through self._held above and is not
        # double-counted)
        if req.ctx is not None:
            record_span("queue_wait", req.t_enqueue_us, ctx=req.ctx,
                        examples=int(req.x.shape[0]))
        return req

    def _next_window(self) -> Optional[List[_Request]]:
        """Coalesce one batch window, never exceeding batch_limit (the
        shared heart of both the sync loop and the async batcher)."""
        first = self._take_request(timeout=0.1)
        if first is None:
            return None
        obs = _ServingMetrics.get()
        obs.queue_depth.set(self._queue.qsize())
        batch: List[_Request] = [first]
        total = first.x.shape[0]
        deadline = time.monotonic() + self.max_wait_ms / 1e3
        while total < self.batch_limit:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            nxt = self._take_request(timeout=remaining)
            if nxt is None:
                break
            if total + nxt.x.shape[0] > self.batch_limit:
                # too big for this batch: hold it locally to seed the
                # next one — putting it back on a bounded queue that
                # producers may have refilled would deadlock the sole
                # consumer (and break FIFO order)
                self._held = nxt
                break
            batch.append(nxt)
            total += nxt.x.shape[0]
        return batch

    def _bucket_for(self, n: int) -> int:
        """Smallest configured bucket that fits ``n`` examples."""
        i = bisect.bisect_left(self.bucket_sizes, n)
        return self.bucket_sizes[min(i, len(self.bucket_sizes) - 1)]

    def _pad_concat(self, batch: List[_Request], target: int):
        """Concatenate a window and zero-pad the leading axis to ``target``
        so the compiled executable for that shape is reused."""
        X = np.concatenate([r.x for r in batch], axis=0)
        n = X.shape[0]
        if n < target:
            pad = np.zeros((target - n,) + X.shape[1:], X.dtype)
            X = np.concatenate([X, pad], axis=0)
        return X, n

    @staticmethod
    def _fail(batch: List[_Request], error: BaseException):
        for r in batch:
            r.error = error
            r.event.set()

    @staticmethod
    def _distribute(batch: List[_Request], out: np.ndarray):
        off = 0
        for r in batch:
            k = r.x.shape[0]
            r.result = out[off:off + k]
            off += k
            r.event.set()

    @staticmethod
    def _record_phase(name: str, batch: List[_Request], start_us: float,
                      end_us: float, **attrs):
        """Stamp one pipeline phase into EVERY member request's trace —
        the per-request decomposition the batch-level spans can't give
        (a batch mixes requests from different traces)."""
        for r in batch:
            if r.ctx is not None:
                record_span(name, start_us, end_us, ctx=r.ctx, **attrs)

    def _observe_batch(self, obs: "_ServingMetrics", n: int, target: int):
        obs.batch_occupancy.observe(n / max(self.batch_limit, 1))
        obs.bucket_fill.observe(n / max(target, 1))
        key = (target,)
        if key in self._seen_buckets:
            obs.bucket_hits.inc()
        else:
            self._seen_buckets.add(key)
            obs.bucket_misses.inc()
            # first use of this padded shape — the trace/compile it
            # provokes in the model's _output_jit claims this cause, so
            # /debug/compiles names the bucket behind the compile. The
            # cause is noted per MODEL, not per instance: the jit cache
            # lives on the model, so a second ParallelInference over the
            # same net records a (per-instance) miss that compiles
            # nothing — a pending cause there would mislabel the next
            # unrelated compile within the claim window
            model_seen = self.model.__dict__.setdefault(
                "_cw_seen_buckets", set())
            if key not in model_seen:
                model_seen.add(key)
                _cw.note_cause("bucket_miss", bucket=target)
        obs.batches.inc()

    # ------------------------------------------------- sync loop (ASYNC=0)
    def _serve_loop(self):
        """Single-threaded synchronous serve loop: one batch in flight,
        pad to batch_limit — the DL4J_TPU_ASYNC=0 behavior."""
        obs = _ServingMetrics.get()
        while not self._stop.is_set():
            batch = self._next_window()
            if batch is None:
                continue
            try:
                t_pad = now_us()
                X, n = self._pad_concat(batch, self.batch_limit)
                self._record_phase("bucket_pad", batch, t_pad, now_us(),
                                   bucket=self.batch_limit)
                self._observe_batch(obs, n, self.batch_limit)
                t0 = time.perf_counter()
                t_dev = now_us()
                with _span("inference_batch", requests=len(batch),
                           examples=n):
                    # sync loop: dispatch + device + transfer are one
                    # blocking call, so the whole thing is the request's
                    # "device" phase
                    out = self._forward(X)[:n]
                t_done = now_us()
                self._record_phase("device", batch, t_dev, t_done,
                                   examples=n)
                obs.straggler.observe(time.perf_counter() - t0)
                self._distribute(batch, out)
                self._record_phase("complete", batch, t_done, now_us())
                _flight().progress("inference_batch")
                _devmem.sample()
            except Exception as e:             # surface errors to callers
                self._fail(batch, e)
        if self._held is not None:             # don't strand the overflow
            self._held.error = RuntimeError("ParallelInference shut down")
            self._held.event.set()
            self._held = None

    # ------------------------------------------- async pipeline (default)
    def _put_stage(self, q: queue.Queue, item) -> bool:
        """Stop-aware bounded put between pipeline stages (backpressure:
        a full downstream queue throttles this stage)."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _batch_loop(self):
        """Stage 1 — coalesce request windows, pad to the shape bucket."""
        obs = _ServingMetrics.get()
        while not self._stop.is_set():
            batch = self._next_window()
            if batch is None:
                continue
            try:
                total = sum(r.x.shape[0] for r in batch)
                target = self._bucket_for(total)
                t_pad = now_us()
                X, n = self._pad_concat(batch, target)
                self._record_phase("bucket_pad", batch, t_pad, now_us(),
                                   bucket=target)
                self._observe_batch(obs, n, target)
            except Exception as e:
                self._fail(batch, e)
                continue
            if not self._put_stage(self._dispatch_q, (X, batch, n)):
                self._fail(batch,
                           RuntimeError("ParallelInference shut down"))
        if self._held is not None:             # don't strand the overflow
            self._held.error = RuntimeError("ParallelInference shut down")
            self._held.event.set()
            self._held = None

    _DONE = object()    # dispatcher→completer end-of-stream marker

    def _dispatch_loop(self):
        """Stage 2 — non-blocking device dispatch; up to inflight_limit
        batches queued on the device while earlier results transfer back."""
        obs = _ServingMetrics.get()
        try:
            while True:
                try:
                    X, batch, n = self._dispatch_q.get(timeout=0.05)
                except queue.Empty:
                    if self._stop.is_set():
                        break
                    continue
                t_disp = time.perf_counter()
                try:
                    t_us = now_us()
                    with _span("inference_dispatch", requests=len(batch),
                               examples=n):
                        dev = self._forward_async(X)
                    self._record_phase("dispatch", batch, t_us, now_us(),
                                       examples=n)
                except Exception as e:         # trace/compile-time errors
                    self._fail(batch, e)
                    continue
                if self._put_stage(self._complete_q,
                                   (dev, batch, n, t_disp)):
                    obs.inflight.set(self._complete_q.qsize())
                else:
                    # shutdown raced the handoff: materialize inline so
                    # the callers still get their (valid) results
                    self._complete_one(obs, dev, batch, n, t_disp)
        finally:
            # end-of-stream marker: a plain blocking put is safe because
            # the completer consumes until it sees the marker (it cannot
            # exit first), and it happens-after every real put from this
            # thread — so no dispatched batch is stranded behind the
            # completer's exit check (that race existed with a
            # stop-flag-only exit)
            self._complete_q.put(self._DONE)

    def _complete_one(self, obs, dev, batch, n, t_dispatch=None):
        try:
            t_dev = now_us()
            with _span("inference_complete", requests=len(batch),
                       examples=n):
                out = np.asarray(dev)[:n]      # device→host sync point
            t_done = now_us()
            # "device" = dispatch→materialize (execution + transfer tail);
            # "complete" = slicing the host buffer out to callers
            self._record_phase("device", batch, t_dev, t_done, examples=n)
            self._distribute(batch, out)
            self._record_phase("complete", batch, t_done, now_us())
            if t_dispatch is not None:
                # straggler check over the batch's dispatch→complete wall
                # time — the serving analog of a slow train step
                obs.straggler.observe(time.perf_counter() - t_dispatch)
            _flight().progress("inference_batch")
            # batch boundary: sample device memory (throttled; no-op on
            # stat-less CPU backends)
            _devmem.sample()
        except Exception as e:                 # execution-time errors
            self._fail(batch, e)

    def _complete_loop(self):
        """Stage 3 — block on the device→host transfer, hand out slices.
        Exits only on the dispatcher's end-of-stream marker, which follows
        every real item in queue order — in-flight batches always land."""
        obs = _ServingMetrics.get()
        while True:
            item = self._complete_q.get()
            if item is self._DONE:
                break
            self._complete_one(obs, *item)
            obs.inflight.set(self._complete_q.qsize())
