"""Parameter-sharding rules: layer param pytree → PartitionSpec pytree.

This is the TP (tensor-parallel) policy layer — net-new capability vs the
reference (SURVEY P4: absent upstream). Megatron-style column sharding of
matmul weights over the ``model`` axis; XLA GSPMD propagates activations and
inserts the allreduce/allgather collectives.
"""
from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS, axis_size


def param_pspec(pname: str, ndim: int, model_axis: str = MODEL_AXIS) -> P:
    """Default tensor-parallel rule for one parameter.

    - 2D kernels  (in, out)        → shard out over ``model`` (column parallel)
    - 4D conv     (H, W, I, O)     → shard O over ``model``
    - recurrent RW and norm/scale params → replicated (recurrent TP would
      put a collective inside the scan body; deliberately avoided)
    - biases matching a sharded out-dim → sharded to stay aligned
    """
    if pname.startswith(("RW", "bR", "gamma", "beta", "mean", "var", "p")):
        return P()
    if ndim == 2:
        return P(None, model_axis)
    if ndim == 4:
        return P(None, None, None, model_axis)
    if ndim == 1 and pname.startswith("b"):
        return P(model_axis)
    return P()


def tp_shardings(params, mesh: Mesh, enable: bool = True):
    """NamedSharding pytree for a {layer: {param: array}} tree."""
    def one(path, leaf):
        pname = str(path[-1].key) if hasattr(path[-1], "key") else ""
        if not enable or MODEL_AXIS not in mesh.axis_names:
            return NamedSharding(mesh, P())
        spec = param_pspec(pname, leaf.ndim)
        # don't shard dims that aren't divisible — GSPMD requires it
        ok = all(
            ax is None or leaf.shape[i] % axis_size(mesh, ax) == 0
            for i, ax in enumerate(spec))
        return NamedSharding(mesh, spec if ok else P())
    return jax.tree_util.tree_map_with_path(one, params)


def replicate_tree(tree, mesh: Mesh):
    return jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)
