"""Mixture-of-experts with expert parallelism (SURVEY P7: ABSENT in the
reference — net-new TPU capability).

Switch-Transformer-style top-1 routing in the dense-dispatch formulation —
the TPU-canonical shape: routing becomes three einsums over a fixed-capacity
(tokens, experts, capacity) one-hot dispatch tensor, so shapes stay STATIC
under jit (no data-dependent gather/scatter), and sharding the expert axis
over the ``expert`` mesh dimension makes GSPMD insert the token all-to-alls
over ICI. Over-capacity tokens are dropped (their output is the residual
zero), exactly as in Switch/GShard.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import EXPERT_AXIS, axis_size


@dataclasses.dataclass
class MoEConfig:
    d_model: Optional[int] = None   # None: filled in from the host model's
    d_ff: Optional[int] = None      # config (TransformerConfig.moe path)
    num_experts: int = 8
    capacity_factor: float = 1.25
    router_noise: float = 0.0       # jitter for load-balancing exploration
    top_k: int = 1                  # 1 = Switch; 2 = GShard top-2 routing
                                    # (renormalized gates, second choices
                                    # queue behind ALL first choices)

    def __post_init__(self):
        if self.top_k not in (1, 2):
            raise ValueError(f"top_k must be 1 or 2 (got {self.top_k})")


def _check_resolved(cfg: MoEConfig):
    if not cfg.d_model or not cfg.d_ff:
        raise ValueError(
            "MoEConfig.d_model/d_ff are unset — pass them explicitly, or "
            "hand the config to TransformerConfig(moe=...) which fills them "
            "from the host model")


def init_moe_params(cfg: MoEConfig, key, scale: float = 0.02):
    _check_resolved(cfg)
    kg, k1, k2 = jax.random.split(key, 3)
    E, d, f = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "Wg": jax.random.normal(kg, (d, E)) * scale,
        "W1": jax.random.normal(k1, (E, d, f)) * scale,
        "b1": jnp.zeros((E, f)),
        "W2": jax.random.normal(k2, (E, f, d)) * scale,
        "b2": jnp.zeros((E, d)),
    }


def moe_param_specs(expert_axis=None):
    """PartitionSpec tree for the MoE param leaves — the single source of the
    expert-sharding layout (router replicated, expert dim sharded)."""
    e = expert_axis
    return {"Wg": P(), "W1": P(e), "b1": P(e), "W2": P(e), "b2": P(e)}


def moe_param_shardings(cfg: MoEConfig, mesh: Mesh):
    """Expert-dim sharding over the ``expert`` mesh axis (router replicated)."""
    e = EXPERT_AXIS if EXPERT_AXIS in mesh.axis_names else None
    return jax.tree.map(lambda sp: NamedSharding(mesh, sp),
                        moe_param_specs(e),
                        is_leaf=lambda x: isinstance(x, P))


def moe_ffn(params, x, cfg: MoEConfig, mesh: Optional[Mesh] = None,
            rng=None):
    """Top-1 MoE FFN over (B, T, d). Returns (y, aux) where aux carries the
    Switch load-balancing loss and routing stats."""
    _check_resolved(cfg)
    B, T, d = x.shape
    E = cfg.num_experts
    G = B * T
    xt = x.reshape(G, d)

    logits = xt @ params["Wg"]                       # (G, E)
    if rng is not None and cfg.router_noise > 0:
        logits = logits + jax.random.uniform(
            rng, logits.shape, minval=1.0 - cfg.router_noise,
            maxval=1.0 + cfg.router_noise)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)          # (G,) first choice
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    C = int(np.ceil(G / E * cfg.capacity_factor * cfg.top_k))
    onehot = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)       # (G, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0             # (G, E)
    keep = (pos >= 0) & (pos < C)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=x.dtype)  # (G,E,C)
    dispatch = pos_oh * keep.astype(x.dtype)[..., None]          # (G, E, C)
    combine = dispatch * gate[:, None, None]
    n_routed = jnp.asarray(float(G), x.dtype)

    if cfg.top_k == 2:
        # GShard top-2: second choice = argmax with the first masked out;
        # gates renormalized over the two winners; second choices queue
        # BEHIND every first choice in each expert's capacity
        probs2 = probs * (1.0 - onehot)
        idx2 = jnp.argmax(probs2, axis=-1)
        gate2_raw = jnp.take_along_axis(probs2, idx2[:, None],
                                        axis=-1)[:, 0]
        denom = gate + gate2_raw + 1e-9
        g1 = gate / denom
        g2 = gate2_raw / denom
        onehot2 = jax.nn.one_hot(idx2, E, dtype=x.dtype)
        first_counts = jnp.sum(onehot, axis=0, keepdims=True)    # (1, E)
        pos2 = (jnp.cumsum(onehot2, axis=0) + first_counts) \
            * onehot2 - 1.0
        keep2 = (pos2 >= 0) & (pos2 < C)
        pos2_oh = jax.nn.one_hot(pos2.astype(jnp.int32), C, dtype=x.dtype)
        dispatch2 = pos2_oh * keep2.astype(x.dtype)[..., None]
        combine = (dispatch * g1[:, None, None]
                   + dispatch2 * g2[:, None, None])
        dispatch = dispatch + dispatch2
        n_routed = jnp.asarray(float(2 * G), x.dtype)

    # token → expert buffers; sharding hint puts E on the expert axis so
    # GSPMD routes via all-to-all over ICI
    ei = jnp.einsum("gec,gd->ecd", dispatch, xt)                 # (E, C, d)
    if mesh is not None and EXPERT_AXIS in mesh.axis_names:
        ei = lax.with_sharding_constraint(
            ei, NamedSharding(mesh, P(EXPERT_AXIS)))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", ei, params["W1"])
                    + params["b1"][:, None, :])
    out_e = jnp.einsum("ecf,efd->ecd", h, params["W2"]) \
        + params["b2"][:, None, :]
    if mesh is not None and EXPERT_AXIS in mesh.axis_names:
        out_e = lax.with_sharding_constraint(
            out_e, NamedSharding(mesh, P(EXPERT_AXIS)))
    y = jnp.einsum("gec,ecd->gd", combine, out_e)                # (G, d)

    # Switch/GShard aux loss: E * Σ_e fraction_first_choice_e · mean_prob_e
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(frac * mean_prob)
    dropped = jnp.maximum(0.0, 1.0 - jnp.sum(dispatch) / n_routed)
    return y.reshape(B, T, d), {"aux_loss": aux_loss,
                                "dropped_fraction": dropped,
                                "expert_fraction": frac}


def moe_reference_dense(params, x, cfg: MoEConfig):
    """Unrouted check path: every token through its top-k expert(s) with no
    capacity limit (the semantics dispatch must match when nothing drops)."""
    B, T, d = x.shape
    xt = x.reshape(-1, d)
    probs = jax.nn.softmax(xt @ params["Wg"], axis=-1)

    def expert_out(idx):
        W1 = params["W1"][idx]        # (G, d, f)
        h = jax.nn.gelu(jnp.einsum("gd,gdf->gf", xt, W1)
                        + params["b1"][idx])
        return jnp.einsum("gf,gfd->gd", h, params["W2"][idx]) \
            + params["b2"][idx]

    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
    if cfg.top_k == 1:
        y = expert_out(idx) * gate[:, None]
    else:
        probs2 = probs * (1.0 - jax.nn.one_hot(idx, cfg.num_experts,
                                               dtype=x.dtype))
        idx2 = jnp.argmax(probs2, axis=-1)
        gate2 = jnp.take_along_axis(probs2, idx2[:, None], axis=-1)[:, 0]
        denom = gate + gate2 + 1e-9
        y = expert_out(idx) * (gate / denom)[:, None] \
            + expert_out(idx2) * (gate2 / denom)[:, None]
    return y.reshape(B, T, d)
