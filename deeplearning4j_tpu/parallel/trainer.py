"""ShardedTrainer — the distributed training engine.

Replaces the reference's three data-parallel mechanisms (SURVEY P1–P3):
``ParallelWrapper`` per-device trainer threads, Spark parameter averaging,
and the Aeron gradient-sharing stack (EncodedGradientsAccumulator +
threshold codec + UDP mesh). TPU-native design: ONE jitted train step whose
inputs carry shardings — batch sharded over ``data``, params sharded over
``model`` (TP) or replicated — and XLA GSPMD emits the gradient allreduce
over ICI. There is no accumulator, residual, or transport; synchronous dense
allreduce replaces async sparse updates (convergence-parity note in
BASELINE.md).
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.ndarray import _unwrap
from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.observability import device_memory as _devmem
from deeplearning4j_tpu.observability import global_registry
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.parallel import mesh as _mesh
from deeplearning4j_tpu.parallel.mesh import MeshSpec, DATA_AXIS
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.parallel.sharding import replicate_tree, tp_shardings
from jax.sharding import NamedSharding, PartitionSpec as P


class ShardedTrainer:
    """Train a MultiLayerNetwork/ComputationGraph over a device mesh.

    The wrapped net keeps its API; this class re-homes its params/opt-state
    onto the mesh and swaps the train step for a sharded one.
    """

    def __init__(self, net, mesh_spec: Optional[MeshSpec] = None, devices=None,
                 tensor_parallel: bool = False,
                 shard_optimizer_state: bool = False,
                 preemption_handler=None, checkpoint_dir: Optional[str] = None):
        self.net = net
        self.mesh = (mesh_spec or MeshSpec.data_parallel()).build(devices)
        self.tensor_parallel = tensor_parallel
        # preemption safety (SURVEY §5.3): when a handler is given (or one is
        # installed process-wide), fit() checks the latch at every batch
        # boundary, writes a final checkpoint into ``checkpoint_dir`` and
        # raises TrainingPreempted — the pod-reclaim path, first-class
        self.preemption_handler = preemption_handler
        self.checkpoint_dir = checkpoint_dir
        # ZeRO-style cross-replica weight-update sharding (Xu et al. 2020,
        # arXiv:2004.13336 — the XLA weight-update-sharding recipe): optimizer
        # moments shard over the data axis while params stay replicated; XLA
        # converts the allreduce into reduce-scatter + sharded update +
        # all-gather, cutting per-chip optimizer memory by the DP degree
        self.shard_optimizer_state = shard_optimizer_state
        self._placed = False
        self._grad_bytes = 0     # per-step gradient allreduce payload
        self._collective_bytes = {}    # per-op bytes/step expectation
        self._collective_counters = {}
        self._obs = None         # lazily-bound collective instruments

    # ------------------------------------------------------------------ setup
    def _place(self):
        net = self.net
        if not net._initialized:
            net.init()
        pshard = tp_shardings(net._params, self.mesh, enable=self.tensor_parallel)
        net._params = jax.device_put(net._params, pshard)
        if net._states:
            net._states = jax.device_put(net._states, replicate_tree(net._states, self.mesh))
        if net._opt_state is None or net._iteration == 0:
            # fresh net: init under jit so Adam moments inherit param shardings
            net._opt_state = jax.jit(net._opt.init)(net._params)
        else:
            # warm start: PRESERVE accumulated moments/step count; the
            # name-keyed TP rule applies to the param-shaped state leaves too
            oshard = tp_shardings(net._opt_state, self.mesh, enable=self.tensor_parallel)
            net._opt_state = jax.device_put(net._opt_state, oshard)
        if self.shard_optimizer_state:
            net._opt_state = jax.device_put(
                net._opt_state, self._opt_state_shardings(net._opt_state))
        # observability: the synchronous data-parallel step allreduces every
        # gradient leaf once — the payload is exactly the param-tree bytes
        # (GSPMD fuses the collective into the step, so duration is the
        # sharded step's wall time; bytes are exact)
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS) \
            if DATA_AXIS in self.mesh.axis_names else 1
        param_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(net._params)
            if hasattr(leaf, "size"))
        self._grad_bytes = param_bytes if n_data > 1 else 0
        # per-collective traffic expectation (analytic): the plain
        # synchronous step allreduces the whole gradient tree once; under
        # ZeRO-style weight-update sharding XLA rewrites that into a
        # reduce-scatter + all-gather pair, each moving (n-1)/n of the
        # param bytes over the wire (ring schedule). Counted per step
        # into dl4j_collective_bytes_total{collective} and served next to
        # the measured cost-model numbers on /debug/perf.
        if n_data > 1 and self.shard_optimizer_state:
            wire = param_bytes * (n_data - 1) // n_data
            self._collective_bytes = {"reduce_scatter": wire,
                                      "all_gather": wire}
        elif n_data > 1:
            self._collective_bytes = {"allreduce": param_bytes}
        else:
            self._collective_bytes = {}
        reg = global_registry()
        bytes_c = reg.counter(
            "dl4j_collective_bytes_total",
            "bytes moved per collective op (gradient allreduce payload = "
            "param bytes x steps; ZeRO mode splits into reduce-scatter + "
            "all-gather wire bytes)",
            label_names=("collective",))
        expected_g = reg.gauge(
            "dl4j_collective_expected_bytes",
            "analytic per-step traffic expectation of each collective the "
            "sharded train step fuses (compare against the cost model's "
            "bytes accessed on /debug/perf)",
            label_names=("collective",))
        self._collective_counters = {}
        for op, nbytes in self._collective_bytes.items():
            self._collective_counters[op] = bytes_c.labels(collective=op)
            expected_g.labels(collective=op).set(nbytes)
        self._obs = (
            reg.histogram("dl4j_collective_step_seconds",
                          "wall time of the sharded train step (compute + "
                          "fused gradient allreduce)",
                          label_names=("collective",)).labels(
                              collective="allreduce"),
            reg.gauge("dl4j_mesh_devices", "devices in the active mesh",
                      label_names=("axis",)))
        for axis in self.mesh.axis_names:
            self._obs[1].labels(axis=str(axis)).set(
                _mesh.axis_size(self.mesh, axis))
        # cost observatory: steps through this trainer account under their
        # own entry (global-program FLOPs over a mesh-sized peak). The
        # placement recompile often hits the jaxpr cache WITHOUT a retrace,
        # so the entry is invalidated explicitly — the next step
        # re-lowers at the sharded signature
        _cost.global_cost_model().set_scale(
            "ShardedTrainer.step", self.mesh.size)
        _cost.global_cost_model().note_collectives(
            "ShardedTrainer.step", self._collective_bytes)
        _cost.global_cost_model().invalidate("ShardedTrainer.step")
        # re-homing params onto the mesh changes the step's sharding
        # signature — the wrapped net's _train_step retraces once, and
        # the compile watch attributes that compile to this placement
        _cw.note_cause("sharded_placement",
                       mesh_axes=",".join(str(a)
                                          for a in self.mesh.axis_names))
        _devmem.sample()        # post-placement HBM baseline
        self._placed = True

    def _opt_state_shardings(self, opt_state):
        """Data-axis sharding for param-shaped optimizer moments: leaves
        whose largest dim divides the DP degree shard on that dim, scalars/
        indivisible leaves replicate."""
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS)

        def spec_for(leaf):
            shape = getattr(leaf, "shape", ())
            # compose with TP: a leaf already model-sharded keeps its layout
            # (re-sharding it over data would force per-step reshards and
            # fight the Megatron placement)
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and not sharding.is_fully_replicated:
                return sharding
            if n_data > 1 and shape:
                dim = int(np.argmax(shape))
                if shape[dim] % n_data == 0 and shape[dim] >= n_data:
                    parts = [None] * len(shape)
                    parts[dim] = DATA_AXIS
                    return NamedSharding(self.mesh, P(*parts))
            return NamedSharding(self.mesh, P())

        return jax.tree.map(spec_for, opt_state)

    def _shard_batch(self, x):
        if x is None:
            return None
        if isinstance(x, (tuple, list)):
            return type(x)(self._shard_batch(e) for e in x)
        if jax.process_count() > 1 and DATA_AXIS in self.mesh.axis_names:
            # multi-host (DCN) path: each process feeds its LOCAL partition
            # (ref: SharedTrainingWorker consumes worker-local RDD
            # partitions); assemble the global sharded batch across hosts
            x = np.asarray(_unwrap(x))
            n_shards = _mesh.axis_size(self.mesh, DATA_AXIS)
            per_proc = max(1, n_shards // jax.process_count())
            if x.shape[0] % per_proc != 0:
                # replicating would need identical values on every process,
                # which a process-local partition is not — fail loudly
                # instead of training on silently inconsistent data
                raise ValueError(
                    f"multi-host batch: local partition of {x.shape[0]} "
                    f"examples is not divisible by the {per_proc} data "
                    f"shards this process owns; feed equal-sized divisible "
                    f"partitions per process")
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P(DATA_AXIS)), x)
        x = jnp.asarray(_unwrap(x))
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS)
        # an indivisible (e.g. final partial) batch replicates instead of
        # erroring — the reference's ParallelWrapper accepts any batch size
        spec = (P(DATA_AXIS) if DATA_AXIS in self.mesh.axis_names
                and x.shape[0] % n_data == 0 else P())
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------ train
    def _active_preemption_handler(self):
        if self.preemption_handler is not None:
            return self.preemption_handler
        from deeplearning4j_tpu.utils.preemption import PreemptionHandler
        return PreemptionHandler._installed

    def _check_preemption(self):
        """Batch-boundary preemption latch check: checkpoint + unwind.
        Runs between jitted steps so no donated buffer is mid-flight."""
        handler = self._active_preemption_handler()
        if handler is None or not handler.preempted:
            return
        from deeplearning4j_tpu.utils.preemption import (
            PreemptionSafeListener, TrainingPreempted)
        path = None
        if self.checkpoint_dir is not None:
            import os
            # the filename contract of PreemptionSafeListener so
            # resume_or_new discovers trainer-written checkpoints; every
            # rank reports the same path (shared storage), rank 0 writes it
            path = os.path.join(
                self.checkpoint_dir,
                PreemptionSafeListener.FINAL_NAME.format(
                    model=type(self.net).__name__))
            if jax.process_index() == 0:
                from deeplearning4j_tpu.utils.serialization import (
                    save_model_atomic)
                os.makedirs(self.checkpoint_dir, exist_ok=True)
                # atomic: a hard kill after the grace window must never
                # leave a torn zip for resume_or_new to trust
                save_model_atomic(self.net, path)
        # no cross-rank barrier (a single-rank latch would deadlock one);
        # non-zero ranks keep the REAL path but flag it possibly in flight
        raise TrainingPreempted(path or "<no checkpoint_dir configured>",
                                self.net._iteration,
                                checkpoint_ready=(path is not None
                                                  and jax.process_index() == 0))

    def fit(self, data, labels=None, epochs: int = 1):
        """Same surface as the wrapped net's fit; batches are sharded over the
        ``data`` axis before entering the jitted step. Runs under a root
        ``fit`` span (steps + the mesh-placement prefetch thread share one
        trace) and armed on the flight recorder — a wedged collective
        shows up as a postmortem bundle, not a silent hang."""
        with _flight().arm("fit:ShardedTrainer"), \
                _span("fit", model=type(self.net).__name__, sharded=True,
                      epochs=epochs):
            return self._fit_impl(data, labels, epochs)

    def _fit_impl(self, data, labels=None, epochs: int = 1):
        if not self._placed:
            self._place()
        net = self.net
        if labels is not None:
            self._fit_batch(data, labels)
            self._check_preemption()
            return self
        if hasattr(data, "features"):
            self._fit_batch(data.features, data.labels,
                            self._ds_mask(data, "features"),
                            self._ds_mask(data, "labels"))
            self._check_preemption()
            return self
        # device prefetch with the trainer's own placement: batch k+1 is
        # sharded onto the mesh on a background thread while step k
        # computes (skipped multi-host — the global-array assembly there
        # must happen on the thread that owns the per-process partition)
        we_wrapped = False
        if jax.process_count() == 1:
            from deeplearning4j_tpu.data.iterators import (
                DevicePrefetchIterator, _place_dataset)
            wrapped = DevicePrefetchIterator.wrap(
                data, placement=lambda ds: _place_dataset(
                    ds, self._shard_batch))
            we_wrapped, data = wrapped is not data, wrapped
        try:
            for _ in range(epochs):
                for lst in net._listeners:
                    lst.on_epoch_start(net, net._epoch)
                if hasattr(data, "reset"):
                    data.reset()
                for ds in data:
                    self._fit_batch(ds.features, ds.labels,
                                    self._ds_mask(ds, "features"),
                                    self._ds_mask(ds, "labels"))
                    self._check_preemption()
                # epoch boundary is a mandatory sync point (deferred loss)
                net._sync_score()
                for lst in net._listeners:
                    lst.on_epoch_end(net, net._epoch)
                net._epoch += 1
        finally:
            if we_wrapped:
                # preemption/interrupt must not strand the prefetch thread
                # with sharded device batches pinned
                data.close()
        return self

    @staticmethod
    def _ds_mask(ds, which: str):
        return (getattr(ds, f"{which}_masks", None) or
                getattr(ds, f"{which}_mask", None))

    def _fit_batch(self, x, y, fmask=None, lmask=None):
        """Shard the batch onto the mesh, then delegate to the net's own
        _fit_batch — it already handles TBPTT chunking, RNN carries, masks,
        listeners, and MLN/CG arity; shardings survive the jnp.asarray
        pass-through and GSPMD does the rest."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if _faults.armed():
            # chaos injection point for the collective path: fires before
            # the batch is placed on the mesh, i.e. before the sharded
            # step (and its fused gradient allreduce) owns any buffer
            _faults.check("allreduce")
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        t0 = time.perf_counter()
        # only steps driven THROUGH the trainer book under the sharded
        # entry (mesh-scaled peak); cleared so a later direct net.fit()
        # reverts to the single-device entry
        self.net._cost_fn_name = "ShardedTrainer.step"
        try:
            with _span("sharded_step", grad_bytes=self._grad_bytes):
                if isinstance(self.net, MultiLayerNetwork):
                    self.net._fit_batch(x, y, fmask, lmask)
                else:  # ComputationGraph: tuple-valued inputs/labels/masks
                    tup = lambda v: (() if v is None
                                     else tuple(v) if isinstance(v, (tuple,
                                                                     list))
                                     else (v,))
                    self.net._fit_batch(tup(x), tup(y), tup(fmask),
                                        tup(lmask))
        finally:
            self.net._cost_fn_name = None
        if self._obs is not None:
            for op, counter in self._collective_counters.items():
                counter.inc(self._collective_bytes[op])
            self._obs[0].observe(time.perf_counter() - t0)

    # --------------------------------------------------------------- inference
    def output(self, x):
        if not self._placed:
            self._place()
        x = self._shard_batch(x)
        return self.net.output(x)

    def score(self):
        return self.net._sync_score()


class ParallelWrapper:
    """Single-host multi-device data-parallel facade
    (ref: ``org.deeplearning4j.parallelism.ParallelWrapper`` — SURVEY P1).

    The reference clones the model per GPU and averages params every
    ``averagingFrequency`` iterations on separate trainer threads; here the
    same devices form a ``data`` mesh and every step IS the averaged step
    (sync allreduce), so ``averagingFrequency`` is accepted for API parity
    and ignored (documented divergence)."""

    def __init__(self, model, workers: Optional[int] = None,
                 prefetch_buffer: int = 2, averaging_frequency: int = 1,
                 report_score_after_averaging: bool = True):
        n = workers or len(jax.devices())
        self._trainer = ShardedTrainer(model, MeshSpec.data_parallel(n),
                                       devices=jax.devices()[:n])
        self.model = model

    @staticmethod
    def builder(model):
        return _PWBuilder(model)

    def fit(self, data, labels=None, epochs: int = 1):
        return self._trainer.fit(data, labels, epochs)

    def shutdown(self):
        pass


class _PWBuilder:
    """ref: ParallelWrapper.Builder fluent API."""

    def __init__(self, model):
        self._model = model
        self._workers = None
        self._prefetch = 2
        self._avg_freq = 1

    def workers(self, n: int):
        self._workers = n
        return self

    def prefetch_buffer(self, n: int):
        self._prefetch = n
        return self

    prefetchBuffer = prefetch_buffer

    def averaging_frequency(self, n: int):
        self._avg_freq = n
        return self

    averagingFrequency = averaging_frequency

    def build(self) -> ParallelWrapper:
        return ParallelWrapper(self._model, self._workers, self._prefetch, self._avg_freq)
