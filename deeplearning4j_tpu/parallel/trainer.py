"""ShardedTrainer — the distributed training engine.

Replaces the reference's three data-parallel mechanisms (SURVEY P1–P3):
``ParallelWrapper`` per-device trainer threads, Spark parameter averaging,
and the Aeron gradient-sharing stack (EncodedGradientsAccumulator +
threshold codec + UDP mesh). TPU-native design: ONE jitted train step whose
inputs carry shardings — batch sharded over ``data``, params sharded over
``model`` (TP) or replicated — and XLA GSPMD emits the gradient allreduce
over ICI. Synchronous dense allreduce replaces async sparse updates by
default (convergence-parity note in BASELINE.md); the reference's
threshold-codec accumulator survives as the OPT-IN compressed exchange
(``grad_compression`` / ``DL4J_TPU_GRAD_COMPRESS`` → error-feedback
threshold collectives, parallel/compression.py).
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu import async_runtime as _async
from deeplearning4j_tpu.ndarray.ndarray import _unwrap
from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.observability import device_memory as _devmem
from deeplearning4j_tpu.observability import global_registry
from deeplearning4j_tpu.observability import numerics as _num
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability import train_metrics as _tm
from deeplearning4j_tpu.nn._step_tail import finish_train_step
from deeplearning4j_tpu.observability.flight_recorder import (
    global_flight_recorder as _flight)
from deeplearning4j_tpu.parallel import compression as _comp
from deeplearning4j_tpu.parallel import mesh as _mesh
from deeplearning4j_tpu.parallel.mesh import MeshSpec, DATA_AXIS
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.parallel.sharding import replicate_tree, tp_shardings
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax spells it jax.experimental.shard_map
    from jax.experimental.shard_map import shard_map

log = logging.getLogger("deeplearning4j_tpu")


class ShardedTrainer:
    """Train a MultiLayerNetwork/ComputationGraph over a device mesh.

    The wrapped net keeps its API; this class re-homes its params/opt-state
    onto the mesh and swaps the train step for a sharded one.
    """

    def __init__(self, net, mesh_spec: Optional[MeshSpec] = None, devices=None,
                 tensor_parallel: bool = False,
                 shard_optimizer_state: bool = False,
                 preemption_handler=None, checkpoint_dir: Optional[str] = None,
                 grad_compression=None):
        self.net = net
        # the declarative spec is kept so elastic shrink/re-expand can
        # rebuild the mesh over a different device set (resize_mesh)
        self._mesh_spec = mesh_spec or MeshSpec.data_parallel()
        self.mesh = self._mesh_spec.build(devices)
        self.tensor_parallel = tensor_parallel
        # compressed gradient exchange (Strom 2015 error-feedback threshold
        # collectives — the EncodedGradientsAccumulator analog): a
        # ThresholdAlgorithm / spec string / True enables it; None defers
        # to the DL4J_TPU_GRAD_COMPRESS env knob; the env knob "0" is the
        # kill switch (dense path, byte-identical) either way. Resolved at
        # placement time so the knob is read live.
        self.grad_compression = grad_compression
        self._compression = None      # resolved ThresholdAlgorithm
        self._comp_layout = None      # bucketed-flattening plan
        self._comp_step = None        # cached jitted compressed step
        self._comp_obs = None         # (sparsity gauge, residual-norm hist)
        self._pending_comp_stats = [] # device scalars awaiting a sync point
        self._comp_fallback_warned = False
        # preemption safety (SURVEY §5.3): when a handler is given (or one is
        # installed process-wide), fit() checks the latch at every batch
        # boundary, writes a final checkpoint into ``checkpoint_dir`` and
        # raises TrainingPreempted — the pod-reclaim path, first-class
        self.preemption_handler = preemption_handler
        self.checkpoint_dir = checkpoint_dir
        # ZeRO-style cross-replica weight-update sharding (Xu et al. 2020,
        # arXiv:2004.13336 — the XLA weight-update-sharding recipe): optimizer
        # moments shard over the data axis while params stay replicated; XLA
        # converts the allreduce into reduce-scatter + sharded update +
        # all-gather, cutting per-chip optimizer memory by the DP degree
        self.shard_optimizer_state = shard_optimizer_state
        self._placed = False
        self._grad_bytes = 0     # per-step gradient allreduce payload
        self._collective_bytes = {}    # per-op bytes/step expectation
        self._collective_counters = {}
        self._obs = None         # lazily-bound collective instruments

    # ------------------------------------------------------------------ setup
    def _place(self):
        net = self.net
        if not net._initialized:
            net.init()
        pshard = tp_shardings(net._params, self.mesh, enable=self.tensor_parallel)
        net._params = jax.device_put(net._params, pshard)
        if net._states:
            net._states = jax.device_put(net._states, replicate_tree(net._states, self.mesh))
        if net._opt_state is None or net._iteration == 0:
            # fresh net: init under jit so Adam moments inherit param shardings
            net._opt_state = jax.jit(net._opt.init)(net._params)
        else:
            # warm start: PRESERVE accumulated moments/step count; the
            # name-keyed TP rule applies to the param-shaped state leaves too
            oshard = tp_shardings(net._opt_state, self.mesh, enable=self.tensor_parallel)
            net._opt_state = jax.device_put(net._opt_state, oshard)
        if self.shard_optimizer_state:
            net._opt_state = jax.device_put(
                net._opt_state, self._opt_state_shardings(net._opt_state))
        # observability: the synchronous data-parallel step allreduces every
        # gradient leaf once — the payload is exactly the param-tree bytes
        # (GSPMD fuses the collective into the step, so duration is the
        # sharded step's wall time; bytes are exact)
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS) \
            if DATA_AXIS in self.mesh.axis_names else 1
        param_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(net._params)
            if hasattr(leaf, "size"))
        self._grad_bytes = param_bytes if n_data > 1 else 0
        # compressed gradient exchange: resolve the knob/arg LIVE at every
        # placement (the kill switch must also disarm an already-built
        # trainer on re-place) and seed/restore the error-feedback state
        self._resolve_compression(n_data)
        # per-collective traffic expectation (analytic): the plain
        # synchronous step allreduces the whole gradient tree once; under
        # ZeRO-style weight-update sharding XLA rewrites that into a
        # reduce-scatter + all-gather pair, each moving (n-1)/n of the
        # param bytes over the wire (ring schedule); the compressed path
        # moves the int8 sign payload + per-bucket scales instead. Counted
        # per step into dl4j_collective_bytes_total{collective} and served
        # next to the measured cost-model numbers on /debug/perf.
        self._fallback_bytes = {}
        if n_data > 1 and self._compression is not None:
            self._collective_bytes = {
                "compressed_allreduce":
                    _comp.payload_bytes(self._comp_layout, n_data)}
            # an indivisible batch falls back to the dense exchange for
            # that batch — its traffic books as a plain allreduce, never
            # as compressed wire bytes
            self._fallback_bytes = {"allreduce": param_bytes}
        elif n_data > 1 and self.shard_optimizer_state:
            wire = param_bytes * (n_data - 1) // n_data
            self._collective_bytes = {"reduce_scatter": wire,
                                      "all_gather": wire}
        elif n_data > 1:
            self._collective_bytes = {"allreduce": param_bytes}
        else:
            self._collective_bytes = {}
        reg = global_registry()
        bytes_c = reg.counter(
            "dl4j_collective_bytes_total",
            "bytes moved per collective op (gradient allreduce payload = "
            "param bytes x steps; ZeRO mode splits into reduce-scatter + "
            "all-gather wire bytes)",
            label_names=("collective",))
        expected_g = reg.gauge(
            "dl4j_collective_expected_bytes",
            "analytic per-step traffic expectation of each collective the "
            "sharded train step fuses (compare against the cost model's "
            "bytes accessed on /debug/perf)",
            label_names=("collective",))
        self._collective_counters = {}
        for op in {**self._fallback_bytes, **self._collective_bytes}:
            self._collective_counters[op] = bytes_c.labels(collective=op)
        for op, nbytes in self._collective_bytes.items():
            expected_g.labels(collective=op).set(nbytes)
        self._obs = (
            reg.histogram("dl4j_collective_step_seconds",
                          "wall time of the sharded train step (compute + "
                          "fused gradient allreduce)",
                          label_names=("collective",)).labels(
                              collective="allreduce"),
            reg.gauge("dl4j_mesh_devices", "devices in the active mesh",
                      label_names=("axis",)))
        for axis in self.mesh.axis_names:
            self._obs[1].labels(axis=str(axis)).set(
                _mesh.axis_size(self.mesh, axis))
        # cost observatory: steps through this trainer account under their
        # own entry (global-program FLOPs over a mesh-sized peak). The
        # placement recompile often hits the jaxpr cache WITHOUT a retrace,
        # so the entry is invalidated explicitly — the next step
        # re-lowers at the sharded signature
        _cost.global_cost_model().set_scale(
            "ShardedTrainer.step", self.mesh.size)
        _cost.global_cost_model().note_collectives(
            "ShardedTrainer.step", self._collective_bytes)
        if self._compression is not None:
            payload = _comp.payload_bytes(self._comp_layout, n_data)
            dense = _comp.dense_bytes(self._comp_layout)
            ratio = dense / max(1, payload)
            reg.gauge(
                "dl4j_grad_compression_ratio",
                "dense gradient bytes / encoded wire payload bytes of the "
                "compressed exchange (sign-mask int8 + per-bucket scale)"
            ).set(ratio)
            self._comp_obs = (
                reg.gauge(
                    "dl4j_grad_compression_sparsity_ratio",
                    "fraction of gradient elements whose magnitude cleared "
                    "the threshold in the last synced compressed step "
                    "(the reference's 'sparsity ratio')"),
                reg.histogram(
                    "dl4j_grad_residual_norm",
                    "global L2 norm of the error-feedback residual after "
                    "each compressed step (mass deferred to later steps)"))
            _cost.global_cost_model().note_compression(
                "ShardedTrainer.step", {
                    **self._compression.describe(),
                    "buckets": list(zip(self._comp_layout.bucket_dtypes,
                                        self._comp_layout.bucket_sizes)),
                    "wire_payload_bytes": payload,
                    "dense_bytes": dense,
                    "compression_ratio": ratio,
                })
        _cost.global_cost_model().invalidate("ShardedTrainer.step")
        # re-homing params onto the mesh changes the step's sharding
        # signature — the wrapped net's _train_step retraces once, and
        # the compile watch attributes that compile to this placement
        _cw.note_cause("sharded_placement",
                       mesh_axes=",".join(str(a)
                                          for a in self.mesh.axis_names))
        _devmem.sample()        # post-placement HBM baseline
        self._placed = True

    def resize_mesh(self, devices=None):
        """Rebuild the mesh over a different device set (elastic shrink
        after host/device loss, re-expand when capacity returns). The
        next batch re-places params/opt-state/compression state onto the
        new mesh (``_place`` handles warm re-placement and replica-count
        reshaping of replica-keyed state); cached jitted steps keyed on
        the old mesh are dropped."""
        old = self.mesh.size
        self.mesh = self._mesh_spec.build(devices)
        self._placed = False
        self._comp_step = None
        self._comp_fallback_warned = False
        log.warning("mesh resized: %d -> %d devices (re-placement on the "
                    "next batch)", old, self.mesh.size)
        return self

    def _opt_state_shardings(self, opt_state):
        """Data-axis sharding for param-shaped optimizer moments: leaves
        whose largest dim divides the DP degree shard on that dim, scalars/
        indivisible leaves replicate."""
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS)

        def spec_for(leaf):
            shape = getattr(leaf, "shape", ())
            # compose with TP: a leaf already model-sharded keeps its layout
            # (re-sharding it over data would force per-step reshards and
            # fight the Megatron placement)
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and not sharding.is_fully_replicated:
                return sharding
            if n_data > 1 and shape:
                dim = int(np.argmax(shape))
                if shape[dim] % n_data == 0 and shape[dim] >= n_data:
                    parts = [None] * len(shape)
                    parts[dim] = DATA_AXIS
                    return NamedSharding(self.mesh, P(*parts))
            return NamedSharding(self.mesh, P())

        return jax.tree.map(spec_for, opt_state)

    # ------------------------------------------------- compressed exchange
    def _resolve_compression(self, n_data: int):
        """Resolve the builder arg + env knob into an active algorithm and
        seed (or restore) the error-feedback state. Runs at every
        placement so the kill switch works live."""
        self._compression = None
        self._comp_step = None
        algo = _comp.resolve_compression(self.grad_compression)
        reason = (None if algo is None
                  else self._compression_unsupported_reason())
        if algo is None or reason is not None:
            if reason is not None:
                log.warning("gradient compression requested but %s; using "
                            "the dense exchange", reason)
            # drop any carried error-feedback state: a dense run must not
            # keep checkpointing (or pin in device memory) a residual that
            # goes stale with every dense step — re-enabling compression
            # later re-seeds at zero instead of resuming stale mass
            if getattr(self.net, "_grad_compression_state", None) is not None:
                log.warning("dropping carried gradient-compression state "
                            "(dense exchange in force; re-enabling later "
                            "re-seeds the residual at zero)")
                self.net._grad_compression_state = None
            return
        self._compression = algo
        self._comp_layout = _comp.build_layout(self.net._params)
        self._init_comp_state(n_data)

    def _compression_unsupported_reason(self) -> Optional[str]:
        from deeplearning4j_tpu.nn.conf.configuration import BackpropType
        if DATA_AXIS not in self.mesh.axis_names:
            return "the mesh has no data axis to exchange over"
        for axis in self.mesh.axis_names:
            if axis != DATA_AXIS and _mesh.axis_size(self.mesh, axis) > 1:
                return (f"the mesh shards over {axis!r} too (threshold "
                        "collectives are data-parallel only)")
        if jax.process_count() > 1:
            return "multi-host meshes are not supported yet"
        if getattr(self.net.conf, "backprop_type", None) == \
                BackpropType.TruncatedBPTT:
            return ("TBPTT carries cross jitted-step boundaries (the "
                    "compressed step has no carry slot)")
        return None

    def _init_comp_state(self, n_data: int):
        """Attach the residual/threshold state to the NET (the checkpoint
        unit — ModelSerializer rides it as ``gradCompression.npz``, so
        ResilientTrainer restore-resume replays byte-equal), placed on the
        mesh: residual buckets shard over ``data`` (one residual per
        replica), thresholds replicate."""
        state = getattr(self.net, "_grad_compression_state", None)
        if not _comp.state_matches(state, self._comp_layout, n_data):
            if state is not None:
                # topology change (elastic shrink/expand, or a checkpoint
                # from a different mesh): replica-keyed residuals cannot
                # survive byte-exactly — re-bucket them mean-preservingly
                # (or re-seed at zero when the counts don't divide) but
                # KEEP the layout-keyed threshold state either way
                reshaped, mode = _comp.reshape_state(
                    state, self._comp_layout, n_data)
                if reshaped is not None:
                    old_n = int(np.shape(state["residual"][0])[0])
                    log.warning(
                        "gradient-compression state was written on a "
                        "%d-replica mesh, restoring onto %d replicas: "
                        "residuals %s (replica-keyed state cannot survive "
                        "a reshape byte-exactly), thresholds kept",
                        old_n, n_data, mode)
                    state = reshaped
                else:
                    log.warning(
                        "restored gradient-compression state does not "
                        "match the current layout; re-seeding the "
                        "residual at zero")
                    state = _comp.init_state(
                        self._comp_layout, self._compression, n_data)
            else:
                state = _comp.init_state(self._comp_layout,
                                         self._compression, n_data)
        rshard = NamedSharding(self.mesh, P(DATA_AXIS, None))
        rep = NamedSharding(self.mesh, P())
        self.net._grad_compression_state = {
            "residual": [jax.device_put(jnp.asarray(r, jnp.float32), rshard)
                         for r in state["residual"]],
            "threshold": [jax.device_put(jnp.asarray(t, jnp.float32), rep)
                          for t in state["threshold"]],
        }

    def _build_compressed_step(self):
        """The compressed train step: per-replica local gradients under
        shard_map, error-feedback threshold encode (dense int8 sign mask +
        per-bucket scale — static shapes), ONE sign-sum exchange per
        dtype-homogeneous bucket over the ``data`` axis, decode, then the
        replicated optimizer update outside the shard_map (which composes
        with ZeRO optimizer-state sharding: XLA re-shards the update onto
        the data-sharded moments as reduce-scatter + sharded update)."""
        net = self.net
        mesh = self.mesh
        layout = self._comp_layout
        algo = self._compression
        n = _mesh.axis_size(mesh, DATA_AXIS)
        total = layout.total_elements()

        def exchange(params, states, residual, thresholds, x, y, fmask,
                     lmask, rng):
            # per-replica half: runs on each replica's batch shard; params
            # and thresholds arrive replicated, residual arrives as this
            # replica's (1, size) block
            if n > 1:
                # distinct dropout streams per replica (the dense GSPMD
                # path shards one global mask instead; documented
                # divergence — same distribution, different draw)
                rng2 = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
            else:
                rng2 = rng
            (loss, (new_states, _)), grads = jax.value_and_grad(
                net._loss_fn, has_aux=True)(
                params, states, x, y, fmask, lmask, rng2, None)
            loss = lax.pmean(loss, DATA_AXIS)
            # running stats (batchnorm etc.) average like the dense
            # global-batch computation would
            new_states = jax.tree.map(
                lambda a: lax.pmean(a, DATA_AXIS)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, new_states)
            gb = _comp.flatten_buckets(grads, layout)
            decoded, new_res, new_thr = [], [], []
            frac_weighted = jnp.float32(0.0)
            res_sq = jnp.float32(0.0)
            for i, g in enumerate(gb):
                acc = g + residual[i].reshape(-1)     # error feedback
                t = thresholds[i]
                # the shared encode/scale/psum/decode pipeline (one
                # spelling — the allreduce A/B bench runs the same fn)
                dec, sent, _, frac = _comp.exchange_bucket(
                    acc, t, DATA_AXIS, n)
                decoded.append(dec)
                new_res.append((acc - sent)[None, :])
                new_thr.append(algo.update(t, frac))
                frac_weighted = frac_weighted + frac * (g.size / total)
                res_sq = res_sq + lax.psum(jnp.sum(jnp.square(acc - sent)),
                                           DATA_AXIS)
            stats = {"encoded_fraction": frac_weighted,
                     "residual_norm": jnp.sqrt(res_sq)}
            return loss, new_states, decoded, new_res, new_thr, stats

        @functools.partial(jax.jit, static_argnums=(10,),
                           donate_argnums=(0, 1, 2, 3, 4))
        def step(params, opt_state, states, residual, thresholds, x, y,
                 fmask, lmask, rng, frozen):
            # trace probe: counts exactly the (re)compiles of the
            # compressed entry point (compile_watch)
            _cw.note_trace("ShardedTrainer._compressed_step",
                           (x, y, fmask, lmask))
            sm = shard_map(
                exchange, mesh=mesh,
                in_specs=(P(), P(), P(DATA_AXIS, None), P(), P(DATA_AXIS),
                          P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
                out_specs=(P(), P(), P(), P(DATA_AXIS, None), P(), P()),
                check_rep=False)
            loss, new_states, decoded, new_res, new_thr, stats = sm(
                params, states, residual, thresholds, x, y, fmask, lmask,
                rng)
            grads = _comp.unflatten_buckets(decoded, layout)
            # shared freeze/optimizer/numerics tail (nn/_step_tail.py); a
            # skipped (non-finite) step must ALSO keep the old residual /
            # threshold — the poison is inside the accumulator otherwise
            (new_params, new_opt_state,
             (new_states, new_res, new_thr), health) = finish_train_step(
                net._opt, params, opt_state, grads, loss, frozen,
                guarded=((new_states, states), (new_res, residual),
                         (new_thr, thresholds)))
            return (new_params, new_opt_state, new_states, loss, new_res,
                    new_thr, stats, health)

        return step

    def _compressible_batch(self, x) -> bool:
        """The shard_map step needs the batch divisible over the data
        axis; an indivisible (e.g. final partial) batch falls back to the
        dense step for that batch — the residual simply carries over."""
        first = x[0] if isinstance(x, (tuple, list)) else x
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS)
        ok = first is not None and hasattr(first, "shape") and \
            first.shape[0] % n_data == 0
        if not ok and not self._comp_fallback_warned:
            self._comp_fallback_warned = True
            log.warning(
                "batch of %s examples is not divisible by the %d-way data "
                "axis; falling back to the dense exchange for such batches",
                getattr(first, "shape", ("?",))[0], n_data)
        return ok

    def _fit_batch_compressed(self, x, y, fmask, lmask):
        """Compressed-exchange twin of the net's ``_fit_batch`` tail:
        same deferred-score cadence, listener/metrics/flight bookkeeping,
        and cost-observatory feed — with the error-feedback state carried
        through the step and re-attached to the net (so the NEXT
        checkpoint write snapshots residuals consistent with the params)."""
        net = self.net
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        if self._comp_step is None:
            self._comp_step = self._build_compressed_step()
        if not isinstance(net, MultiLayerNetwork):
            tup = lambda v: (() if v is None
                             else tuple(v) if isinstance(v, (tuple, list))
                             else (v,))
            x, y, fmask, lmask = tup(x), tup(y), tup(fmask), tup(lmask)
        if _faults.armed():
            # same chaos point as the dense twin: fires BEFORE the jitted
            # step touches its donated buffers (retry-in-place safe; a nan
            # corruption composes with the numerics skip, which on this
            # path also preserves the residual/threshold state)
            _faults.check("train.step")
            if isinstance(x, tuple):
                x = tuple(jnp.asarray(v) for v in
                          _faults.corrupt("train.step", x))
            else:
                x = jnp.asarray(_faults.corrupt("train.step", x))
        batch_n = int((x[0] if isinstance(x, tuple) else x).shape[0])
        net._last_batch_size = batch_n
        # pinned only when a listener collects activation histograms (same
        # contract as the dense _fit_batch — StatsListener reads it)
        if any(getattr(l, "collect_activations", False)
               for l in net._listeners):
            net._last_input = x[0] if isinstance(x, tuple) else x
        comp = net._grad_compression_state
        defer_mode = _async.async_enabled() and not net._listeners
        score_every = (net.score_every if net.score_every is not None
                       else _async.score_sync_every())
        sync_now = (not defer_mode
                    or (net._iteration + 1) % max(1, score_every) == 0)
        t0 = time.perf_counter()
        with _span("train_step", model=type(net).__name__,
                   iteration=net._iteration, batch=batch_n,
                   compressed=True):
            net._key, rng = jax.random.split(net._key)
            (net._params, net._opt_state, net._states, loss, new_res,
             new_thr, stats, health) = self._comp_step(
                net._params, net._opt_state, net._states, comp["residual"],
                comp["threshold"], x, y, fmask, lmask, rng,
                frozenset(net._frozen))
            net._grad_compression_state = {"residual": new_res,
                                           "threshold": new_thr}
            if health is not None:
                net._pending_health.append(_num.stamp_step(health))
            self._pending_comp_stats.append(stats)
            if sync_now:
                net._pending_score = None
                net._score = float(loss)
                net._drain_numerics()
                self._publish_comp_stats()
            else:
                net._pending_score = loss
                if len(net._pending_health) >= 64:
                    old = net._pending_health[:32]
                    net._pending_health = net._pending_health[32:]
                    _num.publish(net, old)
                if len(self._pending_comp_stats) >= 64:
                    # same older-half drain as the numerics backlog: the
                    # newest entries may still be in flight on device
                    old, self._pending_comp_stats = (
                        self._pending_comp_stats[:32],
                        self._pending_comp_stats[32:])
                    self._publish_comp_stats(old)
        t1 = time.perf_counter()
        _cost.on_step(
            "ShardedTrainer._compressed_step", "ShardedTrainer.step",
            t1 - t0,
            lambda: self._comp_step.lower(
                net._params, net._opt_state, net._states,
                net._grad_compression_state["residual"],
                net._grad_compression_state["threshold"],
                x, y, fmask, lmask, rng, frozenset(net._frozen)))
        net._iteration += 1
        with _span("listeners", model=type(net).__name__):
            for lst in net._listeners:
                lst.iteration_done(net, net._iteration, net._epoch,
                                   net._score)
        _tm.for_model(net).record_step(
            batch_n, net._score if sync_now else float("nan"),
            t1 - t0, time.perf_counter() - t1, None, pipelined=defer_mode)
        _flight().progress("train_step")

    def _publish_comp_stats(self, pend=None):
        """Materialize deferred compression scalars (sparsity fraction,
        residual norm) — called only at the sync points the deferred-score
        cadence already pays for."""
        if pend is None:
            pend, self._pending_comp_stats = self._pending_comp_stats, []
        if not pend or self._comp_obs is None:
            return
        spars_g, res_h = self._comp_obs
        last = None
        for s in pend:
            last = float(s["encoded_fraction"])
            res_h.observe(float(s["residual_norm"]))
        spars_g.set(last)
        _cost.global_cost_model().note_compression(
            "ShardedTrainer.step", {"encoded_fraction_last": last})

    def _shard_batch(self, x):
        if x is None:
            return None
        if isinstance(x, (tuple, list)):
            return type(x)(self._shard_batch(e) for e in x)
        if jax.process_count() > 1 and DATA_AXIS in self.mesh.axis_names:
            # multi-host (DCN) path: each process feeds its LOCAL partition
            # (ref: SharedTrainingWorker consumes worker-local RDD
            # partitions); assemble the global sharded batch across hosts
            x = np.asarray(_unwrap(x))
            n_shards = _mesh.axis_size(self.mesh, DATA_AXIS)
            per_proc = max(1, n_shards // jax.process_count())
            if x.shape[0] % per_proc != 0:
                # replicating would need identical values on every process,
                # which a process-local partition is not — fail loudly
                # instead of training on silently inconsistent data
                raise ValueError(
                    f"multi-host batch: local partition of {x.shape[0]} "
                    f"examples is not divisible by the {per_proc} data "
                    f"shards this process owns; feed equal-sized divisible "
                    f"partitions per process")
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P(DATA_AXIS)), x)
        x = jnp.asarray(_unwrap(x))
        n_data = _mesh.axis_size(self.mesh, DATA_AXIS)
        # an indivisible (e.g. final partial) batch replicates instead of
        # erroring — the reference's ParallelWrapper accepts any batch size
        spec = (P(DATA_AXIS) if DATA_AXIS in self.mesh.axis_names
                and x.shape[0] % n_data == 0 else P())
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------ train
    def _active_preemption_handler(self):
        if self.preemption_handler is not None:
            return self.preemption_handler
        from deeplearning4j_tpu.utils.preemption import PreemptionHandler
        return PreemptionHandler._installed

    def _check_preemption(self):
        """Batch-boundary preemption latch check: checkpoint + unwind.
        Runs between jitted steps so no donated buffer is mid-flight."""
        handler = self._active_preemption_handler()
        if handler is None or not handler.preempted:
            return
        from deeplearning4j_tpu.utils.preemption import (
            PreemptionSafeListener, TrainingPreempted)
        path = None
        if self.checkpoint_dir is not None:
            import os
            # the filename contract of PreemptionSafeListener so
            # resume_or_new discovers trainer-written checkpoints; every
            # rank reports the same path (shared storage), rank 0 writes it
            path = os.path.join(
                self.checkpoint_dir,
                PreemptionSafeListener.FINAL_NAME.format(
                    model=type(self.net).__name__))
            if jax.process_index() == 0:
                from deeplearning4j_tpu.utils.serialization import (
                    save_model_atomic)
                os.makedirs(self.checkpoint_dir, exist_ok=True)
                # atomic: a hard kill after the grace window must never
                # leave a torn zip for resume_or_new to trust
                save_model_atomic(self.net, path)
        # no cross-rank barrier (a single-rank latch would deadlock one);
        # non-zero ranks keep the REAL path but flag it possibly in flight
        raise TrainingPreempted(path or "<no checkpoint_dir configured>",
                                self.net._iteration,
                                checkpoint_ready=(path is not None
                                                  and jax.process_index() == 0))

    def fit(self, data, labels=None, epochs: int = 1):
        """Same surface as the wrapped net's fit; batches are sharded over the
        ``data`` axis before entering the jitted step. Runs under a root
        ``fit`` span (steps + the mesh-placement prefetch thread share one
        trace) and armed on the flight recorder — a wedged collective
        shows up as a postmortem bundle, not a silent hang."""
        with _flight().arm("fit:ShardedTrainer"), \
                _span("fit", model=type(self.net).__name__, sharded=True,
                      epochs=epochs):
            return self._fit_impl(data, labels, epochs)

    def _fit_impl(self, data, labels=None, epochs: int = 1):
        if not self._placed:
            self._place()
        net = self.net
        if labels is not None:
            self._fit_batch(data, labels)
            self._check_preemption()
            return self
        if hasattr(data, "features"):
            self._fit_batch(data.features, data.labels,
                            self._ds_mask(data, "features"),
                            self._ds_mask(data, "labels"))
            self._check_preemption()
            return self
        # device prefetch with the trainer's own placement: batch k+1 is
        # sharded onto the mesh on a background thread while step k
        # computes (skipped multi-host — the global-array assembly there
        # must happen on the thread that owns the per-process partition)
        we_wrapped = False
        if jax.process_count() == 1:
            from deeplearning4j_tpu.data.iterators import (
                DevicePrefetchIterator, _place_dataset)
            wrapped = DevicePrefetchIterator.wrap(
                data, placement=lambda ds: _place_dataset(
                    ds, self._shard_batch))
            we_wrapped, data = wrapped is not data, wrapped
        try:
            for _ in range(epochs):
                for lst in net._listeners:
                    lst.on_epoch_start(net, net._epoch)
                if hasattr(data, "reset"):
                    data.reset()
                for ds in data:
                    self._fit_batch(ds.features, ds.labels,
                                    self._ds_mask(ds, "features"),
                                    self._ds_mask(ds, "labels"))
                    self._check_preemption()
                # epoch boundary is a mandatory sync point (deferred loss
                # + the compression sparsity/residual scalars)
                net._sync_score()
                self._publish_comp_stats()
                for lst in net._listeners:
                    lst.on_epoch_end(net, net._epoch)
                net._epoch += 1
        finally:
            if we_wrapped:
                # preemption/interrupt must not strand the prefetch thread
                # with sharded device batches pinned
                data.close()
        return self

    @staticmethod
    def _ds_mask(ds, which: str):
        return (getattr(ds, f"{which}_masks", None) or
                getattr(ds, f"{which}_mask", None))

    def _fit_batch(self, x, y, fmask=None, lmask=None):
        """Shard the batch onto the mesh, then delegate to the net's own
        _fit_batch — it already handles TBPTT chunking, RNN carries, masks,
        listeners, and MLN/CG arity; shardings survive the jnp.asarray
        pass-through and GSPMD does the rest."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if _faults.armed():
            # chaos injection point for the collective path: fires before
            # the batch is placed on the mesh, i.e. before the sharded
            # step (and its fused gradient allreduce) owns any buffer
            _faults.check("allreduce")
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        if self._compression is not None and self._compressible_batch(x):
            t0 = time.perf_counter()
            with _span("sharded_step",
                       grad_bytes=self._collective_bytes.get(
                           "compressed_allreduce", 0)):
                self._fit_batch_compressed(x, y, fmask, lmask)
            if self._obs is not None:
                for op, nbytes in self._collective_bytes.items():
                    self._collective_counters[op].inc(nbytes)
                self._obs[0].observe(time.perf_counter() - t0)
            return
        t0 = time.perf_counter()
        # only steps driven THROUGH the trainer book under the sharded
        # entry (mesh-scaled peak); cleared so a later direct net.fit()
        # reverts to the single-device entry
        self.net._cost_fn_name = "ShardedTrainer.step"
        try:
            with _span("sharded_step", grad_bytes=self._grad_bytes):
                if isinstance(self.net, MultiLayerNetwork):
                    self.net._fit_batch(x, y, fmask, lmask)
                else:  # ComputationGraph: tuple-valued inputs/labels/masks
                    tup = lambda v: (() if v is None
                                     else tuple(v) if isinstance(v, (tuple,
                                                                     list))
                                     else (v,))
                    self.net._fit_batch(tup(x), tup(y), tup(fmask),
                                        tup(lmask))
        finally:
            self.net._cost_fn_name = None
        if self._obs is not None:
            # under active compression this tail only runs for the
            # indivisible-batch fallback, whose exchange was DENSE
            books = (self._fallback_bytes if self._compression is not None
                     else self._collective_bytes)
            for op, nbytes in books.items():
                self._collective_counters[op].inc(nbytes)
            self._obs[0].observe(time.perf_counter() - t0)

    # --------------------------------------------------------------- inference
    def output(self, x):
        if not self._placed:
            self._place()
        x = self._shard_batch(x)
        return self.net.output(x)

    def score(self):
        score = self.net._sync_score()
        self._publish_comp_stats()
        return score


class ParallelWrapper:
    """Single-host multi-device data-parallel facade
    (ref: ``org.deeplearning4j.parallelism.ParallelWrapper`` — SURVEY P1).

    The reference clones the model per GPU and averages params every
    ``averagingFrequency`` iterations on separate trainer threads; here the
    same devices form a ``data`` mesh and every step IS the averaged step
    (sync allreduce), so ``averagingFrequency`` is accepted for API parity
    and ignored (documented divergence)."""

    def __init__(self, model, workers: Optional[int] = None,
                 prefetch_buffer: int = 2, averaging_frequency: int = 1,
                 report_score_after_averaging: bool = True):
        n = workers or len(jax.devices())
        self._trainer = ShardedTrainer(model, MeshSpec.data_parallel(n),
                                       devices=jax.devices()[:n])
        self.model = model

    @staticmethod
    def builder(model):
        return _PWBuilder(model)

    def fit(self, data, labels=None, epochs: int = 1):
        return self._trainer.fit(data, labels, epochs)

    def shutdown(self):
        pass


class _PWBuilder:
    """ref: ParallelWrapper.Builder fluent API."""

    def __init__(self, model):
        self._model = model
        self._workers = None
        self._prefetch = 2
        self._avg_freq = 1

    def workers(self, n: int):
        self._workers = n
        return self

    def prefetch_buffer(self, n: int):
        self._prefetch = n
        return self

    prefetchBuffer = prefetch_buffer

    def averaging_frequency(self, n: int):
        self._avg_freq = n
        return self

    averagingFrequency = averaging_frequency

    def build(self) -> ParallelWrapper:
        return ParallelWrapper(self._model, self._workers, self._prefetch, self._avg_freq)
