"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Net-new capability (SURVEY P6/§5.7: the reference has NO sequence-dimension
distribution; its long-sequence story is truncated BPTT). Design follows the
blockwise/ring-attention recipe: Q stays resident, K/V blocks rotate around
the ring via ``lax.ppermute`` over ICI neighbors, and softmax is accumulated
online (running max / sum-exp) in float32 so the full T×T score matrix never
materializes on any chip. Compute for block i overlaps the permute of block
i+1 (XLA schedules the collective-permute off the critical path).

Memory per chip: O(T/P · d) activations instead of O(T²) scores — this is
what makes >100k-token sequences trainable on a slice.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
try:
    from jax import shard_map
except ImportError:         # pre-0.6 jax: experimental home, same signature
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, axis_size)


def _block_attn_update(q, k, v, m, l, o, q_start, k_start, causal, scale):
    """One online-softmax accumulation step against a K/V block.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); m/l: (B, H, Tq); o: (B, Tq, H, D).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        qi = q_start + jnp.arange(q.shape[1])
        ki = k_start + jnp.arange(k.shape[1])
        mask = qi[:, None] >= ki[None, :]            # allow key_pos <= query_pos
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                      # (B, H, Tq)
    m_new = jnp.maximum(m, m_blk)
    # fully-masked rows: keep m finite so exp() stays well-defined
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), m_safe * 0 - jnp.inf, m - m_safe))
    corr = jnp.where(jnp.isneginf(m), 0.0, corr)
    l_new = l * corr + jnp.sum(p, axis=-1)
    # bf16 operands + f32 accumulation (preferred_element_type) — an
    # f32×f32 matmul would fall off the fast MXU path
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          p_size: int, vary_axes=()):
    """Per-shard body under shard_map. q/k/v: (B, T/P, H, D) local blocks.
    ``p_size`` is passed statically by the caller (from the mesh): older jax
    has no ``lax.axis_size`` and the ring-unroll needs a concrete int."""
    my_idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / np.sqrt(d)

    m0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    # mark accumulators device-varying over every axis the block inputs vary
    # on, so the fori_loop carry type matches the body output (shard_map vma
    # typing; pre-vma jax has no pcast and needs no marking)
    if hasattr(lax, "pcast"):
        vary = tuple(vary_axes) or (axis_name,)
        m0, l0, o0 = (lax.pcast(a, vary, to="varying")
                      for a in (m0, l0, o0))
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    def body(i, carry):
        k_blk, v_blk, m, l, o = carry
        # after i rotations, this device holds the block that started at
        # ring position (my_idx - i) mod P
        blk_idx = jnp.mod(my_idx - i, p_size)
        m, l, o = _block_attn_update(q, k_blk, v_blk, m, l, o,
                                     my_idx * tq, blk_idx * tk, causal, scale)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    _, _, m, l, o = lax.fori_loop(0, p_size, body, (k, v, m0, l0, o0))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, seq_axis: str = SEQ_AXIS,
                   causal: bool = False):
    """Sequence-sharded attention. q/k/v: (B, T, H, D) GLOBAL shapes, sharded
    (or shardable) on T over ``seq_axis``. Returns (B, T, H, D) with the same
    sharding. Falls back to plain attention when the axis is absent/size 1."""
    if seq_axis not in mesh.axis_names or axis_size(mesh, seq_axis) == 1:
        return _plain_attention(q, k, v, causal)
    # keep batch sharded over 'data' and heads over 'model' inside the ring —
    # replicating them here would make every device recompute the global batch
    batch_ax = DATA_AXIS if axis_size(mesh, DATA_AXIS) > 1 else None
    head_ax = (MODEL_AXIS if axis_size(mesh, MODEL_AXIS) > 1
               and q.shape[2] % axis_size(mesh, MODEL_AXIS) == 0 else None)
    spec = P(batch_ax, seq_axis, head_ax, None)
    vary = tuple(a for a in (batch_ax, seq_axis, head_ax) if a is not None)
    kw = {}
    if not hasattr(lax, "pcast"):
        # pre-vma jax can't express "carry becomes device-varying in the
        # loop body" — its replication checker rejects the ring accumulators,
        # so disable it (the modern path proves the same property via pcast)
        kw["check_rep"] = False
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis,
                          causal=causal, p_size=axis_size(mesh, seq_axis),
                          vary_axes=vary),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **kw)
    return fn(q, k, v)


def _plain_attention(q, k, v, causal: bool = False):
    """Single-shard XLA attention (the flash-kernel crosscheck baseline).

    The (B,H,T,T) score/probability tensors stay in the compute dtype —
    in bf16 they cost half the HBM traffic of f32 and both matmuls ride the
    fast MXU path (accumulation is f32 inside the MXU regardless). exp/sum
    run in f32 on the fly (XLA fuses; nothing f32 materializes). Full-f32
    softmax accuracy is the flash kernel's job (online f32 accumulation).
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / float(np.sqrt(d))
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        # finite sentinel: -inf arithmetic in low precision breeds NaNs on
        # the (impossible-here, but ragged-block) fully-masked rows
        s = jnp.where(mask[None, None], s, jnp.asarray(-1e30, s.dtype))
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp((s - m).astype(jnp.float32))
    p = (p / jnp.sum(p, axis=-1, keepdims=True)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
