"""Zero-dependency ONNX protobuf wire codec (reader + writer).

The container ships no ``onnx`` package (and no onnxruntime), so this module
speaks the protobuf wire format directly against the stable field numbers of
``onnx.proto3`` (ModelProto et al. — field numbers are frozen by the ONNX
spec). Ref: the reference's ONNX import stack parses the same messages via
generated protos (``nd4j/samediff-import/samediff-import-onnx``, SURVEY J8);
here a ~200-line schema-driven decoder replaces the codegen dependency.

Reader: ``parse_model(bytes) -> dict`` tree (repeated fields always lists).
Writer: ``make_model/make_graph/make_node/make_tensor/...`` — used by the
test corpus to author ONNX models in-container, and available for export.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

# ------------------------------------------------------------------ wire IO
def _uvarint(b: bytes, i: int):
    v = s = 0
    while True:
        x = b[i]
        v |= (x & 0x7F) << s
        i += 1
        if not x & 0x80:
            return v, i
        s += 7


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _enc_uvarint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        x = v & 0x7F
        v >>= 7
        if v:
            out.append(x | 0x80)
        else:
            out.append(x)
            return bytes(out)


# ------------------------------------------------------- schemas (onnx.proto)
# field_no -> (name, kind, repeated, sub_schema)
_T, _F = True, False
TENSOR: Dict[int, tuple] = {
    1: ("dims", "int", _T, None), 2: ("data_type", "int", _F, None),
    4: ("float_data", "float", _T, None), 5: ("int32_data", "int", _T, None),
    6: ("string_data", "bytes", _T, None), 7: ("int64_data", "int", _T, None),
    8: ("name", "string", _F, None), 9: ("raw_data", "bytes", _F, None),
    10: ("double_data", "double", _T, None),
    11: ("uint64_data", "int", _T, None),
}
GRAPH: Dict[int, tuple] = {}   # filled below (recursive via ATTRIBUTE.g)
ATTRIBUTE: Dict[int, tuple] = {
    1: ("name", "string", _F, None), 2: ("f", "float", _F, None),
    3: ("i", "int", _F, None), 4: ("s", "bytes", _F, None),
    5: ("t", "message", _F, TENSOR), 6: ("g", "message", _F, GRAPH),
    7: ("floats", "float", _T, None), 8: ("ints", "int", _T, None),
    9: ("strings", "bytes", _T, None), 10: ("tensors", "message", _T, TENSOR),
    20: ("type", "int", _F, None),
}
NODE = {
    1: ("input", "string", _T, None), 2: ("output", "string", _T, None),
    3: ("name", "string", _F, None), 4: ("op_type", "string", _F, None),
    5: ("attribute", "message", _T, ATTRIBUTE),
    7: ("domain", "string", _F, None),
}
DIM = {1: ("dim_value", "int", _F, None), 2: ("dim_param", "string", _F, None)}
SHAPE = {1: ("dim", "message", _T, DIM)}
TENSOR_TYPE = {1: ("elem_type", "int", _F, None),
               2: ("shape", "message", _F, SHAPE)}
TYPE = {1: ("tensor_type", "message", _F, TENSOR_TYPE)}
VALUE_INFO = {1: ("name", "string", _F, None),
              2: ("type", "message", _F, TYPE)}
GRAPH.update({
    1: ("node", "message", _T, NODE), 2: ("name", "string", _F, None),
    5: ("initializer", "message", _T, TENSOR),
    11: ("input", "message", _T, VALUE_INFO),
    12: ("output", "message", _T, VALUE_INFO),
    13: ("value_info", "message", _T, VALUE_INFO),
})
OPSET = {1: ("domain", "string", _F, None), 2: ("version", "int", _F, None)}
MODEL = {
    1: ("ir_version", "int", _F, None), 2: ("producer_name", "string", _F, None),
    7: ("graph", "message", _F, GRAPH), 8: ("opset_import", "message", _T, OPSET),
}


def _decode(data: bytes, schema: Dict[int, tuple]) -> dict:
    out: dict = {name: [] for name, _, rep, _ in schema.values() if rep}
    i, n = 0, len(data)
    while i < n:
        tag, i = _uvarint(data, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            raw, i = _uvarint(data, i)
        elif wt == 1:
            raw, i = data[i:i + 8], i + 8
        elif wt == 5:
            raw, i = data[i:i + 4], i + 4
        elif wt == 2:
            ln, j = _uvarint(data, i)
            raw, i = data[j:j + ln], j + ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        spec = schema.get(fno)
        if spec is None:
            continue
        name, kind, rep, sub = spec
        if kind == "int":
            if wt == 0:
                vals = [_signed(raw)]
            else:                      # packed
                vals, j = [], 0
                while j < len(raw):
                    v, j = _uvarint(raw, j)
                    vals.append(_signed(v))
        elif kind == "float":
            vals = (list(struct.unpack(f"<{len(raw)//4}f", raw))
                    if wt == 2 else [struct.unpack("<f", raw)[0]])
        elif kind == "double":
            vals = (list(struct.unpack(f"<{len(raw)//8}d", raw))
                    if wt == 2 else [struct.unpack("<d", raw)[0]])
        elif kind == "string":
            vals = [raw.decode("utf-8", "replace")]
        elif kind == "bytes":
            vals = [raw]
        elif kind == "message":
            vals = [_decode(raw, sub)]
        else:  # pragma: no cover
            raise ValueError(kind)
        if rep:
            out[name].extend(vals)
        else:
            out[name] = vals[-1]
    return out


def parse_model(data) -> dict:
    if isinstance(data, str):
        with open(data, "rb") as f:
            data = f.read()
    return _decode(bytes(data), MODEL)


# --------------------------------------------------------------- dtype maps
# onnx TensorProto.DataType enum
_ONNX_DT = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
            6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
            11: np.float64, 12: np.uint32, 13: np.uint64}
_NP_TO_ONNX = {np.dtype(v): k for k, v in _ONNX_DT.items()}


def onnx_dtype(enum: int) -> np.dtype:
    if enum == 16:  # bfloat16
        try:
            import ml_dtypes
            return np.dtype(ml_dtypes.bfloat16)
        except ImportError:
            return np.dtype(np.float32)
    if enum not in _ONNX_DT:
        raise ValueError(f"unsupported ONNX dtype enum {enum}")
    return np.dtype(_ONNX_DT[enum])


def tensor_to_np(t: dict) -> np.ndarray:
    dt = onnx_dtype(t.get("data_type", 1))
    dims = [int(d) for d in t.get("dims", [])]
    raw = t.get("raw_data")
    if raw:
        return np.frombuffer(raw, dtype=dt.newbyteorder("<")).reshape(dims) \
            .astype(dt, copy=True)
    for field in ("float_data", "int64_data", "int32_data", "double_data",
                  "uint64_data"):
        vals = t.get(field)
        if vals:
            return np.asarray(vals).astype(dt).reshape(dims)
    return np.zeros(dims, dt)


# ------------------------------------------------------------------- writer
def _field(fno: int, wt: int, payload: bytes) -> bytes:
    head = _enc_uvarint((fno << 3) | wt)
    if wt == 2:
        return head + _enc_uvarint(len(payload)) + payload
    return head + payload


def _s(fno: int, text: str) -> bytes:
    return _field(fno, 2, text.encode())


def _i(fno: int, v: int) -> bytes:
    return _field(fno, 0, _enc_uvarint(v))


def make_tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    enum = _NP_TO_ONNX[arr.dtype]
    out = b"".join(_i(1, d) for d in arr.shape)
    out += _i(2, enum) + _s(8, name)
    out += _field(9, 2, arr.astype(arr.dtype.newbyteorder("<")).tobytes())
    return out


def _attr(name: str, v) -> bytes:
    out = _s(1, name)
    if isinstance(v, bool):
        out += _i(3, int(v)) + _i(20, 2)
    elif isinstance(v, int):
        out += _i(3, v) + _i(20, 2)
    elif isinstance(v, float):
        out += _field(2, 5, struct.pack("<f", v)) + _i(20, 1)
    elif isinstance(v, str):
        out += _field(4, 2, v.encode()) + _i(20, 3)
    elif isinstance(v, np.ndarray):
        out += _field(5, 2, make_tensor("", v)) + _i(20, 4)
    elif isinstance(v, bytes):              # serialized GraphProto (If/Loop)
        out += _field(6, 2, v) + _i(20, 5)
    elif isinstance(v, (list, tuple)) and all(isinstance(x, int) for x in v):
        out += b"".join(_i(8, x) for x in v) + _i(20, 7)
    elif isinstance(v, (list, tuple)):
        out += b"".join(_field(7, 5, struct.pack("<f", float(x))) for x in v) \
            + _i(20, 6)
    else:  # pragma: no cover
        raise TypeError(f"attr {name}: {type(v)}")
    return out


def make_node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
              name: str = "", **attrs) -> bytes:
    out = b"".join(_s(1, x) for x in inputs)
    out += b"".join(_s(2, x) for x in outputs)
    out += _s(3, name or f"{op_type}_{outputs[0]}") + _s(4, op_type)
    out += b"".join(_field(5, 2, _attr(k, v)) for k, v in attrs.items())
    return out


def make_value_info(name: str, dtype, shape: Sequence[Optional[int]]) -> bytes:
    dims = b""
    for d in shape:
        dims += _field(1, 2, _s(2, "N") if d is None else _i(1, int(d)))
    tt = _i(1, _NP_TO_ONNX[np.dtype(dtype)]) + _field(2, 2, dims)
    return _s(1, name) + _field(2, 2, _field(1, 2, tt))


def make_graph(nodes: Sequence[bytes], name: str,
               inputs: Sequence[bytes], outputs: Sequence[bytes],
               initializers: Sequence[bytes] = ()) -> bytes:
    out = b"".join(_field(1, 2, n) for n in nodes)
    out += _s(2, name)
    out += b"".join(_field(5, 2, t) for t in initializers)
    out += b"".join(_field(11, 2, vi) for vi in inputs)
    out += b"".join(_field(12, 2, vi) for vi in outputs)
    return out


def make_model(graph: bytes, opset: int = 17) -> bytes:
    return (_i(1, 8)                              # ir_version 8
            + _s(2, "deeplearning4j_tpu")
            + _field(7, 2, graph)
            + _field(8, 2, _s(1, "") + _i(2, opset)))
