"""ONNX model import into the SameDiff-equivalent graph engine.

Reference: ``nd4j/samediff-import/samediff-import-onnx`` (Kotlin
``OnnxOpMappingRegistry``; SURVEY J8) — the second of the reference's two
importers, sharing one rule architecture with the TF importer (theirs via
``samediff-import-api``, ours via the same per-op mapping-rule registry
pattern as ``tfimport``). Proto parsing is the in-repo zero-dependency wire
codec (``onnx_proto``) — no onnx/onnxruntime needed.

Design notes (TPU-first):
- ONNX is NCHW/OIHW-native; conv/pool/BN map to the registry's ``*_nchw``
  lowerings (explicit dimension_numbers — no host transposes, XLA picks the
  TPU layout).
- Initializers import as CONSTANTs; call
  ``SDVariable.convert_to_variable()`` (or import with ``trainable=True``)
  to fine-tune, mirroring the TF path.
- ONNX tensor names are the graph's variable names; outputs are addressable
  by their model-declared names.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable
from deeplearning4j_tpu.modelimport import onnx_proto as op_

_ONNX_RULES: Dict[str, Callable] = {}


def onnx_rule(*op_types):
    def deco(fn):
        for t in op_types:
            _ONNX_RULES[t] = fn
        return fn
    return deco


class ONNXImportError(ValueError):
    pass


class _Ctx:
    def __init__(self, sd: SameDiff):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}
        self.consts: Dict[str, np.ndarray] = {}

    def const(self, name: Optional[str], default=None) -> np.ndarray:
        if not name:                      # optional input omitted ('')
            if default is not None:
                return default
            raise ONNXImportError("missing required constant input")
        if name in self.consts:
            return self.consts[name]
        var = self.vars.get(name)
        if var is not None:               # constant-fold computed structurals
            from deeplearning4j_tpu.modelimport.common import fold_constant
            arr = fold_constant(self.sd, var)
            if arr is not None:
                self.consts[name] = arr
                return arr
        raise ONNXImportError(
            f"input {name!r} must be a constant (or constant-foldable) "
            f"structural argument")


def _attrs(node: dict) -> dict:
    out = {}
    for a in node.get("attribute", []):
        t = a.get("type", 0)
        if t == 1:
            out[a["name"]] = a.get("f", 0.0)
        elif t == 2:
            out[a["name"]] = a.get("i", 0)
        elif t == 3:
            out[a["name"]] = a.get("s", b"").decode()
        elif t == 4:
            out[a["name"]] = op_.tensor_to_np(a["t"])
        elif t == 5:                       # GRAPH — subgraph (If/Loop/Scan)
            out[a["name"]] = a.get("g")
        elif t == 6:
            out[a["name"]] = a.get("floats", [])
        elif t == 7:
            out[a["name"]] = [int(x) for x in a.get("ints", [])]
        else:
            out[a["name"]] = a.get("i", a.get("f", a.get("s")))
    return out


def _pads(attrs, spatial: int):
    """ONNX pads [x1_b, x2_b, ..., x1_e, x2_e] → lax [(b, e), ...]."""
    auto = attrs.get("auto_pad", "NOTSET")
    if auto and auto not in ("NOTSET",):
        if auto == "VALID":
            return [(0, 0)] * spatial
        raise ONNXImportError(f"auto_pad={auto} unsupported; use explicit pads")
    p = attrs.get("pads", [0] * 2 * spatial)
    return [(int(p[i]), int(p[i + spatial])) for i in range(spatial)]


# -------------------------------------------------------------------- rules
def _axes_arg(ctx, node, attrs):
    """ONNX axes: attribute (opset < 18) or second input (opset >= 18).
    Returns (axes_list_or_None, is_empty) — empty axes pair with the
    noop_with_empty_axes=1 attr to mean "no reduction" per spec."""
    axes = attrs.get("axes")
    if axes is None and len(node.get("input", [])) > 1 and node["input"][1]:
        axes = [int(a) for a in
                np.asarray(ctx.const(node["input"][1])).reshape(-1)]
    if axes is None:
        return None, False
    axes = list(axes)
    return axes, len(axes) == 0


def _register_onnx_rules():
    def passthru(onnx_op, reg_op):
        @onnx_rule(onnx_op)
        def _r(ctx, node, inputs, attrs, _op=reg_op):
            return ctx.sd._op(_op, *inputs)

    @onnx_rule("Div")
    def _div(ctx, node, inputs, attrs):
        # ONNX Div truncates toward zero on integer tensors
        if np.issubdtype(np.dtype(inputs[0].dtype), np.integer):
            return ctx.sd._op("TruncateDiv", *inputs)
        return ctx.sd._op("RealDiv", *inputs)

    for o, r in [
        ("Add", "Add"), ("Sub", "Sub"), ("Mul", "Mul"),
        ("Pow", "Pow"), ("Sqrt", "sqrt"), ("Exp", "exp"), ("Log", "log"),
        ("Abs", "abs"), ("Neg", "neg"), ("Erf", "erf"), ("Floor", "floor"),
        ("Ceil", "ceil"), ("Round", "round"), ("Sign", "sign"),
        ("Relu", "Relu"), ("Sigmoid", "Sigmoid"), ("Tanh", "Tanh"),
        ("Softplus", "Softplus"), ("Softsign", "Softsign"),
        ("Max", "Maximum"), ("Min", "Minimum"),
        ("Greater", "Greater"), ("GreaterOrEqual", "GreaterEqual"),
        ("Less", "Less"), ("LessOrEqual", "LessEqual"), ("Equal", "Equal"),
        ("And", "LogicalAnd"), ("Or", "LogicalOr"), ("Not", "LogicalNot"),
        ("Where", "Select"), ("MatMul", "MatMul"), ("Identity", "Identity"),
        ("Reciprocal", "reciprocal"), ("Sin", "sin"), ("Cos", "cos"),
    ]:
        passthru(o, r)

    @onnx_rule("Gelu")
    def _gelu(ctx, node, inputs, attrs):
        return ctx.sd._op("Gelu", inputs[0])

    @onnx_rule("LeakyRelu")
    def _leaky(ctx, node, inputs, attrs):
        return ctx.sd._op("LeakyRelu", inputs[0],
                          alpha=attrs.get("alpha", 0.01))

    @onnx_rule("Elu")
    def _elu(ctx, node, inputs, attrs):
        return ctx.sd._op("Elu", inputs[0])

    @onnx_rule("Clip")
    def _clip(ctx, node, inputs, attrs):
        lo = attrs.get("min")
        hi = attrs.get("max")
        if lo is None and len(node["input"]) > 1 and node["input"][1]:
            lo = float(ctx.const(node["input"][1]))
        if hi is None and len(node["input"]) > 2 and node["input"][2]:
            hi = float(ctx.const(node["input"][2]))
        return ctx.sd._op("clipbyvalue", inputs[0],
                          clip_value_min=lo if lo is not None else -np.inf,
                          clip_value_max=hi if hi is not None else np.inf)

    @onnx_rule("Gemm")
    def _gemm(ctx, node, inputs, attrs):
        a, b = inputs[0], inputs[1]
        y = ctx.sd._op("MatMul", a, b,
                       transpose_a=bool(attrs.get("transA", 0)),
                       transpose_b=bool(attrs.get("transB", 0)))
        alpha = attrs.get("alpha", 1.0)
        beta = attrs.get("beta", 1.0)
        if alpha != 1.0:
            y = y * alpha
        if len(inputs) > 2:
            c = inputs[2]
            y = y + (c * beta if beta != 1.0 else c)
        return y

    @onnx_rule("Softmax")
    def _softmax(ctx, node, inputs, attrs):
        return ctx.sd._op("Softmax", inputs[0], axis=attrs.get("axis", -1))

    @onnx_rule("LogSoftmax")
    def _logsoftmax(ctx, node, inputs, attrs):
        return ctx.sd._op("LogSoftmax", inputs[0], axis=attrs.get("axis", -1))

    @onnx_rule("Conv")
    def _conv(ctx, node, inputs, attrs):
        spatial = len(attrs.get("kernel_shape", [0, 0]))
        if spatial != 2:
            raise ONNXImportError("only Conv2D (4-D NCHW) supported")
        return ctx.sd._op(
            "conv2d_nchw", *inputs,
            strides=tuple(attrs.get("strides", [1] * spatial)),
            padding=_pads(attrs, spatial),
            dilation=tuple(attrs.get("dilations", [1] * spatial)),
            groups=attrs.get("group", 1))

    @onnx_rule("MaxPool")
    def _maxpool(ctx, node, inputs, attrs):
        k = attrs["kernel_shape"]
        # ONNX default stride is 1 per axis (overlapping windows), NOT k
        return ctx.sd._op("maxpool2d_nchw", inputs[0], kernel=tuple(k),
                          strides=tuple(attrs.get("strides", [1] * len(k))),
                          padding=_pads(attrs, len(k)))

    @onnx_rule("AveragePool")
    def _avgpool(ctx, node, inputs, attrs):
        k = attrs["kernel_shape"]
        return ctx.sd._op(
            "avgpool2d_nchw", inputs[0], kernel=tuple(k),
            strides=tuple(attrs.get("strides", [1] * len(k))),
            padding=_pads(attrs, len(k)),
            count_include_pad=bool(attrs.get("count_include_pad", 0)))

    @onnx_rule("GlobalAveragePool")
    def _gap(ctx, node, inputs, attrs):
        return ctx.sd._op("global_avgpool_nchw", inputs[0])

    @onnx_rule("BatchNormalization")
    def _bn(ctx, node, inputs, attrs):
        x, scale, b, mean, var = inputs[:5]
        return ctx.sd._op("batchnorm_nchw", x, scale, b, mean, var,
                          epsilon=attrs.get("epsilon", 1e-5))

    @onnx_rule("Dropout")
    def _dropout(ctx, node, inputs, attrs):
        return ctx.sd._op("Identity", inputs[0])   # inference import

    @onnx_rule("Flatten")
    def _flatten(ctx, node, inputs, attrs):
        axis = attrs.get("axis", 1)
        shp = inputs[0].shape or ()
        head, tail = shp[:axis], shp[axis:]
        dyn_head, dyn_tail = None in head, None in tail
        if dyn_head and dyn_tail:
            raise ONNXImportError(
                "Flatten: dynamic dims on both sides of the axis")
        lead = -1 if dyn_head else int(np.prod(head)) if head else 1
        rest = -1 if dyn_tail else int(np.prod(tail)) if tail else 1
        return ctx.sd._op("Reshape", inputs[0], shape=[lead, rest])

    @onnx_rule("Reshape")
    def _reshape(ctx, node, inputs, attrs):
        target = [int(s) for s in ctx.const(node["input"][1])]
        shp = inputs[0].shape
        if not attrs.get("allowzero", 0):
            target = [shp[i] if s == 0 else s for i, s in enumerate(target)]
        return ctx.sd._op("Reshape", inputs[0], shape=target)

    @onnx_rule("Transpose")
    def _transpose(ctx, node, inputs, attrs):
        return ctx.sd._op("Transpose", inputs[0],
                          perm=attrs.get("perm") or None)

    @onnx_rule("Concat")
    def _concat(ctx, node, inputs, attrs):
        return ctx.sd._op("Concat", *inputs, axis=attrs["axis"])

    @onnx_rule("Squeeze")
    def _squeeze(ctx, node, inputs, attrs):
        axes, _ = _axes_arg(ctx, node, attrs)
        return ctx.sd._op("Squeeze", inputs[0], axis=axes)

    @onnx_rule("Unsqueeze")
    def _unsqueeze(ctx, node, inputs, attrs):
        axes, _ = _axes_arg(ctx, node, attrs)
        out = inputs[0]
        for a in sorted(axes):
            out = ctx.sd._op("ExpandDims", out, axis=int(a))
        return out

    @onnx_rule("Gather")
    def _gather(ctx, node, inputs, attrs):
        return ctx.sd._op("Gather", inputs[0], inputs[1],
                          axis=attrs.get("axis", 0))

    @onnx_rule("Slice")
    def _slice(ctx, node, inputs, attrs):
        ins = node["input"]
        if "starts" in attrs:              # opset < 10 attribute form
            starts, ends = attrs["starts"], attrs["ends"]
            axes = attrs.get("axes")
            steps = None
        else:
            starts = [int(v) for v in ctx.const(ins[1])]
            ends = [int(v) for v in ctx.const(ins[2])]
            axes = ([int(v) for v in ctx.const(ins[3])]
                    if len(ins) > 3 and ins[3] else None)
            steps = ([int(v) for v in ctx.const(ins[4])]
                     if len(ins) > 4 and ins[4] else None)
        rank = len(inputs[0].shape)
        axes = axes if axes is not None else list(range(len(starts)))
        steps = steps if steps is not None else [1] * len(starts)
        INT_MAX = 2 ** 63 - 1
        begin = [None] * rank
        end = [None] * rank
        stride = [1] * rank
        for s, e, ax, st in zip(starts, ends, axes, steps):
            begin[ax] = None if abs(s) >= INT_MAX else s
            end[ax] = None if abs(e) >= INT_MAX - 1 else e
            stride[ax] = st
        return ctx.sd._op("StridedSlice", inputs[0], begin=begin, end=end,
                          strides=stride)

    @onnx_rule("Split")
    def _split(ctx, node, inputs, attrs):
        axis = attrs.get("axis", 0)
        sizes = attrs.get("split")
        if sizes is None and len(node["input"]) > 1 and node["input"][1]:
            sizes = [int(v) for v in ctx.const(node["input"][1])]
        n_out = len(node["output"])
        if sizes is None:
            return ctx.sd._op("Split", inputs[0], num_split=n_out, axis=axis,
                              n_out=n_out)
        return ctx.sd._op("SplitV", inputs[0], size_splits=sizes, axis=axis,
                          n_out=n_out)

    @onnx_rule("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin",
               "ReduceProd")
    def _reduce(ctx, node, inputs, attrs):
        reg = {"ReduceMean": "Mean", "ReduceSum": "Sum", "ReduceMax": "Max",
               "ReduceMin": "Min", "ReduceProd": "Prod"}[node["op_type"]]
        axes, empty = _axes_arg(ctx, node, attrs)
        if empty and attrs.get("noop_with_empty_axes"):
            return ctx.sd._op("Identity", inputs[0])
        return ctx.sd._op(reg, inputs[0],
                          axis=tuple(axes) if axes else None,
                          keepdims=bool(attrs.get("keepdims", 1)))

    @onnx_rule("ArgMax", "ArgMin")
    def _arg(ctx, node, inputs, attrs):
        out = ctx.sd._op(node["op_type"], inputs[0],
                         axis=attrs.get("axis", 0))
        if attrs.get("keepdims", 1):
            out = ctx.sd._op("ExpandDims", out, axis=attrs.get("axis", 0))
        return out

    @onnx_rule("Cast")
    def _cast(ctx, node, inputs, attrs):
        return ctx.sd._op("Cast", inputs[0],
                          dtype=op_.onnx_dtype(attrs["to"]).name)

    @onnx_rule("Shape")
    def _shape(ctx, node, inputs, attrs):
        shp = inputs[0].shape
        if shp is not None and all(d is not None for d in shp):
            arr = np.asarray(shp, np.int64)
            ctx.consts[node["output"][0]] = arr
            return ctx.sd.constant(arr, name=node["output"][0])
        return ctx.sd._op("Shape", inputs[0])

    @onnx_rule("Constant")
    def _constant(ctx, node, inputs, attrs):
        arr = attrs.get("value")
        if arr is None:
            for k in ("value_float", "value_int"):
                if k in attrs:
                    arr = np.asarray(attrs[k])
        arr = np.asarray(arr)
        ctx.consts[node["output"][0]] = arr
        return ctx.sd.constant(arr, name=node["output"][0])

    @onnx_rule("ConstantOfShape")
    def _const_of_shape(ctx, node, inputs, attrs):
        dims = [int(v) for v in ctx.const(node["input"][0])]
        val = attrs.get("value")
        val = np.zeros(1, np.float32) if val is None else np.asarray(val)
        arr = np.full(dims, val.reshape(-1)[0], dtype=val.dtype)
        ctx.consts[node["output"][0]] = arr
        return ctx.sd.constant(arr, name=node["output"][0])

    @onnx_rule("Range")
    def _range(ctx, node, inputs, attrs):
        start, limit, delta = (ctx.const(node["input"][i]) for i in range(3))
        arr = np.arange(np.asarray(start).item(), np.asarray(limit).item(),
                        np.asarray(delta).item(),
                        dtype=np.asarray(start).dtype)
        ctx.consts[node["output"][0]] = arr
        return ctx.sd.constant(arr, name=node["output"][0])

    @onnx_rule("Expand")
    def _expand(ctx, node, inputs, attrs):
        shape = [int(v) for v in ctx.const(node["input"][1])]
        return ctx.sd._op("broadcast_to", inputs[0], shape=shape)

    @onnx_rule("Tile")
    def _tile(ctx, node, inputs, attrs):
        reps = [int(v) for v in ctx.const(node["input"][1])]
        return ctx.sd._op("Tile", inputs[0], reps=reps)

    @onnx_rule("Pad")
    def _pad(ctx, node, inputs, attrs):
        pads = attrs.get("pads")
        if pads is None:
            pads = [int(v) for v in ctx.const(node["input"][1])]
        rank = len(pads) // 2
        paddings = [[pads[i], pads[i + rank]] for i in range(rank)]
        return ctx.sd._op("Pad", inputs[0], paddings=paddings)

    @onnx_rule("Einsum")
    def _einsum(ctx, node, inputs, attrs):
        return ctx.sd._op("Einsum", *inputs, equation=attrs["equation"])

    @onnx_rule("OneHot")
    def _onehot(ctx, node, inputs, attrs):
        depth = int(ctx.const(node["input"][1]))
        values = ctx.const(node["input"][2])   # [off, on]; sets output dtype
        return ctx.sd._op("OneHot", inputs[0], depth=depth,
                          on_value=values[1].item(),
                          off_value=values[0].item(),
                          axis=attrs.get("axis", -1),
                          dtype=np.dtype(values.dtype).name)


    # ---------------------------------------------------- opset long tail
    for o, r in [
        ("Tan", "tan"), ("Asin", "asin"), ("Acos", "acos"), ("Atan", "atan"),
        ("Sinh", "sinh"), ("Cosh", "cosh"), ("Asinh", "asinh"),
        ("Acosh", "acosh"), ("Atanh", "atanh"), ("Xor", "boolean_xor"),
        ("Selu", "selu"), ("Mish", "mish"), ("Expm1", "expm1"),
    ]:
        passthru(o, r)

    @onnx_rule("Sum")          # variadic elementwise ops
    def _vsum(ctx, node, inputs, attrs):
        out = inputs[0]
        for v in inputs[1:]:
            out = ctx.sd._op("Add", out, v)
        return out

    @onnx_rule("Mean")
    def _vmean(ctx, node, inputs, attrs):
        out = inputs[0]
        for v in inputs[1:]:
            out = ctx.sd._op("Add", out, v)
        return ctx.sd._op("Mul", out, ctx.sd.constant(
            np.float32(1.0 / len(inputs))))

    @onnx_rule("Mod")
    def _mod(ctx, node, inputs, attrs):
        if attrs.get("fmod", 0):
            raise ONNXImportError("Mod with fmod=1 (C-style) unsupported; "
                                  "only integer/floor Mod")
        return ctx.sd._op("FloorMod", *inputs)

    @onnx_rule("HardSwish")
    def _hard_swish(ctx, node, inputs, attrs):
        # onnx: x · max(0, min(1, x/6 + 1/2))
        x = inputs[0]
        ax = ctx.sd._op("Mul", x, ctx.sd.constant(np.float32(1.0 / 6.0)))
        axb = ctx.sd._op("Add", ax, ctx.sd.constant(np.float32(0.5)))
        return ctx.sd._op("Mul", x, ctx.sd._op("clipbyvalue", axb,
                                               lo=0.0, hi=1.0))

    @onnx_rule("HardSigmoid")
    def _hard_sigmoid(ctx, node, inputs, attrs):
        # onnx: max(0, min(1, alpha·x + beta))
        alpha = attrs.get("alpha", 0.2)
        beta = attrs.get("beta", 0.5)
        ax = ctx.sd._op("Mul", inputs[0], ctx.sd.constant(np.float32(alpha)))
        axb = ctx.sd._op("Add", ax, ctx.sd.constant(np.float32(beta)))
        return ctx.sd._op("clipbyvalue", axb, lo=0.0, hi=1.0)

    @onnx_rule("PRelu")
    def _prelu_rule(ctx, node, inputs, attrs):
        x, slope = inputs
        neg = ctx.sd._op("Mul", ctx.sd._op("minimum", x,
                                           ctx.sd.constant(np.float32(0.0))),
                         slope)
        pos = ctx.sd._op("Relu", x)
        return ctx.sd._op("Add", pos, neg)

    @onnx_rule("ThresholdedRelu")
    def _trelu(ctx, node, inputs, attrs):
        return ctx.sd._op("thresholdedrelu", inputs[0],
                          theta=attrs.get("alpha", 1.0))

    @onnx_rule("CumSum")
    def _cumsum(ctx, node, inputs, attrs):
        axis = int(ctx.const(node["input"][1]))
        return ctx.sd._op("cumsum", inputs[0], axis=axis,
                          exclusive=bool(attrs.get("exclusive", 0)),
                          reverse=bool(attrs.get("reverse", 0)))

    @onnx_rule("TopK")
    def _topk(ctx, node, inputs, attrs):
        k = int(ctx.const(node["input"][1]))
        if attrs.get("axis", -1) not in (-1,):
            raise ONNXImportError("TopK only supports the last axis")
        if not attrs.get("largest", 1):
            raise ONNXImportError("TopK largest=0 (smallest-k) unsupported")
        return ctx.sd._op("top_k", inputs[0], k=k, n_out=2)

    @onnx_rule("GatherND")
    def _gather_nd(ctx, node, inputs, attrs):
        if attrs.get("batch_dims", 0):
            raise ONNXImportError("GatherND batch_dims unsupported")
        return ctx.sd._op("gather_nd", inputs[0], inputs[1])

    @onnx_rule("ScatterND")
    def _scatter_nd(ctx, node, inputs, attrs):
        return ctx.sd._op("scatter_nd_update", *inputs)

    @onnx_rule("InstanceNormalization")
    def _instancenorm(ctx, node, inputs, attrs):
        # NCHW: normalize over spatial dims per channel per example
        x, scale, b = inputs
        eps = attrs.get("epsilon", 1e-5)
        mean = ctx.sd._op("reduce_mean", x, axis=(2, 3), keepdims=True)
        var = ctx.sd._op("reduce_variance", x, axis=(2, 3), keepdims=True)
        xc = ctx.sd._op("Sub", x, mean)
        denom = ctx.sd._op("sqrt", ctx.sd._op(
            "Add", var, ctx.sd.constant(np.float32(eps))))
        xn = ctx.sd._op("RealDiv", xc, denom)
        s4 = ctx.sd._op("reshape", scale, shape=[1, -1, 1, 1])
        b4 = ctx.sd._op("reshape", b, shape=[1, -1, 1, 1])
        return ctx.sd._op("Add", ctx.sd._op("Mul", xn, s4), b4)

    @onnx_rule("LayerNormalization")
    def _layernorm_rule(ctx, node, inputs, attrs):
        if attrs.get("axis", -1) != -1:
            raise ONNXImportError("LayerNormalization only supports axis=-1")
        x, scale = inputs[0], inputs[1]
        b = inputs[2] if len(inputs) > 2 else None
        out = ctx.sd._op("layer_norm", x, scale,
                         b if b is not None else
                         ctx.sd.constant(np.zeros(1, np.float32)),
                         epsilon=attrs.get("epsilon", 1e-5))
        return out

    @onnx_rule("DepthToSpace")
    def _d2s(ctx, node, inputs, attrs):
        # our op is NHWC; onnx is NCHW — transpose around it
        bs = attrs.get("blocksize", 2)
        nhwc = ctx.sd._op("transpose", inputs[0], perm=[0, 2, 3, 1])
        out = ctx.sd._op("depth_to_space", nhwc, block_size=bs)
        return ctx.sd._op("transpose", out, perm=[0, 3, 1, 2])

    @onnx_rule("SpaceToDepth")
    def _s2d(ctx, node, inputs, attrs):
        bs = attrs.get("blocksize", 2)
        nhwc = ctx.sd._op("transpose", inputs[0], perm=[0, 2, 3, 1])
        out = ctx.sd._op("space_to_depth", nhwc, block_size=bs)
        return ctx.sd._op("transpose", out, perm=[0, 3, 1, 2])

    @onnx_rule("ReduceL1")
    def _reduce_l1(ctx, node, inputs, attrs):
        axes = attrs.get("axes")
        return ctx.sd._op("reduce_norm1", inputs[0],
                          axis=tuple(axes) if axes else None,
                          keepdims=bool(attrs.get("keepdims", 1)))

    @onnx_rule("ReduceL2")
    def _reduce_l2(ctx, node, inputs, attrs):
        axes = attrs.get("axes")
        return ctx.sd._op("reduce_norm2", inputs[0],
                          axis=tuple(axes) if axes else None,
                          keepdims=bool(attrs.get("keepdims", 1)))

    @onnx_rule("Resize")
    def _resize(ctx, node, inputs, attrs):
        mode = attrs.get("mode", "nearest")
        ins = node["input"]
        # sizes (input 3) preferred; else scales (input 2)
        if len(ins) > 3 and ins[3]:
            sizes = [int(v) for v in ctx.const(ins[3])]
            out_h, out_w = sizes[2], sizes[3]
        elif len(ins) > 2 and ins[2]:
            scales = [float(v) for v in ctx.const(ins[2])]
            shape = ctx.vars[ins[0]].shape
            out_h = int(shape[2] * scales[2])
            out_w = int(shape[3] * scales[3])
        else:
            raise ONNXImportError("Resize needs sizes or scales")
        op = {"nearest": "resize_nearest_neighbor",
              "linear": "resize_bilinear",
              "cubic": "resize_bicubic"}.get(mode, "resize_bilinear")
        nhwc = ctx.sd._op("transpose", inputs[0], perm=[0, 2, 3, 1])
        out = ctx.sd._op(op, nhwc, size=(out_h, out_w))
        return ctx.sd._op("transpose", out, perm=[0, 3, 1, 2])


    # ------------------------------------------------ extended tranche
    def _rnn_fill(ctx, node, x, w, r, gates, b, states):
        """Substitute explicit zeros for omitted optional inputs — the op's
        positional signature must never see shifted slots."""
        d = w.shape[0] if w.shape else None
        hsz = r.shape[2] if r.shape else None
        if d is None or hsz is None:
            raise ONNXImportError(f"{node['op_type']}: W/R shapes must be "
                                  f"static")
        out = []
        if b is None:
            b = ctx.sd.constant(
                np.zeros((d, 2 * gates * hsz), np.float32))
        out.append(b)
        bsz = x.shape[1] if x.shape else None
        for st in states:
            if st is None:
                if bsz is None:
                    raise ONNXImportError(
                        f"{node['op_type']}: initial state required when "
                        f"the batch dimension is dynamic")
                st = ctx.sd.constant(np.zeros((d, bsz, hsz), np.float32))
            out.append(st)
        return tuple(out)

    def _rnn_slots(ctx, node, n_slots):
        """Positional recurrent-op inputs with ''-skipped optionals kept in
        their slots (the generic input list drops empty names)."""
        refs = list(node.get("input", [])) + [""] * n_slots
        return [ctx.vars.get(r) if r else None for r in refs[:n_slots]]

    @onnx_rule("LSTM")
    def _lstm(ctx, node, inputs, attrs):
        if attrs.get("activations"):
            raise ONNXImportError("LSTM with custom activations "
                                  "unsupported")
        if attrs.get("clip"):
            raise ONNXImportError("LSTM with clip unsupported")
        x, w, r, b, seq_lens, h0, c0, peep = _rnn_slots(ctx, node, 8)
        if seq_lens is not None:
            raise ONNXImportError("LSTM with sequence_lens unsupported")
        if peep is not None:
            raise ONNXImportError("LSTM with peephole weights unsupported")
        b, h0, c0 = _rnn_fill(ctx, node, x, w, r, gates=4,
                              b=b, states=[h0, c0])
        return ctx.sd._op("onnx_lstm", x, w, r, b, h0, c0,
                          direction=attrs.get("direction", "forward"))

    @onnx_rule("GRU")
    def _gru(ctx, node, inputs, attrs):
        if attrs.get("activations"):
            raise ONNXImportError("GRU with custom activations "
                                  "unsupported")
        x, w, r, b, seq_lens, h0 = _rnn_slots(ctx, node, 6)
        if seq_lens is not None:
            raise ONNXImportError("GRU with sequence_lens unsupported")
        b, h0 = _rnn_fill(ctx, node, x, w, r, gates=3, b=b, states=[h0])
        return ctx.sd._op("onnx_gru", x, w, r, b, h0,
                          direction=attrs.get("direction", "forward"),
                          linear_before_reset=int(
                              attrs.get("linear_before_reset", 0)))

    @onnx_rule("RNN")
    def _rnn(ctx, node, inputs, attrs):
        if attrs.get("activations"):
            raise ONNXImportError("RNN with custom activations "
                                  "unsupported")
        x, w, r, b, seq_lens, h0 = _rnn_slots(ctx, node, 6)
        if seq_lens is not None:
            raise ONNXImportError("RNN with sequence_lens unsupported")
        b, h0 = _rnn_fill(ctx, node, x, w, r, gates=1, b=b, states=[h0])
        return ctx.sd._op("onnx_rnn", x, w, r, b, h0,
                          direction=attrs.get("direction", "forward"))

    @onnx_rule("ConvTranspose")
    def _convt(ctx, node, inputs, attrs):
        spatial = len(attrs.get("kernel_shape", [0, 0]))
        if spatial != 2:
            raise ONNXImportError("only 2-D ConvTranspose supported")
        if any(attrs.get("output_padding", [])) or attrs.get("group", 1) != 1:
            raise ONNXImportError("ConvTranspose output_padding/groups "
                                  "unsupported")
        if any(v != 1 for v in attrs.get("dilations", [])):
            raise ONNXImportError("ConvTranspose dilations unsupported")
        if attrs.get("auto_pad") not in (None, "", "NOTSET"):
            raise ONNXImportError("ConvTranspose auto_pad unsupported "
                                  "(use explicit pads)")
        if attrs.get("output_shape"):
            raise ONNXImportError("ConvTranspose output_shape unsupported")
        pads = attrs.get("pads", [0] * 4)
        padding = ((pads[0], pads[2]), (pads[1], pads[3]))
        return ctx.sd._op("deconv2d_nchw", *inputs,
                          strides=tuple(attrs.get("strides", [1, 1])),
                          padding=padding)

    @onnx_rule("LRN")
    def _lrn(ctx, node, inputs, attrs):
        size = int(attrs.get("size", 5))
        if size % 2 == 0:
            raise ONNXImportError("LRN with even size unsupported "
                                  "(depth_radius windows are odd)")
        # our lrn is NHWC with depth_radius; ONNX size = full window
        x = ctx.sd._op("Transpose", inputs[0], perm=[0, 2, 3, 1])
        y = ctx.sd._op("lrn", x, depth_radius=(size - 1) // 2,
                       bias=float(attrs.get("bias", 1.0)),
                       alpha=float(attrs.get("alpha", 1e-4)) / size,
                       beta=float(attrs.get("beta", 0.75)))
        return ctx.sd._op("Transpose", y, perm=[0, 3, 1, 2])

    @onnx_rule("GroupNormalization")
    def _groupnorm(ctx, node, inputs, attrs):
        return ctx.sd._op("group_norm", *inputs,
                          num_groups=int(attrs["num_groups"]),
                          epsilon=float(attrs.get("epsilon", 1e-5)))

    @onnx_rule("ReduceLogSumExp", "ReduceSumSquare")
    def _reduce_extra(ctx, node, inputs, attrs):
        axes, empty = _axes_arg(ctx, node, attrs)
        if empty and attrs.get("noop_with_empty_axes"):
            return ctx.sd._op("Identity", inputs[0])
        axes = tuple(axes) if axes else None
        kd = bool(attrs.get("keepdims", 1))
        name = ("reduce_logsumexp_axes" if node["op_type"] ==
                "ReduceLogSumExp" else "reduce_sqnorm")
        return ctx.sd._op(name, inputs[0], axis=axes, keepdims=kd)

    @onnx_rule("Trilu")
    def _trilu(ctx, node, inputs, attrs):
        k = 0
        if len(inputs) > 1:
            k = int(np.asarray(ctx.const(node["input"][1])).item())
        return ctx.sd._op("trilu", inputs[0], k=k,
                          upper=bool(attrs.get("upper", 1)))

    @onnx_rule("Hardmax")
    def _hardmax(ctx, node, inputs, attrs):
        return ctx.sd._op("hardmax", inputs[0],
                          axis=int(attrs.get("axis", -1)))

    @onnx_rule("GlobalMaxPool")
    def _gmp(ctx, node, inputs, attrs):
        return ctx.sd._op("global_maxpool_nchw", inputs[0])

    @onnx_rule("IsInf")
    def _isinf(ctx, node, inputs, attrs):
        pos = bool(attrs.get("detect_positive", 1))
        neg = bool(attrs.get("detect_negative", 1))
        if pos and neg:
            return ctx.sd._op("isinf", inputs[0])
        inf = ctx.sd._op("isinf", inputs[0])
        sign_ok = (ctx.sd._op("Greater", inputs[0],
                              ctx.sd.constant(np.float32(0.0))) if pos
                   else ctx.sd._op("Less", inputs[0],
                                   ctx.sd.constant(np.float32(0.0))))
        return ctx.sd._op("boolean_and", inf, sign_ok)

    @onnx_rule("IsNaN")
    def _isnan(ctx, node, inputs, attrs):
        return ctx.sd._op("isnan", inputs[0])

    @onnx_rule("Det")
    def _det(ctx, node, inputs, attrs):
        return ctx.sd._op("matrix_determinant", inputs[0])

    @onnx_rule("ReverseSequence")
    def _revseq_onnx(ctx, node, inputs, attrs):
        return ctx.sd._op("reverse_sequence", inputs[0], inputs[1],
                          seq_axis=int(attrs.get("time_axis", 0)),
                          batch_axis=int(attrs.get("batch_axis", 1)))

    @onnx_rule("ScatterElements")
    def _scatter_el(ctx, node, inputs, attrs):
        return ctx.sd._op("scatter_elements", *inputs,
                          axis=int(attrs.get("axis", 0)),
                          reduction=attrs.get("reduction", "none"))

    @onnx_rule("Shrink")
    def _shrink(ctx, node, inputs, attrs):
        return ctx.sd._op("shrink", inputs[0],
                          bias=float(attrs.get("bias", 0.0)),
                          lambd=float(attrs.get("lambd", 0.5)))

    @onnx_rule("Celu")
    def _celu(ctx, node, inputs, attrs):
        return ctx.sd._op("celu", inputs[0],
                          alpha=float(attrs.get("alpha", 1.0)))



_register_onnx_rules()


def _walk_nodes(ctx: "_Ctx", graph: dict):
    """Map every node of ``graph`` through the rule registry into
    ``ctx.sd`` — shared by the top-level import and subgraph (If/Loop)
    body builders."""
    sd = ctx.sd
    for node in graph.get("node", []):
        rule = _ONNX_RULES.get(node.get("op_type"))
        if rule is None:
            raise ONNXImportError(
                f"No mapping rule for ONNX op {node.get('op_type')!r} "
                f"(node {node.get('name')!r}); register one with "
                f"@onnximport.onnx_rule({node.get('op_type')!r})")
        inputs = [ctx.vars[r] for r in node.get("input", []) if r]
        attrs = _attrs(node)
        out = rule(ctx, node, inputs, attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for tensor_name, o in zip(node["output"], outs):
            ctx.vars[tensor_name] = o
            if o.name != tensor_name and tensor_name not in sd._vars:
                o.rename(tensor_name)


def _subgraph_captures(graph: dict, outer_ctx: "_Ctx") -> List[str]:
    """Outer-scope tensor names referenced by ``graph``'s nodes (ONNX
    subgraphs capture implicitly), in first-use order."""
    needed: List[str] = []

    def walk(g, local):
        local = set(local)
        local |= {i["name"] for i in g.get("initializer", [])}
        local |= {vi["name"] for vi in g.get("input", [])}
        for node in g.get("node", []):
            for r in node.get("input", []):
                if r and r not in local and r in outer_ctx.vars \
                        and r not in needed:
                    needed.append(r)
            for a in node.get("attribute", []):
                if a.get("type") == 5 and a.get("g"):
                    walk(a["g"], local)
            local |= set(node.get("output", []))

    walk(graph, set())
    return needed


def _subgraph_body(outer_ctx: "_Ctx", graph: dict, seed_names: List[str]):
    """Build an ``fn(sub_sd, *args)`` body that maps ``graph`` with
    ``seed_names[i]`` bound to ``args[i]`` and returns the graph outputs."""

    def body(sub_sd, *args):
        ctx2 = _Ctx(sub_sd)
        ctx2.consts.update(outer_ctx.consts)
        for nm, a in zip(seed_names, args):
            ctx2.vars[nm] = a
        for init in graph.get("initializer", []):
            arr = op_.tensor_to_np(init)
            ctx2.consts[init["name"]] = arr
            ctx2.vars[init["name"]] = sub_sd.constant(arr,
                                                      name=init["name"])
        _walk_nodes(ctx2, graph)
        outs = [ctx2.vars[o["name"]] for o in graph.get("output", [])]
        return outs if len(outs) != 1 else outs[0]

    return body


class _FakeVar:
    """Shape/dtype template standing in for an SDVariable when pre-tracing
    a subgraph against element (sliced) shapes."""

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


class OnnxGraphMapper:
    """ref: OnnxFrameworkImporter#runImport — ONNX ModelProto → SameDiff."""

    @staticmethod
    def import_model(model, trainable: bool = False) -> SameDiff:
        """``model``: path, bytes, or a parsed dict from onnx_proto.

        ``trainable=True`` imports float initializers as VARIABLEs
        (fine-tunable through ``sd.fit``) instead of CONSTANTs.
        """
        if not isinstance(model, dict):
            model = op_.parse_model(model)
        graph = model.get("graph") or {}
        sd = SameDiff.create()
        ctx = _Ctx(sd)
        for init in graph.get("initializer", []):
            arr = op_.tensor_to_np(init)
            ctx.consts[init["name"]] = arr
            if trainable and np.issubdtype(arr.dtype, np.floating) \
                    and arr.ndim >= 1:
                ctx.vars[init["name"]] = sd.var(init["name"], init=arr)
            else:
                ctx.vars[init["name"]] = sd.constant(arr, name=init["name"])
        for vi in graph.get("input", []):
            if vi["name"] in ctx.vars:
                continue                   # initializer re-listed as input
            tt = (vi.get("type") or {}).get("tensor_type") or {}
            shape_msg = tt.get("shape")
            if shape_msg is None:
                shape = None               # no shape field: truly unknown
            else:
                # empty dim list = a SCALAR (shape ()), not unknown —
                # collapsing () to None loses the rank and downstream
                # dtype inference (eval_shape can't run on shape None)
                shape = tuple(int(d["dim_value"]) if "dim_value" in d
                              else None for d in shape_msg.get("dim", []))
            dt = op_.onnx_dtype(tt.get("elem_type", 1))
            ctx.vars[vi["name"]] = sd.placeholder(vi["name"], shape, dt)
        _walk_nodes(ctx, graph)
        return sd

    importModel = import_model
    import_graph = import_model


# --------------------------------------------------------------------------
# rule tranche 2 (round 3): the remaining common-opset tail
def _register_onnx_rules_t2():
    @onnx_rule("Size")
    def _size(ctx, node, inputs, attrs):
        return ctx.sd._op("size", inputs[0])

    @onnx_rule("EyeLike")
    def _eyelike(ctx, node, inputs, attrs):
        if int(attrs.get("k", 0)) != 0:
            raise ONNXImportError("EyeLike with k != 0 unsupported")
        x = inputs[0]
        # ONNX contract: dtype attr wins, else the INPUT's dtype
        dt = (op_.onnx_dtype(attrs["dtype"]).name if "dtype" in attrs
              else str(x.dtype))
        e = ctx.sd._op("eye", n=int(x.shape[-2]), m=int(x.shape[-1]))
        return ctx.sd._op("Cast", e, dtype=dt)

    @onnx_rule("GatherElements")
    def _gather_elements(ctx, node, inputs, attrs):
        # take_along_axis semantics — the registry's scatter_elements dual
        return ctx.sd._op("gather_elements", *inputs,
                          axis=int(attrs.get("axis", 0)))

    @onnx_rule("ReduceLogSum")
    def _reduce_log_sum(ctx, node, inputs, attrs):
        axes, empty = _axes_arg(ctx, node, attrs)
        if empty and attrs.get("noop_with_empty_axes"):
            return ctx.sd._op("log", inputs[0])
        s = ctx.sd._op("reduce_sum", inputs[0],
                       axis=tuple(axes) if axes else None,
                       keepdims=bool(attrs.get("keepdims", 1)))
        return ctx.sd._op("log", s)

    @onnx_rule("NonMaxSuppression")
    def _nms(ctx, node, inputs, attrs):
        boxes, scores = inputs[0], inputs[1]
        if int(attrs.get("center_point_box", 0)) != 0:
            raise ONNXImportError(
                "NonMaxSuppression center_point_box=1 (center/width format) "
                "unsupported — convert boxes to corner coords first")
        max_out = int(np.asarray(ctx.const(node["input"][2], 0)).reshape(()))\
            if len(node.get("input", [])) > 2 and node["input"][2] else 0
        iou_t = float(np.asarray(ctx.const(node["input"][3], 0.5))
                      .reshape(())) if len(node.get("input", [])) > 3 \
            and node["input"][3] else 0.5
        score_t = float(np.asarray(ctx.const(node["input"][4], -np.inf))
                        .reshape(())) if len(node.get("input", [])) > 4 \
            and node["input"][4] else float("-inf")
        # single batch + single class only (the registry op's contract);
        # the batched/multi-class loop is a loud error, not a shape crash
        if len(boxes.shape) == 3 and boxes.shape[0] not in (1, None):
            raise ONNXImportError(
                "batched NonMaxSuppression (num_batches > 1) unsupported")
        if len(scores.shape) == 3 and scores.shape[1] not in (1, None):
            raise ONNXImportError(
                "multi-class NonMaxSuppression (num_classes > 1) unsupported")
        b2 = ctx.sd._op("Reshape", boxes, shape=(-1, 4))
        s2 = ctx.sd._op("Reshape", scores, shape=(-1,))
        # ONNX default max_output_boxes_per_class IS 0 = select nothing
        idx = ctx.sd._op("non_max_suppression", b2, s2,
                         max_output_size=max_out,
                         iou_threshold=iou_t, score_threshold=score_t)
        # ONNX layout: (num_selected, 3) rows of [batch, class, box_idx].
        # Whole-graph jit needs STATIC shapes, so num_selected is the padded
        # max_output_size with -1 rows for unselected slots (documented
        # divergence; the reference's dynamic row count cannot exist here)
        zeros = ctx.sd._op("zeros_as", idx)
        return ctx.sd._op("stack", zeros, zeros, idx, axis=1)

    @onnx_rule("NonZero")
    def _nonzero(ctx, node, inputs, attrs):
        # data-dependent output SHAPE cannot exist under whole-graph jit
        # (the executor emits ONE compiled program; SURVEY §3.3 north star).
        # A specific error beats the generic no-rule hint.
        raise ONNXImportError(
            "NonZero has a data-dependent output shape, which the "
            "whole-graph-jit executor cannot represent; replace it with a "
            "mask (Equal/Where) or precompute indices host-side "
            "(ops.registry 'nonzero_coords' works eagerly)")

    @onnx_rule("CastLike")
    def _castlike(ctx, node, inputs, attrs):
        return ctx.sd._op("cast", inputs[0],
                          dtype=str(inputs[1].dtype))

    @onnx_rule("Shrink")
    def _shrink(ctx, node, inputs, attrs):
        return ctx.sd._op("shrink", inputs[0],
                          lambd=float(attrs.get("lambd", 0.5)),
                          bias=float(attrs.get("bias", 0.0)))

    @onnx_rule("Bernoulli")
    def _bernoulli(ctx, node, inputs, attrs):
        # per-element probabilities (the input IS the p tensor)
        out = ctx.sd._op("bernoulli_sample", inputs[0],
                         seed=(int(attrs["seed"])
                               if attrs.get("seed") is not None else None))
        if "dtype" in attrs:
            out = ctx.sd._op("Cast", out,
                             dtype=op_.onnx_dtype(attrs["dtype"]).name)
        return out

    @onnx_rule("Multinomial")
    def _multinomial(ctx, node, inputs, attrs):
        seed = attrs.get("seed")
        out = ctx.sd._op("random_multinomial", inputs[0],
                         num_samples=int(attrs.get("sample_size", 1)),
                         seed=int(seed) if seed is not None else None)
        # spec default output dtype is int32; dtype attr overrides
        dt = (op_.onnx_dtype(attrs["dtype"]).name if "dtype" in attrs
              else "int32")
        return ctx.sd._op("Cast", out, dtype=dt)


_register_onnx_rules_t2()


# --------------------------------------------------------------------------
# rule tranche 3 (round 3, continued): control flow, quantized ops, image
# sampling, Lp family, random generators, and loud informative errors for
# the dynamic-shape / sequence-typed remainder
def _register_onnx_rules_t3():
    @onnx_rule("Upsample")
    def _upsample(ctx, node, inputs, attrs):
        # deprecated opset-9 alias of Resize: scales via input 1 (or the
        # even older 'scales' attr)
        mode = attrs.get("mode", "nearest")
        ins = node["input"]
        if len(ins) > 1 and ins[1]:
            scales = [float(v) for v in ctx.const(ins[1])]
        elif attrs.get("scales"):
            scales = [float(v) for v in attrs["scales"]]
        else:
            raise ONNXImportError("Upsample needs scales")
        shape = inputs[0].shape
        if len(shape) != 4 or len(scales) != 4:
            raise ONNXImportError(
                f"Upsample: only 4-D NCHW is supported (got rank "
                f"{len(shape)} input, {len(scales)} scales); use Resize "
                f"for other ranks")
        out_h = int(shape[2] * scales[2])
        out_w = int(shape[3] * scales[3])
        op = {"nearest": "resize_nearest_neighbor",
              "linear": "resize_bilinear"}.get(mode, "resize_bilinear")
        nhwc = ctx.sd._op("transpose", inputs[0], perm=[0, 2, 3, 1])
        out = ctx.sd._op(op, nhwc, size=(out_h, out_w))
        return ctx.sd._op("transpose", out, perm=[0, 3, 1, 2])

    @onnx_rule("Scatter")
    def _scatter_deprecated(ctx, node, inputs, attrs):
        # opset-9 deprecated alias of ScatterElements
        return ctx.sd._op("scatter_elements", *inputs,
                          axis=int(attrs.get("axis", 0)))

    @onnx_rule("LpNormalization")
    def _lp_norm(ctx, node, inputs, attrs):
        axis = int(attrs.get("axis", -1))
        p = int(attrs.get("p", 2))
        x = inputs[0]
        if p == 2:
            n = ctx.sd._op("reduce_norm2", x, axis=(axis,), keepdims=True)
        else:                              # p == 1
            n = ctx.sd._op("reduce_sum", ctx.sd._op("abs", x),
                           axis=(axis,), keepdims=True)
        return ctx.sd._op("div", x, n)

    @onnx_rule("LpPool")
    def _lp_pool(ctx, node, inputs, attrs):
        k = attrs["kernel_shape"]
        return ctx.sd._op("lp_pool2d_nchw", inputs[0], kernel=tuple(k),
                          strides=tuple(attrs.get("strides", [1] * len(k))),
                          padding=_pads(attrs, len(k)),
                          p=float(attrs.get("p", 2)))

    @onnx_rule("GlobalLpPool")
    def _global_lp_pool(ctx, node, inputs, attrs):
        h, w = inputs[0].shape[2], inputs[0].shape[3]
        return ctx.sd._op("lp_pool2d_nchw", inputs[0], kernel=(int(h),
                                                               int(w)),
                          p=float(attrs.get("p", 2)))

    @onnx_rule("MeanVarianceNormalization")
    def _mvn(ctx, node, inputs, attrs):
        axes = tuple(attrs.get("axes", [0, 2, 3]))
        x = inputs[0]
        mean = ctx.sd._op("reduce_mean", x, axis=axes, keepdims=True)
        centered = ctx.sd._op("subtract", x, mean)
        var = ctx.sd._op("reduce_mean", ctx.sd._op("square", centered),
                         axis=axes, keepdims=True)
        return ctx.sd._op("div", centered, ctx.sd._op("sqrt", var))

    @onnx_rule("SoftmaxCrossEntropyLoss")
    def _sce_loss(ctx, node, inputs, attrs):
        if attrs.get("ignore_index") is not None:
            raise ONNXImportError(
                "SoftmaxCrossEntropyLoss ignore_index unsupported")
        scores, labels = inputs[0], inputs[1]
        weights = inputs[2] if len(inputs) > 2 else None
        logp = ctx.sd._op("log_softmax", scores, axis=1)
        oh = ctx.sd._op("one_hot", labels, depth=int(scores.shape[1]),
                        axis=1)
        nll = ctx.sd._op("neg", ctx.sd._op(
            "reduce_sum", ctx.sd._op("multiply", logp, oh), axis=(1,)))
        if weights is not None:
            w_per = ctx.sd._op("gather", weights, labels, axis=0)
            nll = ctx.sd._op("multiply", nll, w_per)
        red = attrs.get("reduction", "mean")
        if red == "none":
            loss = nll
        elif red == "sum":
            loss = ctx.sd._op("reduce_sum", nll)
        elif weights is not None:
            # spec: weighted mean divides by the SUM OF WEIGHTS
            loss = ctx.sd._op("div", ctx.sd._op("reduce_sum", nll),
                              ctx.sd._op("reduce_sum", w_per))
        else:
            loss = ctx.sd._op("reduce_mean", nll)
        return [loss, logp]

    @onnx_rule("QuantizeLinear")
    def _quantize_linear(ctx, node, inputs, attrs):
        x = inputs[0]
        scale = np.asarray(ctx.const(node["input"][1]))
        ins = node.get("input", [])
        zp = (np.asarray(ctx.const(ins[2]))
              if len(ins) > 2 and ins[2] else np.zeros((), np.uint8))
        axis = int(attrs.get("axis", 1))
        qdt = zp.dtype
        lo, hi = np.iinfo(qdt).min, np.iinfo(qdt).max
        if scale.ndim == 1:                # per-axis: broadcast along axis
            bshape = [1] * len(x.shape)
            bshape[axis] = scale.shape[0]
            scale = scale.reshape(bshape)
            zp = zp.reshape(bshape) if zp.ndim == 1 else zp
        scaled = ctx.sd._op("div", x,
                            ctx.sd.constant(scale.astype(np.float32)))
        rounded = ctx.sd._op("rint", scaled)   # round half-to-even (spec)
        shifted = ctx.sd._op("add", rounded, ctx.sd.constant(
            zp.astype(np.float32)))
        clipped = ctx.sd._op("clip_by_value", shifted, clip_value_min=lo,
                             clip_value_max=hi)
        return ctx.sd._op("Cast", clipped, dtype=np.dtype(qdt).name)

    @onnx_rule("DequantizeLinear")
    def _dequantize_linear(ctx, node, inputs, attrs):
        x = inputs[0]
        scale = np.asarray(ctx.const(node["input"][1]))
        ins = node.get("input", [])
        zp = (np.asarray(ctx.const(ins[2]))
              if len(ins) > 2 and ins[2] else np.zeros((), np.int32))
        axis = int(attrs.get("axis", 1))
        if scale.ndim == 1:
            bshape = [1] * len(x.shape)
            bshape[axis] = scale.shape[0]
            scale = scale.reshape(bshape)
            zp = zp.reshape(bshape) if zp.ndim == 1 else zp
        xf = ctx.sd._op("Cast", x, dtype="float32")
        centered = ctx.sd._op("subtract", xf, ctx.sd.constant(
            zp.astype(np.float32)))
        return ctx.sd._op("multiply", centered, ctx.sd.constant(
            scale.astype(np.float32)))

    @onnx_rule("MatMulInteger")
    def _matmul_integer(ctx, node, inputs, attrs):
        a, b = inputs[0], inputs[1]
        ins = node.get("input", [])
        ai = ctx.sd._op("Cast", a, dtype="int32")
        bi = ctx.sd._op("Cast", b, dtype="int32")
        if len(ins) > 2 and ins[2]:
            ai = ctx.sd._op("subtract", ai, ctx.sd._op(
                "Cast", ctx.vars[ins[2]], dtype="int32"))
        if len(ins) > 3 and ins[3]:
            bi = ctx.sd._op("subtract", bi, ctx.sd._op(
                "Cast", ctx.vars[ins[3]], dtype="int32"))
        return ctx.sd._op("matmul", ai, bi)

    @onnx_rule("ConvInteger")
    def _conv_integer(ctx, node, inputs, attrs):
        ins = node.get("input", [])
        x_zp = (ctx.vars[ins[2]] if len(ins) > 2 and ins[2]
                else ctx.sd.constant(np.zeros((), np.int32)))
        w_zp = (ctx.vars[ins[3]] if len(ins) > 3 and ins[3]
                else ctx.sd.constant(np.zeros((), np.int32)))
        k = attrs.get("kernel_shape", [1, 1])
        return ctx.sd._op(
            "conv_integer", inputs[0], inputs[1], x_zp, w_zp,
            strides=tuple(attrs.get("strides", [1] * len(k))),
            padding=_pads(attrs, len(k)),
            dilations=tuple(attrs.get("dilations", [1] * len(k))))

    @onnx_rule("GridSample")
    def _grid_sample(ctx, node, inputs, attrs):
        return ctx.sd._op(
            "grid_sample", inputs[0], inputs[1],
            mode={"linear": "bilinear"}.get(attrs.get("mode", "bilinear"),
                                            attrs.get("mode", "bilinear")),
            padding_mode=attrs.get("padding_mode", "zeros"),
            align_corners=bool(attrs.get("align_corners", 0)))

    @onnx_rule("MaxUnpool")
    def _max_unpool(ctx, node, inputs, attrs):
        x, indices = inputs[0], inputs[1]
        ins = node.get("input", [])
        if len(ins) > 2 and ins[2]:
            out_shape = [int(v) for v in ctx.const(ins[2])]
        else:
            k = attrs["kernel_shape"]
            st = attrs.get("strides", [1] * len(k))   # ONNX default: 1s
            pads = attrs.get("pads", [0] * (2 * len(k)))
            n, c, ph, pw = x.shape
            # spec: out = (in - 1)*stride + kernel - pad_begin - pad_end
            out_shape = [int(n), int(c),
                         (int(ph) - 1) * st[0] + k[0] - pads[0]
                         - pads[len(k)],
                         (int(pw) - 1) * st[1] + k[1] - pads[1]
                         - pads[len(k) + 1]]
        spatial = int(np.prod(out_shape[2:]))
        # ONNX MaxPool indices are flat over the WHOLE NCHW tensor;
        # max_unpool wants per-(N,C) spatial offsets — mod folds them
        local = ctx.sd._op("mod", indices, ctx.sd.constant(
            np.asarray(spatial, np.int64)))
        return ctx.sd._op("max_unpool", x, local,
                          output_shape=tuple(out_shape))

    @onnx_rule("Compress")
    def _compress(ctx, node, inputs, attrs):
        cond = np.asarray(ctx.const(node["input"][1])).astype(bool)
        idx = np.nonzero(cond)[0].astype(np.int64)
        axis = attrs.get("axis")
        gather_idx = ctx.sd.constant(idx)
        if axis is None:
            flat = ctx.sd._op("Reshape", inputs[0], shape=(-1,))
            return ctx.sd._op("gather", flat, gather_idx, axis=0)
        return ctx.sd._op("gather", inputs[0], gather_idx,
                          axis=int(axis))

    @onnx_rule("RandomNormal", "RandomNormalLike")
    def _random_normal(ctx, node, inputs, attrs):
        if node["op_type"].endswith("Like"):
            shape = tuple(int(s) for s in inputs[0].shape)
            default_dt = str(inputs[0].dtype)     # spec: inherit input dtype
        else:
            shape = tuple(int(s) for s in attrs["shape"])
            default_dt = "float32"
        dt = (op_.onnx_dtype(attrs["dtype"]).name if "dtype" in attrs
              else default_dt)
        seed = attrs.get("seed")
        out = ctx.sd._op("random_normal_gen", shape=shape,
                         mean=float(attrs.get("mean", 0.0)),
                         scale=float(attrs.get("scale", 1.0)),
                         seed=int(seed) if seed is not None else None)
        return ctx.sd._op("Cast", out, dtype=dt)

    @onnx_rule("RandomUniform", "RandomUniformLike")
    def _random_uniform(ctx, node, inputs, attrs):
        if node["op_type"].endswith("Like"):
            shape = tuple(int(s) for s in inputs[0].shape)
            default_dt = str(inputs[0].dtype)
        else:
            shape = tuple(int(s) for s in attrs["shape"])
            default_dt = "float32"
        dt = (op_.onnx_dtype(attrs["dtype"]).name if "dtype" in attrs
              else default_dt)
        seed = attrs.get("seed")
        out = ctx.sd._op("random_uniform_gen", shape=shape,
                         low=float(attrs.get("low", 0.0)),
                         high=float(attrs.get("high", 1.0)),
                         seed=int(seed) if seed is not None else None)
        return ctx.sd._op("Cast", out, dtype=dt)

    @onnx_rule("If")
    def _if(ctx, node, inputs, attrs):
        then_g, else_g = attrs["then_branch"], attrs["else_branch"]
        if then_g is None or else_g is None:
            raise ONNXImportError("If: missing branch subgraph")
        caps = _subgraph_captures(then_g, ctx)
        for nm in _subgraph_captures(else_g, ctx):
            if nm not in caps:
                caps.append(nm)
        operands = [ctx.vars[nm] for nm in caps]
        return ctx.sd.if_cond(inputs[0],
                              _subgraph_body(ctx, then_g, caps),
                              _subgraph_body(ctx, else_g, caps),
                              *operands)

    def _pretrace_outputs(ctx, graph, seed_names, arg_templates):
        """Trace a subgraph against placeholder templates to learn its
        output shapes/dtypes without touching the real graph."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff as _SD
        tmp = _SD.create()
        args = [tmp.placeholder(f"__t{i}", v.shape, v.dtype)
                for i, v in enumerate(arg_templates)]
        outs = _subgraph_body(ctx, graph, seed_names)(tmp, *args)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]

    def _loop_cond_statically_true(ctx, body_g, cond_name):
        """True when the Loop can provably never exit early: the initial
        cond is a constant True AND the body's cond_out is the cond input
        (or a constant True) threaded through Identity nodes — the pattern
        for-loop exporters emit."""
        init = ctx.consts.get(cond_name)
        if init is None or not bool(np.asarray(init).reshape(())):
            return False
        b_inputs = [vi["name"] for vi in body_g.get("input", [])]
        cond_in = b_inputs[1] if len(b_inputs) > 1 else None
        outs = body_g.get("output", [])
        if not outs:
            return False
        src = outs[0]["name"]
        producers = {o: n for n in body_g.get("node", [])
                     for o in n.get("output", [])}
        for _ in range(64):                # follow the Identity chain
            if src == cond_in:
                return True
            for init_t in body_g.get("initializer", []):
                if init_t["name"] == src:
                    return bool(np.asarray(
                        op_.tensor_to_np(init_t)).reshape(()))
            n = producers.get(src)
            if n is None or n.get("op_type") not in ("Identity", "Cast"):
                return False
            src = n["input"][0]
        return False

    @onnx_rule("Loop")
    def _loop(ctx, node, inputs, attrs):
        body_g = attrs["body"]
        ins = node.get("input", [])
        b_inputs = [vi["name"] for vi in body_g.get("input", [])]
        n_carried = len(b_inputs) - 2
        n_body_out = len(body_g.get("output", []))
        n_scan = n_body_out - 1 - n_carried
        m_name = ins[0] if len(ins) > 0 else ""
        cond_name = ins[1] if len(ins) > 1 else ""
        trip_max = (int(np.asarray(ctx.const(m_name)).reshape(()))
                    if m_name else None)
        if n_scan > 0 and trip_max is None:
            raise ONNXImportError(
                "Loop scan outputs need a static trip count (constant M "
                "input): the whole-graph-jit executor preallocates the "
                "stacked output, so its length must be known at trace time")
        carried = [ctx.vars[r] for r in ins[2:]]
        caps = _subgraph_captures(body_g, ctx)
        cap_vars = [ctx.vars[nm] for nm in caps]
        i0 = ctx.sd.constant(np.asarray(0, np.int64))
        c0 = (ctx.vars[cond_name] if cond_name
              else ctx.sd.constant(np.asarray(True)))
        n_car = len(carried)
        seeds = ([b_inputs[0], b_inputs[1]] + list(b_inputs[2:])
                 + list(caps))

        accs = []
        if n_scan > 0:
            # scan accumulators: (M, *elem) zeros, rows written at index i.
            # If the body's cond_out goes false before M trips (dynamic
            # early exit), the remaining rows stay zero — a documented
            # divergence from ONNX's true-length scan output, which cannot
            # exist under static shapes. Surfaced at import time (not just
            # here): consumers that rely on the true-length semantics must
            # mask the tail rows themselves. NOT warned for the ubiquitous
            # for-loop export pattern (constant-true cond threaded through
            # unchanged) where early exit is statically impossible.
            if cond_name and not _loop_cond_statically_true(
                    ctx, body_g, cond_name):
                import warnings

                warnings.warn(
                    f"ONNX Loop {node.get('name') or ''!r}: scan outputs "
                    f"are padded to the static trip count M={trip_max}; on "
                    f"dynamic early exit the tail rows are ZEROS, not "
                    f"truncated as ONNX specifies. Mask them using the "
                    f"final iteration count if your consumer depends on "
                    f"true-length scan outputs.", stacklevel=2)
            tmpl = _pretrace_outputs(ctx, body_g, seeds,
                                     [i0, c0, *carried, *cap_vars])
            for t in tmpl[1 + n_car:]:
                accs.append(ctx.sd.constant(np.zeros(
                    (trip_max,) + tuple(int(d) for d in (t.shape or ())),
                    np.dtype(t.dtype))))

        def cond_body(sub_sd, i, c, *rest):
            out = c
            if trip_max is not None:
                lim = sub_sd.constant(np.asarray(trip_max, np.int64))
                out = sub_sd._op("boolean_and",
                                 sub_sd._op("Cast", c, dtype="bool"),
                                 sub_sd._op("less", i, lim))
            return sub_sd._op("Cast", out, dtype="bool")

        def loop_body(sub_sd, i, c, *rest):
            vs = rest[:n_car]
            acc_vs = rest[n_car:n_car + n_scan]
            cvs = rest[n_car + n_scan:]
            body = _subgraph_body(ctx, body_g, seeds)
            outs = body(sub_sd, i, c, *vs, *cvs)
            outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            new_accs = []
            row = sub_sd._op("Reshape", i, shape=(1,))
            for acc, step in zip(acc_vs, outs[1 + n_car:]):
                new_accs.append(sub_sd._op(
                    "scatter_update", acc, row,
                    sub_sd._op("expand_dims", step, axis=0)))
            one = sub_sd.constant(np.asarray(1, np.int64))
            return [sub_sd._op("add", i, one), outs[0],
                    *outs[1:1 + n_car], *new_accs, *cvs]

        final = ctx.sd.while_loop(cond_body, loop_body,
                                  i0, c0, *carried, *accs, *cap_vars)
        final = list(final) if isinstance(final, (list, tuple)) else [final]
        return final[2:2 + n_car + n_scan]

    @onnx_rule("Scan")
    def _scan(ctx, node, inputs, attrs):
        body_g = attrs["body"]
        m = int(attrs["num_scan_inputs"])
        if attrs.get("scan_input_axes") or attrs.get("scan_input_directions") \
                or attrs.get("scan_output_axes") \
                or attrs.get("scan_output_directions"):
            raise ONNXImportError(
                "Scan with non-default axes/directions unsupported "
                "(transpose/reverse the scan tensors around the node)")
        ins = node.get("input", [])
        b_inputs = [vi["name"] for vi in body_g.get("input", [])]
        n_state = len(b_inputs) - m
        states = [ctx.vars[r] for r in ins[:n_state]]
        scans = [ctx.vars[r] for r in ins[n_state:]]
        trips = {int(v.shape[0]) for v in scans if v.shape}
        if len(trips) != 1:
            raise ONNXImportError(
                f"Scan inputs must share one static leading length, "
                f"got {sorted(trips)}")
        trip = trips.pop()
        n_body_out = len(body_g.get("output", []))
        n_scan_out = n_body_out - n_state
        caps = _subgraph_captures(body_g, ctx)
        cap_vars = [ctx.vars[nm] for nm in caps]
        seeds = list(b_inputs) + list(caps)
        elem_tmpl = [_FakeVar(tuple((v.shape or ())[1:]), v.dtype)
                     for v in scans]
        tmpl = _pretrace_outputs(ctx, body_g, seeds,
                                 [*states, *elem_tmpl, *cap_vars])
        accs = [ctx.sd.constant(np.zeros(
                    (trip,) + tuple(int(d) for d in (t.shape or ())),
                    np.dtype(t.dtype)))
                for t in tmpl[n_state:]]
        i0 = ctx.sd.constant(np.asarray(0, np.int64))
        n_st, n_sc = len(states), len(scans)

        def cond_body(sub_sd, i, *rest):
            lim = sub_sd.constant(np.asarray(trip, np.int64))
            return sub_sd._op("less", i, lim)

        def loop_body(sub_sd, i, *rest):
            sts = rest[:n_st]
            acc_vs = rest[n_st:n_st + n_scan_out]
            sc_ins = rest[n_st + n_scan_out:n_st + n_scan_out + n_sc]
            cvs = rest[n_st + n_scan_out + n_sc:]
            elems = [sub_sd._op("gather", sv, i, axis=0) for sv in sc_ins]
            body = _subgraph_body(ctx, body_g, seeds)
            outs = body(sub_sd, *sts, *elems, *cvs)
            outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            row = sub_sd._op("Reshape", i, shape=(1,))
            new_accs = [sub_sd._op("scatter_update", acc, row,
                                   sub_sd._op("expand_dims", step, axis=0))
                        for acc, step in zip(acc_vs, outs[n_st:])]
            one = sub_sd.constant(np.asarray(1, np.int64))
            return [sub_sd._op("add", i, one), *outs[:n_st], *new_accs,
                    *sc_ins, *cvs]

        final = ctx.sd.while_loop(cond_body, loop_body,
                                  i0, *states, *accs, *scans, *cap_vars)
        final = list(final) if isinstance(final, (list, tuple)) else [final]
        return final[1:1 + n_st + n_scan_out]

    for seq_op in ("RoiAlign", "MaxRoiPool"):
        @onnx_rule(seq_op)
        def _heavy_unsupported(ctx, node, inputs, attrs,
                               _op_name=seq_op):
            raise ONNXImportError(
                f"{_op_name} unsupported in this build — "
                f"use crop_and_resize + pooling (ops registry) host-side")

    @onnx_rule("Unique")
    def _unique(ctx, node, inputs, attrs):
        raise ONNXImportError(
            "Unique has a data-dependent output shape, which the "
            "whole-graph-jit executor cannot represent; the eager registry "
            "op 'unique' covers host-side use")

    for seq_op in ("SequenceAt", "SequenceConstruct", "SequenceEmpty",
                   "SequenceErase", "SequenceInsert", "SequenceLength",
                   "SplitToSequence", "ConcatFromSequence",
                   "StringNormalizer", "TfIdfVectorizer"):
        @onnx_rule(seq_op)
        def _seq_unsupported(ctx, node, inputs, attrs, _op_name=seq_op):
            raise ONNXImportError(
                f"{_op_name}: sequence/string-typed ONNX values are outside "
                f"the dense-tensor model (the reference importer shares "
                f"this gap); restructure with dense tensors")


_register_onnx_rules_t3()
