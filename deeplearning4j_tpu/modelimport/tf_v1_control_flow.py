"""TF V1 (frame-based) control-flow reconstruction for the GraphDef importer.

Reference: the legacy Enter/Exit/Merge/Switch/NextIteration frame protocol
handled by ``org.nd4j.autodiff.samediff.internal.AbstractSession``'s
dependency tracker (SURVEY.md:314-317 — "control-flow Enter/Exit/Merge/Switch
supported for imported TF graphs"). The reference *interprets* frames at
session run time; that per-op interpreter is exactly what a TPU build must
not do. Here the frames are statically rewritten at import time into the
functional ``sd.while_loop`` / ``sd.if_cond`` composites (which lower to
``lax.while_loop`` / ``lax.cond`` inside the one jitted program):

- a V1 while frame::

      outer --Enter--> Merge <--NextIteration-- body
                         |--> cond --LoopCond--+
                         v                     v
                       Switch(data, loopcond) --:1--> body
                         '--:0--> Exit --> outer

  becomes ``sd.while_loop(cond_builder, body_builder, *enter_inputs)`` with
  loop-invariant ``Enter(is_constant)`` tensors riding as pass-through state.
  A V1 ``tf.cond`` inside the loop body is handled recursively: only Merges
  fed by Enter+NextIteration count as loop vars; Switch/Merge pairs guarded
  by something other than LoopCond stay in the body node set and are
  rewritten by the same cond machinery when the body is replayed.

- a V1 cond: all Merges of one ``tf.cond`` call (connected through shared
  ``Switch`` guards) are grouped into ONE ``sd.if_cond`` with one output per
  Merge — shared branch nodes are traced once, not once per output.

Nested while frames (loop-in-loop) are rejected with a clear error.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List

_LOOP_OPS = frozenset({"Enter", "Exit", "Merge", "Switch", "NextIteration",
                       "LoopCond"})


def _ref_node(ref: str) -> str:
    ref = ref[1:] if ref.startswith("^") else ref
    return ref.split(":")[0]


def has_v1_control_flow(nodes) -> bool:
    return any(n.op in ("Enter", "Switch") for n in nodes)


@dataclasses.dataclass
class LoopInfo:
    frame: str
    enters: list            # loop-var Enter nodes (merge order)
    inv_enters: list        # is_constant / invariant Enter nodes
    merges: list            # loop-var Merges only
    switches: list          # aligned with merges (None if var unused)
    exits: list             # aligned with merges (None if output unused)
    next_iters: list        # aligned with merges
    loop_cond: object
    cond_nodes: list        # replayed in cond builder (original order)
    body_nodes: list        # replayed in body builder (original order)
    all_names: set          # every node name consumed by the rewrite


@dataclasses.dataclass
class CondGroup:
    """One V1 ``tf.cond`` call: Merges connected through shared Switches."""
    merges: list
    pred_ref: str
    switches: list           # data-guarding switches (operand order)
    true_refs: list          # aligned with merges
    false_refs: list
    branch_nodes: list       # union, original order — replayed per branch
    skip_names: set


def _ancestors(start_refs, by_name, stop_names, nodes_order):
    """Nodes strictly between stop_names and start_refs, in graph order."""
    seen, stack = set(), [_ref_node(r) for r in start_refs]
    while stack:
        nm = stack.pop()
        if nm in seen or nm in stop_names:
            continue
        seen.add(nm)
        node = by_name.get(nm)
        if node is None:
            continue
        for ref in node.input:
            stack.append(_ref_node(ref))
    return [n for n in nodes_order if n.name in seen]


def _is_loop_merge(m, by_name):
    if len(m.input) != 2:
        return False
    a = by_name.get(_ref_node(m.input[0]))
    b = by_name.get(_ref_node(m.input[1]))
    ops = {a.op if a else None, b.op if b else None}
    return ops == {"Enter", "NextIteration"}


def analyze_loops(nodes) -> List[LoopInfo]:
    by_name = {n.name: n for n in nodes}
    frames: Dict[str, list] = {}
    for n in nodes:
        if n.op == "Enter":
            fr = n.attr["frame_name"].s.decode()
            frames.setdefault(fr, []).append(n)

    # frame membership: forward-propagate from Enters
    member: Dict[str, str] = {}
    for fr, ens in frames.items():
        for e in ens:
            member[e.name] = fr
    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n.name in member or n.op == "Enter":
                continue
            for ref in n.input:
                fr = member.get(_ref_node(ref))
                if fr is not None:
                    src = by_name.get(_ref_node(ref))
                    if src is not None and src.op == "Exit":
                        continue        # Exit outputs live in the parent
                    member[n.name] = fr
                    changed = True
                    break

    # an Enter whose input is itself inside a frame ⇒ loop-in-loop
    for fr, ens in frames.items():
        for e in ens:
            if member.get(_ref_node(e.input[0])) is not None:
                raise ValueError(
                    f"nested V1 while frames are not supported (frame "
                    f"{fr!r}); re-export with TF2 functional control flow")

    loops = []
    for fr, ens in frames.items():
        fnodes = [n for n in nodes if member.get(n.name) == fr]
        fnames = {n.name for n in fnodes}
        loop_conds = [n for n in fnodes if n.op == "LoopCond"]
        if len(loop_conds) != 1:
            raise ValueError(f"malformed V1 frame {fr!r}: "
                             f"{len(loop_conds)} LoopCond nodes")
        loop_cond = loop_conds[0]
        # loop-var Merges only; cond-in-body Merges stay in the body set
        merges = [n for n in fnodes
                  if n.op == "Merge" and _is_loop_merge(n, by_name)]
        if not merges:
            raise ValueError(f"malformed V1 frame {fr!r}: no loop-var "
                             f"Merge nodes")
        # loop-var switches are guarded by LoopCond; cond switches are not
        switch_by_data = {}
        for n in fnodes:
            if n.op == "Switch" \
                    and _ref_node(n.input[1]) == loop_cond.name:
                switch_by_data[_ref_node(n.input[0])] = n
        exits_by_switch = {}
        for n in fnodes:
            if n.op == "Exit":
                exits_by_switch[_ref_node(n.input[0])] = n

        enters_lv, switches, exits, next_iters = [], [], [], []
        for m in merges:
            ent = by_name[_ref_node(m.input[0])]
            ni = by_name[_ref_node(m.input[1])]
            if ent.op == "NextIteration" and ni.op == "Enter":
                ent, ni = ni, ent
            enters_lv.append(ent)
            next_iters.append(ni)
            sw = switch_by_data.get(m.name)
            switches.append(sw)
            exits.append(exits_by_switch.get(sw.name) if sw is not None
                         else None)
        inv_enters = [e for e in ens if e not in enters_lv]

        stop = {n.name for n in enters_lv} | {n.name for n in inv_enters} \
            | {m.name for m in merges} \
            | {s.name for s in switches if s is not None} \
            | {e.name for e in exits if e is not None} \
            | {ni.name for ni in next_iters} | {loop_cond.name}
        cond_set = {n.name for n in _ancestors(
            [loop_cond.input[0]], by_name, stop, fnodes)}
        body_start = [ni.input[0] for ni in next_iters]
        body_set = {n.name for n in _ancestors(
            body_start, by_name, stop, fnodes)}
        cond_nodes = [n for n in fnodes if n.name in cond_set]
        body_nodes = [n for n in fnodes if n.name in body_set]

        all_names = set(fnames)
        for es in exits:
            if es is not None:
                all_names.add(es.name)
        loops.append(LoopInfo(fr, enters_lv, inv_enters, merges, switches,
                              exits, next_iters, loop_cond, cond_nodes,
                              body_nodes, all_names))
    return loops


def _branch_is_true(ref, by_name) -> bool:
    """Does this merge input come from the TRUE branch? Signals, in order:
    a data path to ``Switch:1`` (output_true), else a control edge to the
    ``switch_t`` pivot (an Identity of ``Switch:1``) — the only connection
    a constant-only branch has. Iterative (explicit stack), like every
    other traversal here — deep unrolled branches must not hit the Python
    recursion limit."""
    seen = set()
    stack = [ref]
    while stack:
        r = stack.pop()
        nm = _ref_node(r)
        if nm in seen:
            continue
        seen.add(nm)
        node = by_name.get(nm)
        if node is None:
            continue
        if node.op == "Switch":
            return r.endswith(":1")
        for cr in node.input:
            if cr.startswith("^"):
                piv = by_name.get(_ref_node(cr))
                if piv is not None and piv.op == "Identity" and piv.input:
                    src = by_name.get(_ref_node(piv.input[0]))
                    if src is not None and src.op == "Switch":
                        return piv.input[0].endswith(":1")
        for dr in node.input:
            if not dr.startswith("^"):
                stack.append(dr)
    raise ValueError(f"cannot classify V1 cond branch for merge input "
                     f"{ref!r} (no Switch reachable by data or pivot "
                     f"control edge)")


def analyze_conds(nodes, loop_names: set) -> List[CondGroup]:
    """Group frameless Switch/Merge pairs (V1 tf.cond) into one CondGroup
    per original tf.cond call (Merges connected through shared Switches)."""
    by_name = {n.name: n for n in nodes}
    consumers: Dict[str, set] = {}
    for n in nodes:
        for ref in n.input:
            consumers.setdefault(_ref_node(ref), set()).add(n.name)

    raw = []      # (merge, switches:set, branch:set, true_ref, false_ref)
    for n in nodes:
        if n.op != "Merge" or n.name in loop_names:
            continue
        if len(n.input) != 2:
            raise ValueError(f"V1 cond Merge {n.name!r} with "
                             f"{len(n.input)} inputs unsupported")

        def branch(ref):
            sws, seen, bnodes = set(), set(), set()
            stack = [_ref_node(ref)]
            while stack:
                nm = stack.pop()
                if nm in seen:
                    continue
                seen.add(nm)
                node = by_name[nm]
                if node.op == "Switch":
                    sws.add(nm)
                    continue
                bnodes.add(nm)
                for r in node.input:
                    if not r.startswith("^"):
                        stack.append(_ref_node(r))
            return sws, bnodes

        sws_a, nodes_a = branch(n.input[0])
        sws_b, nodes_b = branch(n.input[1])
        if _branch_is_true(n.input[0], by_name):
            t_ref, f_ref = n.input[0], n.input[1]
        else:
            t_ref, f_ref = n.input[1], n.input[0]
        raw.append((n, sws_a | sws_b, nodes_a | nodes_b, t_ref, f_ref))

    # connected components over shared switches / shared branch nodes —
    # union-find, so a Merge that bridges two earlier components fuses
    # them (first-match-append would split one tf.cond into two groups)
    parent = list(range(len(raw)))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i in range(len(raw)):
        for j in range(i):
            if (raw[i][1] & raw[j][1]) or (raw[i][2] & raw[j][2]):
                ri, rj = find(i), find(j)
                if ri != rj:
                    parent[ri] = rj
    comp: Dict[int, List[int]] = {}
    for i in range(len(raw)):
        comp.setdefault(find(i), []).append(i)
    groups = list(comp.values())

    out = []
    for g in groups:
        merges = [raw[i][0] for i in g]
        sw_names = sorted(set().union(*(raw[i][1] for i in g)))
        switches = [by_name[s] for s in sw_names]
        if not switches:
            raise ValueError(f"V1 cond Merge(s) "
                             f"{[m.name for m in merges]} have no Switch "
                             f"guards")
        preds = {s.input[1] for s in switches}
        if len(preds) != 1:
            raise ValueError(f"V1 cond group {[m.name for m in merges]}: "
                             f"switches disagree on predicate ({preds})")
        branch_names = set().union(*(raw[i][2] for i in g))
        branch_nodes = [x for x in nodes if x.name in branch_names]
        internal = branch_names | set(sw_names) | {m.name for m in merges}
        skip = {nm for nm in (branch_names | set(sw_names))
                if consumers.get(nm, set()) <= internal}
        skip |= {m.name for m in merges}
        out.append(CondGroup(merges, next(iter(preds)), switches,
                             [raw[i][3] for i in g],
                             [raw[i][4] for i in g],
                             branch_nodes, skip))
    return out
