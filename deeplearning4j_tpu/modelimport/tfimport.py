"""TensorFlow GraphDef import into the SameDiff-equivalent graph engine.

Reference: ``nd4j/samediff-import/samediff-import-tensorflow`` (Kotlin
``OpMappingRegistry``/``ImportGraph``) and the older
``org.nd4j.imports.graphmapper.tf.TFGraphMapper`` (SURVEY J8).

Same architecture as the reference: a per-op mapping-rule registry walks the
GraphDef topologically, turning each node into graph-engine ops. Structural
inputs (axes, shapes, perms, paddings) are constant-folded at import time —
the reference does the same through its "input frameworks" attribute
resolution. The imported graph then executes as ONE jitted XLA program
(where the reference interprets op-by-op through the JNI executioner).

Protobuf parsing uses the tensorflow pip package's generated proto classes
only (no session/runtime); import fails with a clear message without it.
"""
from __future__ import annotations

import re

from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

_RULES: Dict[str, Callable] = {}


def mapping_rule(*op_types):
    """ref: OpMappingRegistry rule registration."""
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


class TFImportError(ValueError):
    pass


# ------------------------------------------------------------------ attrs
def _parse_attrs(node) -> dict:
    out = {}
    for k, v in node.attr.items():
        field = v.WhichOneof("value")
        if field == "b":
            out[k] = v.b
        elif field == "i":
            out[k] = int(v.i)
        elif field == "f":
            out[k] = float(v.f)
        elif field == "s":
            out[k] = v.s.decode("utf-8", "ignore")
        elif field == "type":
            out[k] = int(v.type)
        elif field == "shape":
            out[k] = [d.size for d in v.shape.dim]
        elif field == "list":
            lv = v.list
            if lv.i:
                out[k] = [int(x) for x in lv.i]
            elif lv.f:
                out[k] = [float(x) for x in lv.f]
            elif lv.s:
                out[k] = [x.decode() for x in lv.s]
            elif lv.b:
                out[k] = list(lv.b)
            else:
                out[k] = []
        elif field == "tensor":
            out[k] = v.tensor
        elif field == "func":
            out[k] = v.func.name
    return out


_TF_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
              5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
              14: np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.float32,
              19: np.float16}


def _dtype_of(enum: int):
    try:
        import ml_dtypes
        if enum == 14:
            return np.dtype(ml_dtypes.bfloat16)
    except ImportError:
        pass
    if enum in _TF_DTYPES:
        return np.dtype(_TF_DTYPES[enum])
    raise TFImportError(f"Unsupported TF dtype enum {enum}")


def _tensor_to_ndarray(tensor_proto) -> np.ndarray:
    """TensorProto → numpy without the TF runtime."""
    dtype = _dtype_of(int(tensor_proto.dtype))
    shape = [d.size for d in tensor_proto.tensor_shape.dim]
    if tensor_proto.tensor_content:
        return np.frombuffer(tensor_proto.tensor_content,
                             dtype=dtype).reshape(shape).copy()
    for field in ("float_val", "double_val", "int_val", "int64_val",
                  "bool_val", "half_val"):
        vals = list(getattr(tensor_proto, field, []))
        if vals:
            arr = np.asarray(vals, dtype=dtype)
            n = int(np.prod(shape)) if shape else 1
            if arr.size == 1 and n > 1:
                arr = np.full(shape, arr[0], dtype=dtype)
            return arr.reshape(shape)
    return np.zeros(shape, dtype=dtype)


# ------------------------------------------------------------------ mapper
class _ImportCtx:
    def __init__(self, sd: SameDiff, library: Optional[dict] = None):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}     # tf tensor name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}   # tf node name -> numpy
        self.node_defs: Dict[str, object] = {}    # tf node name -> NodeDef
        self.library: Dict[str, object] = library or {}  # FunctionDefs by name

    def const_value(self, ref: str) -> np.ndarray:
        key = _fq(ref)
        name, idx = key.rsplit(":", 1)
        # bare-name cache: Const/Range rules (single-output nodes only)
        if idx == "0" and name in self.consts:
            return self.consts[name]
        if key in self.consts:
            return self.consts[key]
        # constant-fold a structural subgraph (Shape→StridedSlice→Pack etc.):
        # if the producing var depends only on constants, evaluate it through
        # the graph engine (the reference resolves these via its attribute-
        # resolution pass; here the real lowering does the arithmetic)
        var = self.vars.get(key)
        if var is not None:
            from deeplearning4j_tpu.modelimport.common import fold_constant
            arr = fold_constant(self.sd, var)
            if arr is not None:
                self.consts[key] = arr
                return arr
        raise TFImportError(
            f"op input {ref!r} must be a constant (or constant-foldable) "
            f"for import (structural argument)")


def _pool_args(attrs):
    k = attrs.get("ksize", [1, 1, 1, 1])
    s = attrs.get("strides", [1, 1, 1, 1])
    if attrs.get("data_format", "NHWC") != "NHWC":
        raise TFImportError("only NHWC supported")
    return tuple(k[1:3]), tuple(s[1:3]), attrs.get("padding", "VALID")


def _register_default_rules():
    E = lambda ctx, name, *a, **kw: ctx.sd._op(name, *a, **kw)

    @mapping_rule("Placeholder", "PlaceholderWithDefault")
    def _ph(ctx, node, inputs, attrs):
        shape = attrs.get("shape")
        shape = tuple(None if d in (-1, 0) and i == 0 else (None if d == -1 else d)
                      for i, d in enumerate(shape or ())) or None
        dt = _dtype_of(attrs.get("dtype", 1))
        return ctx.sd.placeholder(node.name, shape, dt)

    @mapping_rule("Const")
    def _const(ctx, node, inputs, attrs):
        arr = _tensor_to_ndarray(attrs["value"])
        ctx.consts[node.name] = arr
        return ctx.sd.constant(arr, name=node.name)

    @mapping_rule("Identity", "StopGradient", "PreventGradient", "Snapshot",
                  "CheckNumerics")
    def _ident(ctx, node, inputs, attrs):
        # emit a real identity op so the TF node name stays addressable as a
        # graph output (XLA elides it at compile time)
        return ctx.sd._op("Identity", inputs[0])

    # elementwise binaries/unaries ride the registry's TF aliases directly
    _PASSTHRU = [
        "Add", "AddV2", "Sub", "Mul", "RealDiv", "Maximum", "Minimum",
        "SquaredDifference", "Pow", "Neg", "FloorDiv", "FloorMod",
        "Relu", "Relu6", "Elu", "Selu", "Sigmoid", "Tanh", "Softplus",
        "Softsign", "Gelu",
        "Greater", "GreaterEqual", "Less", "LessEqual", "Equal", "NotEqual",
        "LogicalAnd", "LogicalOr", "LogicalNot", "Select", "SelectV2",
    ]
    for op in _PASSTHRU:
        @mapping_rule(op)
        def _ew(ctx, node, inputs, attrs, _op=op):
            alias = {"AddV2": "Add"}.get(_op, _op)
            return ctx.sd._op(alias, *inputs)

    for op, fn in [("Sqrt", "sqrt"), ("Rsqrt", "rsqrt"), ("Exp", "exp"),
                   ("Log", "log"), ("Abs", "abs"), ("Square", "square"),
                   ("Sign", "sign"), ("Floor", "floor"), ("Ceil", "ceil"),
                   ("Round", "round"), ("Erf", "erf"), ("Erfc", "erfc")]:
        @mapping_rule(op)
        def _un(ctx, node, inputs, attrs, _fn=fn):
            return ctx.sd._op(_fn, inputs[0])

    @mapping_rule("LeakyRelu")
    def _leaky(ctx, node, inputs, attrs):
        return ctx.sd._op("LeakyRelu", inputs[0],
                          alpha=attrs.get("alpha", 0.2))

    @mapping_rule("MatMul", "BatchMatMul", "BatchMatMulV2")
    def _mm(ctx, node, inputs, attrs):
        return ctx.sd._op("MatMul", inputs[0], inputs[1],
                          transpose_a=attrs.get("transpose_a",
                                                attrs.get("adj_x", False)),
                          transpose_b=attrs.get("transpose_b",
                                                attrs.get("adj_y", False)))

    @mapping_rule("BiasAdd")
    def _bias(ctx, node, inputs, attrs):
        if attrs.get("data_format", "NHWC") != "NHWC":
            raise TFImportError("BiasAdd: only NHWC supported")
        return ctx.sd._op("Add", inputs[0], inputs[1])

    @mapping_rule("Softmax", "LogSoftmax")
    def _sm(ctx, node, inputs, attrs):
        return ctx.sd._op(node.op, inputs[0])

    @mapping_rule("Mean", "Sum", "Max", "Min", "Prod", "All", "Any")
    def _red(ctx, node, inputs, attrs):
        axis = ctx.const_value(node.input[1])
        axis = tuple(int(a) for a in np.atleast_1d(axis))
        return ctx.sd._op(node.op, inputs[0], axis=axis,
                          keepdims=attrs.get("keep_dims", False))

    @mapping_rule("ArgMax", "ArgMin")
    def _arg(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[1])) if len(node.input) > 1 else -1
        return ctx.sd._op(node.op, inputs[0], axis=axis)

    @mapping_rule("Reshape")
    def _reshape(ctx, node, inputs, attrs):
        shape = [int(s) for s in ctx.const_value(node.input[1])]
        return ctx.sd._op("Reshape", inputs[0], shape=shape)

    @mapping_rule("Transpose")
    def _transpose(ctx, node, inputs, attrs):
        perm = [int(p) for p in ctx.const_value(node.input[1])]
        return ctx.sd._op("Transpose", inputs[0], perm=perm)

    @mapping_rule("Squeeze")
    def _squeeze(ctx, node, inputs, attrs):
        dims = attrs.get("squeeze_dims") or None
        return ctx.sd._op("Squeeze", inputs[0],
                          axis=list(dims) if dims else None)

    @mapping_rule("ExpandDims")
    def _expand(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[1]))
        return ctx.sd._op("ExpandDims", inputs[0], axis=axis)

    @mapping_rule("ConcatV2", "Concat")
    def _concat(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[-1]))
        return ctx.sd._op("Concat", *inputs[:-1], axis=axis)

    @mapping_rule("Pack")
    def _pack(ctx, node, inputs, attrs):
        return ctx.sd._op("Stack", *inputs, axis=attrs.get("axis", 0))

    @mapping_rule("Pad", "PadV2")
    def _pad(ctx, node, inputs, attrs):
        pads = [[int(v) for v in row]
                for row in ctx.const_value(node.input[1])]
        return ctx.sd._op("Pad", inputs[0], paddings=pads)

    @mapping_rule("Cast")
    def _cast(ctx, node, inputs, attrs):
        return ctx.sd._op("Cast", inputs[0],
                          dtype=_dtype_of(attrs["DstT"]).name)

    @mapping_rule("Conv2D")
    def _conv(ctx, node, inputs, attrs):
        if attrs.get("data_format", "NHWC") != "NHWC":
            raise TFImportError("Conv2D: only NHWC supported")
        strides = tuple(attrs.get("strides", [1, 1, 1, 1])[1:3])
        dil = tuple(attrs.get("dilations", [1, 1, 1, 1])[1:3])
        return ctx.sd._op("conv2d", inputs[0], inputs[1],
                          strides=strides, padding=attrs.get("padding", "SAME"),
                          dilation=dil)

    @mapping_rule("DepthwiseConv2dNative")
    def _dwconv(ctx, node, inputs, attrs):
        strides = tuple(attrs.get("strides", [1, 1, 1, 1])[1:3])
        return ctx.sd._op("DepthwiseConv2dNative", inputs[0], inputs[1],
                          strides=strides,
                          padding=attrs.get("padding", "SAME"))

    @mapping_rule("MaxPool", "MaxPoolV2")
    def _maxpool(ctx, node, inputs, attrs):
        k, s, p = _pool_args(attrs)
        return ctx.sd._op("MaxPool", inputs[0], kernel=k, strides=s, padding=p)

    @mapping_rule("AvgPool")
    def _avgpool(ctx, node, inputs, attrs):
        k, s, p = _pool_args(attrs)
        return ctx.sd._op("AvgPool", inputs[0], kernel=k, strides=s, padding=p)

    @mapping_rule("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
    def _fbn(ctx, node, inputs, attrs):
        if attrs.get("is_training", True) and len(node.input) >= 5:
            # inference import of a graph exported in training mode still
            # carries moving stats as inputs 3/4 — use them
            pass
        x, scale, offset, mean, var = inputs[:5]
        return ctx.sd._op("batchnorm", x, mean, var, scale, offset,
                          epsilon=attrs.get("epsilon", 1e-3))

    def _dynamic_ss(ctx, node, inputs, attrs):
        """StridedSlice whose begin/end carry runtime values — the loop-body
        ``x[:, i, :]`` pattern (begin depends on a While loop variable).
        Supported form: every dynamically-indexed axis is a SHRINK axis
        (size-1 select, lowered to a gather on that axis); other axes must
        be fully masked (untouched). Anything else refuses loudly."""
        bm = attrs.get("begin_mask", 0)
        em = attrs.get("end_mask", 0)
        sm = attrs.get("shrink_axis_mask", 0)
        if attrs.get("new_axis_mask", 0) or attrs.get("ellipsis_mask", 0):
            raise TFImportError(
                "dynamic StridedSlice with new_axis/ellipsis unsupported")
        strides = [int(v) for v in ctx.const_value(node.input[3])]
        if any(s != 1 for s in strides):
            raise TFImportError("dynamic StridedSlice needs unit strides")
        nspec = len(strides)
        out = inputs[0]
        # gather from the HIGHEST axis down so earlier axis ids stay valid
        for a in reversed(range(nspec)):
            if (sm >> a) & 1:
                idx = ctx.sd._op("gather", inputs[1],
                                 ctx.sd.constant(np.asarray(a, np.int32)),
                                 axis=0)
                out = ctx.sd._op("gather", out, idx, axis=a)
            elif (bm >> a) & 1 and (em >> a) & 1:
                continue                       # full slice on this axis
            else:
                raise TFImportError(
                    "dynamic StridedSlice: non-shrink, non-full axis "
                    f"{a} unsupported (use masks or constant bounds)")
        return out

    @mapping_rule("StridedSlice")
    def _ss(ctx, node, inputs, attrs):
        try:
            begin = [int(v) for v in ctx.const_value(node.input[1])]
            end = [int(v) for v in ctx.const_value(node.input[2])]
        except TFImportError:
            return _dynamic_ss(ctx, node, inputs, attrs)
        strides = [int(v) for v in ctx.const_value(node.input[3])]
        bm = attrs.get("begin_mask", 0)
        em = attrs.get("end_mask", 0)
        sm = attrs.get("shrink_axis_mask", 0)
        nm = attrs.get("new_axis_mask", 0)
        elm = attrs.get("ellipsis_mask", 0)
        nspec = len(begin)
        if inputs[0].shape is not None:
            rank = len(inputs[0].shape)
        elif not elm:
            # rank only matters for ellipsis expansion / trailing fill;
            # without it, unspecified trailing dims are simply left unsliced
            rank = nspec - bin(nm & ((1 << nspec) - 1)).count("1")
        else:
            raise TFImportError(
                "StridedSlice with ellipsis_mask needs a statically-known "
                "input rank")
        # number of input dims the ellipsis expands into
        n_real = sum(1 for i in range(nspec)
                     if not (nm >> i) & 1 and not (elm >> i) & 1)
        ell_fill = rank - n_real
        # decompose into: one strided slice over input dims (None = full
        # extent in the stride's direction), then Squeeze for shrink dims,
        # then ExpandDims for new axes — mirroring TF's spec-entry walk
        sl_begin, sl_end, sl_str = [], [], []
        squeeze_dims, new_axis_pos = [], []
        out_dim = 0
        for i in range(nspec):
            if (nm >> i) & 1:
                new_axis_pos.append(out_dim)
                out_dim += 1
                continue
            if (elm >> i) & 1:
                for _ in range(ell_fill):
                    sl_begin.append(None); sl_end.append(None)
                    sl_str.append(1)
                    out_dim += 1
                continue
            b = None if (bm >> i) & 1 else begin[i]
            e = None if (em >> i) & 1 else end[i]
            if (sm >> i) & 1:
                # shrink: take exactly the element at begin[i]; begin=-1
                # must map to end=None, not end=0
                bb = b if b is not None else 0
                sl_begin.append(bb)
                sl_end.append(bb + 1 if bb != -1 else None)
                sl_str.append(1)
                squeeze_dims.append(len(sl_begin) - 1)
                continue
            sl_begin.append(b); sl_end.append(e); sl_str.append(strides[i])
            out_dim += 1
        while len(sl_begin) < rank:      # unspecified trailing dims
            sl_begin.append(None); sl_end.append(None); sl_str.append(1)
            out_dim += 1
        out = ctx.sd._op("StridedSlice", inputs[0], begin=sl_begin,
                         end=sl_end, strides=sl_str)
        if squeeze_dims:
            out = ctx.sd._op("Squeeze", out, axis=squeeze_dims)
        for pos in new_axis_pos:         # ascending: prior inserts accounted
            out = ctx.sd._op("ExpandDims", out, axis=pos)
        return out

    # ---------------- BERT-class breadth (ref: OpMappingRegistry long tail)
    @mapping_rule("Gather", "GatherV2")
    def _gather(ctx, node, inputs, attrs):
        if attrs.get("batch_dims", 0):
            raise TFImportError("Gather: batch_dims unsupported")
        axis = 0
        if node.op == "GatherV2" and len(node.input) > 2:
            axis = int(ctx.const_value(node.input[2]))
        return ctx.sd._op("Gather", inputs[0], inputs[1], axis=axis)

    @mapping_rule("GatherNd")
    def _gather_nd(ctx, node, inputs, attrs):
        return ctx.sd._op("GatherNd", inputs[0], inputs[1])

    @mapping_rule("Slice")
    def _slice(ctx, node, inputs, attrs):
        begin = [int(v) for v in ctx.const_value(node.input[1])]
        size = [int(v) for v in ctx.const_value(node.input[2])]
        return ctx.sd._op("Slice", inputs[0], begin=begin, size=size)

    @mapping_rule("Split")
    def _split(ctx, node, inputs, attrs):
        # TF Split input order: (axis, value)
        axis = int(ctx.const_value(node.input[0]))
        n = int(attrs["num_split"])
        return ctx.sd._op("Split", inputs[-1], num_split=n, axis=axis,
                          n_out=n)

    @mapping_rule("SplitV")
    def _split_v(ctx, node, inputs, attrs):
        sizes = [int(v) for v in ctx.const_value(node.input[1])]
        axis = int(ctx.const_value(node.input[2]))
        return ctx.sd._op("SplitV", inputs[0], size_splits=sizes, axis=axis,
                          n_out=len(sizes))

    @mapping_rule("Unpack")
    def _unpack(ctx, node, inputs, attrs):
        n = int(attrs["num"])
        return ctx.sd._op("Unstack", inputs[0], axis=attrs.get("axis", 0),
                          num=n, n_out=n)

    @mapping_rule("OneHot")
    def _one_hot(ctx, node, inputs, attrs):
        depth = int(ctx.const_value(node.input[1]))
        # .item() keeps the native python type; output dtype follows the
        # node's T attr (int OneHot must stay int)
        on = ctx.const_value(node.input[2]).item()
        off = ctx.const_value(node.input[3]).item()
        dt = _dtype_of(attrs["T"]).name if "T" in attrs else None
        return ctx.sd._op("OneHot", inputs[0], depth=depth, on_value=on,
                          off_value=off, axis=attrs.get("axis", -1),
                          dtype=dt)

    @mapping_rule("Einsum")
    def _einsum(ctx, node, inputs, attrs):
        return ctx.sd._op("Einsum", *inputs, equation=attrs["equation"])

    @mapping_rule("Tile")
    def _tile(ctx, node, inputs, attrs):
        reps = [int(v) for v in ctx.const_value(node.input[1])]
        return ctx.sd._op("Tile", inputs[0], reps=reps)

    @mapping_rule("Fill")
    def _fill(ctx, node, inputs, attrs):
        try:
            dims = [int(v) for v in ctx.const_value(node.input[0])]
        except TFImportError:
            # runtime-derived dims — tf.zeros((tf.shape(x)[0], D)) et al.
            # Pattern-fold Pack(Shape(v)[i], const, …): tensor shapes are
            # STATIC under the whole-graph jit, so each Shape slice becomes
            # a template entry resolved from the ref tensor's shape at
            # trace time (fill_template); unfoldable dims raise loudly
            tpl = _shape_template(ctx, node.input[0])
            if tpl is not None:
                refs = [v for v in tpl if not isinstance(v, int)]
                template = tuple(("shape", sum(1 for p in tpl[:i]
                                               if not isinstance(p, int)),
                                  v[1]) if not isinstance(v, int) else v
                                 for i, v in enumerate(tpl))
                return ctx.sd._op("fill_template", inputs[1],
                                  *[r[0] for r in refs], template=template)
            return ctx.sd._op("fill_dynamic", inputs[0], inputs[1])
        try:
            val = ctx.const_value(node.input[1])
            return ctx.sd.constant(np.full(dims, val), name=node.name)
        except TFImportError:
            # dynamic fill value: broadcast it against a ones tensor of the
            # value's own dtype (TF Fill output dtype == value dtype)
            ones = ctx.sd.constant(np.ones(dims, np.dtype(inputs[1].dtype)))
            return ctx.sd._op("Mul", ones, inputs[1])

    @mapping_rule("Shape")
    def _shape(ctx, node, inputs, attrs):
        shp = inputs[0].shape
        if shp is not None and all(d is not None for d in shp):
            # fold statically-known shapes so downstream structural args
            # (Reshape targets computed via Shape→Slice→Pack) stay constant
            arr = np.asarray(shp, np.int32)
            ctx.consts[node.name] = arr
            return ctx.sd.constant(arr, name=node.name)
        return ctx.sd._op("Shape", inputs[0])

    @mapping_rule("Range")
    def _range(ctx, node, inputs, attrs):
        start, limit, delta = (ctx.const_value(node.input[i])
                               for i in range(3))
        arr = np.arange(np.asarray(start).item(), np.asarray(limit).item(),
                        np.asarray(delta).item(),
                        dtype=np.asarray(start).dtype)
        ctx.consts[node.name] = arr
        return ctx.sd.constant(arr, name=node.name)

    @mapping_rule("ReverseV2")
    def _reverse(ctx, node, inputs, attrs):
        axis = [int(v) for v in np.atleast_1d(ctx.const_value(node.input[1]))]
        return ctx.sd._op("ReverseV2", inputs[0], axis=axis)

    # -------- functional control flow (ref: Enter/Exit/Merge/Switch legacy
    # frames collapse to TF2's If/While, which map onto SameDiff's
    # lax.cond/lax.while_loop composite ops)
    @mapping_rule("StatelessIf", "If")
    def _if(ctx, node, inputs, attrs):
        then_f = ctx.library.get(attrs["then_branch"])
        else_f = ctx.library.get(attrs["else_branch"])
        if then_f is None or else_f is None:
            raise TFImportError(f"If branch functions not in graph library "
                                f"({attrs.get('then_branch')}, "
                                f"{attrs.get('else_branch')})")
        return ctx.sd.if_cond(inputs[0],
                              _fdef_builder(then_f, ctx.library),
                              _fdef_builder(else_f, ctx.library),
                              *inputs[1:], name=node.name)

    @mapping_rule("StatelessWhile", "While")
    def _while(ctx, node, inputs, attrs):
        cond_f = ctx.library.get(attrs["cond"])
        body_f = ctx.library.get(attrs["body"])
        if cond_f is None or body_f is None:
            raise TFImportError("While cond/body functions not in library")
        return ctx.sd.while_loop(_fdef_builder(cond_f, ctx.library),
                                 _fdef_builder(body_f, ctx.library),
                                 *inputs, name=node.name)



def _register_extended_rules():
    """Long-tail op-type coverage (trig/special functions, scans, segments,
    spatial reshuffles, linalg, image, quantization) — mechanical maps onto
    registry lowerings; structural inputs constant-folded like the default
    rules (ref: the OpMappingRegistry's several-hundred-rule table)."""
    # tensor-only passthrough onto canonical snake_case registry names
    def _snake(name):
        out = re.sub(r"(?<!^)(?=[A-Z][a-z])|(?<=[a-z0-9])(?=[A-Z])", "_",
                     name)
        return out.lower()

    for op in ["Sin", "Cos", "Tan", "Asin", "Acos", "Atan", "Sinh", "Cosh",
               "Asinh", "Acosh", "Atanh", "Expm1", "Log1p", "Rint",
               "Lgamma", "Digamma", "Atan2", "Betainc", "Igamma", "Igammac",
               "Zeta", "Polygamma", "Cross", "InvertPermutation",
               "MatrixDeterminant", "MatrixInverse",
               "L2Loss", "Cholesky", "LogMatrixDeterminant",
               "ZerosLike", "OnesLike", "RGBToHSV", "HSVToRGB"]:
        @mapping_rule(op)
        def _pt(ctx, node, inputs, attrs, _op=op):
            return ctx.sd._op(_snake(_op), *inputs)

    @mapping_rule("SegmentSum", "SegmentMean", "SegmentMax", "SegmentMin",
                  "SegmentProd")
    def _seg(ctx, node, inputs, attrs):
        # num_segments must be static for XLA: resolvable when the ids are
        # constant (the usual frozen-graph case)
        ids = np.asarray(ctx.const_value(node.input[1]))
        n = int(ids.max()) + 1
        name = "segment_" + node.op.replace("Segment", "").lower()
        return ctx.sd._op(name, inputs[0], inputs[1], num_segments=n)

    @mapping_rule("MatrixDiag")
    def _mdiag_v1(ctx, node, inputs, attrs):
        return ctx.sd._op("matrix_diag", inputs[0])

    @mapping_rule("MatrixDiagV3")
    def _mdiag_v3(ctx, node, inputs, attrs):
        k = int(np.asarray(ctx.const_value(node.input[1])).item())
        if k != 0:
            raise TFImportError("MatrixDiagV3 with k != 0 unsupported")
        rows = int(np.asarray(ctx.const_value(node.input[2])).item())
        cols = int(np.asarray(ctx.const_value(node.input[3])).item())
        padv = float(np.asarray(ctx.const_value(node.input[4])).item())
        diag_len = (inputs[0].shape[-1]
                    if inputs[0].shape and inputs[0].shape[-1] else None)
        for v in (rows, cols):
            if v != -1 and (diag_len is None or v != diag_len):
                raise TFImportError(
                    "MatrixDiagV3 with explicit num_rows/num_cols "
                    "different from the diagonal length unsupported")
        if padv != 0.0:
            raise TFImportError("MatrixDiagV3 with padding_value != 0 "
                                "unsupported")
        return ctx.sd._op("matrix_diag", inputs[0])

    @mapping_rule("MatrixSetDiag")
    def _msetdiag_v1(ctx, node, inputs, attrs):
        return ctx.sd._op("matrix_set_diag", inputs[0], inputs[1])

    @mapping_rule("MatrixSetDiagV3")
    def _msetdiag_v3(ctx, node, inputs, attrs):
        if len(node.input) > 2:
            k = int(np.asarray(ctx.const_value(node.input[2])).item())
            if k != 0:
                raise TFImportError("MatrixSetDiagV3 with k != 0 "
                                    "unsupported")
        return ctx.sd._op("matrix_set_diag", inputs[0], inputs[1])

    @mapping_rule("DenseBincount")
    def _dense_bincount(ctx, node, inputs, attrs):
        if inputs[0].shape is not None and len(inputs[0].shape) > 1:
            raise TFImportError("DenseBincount: only rank-1 input "
                                "supported (TF computes per-row bincounts "
                                "for rank-2)")
        if attrs.get("binary_output"):
            raise TFImportError("DenseBincount: binary_output=True "
                                "unsupported")
        w = np.asarray(ctx.const_value(node.input[2]))
        if w.size:
            raise TFImportError("DenseBincount: weights unsupported")
        size = int(np.asarray(ctx.const_value(node.input[1])).item())
        return ctx.sd._op("bincount", inputs[0], minlength=size,
                          length=size)

    @mapping_rule("ReverseSequence")
    def _revseq(ctx, node, inputs, attrs):
        return ctx.sd._op("reverse_sequence", inputs[0], inputs[1],
                          seq_axis=attrs.get("seq_dim", 1),
                          batch_axis=attrs.get("batch_dim", 0))

    @mapping_rule("MatrixDiagPart", "MatrixDiagPartV3")
    def _mdiagpart(ctx, node, inputs, attrs):
        if node.op == "MatrixDiagPartV3" and len(node.input) > 1:
            k = int(np.asarray(ctx.const_value(node.input[1])).item())
            if k != 0:
                raise TFImportError("MatrixDiagPartV3 with k != 0 "
                                    "unsupported")
        return ctx.sd._op("matrix_diag_part", inputs[0])

    @mapping_rule("Reciprocal", "Inv")
    def _recip(ctx, node, inputs, attrs):
        return ctx.sd._op("reciprocal", inputs[0])

    @mapping_rule("Cumsum", "Cumprod")
    def _cumx(ctx, node, inputs, attrs):
        axis = int(np.asarray(ctx.const_value(node.input[1])).item())
        return ctx.sd._op(node.op.lower(), inputs[0], axis=axis,
                          exclusive=bool(attrs.get("exclusive", False)),
                          reverse=bool(attrs.get("reverse", False)))

    @mapping_rule("TopKV2")
    def _topk(ctx, node, inputs, attrs):
        k = int(np.asarray(ctx.const_value(node.input[1])).item())
        return ctx.sd._op("top_k", inputs[0], k=k)

    @mapping_rule("InTopK", "InTopKV2")
    def _intopk(ctx, node, inputs, attrs):
        if node.op == "InTopKV2":
            k = int(np.asarray(ctx.const_value(node.input[2])).item())
        else:
            k = int(attrs["k"])
        return ctx.sd._op("in_top_k", inputs[0], inputs[1], k=k)

    @mapping_rule("MirrorPad")
    def _mirror_pad(ctx, node, inputs, attrs):
        pads = np.asarray(ctx.const_value(node.input[1])).tolist()
        return ctx.sd._op("mirror_pad", inputs[0], paddings=pads,
                          mode=attrs.get("mode", "REFLECT"))

    @mapping_rule("SpaceToBatchND", "BatchToSpaceND")
    def _sb_nd(ctx, node, inputs, attrs):
        block = np.asarray(ctx.const_value(node.input[1])).tolist()
        aux = np.asarray(ctx.const_value(node.input[2])).tolist()
        if node.op == "SpaceToBatchND":
            return ctx.sd._op("space_to_batch_nd", inputs[0],
                              block_shape=block, paddings=aux)
        return ctx.sd._op("batch_to_space_nd", inputs[0],
                          block_shape=block, crops=aux)

    @mapping_rule("SpaceToBatch", "BatchToSpace")
    def _sb(ctx, node, inputs, attrs):
        aux = np.asarray(ctx.const_value(node.input[1])).tolist()
        b = int(attrs["block_size"])
        if node.op == "SpaceToBatch":
            return ctx.sd._op("space_to_batch", inputs[0], block_size=b,
                              paddings=aux)
        return ctx.sd._op("batch_to_space", inputs[0], block_size=b,
                          crops=aux)

    @mapping_rule("SpaceToDepth", "DepthToSpace")
    def _sd_depth(ctx, node, inputs, attrs):
        name = ("space_to_depth" if node.op == "SpaceToDepth"
                else "depth_to_space")
        return ctx.sd._op(name, inputs[0],
                          block_size=int(attrs["block_size"]))

    @mapping_rule("MatrixBandPart", "BatchMatrixBandPart")
    def _band(ctx, node, inputs, attrs):
        lo = int(np.asarray(ctx.const_value(node.input[1])).item())
        hi = int(np.asarray(ctx.const_value(node.input[2])).item())
        return ctx.sd._op("matrix_band_part", inputs[0], lower=lo, upper=hi)

    @mapping_rule("HistogramFixedWidth")
    def _hfw(ctx, node, inputs, attrs):
        nbins = int(np.asarray(ctx.const_value(node.input[2])).item())
        return ctx.sd._op("histogram_fixed_width", inputs[0], inputs[1],
                          nbins=nbins)

    @mapping_rule("Bincount")
    def _bincount(ctx, node, inputs, attrs):
        if len(node.input) > 2:
            w = np.asarray(ctx.const_value(node.input[2]))
            if w.size:
                raise TFImportError("Bincount: weights unsupported")
        size = int(np.asarray(ctx.const_value(node.input[1])).item())
        return ctx.sd._op("bincount", inputs[0], minlength=size,
                          length=size)

    @mapping_rule("ClipByValue")
    def _clip(ctx, node, inputs, attrs):
        lo = float(np.asarray(ctx.const_value(node.input[1])).item())
        hi = float(np.asarray(ctx.const_value(node.input[2])).item())
        return ctx.sd._op("clipbyvalue", inputs[0], lo=lo, hi=hi)

    @mapping_rule("UnsortedSegmentSum", "UnsortedSegmentMax",
                  "UnsortedSegmentMin", "UnsortedSegmentProd")
    def _useg(ctx, node, inputs, attrs):
        n = int(np.asarray(ctx.const_value(node.input[2])).item())
        kind = node.op.replace("UnsortedSegment", "").lower()
        return ctx.sd._op(f"unsorted_segment_{kind}", inputs[0], inputs[1],
                          num_segments=n)

    @mapping_rule("SparseToDense")
    def _sparse_to_dense(ctx, node, inputs, attrs):
        shape = np.asarray(ctx.const_value(node.input[1])).tolist()
        default = float(np.asarray(ctx.const_value(node.input[3])).item())
        return ctx.sd._op("sparse_to_dense", inputs[0], inputs[2],
                          dense_shape=shape, default_value=default)

    @mapping_rule("ResizeBilinear", "ResizeNearestNeighbor",
                  "ResizeBicubic", "ResizeArea")
    def _resize_rule(ctx, node, inputs, attrs):
        if attrs.get("align_corners"):
            raise TFImportError(f"{node.op}: align_corners=True grid "
                                f"unsupported")
        # ResizeArea has no half_pixel_centers attr (and our lowering is
        # the documented linear approximation); the others must use the
        # modern half-pixel grid
        if node.op != "ResizeArea" and not attrs.get("half_pixel_centers",
                                                     False):
            raise TFImportError(
                f"{node.op}: only the half-pixel grid is supported "
                f"(tf.image.resize / half_pixel_centers=True); the legacy "
                f"asymmetric grid is not")
        size = [int(v) for v in np.asarray(ctx.const_value(node.input[1]))]
        name = {"ResizeBilinear": "resize_bilinear",
                "ResizeNearestNeighbor": "resize_nearest_neighbor",
                "ResizeBicubic": "resize_bicubic",
                "ResizeArea": "resize_area"}[node.op]
        return ctx.sd._op(name, inputs[0], size=size)

    @mapping_rule("AdjustContrastv2", "AdjustSaturation", "AdjustHue")
    def _adjust(ctx, node, inputs, attrs):
        factor = float(np.asarray(ctx.const_value(node.input[1])).item())
        name = {"AdjustContrastv2": "adjust_contrast",
                "AdjustSaturation": "adjust_saturation",
                "AdjustHue": "adjust_hue"}[node.op]
        kw = ("delta" if node.op == "AdjustHue" else "factor")
        return ctx.sd._op(name, inputs[0], **{kw: factor})

    @mapping_rule("CropAndResize")
    def _crop_resize(ctx, node, inputs, attrs):
        size = [int(v) for v in np.asarray(ctx.const_value(node.input[3]))]
        return ctx.sd._op("crop_and_resize", inputs[0], inputs[1],
                          inputs[2], crop_size=size)

    @mapping_rule("NonMaxSuppressionV3")
    def _nms(ctx, node, inputs, attrs):
        mx = int(np.asarray(ctx.const_value(node.input[2])).item())
        iou = float(np.asarray(ctx.const_value(node.input[3])).item())
        st = float(np.asarray(ctx.const_value(node.input[4])).item())
        return ctx.sd._op("non_max_suppression", inputs[0], inputs[1],
                          max_output_size=mx, iou_threshold=iou,
                          score_threshold=st)

    @mapping_rule("FakeQuantWithMinMaxArgs")
    def _fq_args(ctx, node, inputs, attrs):
        return ctx.sd._op("fake_quant_with_min_max_args", inputs[0],
                          min=float(attrs.get("min", -6.0)),
                          max=float(attrs.get("max", 6.0)),
                          num_bits=int(attrs.get("num_bits", 8)),
                          narrow_range=bool(attrs.get("narrow_range",
                                                      False)))

    @mapping_rule("FakeQuantWithMinMaxVars")
    def _fq_vars(ctx, node, inputs, attrs):
        return ctx.sd._op("fake_quant_with_min_max_vars", inputs[0],
                          inputs[1], inputs[2],
                          num_bits=int(attrs.get("num_bits", 8)),
                          narrow_range=bool(attrs.get("narrow_range",
                                                      False)))

    @mapping_rule("LRN")
    def _lrn_rule(ctx, node, inputs, attrs):
        return ctx.sd._op("lrn", inputs[0],
                          depth_radius=int(attrs.get("depth_radius", 5)),
                          bias=float(attrs.get("bias", 1.0)),
                          alpha=float(attrs.get("alpha", 1.0)),
                          beta=float(attrs.get("beta", 0.5)))

    @mapping_rule("Conv2DBackpropInput")
    def _deconv_rule(ctx, node, inputs, attrs):
        st = attrs.get("strides", [1, 1, 1, 1])
        pad = attrs.get("padding", "SAME")
        # lax.conv_transpose SAME always yields in*stride; TF records the
        # true forward-input size — when it is STATICALLY known, reject
        # odd-size gradients we cannot reproduce rather than silently
        # misalign the grid (dynamic input_sizes skips the validation)
        try:
            sizes = np.asarray(ctx.const_value(node.input[0])).tolist()
        except TFImportError:
            sizes = None
        in_shape = inputs[2].shape
        if sizes is not None and pad.upper() == "SAME" \
                and in_shape is not None and None not in in_shape[1:3]:
            want_h, want_w = int(sizes[1]), int(sizes[2])
            got_h = int(in_shape[1]) * int(st[1])
            got_w = int(in_shape[2]) * int(st[2])
            if (want_h, want_w) != (got_h, got_w):
                raise TFImportError(
                    f"Conv2DBackpropInput: recorded input_sizes "
                    f"({want_h}, {want_w}) != stride-inferred "
                    f"({got_h}, {got_w}) — odd-size SAME transposes are "
                    f"unsupported")
        # TF's op is the conv GRADIENT: lax applies the spatial flip +
        # channel swap itself under transpose_kernel=True, taking the
        # filter in TF's own (H, W, out, in) layout unmodified
        return ctx.sd._op("deconv2d", inputs[2], inputs[1],
                          strides=(int(st[1]), int(st[2])),
                          padding=pad, transpose_kernel=True)

    @mapping_rule("Conv3D")
    def _conv3d_rule(ctx, node, inputs, attrs):
        s = attrs.get("strides", [1, 1, 1, 1, 1])
        return ctx.sd._op("conv3d", inputs[0], inputs[1],
                          strides=tuple(int(v) for v in s[1:4]),
                          padding=attrs.get("padding", "SAME"))

    @mapping_rule("MaxPool3D", "AvgPool3D")
    def _pool3d(ctx, node, inputs, attrs):
        k = attrs.get("ksize", [1, 2, 2, 2, 1])
        s = attrs.get("strides", [1, 2, 2, 2, 1])
        name = "maxpool3d" if node.op == "MaxPool3D" else "avgpool3d"
        return ctx.sd._op(name, inputs[0],
                          kernel=tuple(int(v) for v in k[1:4]),
                          strides=tuple(int(v) for v in s[1:4]),
                          padding=attrs.get("padding", "VALID"))

    @mapping_rule("Dilation2D")
    def _dilation_rule(ctx, node, inputs, attrs):
        s = attrs.get("strides", [1, 1, 1, 1])
        r = attrs.get("rates", [1, 1, 1, 1])
        return ctx.sd._op("dilation2d", inputs[0], inputs[1],
                          strides=(int(s[1]), int(s[2])),
                          rates=(int(r[1]), int(r[2])),
                          padding=attrs.get("padding", "SAME"))

    @mapping_rule("MaxPoolWithArgmax")
    def _mpargmax(ctx, node, inputs, attrs):
        k = attrs.get("ksize", [1, 2, 2, 1])
        s = attrs.get("strides", [1, 2, 2, 1])
        return ctx.sd._op("maxpool_with_argmax", inputs[0],
                          kernel=(int(k[1]), int(k[2])),
                          strides=(int(s[1]), int(s[2])),
                          padding=attrs.get("padding", "VALID"))

    @mapping_rule("ExtractImagePatches")
    def _patches(ctx, node, inputs, attrs):
        k = attrs.get("ksizes", [1, 2, 2, 1])
        s = attrs.get("strides", [1, 1, 1, 1])
        r = attrs.get("rates", [1, 1, 1, 1])
        return ctx.sd._op("extract_image_patches", inputs[0],
                          ksizes=(int(k[1]), int(k[2])),
                          strides=(int(s[1]), int(s[2])),
                          rates=(int(r[1]), int(r[2])),
                          padding=attrs.get("padding", "VALID"))


    # tranche 3: remaining raw-op passthroughs (registry alias == TF type)
    @mapping_rule("Mod", "TruncateMod")
    def _mod_trunc(ctx, node, inputs, attrs):
        # TF's raw Mod is the C-style TRUNCATED remainder for floats
        # (pinned by the negative-operand corpus case); FloorMod is floor
        return ctx.sd._op("truncatemod", *inputs)

    for op in ["BitwiseAnd", "BitwiseOr", "BitwiseXor", "IsNan",
               "IsFinite", "Rank", "Size", "ListDiff",
               "TensorScatterAdd", "TensorScatterSub", "TensorScatterUpdate",
               "TruncateDiv", "Erfinv"]:
        @mapping_rule(op)
        def _pt3(ctx, node, inputs, attrs, _op=op):
            return ctx.sd._op(_op, *inputs)

    @mapping_rule("MatrixSolve")
    def _matrix_solve(ctx, node, inputs, attrs):
        a = inputs[0]
        if attrs.get("adjoint"):
            # real dtypes only (no complex in _TF_DTYPES): adjoint == T
            a = ctx.sd._op("matrix_transpose", a)
        return ctx.sd._op("solve", a, inputs[1])

    @mapping_rule("Diag")
    def _tf_diag(ctx, node, inputs, attrs):
        if inputs[0].shape is not None and len(inputs[0].shape) != 1:
            raise TFImportError(
                "Diag: only rank-1 input supported (TF's higher-rank "
                "(i..,j..) tensor-diag form is not)")
        return ctx.sd._op("diag", inputs[0])

    @mapping_rule("DiagPart")
    def _tf_diag_part(ctx, node, inputs, attrs):
        if inputs[0].shape is not None and len(inputs[0].shape) != 2:
            raise TFImportError(
                "DiagPart: only rank-2 input supported (TF's rank-2k "
                "form is not)")
        return ctx.sd._op("diag_part", inputs[0])

    @mapping_rule("LeftShift")
    def _lshift(ctx, node, inputs, attrs):
        return ctx.sd._op("shift_bits", *inputs)

    @mapping_rule("RightShift")
    def _rshift(ctx, node, inputs, attrs):
        return ctx.sd._op("rshift_bits", *inputs)

    @mapping_rule("TopK")
    def _topk_v1(ctx, node, inputs, attrs):
        return ctx.sd._op("top_k", inputs[0], k=int(attrs["k"]))

    @mapping_rule("BroadcastTo")
    def _broadcast_to(ctx, node, inputs, attrs):
        shape = [int(v) for v in np.asarray(ctx.const_value(node.input[1]))]
        return ctx.sd._op("broadcast_to", inputs[0], shape=tuple(shape))

    @mapping_rule("LinSpace")
    def _linspace(ctx, node, inputs, attrs):
        n = int(np.asarray(ctx.const_value(node.input[2])).item())
        return ctx.sd._op("linspace", inputs[0], inputs[1], num=n)

    @mapping_rule("ConfusionMatrix")
    def _confusion(ctx, node, inputs, attrs):
        # num_classes: explicit const input when given, else fold both
        # index inputs and take the max + 1
        try:
            n = int(np.asarray(ctx.const_value(node.input[2])).item())
        except (TFImportError, IndexError):
            a = np.asarray(ctx.const_value(node.input[0]))
            b = np.asarray(ctx.const_value(node.input[1]))
            n = int(max(a.max(), b.max())) + 1
        return ctx.sd._op("confusion_matrix", inputs[0], inputs[1],
                          num_classes=n)

    @mapping_rule("ScatterNd")
    def _scatter_nd_rule(ctx, node, inputs, attrs):
        shape = [int(v) for v in np.asarray(ctx.const_value(node.input[2]))]
        return ctx.sd._op("scatter_nd", inputs[0], inputs[1],
                          shape=tuple(shape))

    @mapping_rule("Qr")
    def _qr(ctx, node, inputs, attrs):
        mode = "complete" if attrs.get("full_matrices") else "reduced"
        return ctx.sd._op("qr", inputs[0], mode=mode)

    @mapping_rule("Svd")
    def _svd(ctx, node, inputs, attrs):
        # ours: (u, s, vh); TF: (s, u, v) with v NOT conjugate-transposed
        u, sdiag, vh = ctx.sd._op("svd", inputs[0],
                                  full_matrices=bool(
                                      attrs.get("full_matrices", 0)))
        v = ctx.sd._op("matrix_transpose", vh)
        return sdiag, u, v

    @mapping_rule("Bitcast")
    def _bitcast_rule(ctx, node, inputs, attrs):
        dt = _dtype_of(int(attrs.get("type", attrs.get("T", 1))))
        return ctx.sd._op("bitcast", inputs[0], dtype=dt)


_register_default_rules()
_register_extended_rules()


def _shape_template(ctx, dims_ref):
    """Fold a Pack of [Shape(v)[i] | const] elements into a template list
    of ints and (SDVariable, axis) pairs; None when the pattern differs."""
    pack = ctx.node_defs.get(dims_ref.split(":")[0])
    if pack is None or pack.op not in ("Pack", "pack"):
        return None
    out = []
    for inp in pack.input:
        try:
            out.append(int(np.asarray(ctx.const_value(inp)).reshape(())))
            continue
        except TFImportError:
            pass
        ss = ctx.node_defs.get(inp.split(":")[0])
        if ss is None or ss.op != "StridedSlice":
            return None
        shp = ctx.node_defs.get(ss.input[0].split(":")[0])
        if shp is None or shp.op not in ("Shape", "ShapeN"):
            return None
        try:
            axis = int(np.asarray(ctx.const_value(ss.input[1])).reshape(-1)[0])
        except TFImportError:
            return None
        ref_var = ctx.vars.get(_fq(shp.input[0]))
        if ref_var is None:
            return None
        out.append((ref_var, axis))
    return out


def _fq(ref: str) -> str:
    """Normalize a tensor ref to 'node:index'. GraphDef refs are 'node' or
    'node:i'; FunctionDef refs are 'arg', 'node:out_name:i'."""
    if ref.count(":") >= 2:                 # FunctionDef 3-part form
        parts = ref.split(":")
        return f"{parts[0]}:{parts[-1]}"
    return ref if ":" in ref else ref + ":0"


def _topo_sorted(nodes):
    """Kahn-sort a node list on intra-list data+control edges, keeping the
    original order among simultaneously-ready nodes.

    TF does NOT guarantee GraphDef/FunctionDef node order is topological —
    function-body graphs in particular come out in hash order that varies
    with PYTHONHASHSEED (found as a flaky whole-suite import failure:
    'consumes unknown tensor ... ReadVariableOp'). External references
    (function args, captures, nodes of an outer graph) are not edges."""
    from collections import deque

    nodes = list(nodes)
    by_name = {n.name: n for n in nodes}
    indeg = {n.name: 0 for n in nodes}
    children = {n.name: [] for n in nodes}
    for n in nodes:
        for ref in n.input:
            base = ref.lstrip("^").split(":")[0]
            if base in by_name and base != n.name:
                indeg[n.name] += 1
                children[base].append(n.name)
    ready = deque(n.name for n in nodes if indeg[n.name] == 0)
    order = []
    while ready:
        nm = ready.popleft()
        order.append(by_name[nm])
        for ch in children[nm]:
            indeg[ch] -= 1
            if indeg[ch] == 0:
                ready.append(ch)
    if len(order) != len(nodes):      # cycle — impossible in a valid
        return nodes                  # GraphDef; fall back to input order
    return order


def _map_nodes(ctx: _ImportCtx, nodes, skip=frozenset()):
    """Shared per-node rule walk for GraphDef.node and FunctionDef.node_def."""
    for node in _topo_sorted(nodes):
        ctx.node_defs[node.name] = node
        if node.name in skip or node.op == "NoOp":
            continue
        if node.op == "Assert":
            # debug-only; Assert's output is never consumed as a tensor
            # (CheckNumerics, by contrast, is an inline identity and routes
            # through the Identity rule below)
            continue
        if node.op == "Const" and int(node.attr["dtype"].type) == 7:
            # DT_STRING constants only ever feed Assert/summary nodes in
            # inference graphs — nothing numeric can consume them
            continue
        rule = _RULES.get(node.op)
        if rule is None:
            raise TFImportError(
                f"No mapping rule for TF op {node.op!r} (node "
                f"{node.name!r}); register one with "
                f"@tfimport.mapping_rule({node.op!r})")
        inputs = []
        for ref in node.input:
            if ref.startswith("^"):      # control edge — execution order
                continue                 # is given by topo order already
            key = _fq(ref)
            if key not in ctx.vars:
                raise TFImportError(
                    f"node {node.name!r} consumes unknown tensor {ref!r} "
                    f"(GraphDef not topologically ordered?)")
            inputs.append(ctx.vars[key])
        attrs = _parse_attrs(node)
        out = rule(ctx, node, inputs, attrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        for i, o in enumerate(outs):
            ctx.vars[f"{node.name}:{i}"] = o
        # canonical graph name: rename single-output ops to the tf name
        if len(outs) == 1 and outs[0].name != node.name \
                and node.name not in ctx.sd._vars:
            outs[0].rename(node.name)


def _emit_v1_loop(ctx: _ImportCtx, loop):
    """Rewrite one analyzed V1 frame into sd.while_loop (see
    tf_v1_control_flow module docstring)."""
    lib = ctx.library
    lv_refs = [e.input[0] for e in loop.enters]
    inv_refs = [e.input[0] for e in loop.inv_enters]
    operands = [ctx.vars[_fq(r)] for r in lv_refs + inv_refs]
    n_lv = len(loop.enters)

    def cond_build(sub, *state):
        c = _ImportCtx(sub, library=lib)
        for m, st in zip(loop.merges, state[:n_lv]):
            c.vars[m.name + ":0"] = st
        for e, st in zip(loop.inv_enters, state[n_lv:]):
            c.vars[e.name + ":0"] = st
        _map_nodes_auto(c, loop.cond_nodes)
        return c.vars[_fq(loop.loop_cond.input[0])]

    def body_build(sub, *state):
        c = _ImportCtx(sub, library=lib)
        for m, sw, st in zip(loop.merges, loop.switches, state[:n_lv]):
            c.vars[m.name + ":0"] = st
            if sw is not None:
                # Switch:1 (output_true) feeds the body; seed :0 too so any
                # stray consumer resolves to the same per-iteration value
                c.vars[sw.name + ":1"] = st
                c.vars[sw.name + ":0"] = st
        for e, st in zip(loop.inv_enters, state[n_lv:]):
            c.vars[e.name + ":0"] = st
        # _auto: a V1 tf.cond inside the body is rewritten recursively
        _map_nodes_auto(c, loop.body_nodes)
        outs = [c.vars[_fq(ni.input[0])] for ni in loop.next_iters]
        outs += list(state[n_lv:])        # invariants pass through unchanged
        return outs if len(outs) > 1 else outs[0]

    res = ctx.sd.while_loop(cond_build, body_build, *operands,
                            name=loop.frame.split("/")[-1] or "v1_while")
    res = res if isinstance(res, tuple) else (res,)
    for i, ex in enumerate(loop.exits):
        if ex is not None:
            ctx.vars[ex.name + ":0"] = res[i]


def _emit_v1_cond(ctx: _ImportCtx, group):
    """Rewrite one V1 tf.cond call (a CondGroup — possibly multi-output)
    into ONE sd.if_cond; branch nodes are traced once per branch, not once
    per output."""
    pred = ctx.vars[_fq(group.pred_ref)]
    operands = [ctx.vars[_fq(s.input[0])] for s in group.switches]

    def make(take_refs):
        def build(sub, *args):
            c = _ImportCtx(sub, library=ctx.library)
            for s, a in zip(group.switches, args):
                c.vars[s.name + ":0"] = a
                c.vars[s.name + ":1"] = a
            _map_nodes_auto(c, group.branch_nodes)
            outs = [c.vars[_fq(r)] for r in take_refs]
            return outs if len(outs) > 1 else outs[0]
        return build

    out = ctx.sd.if_cond(pred, make(group.true_refs),
                         make(group.false_refs), *operands,
                         name=group.merges[0].name.replace("/", "_"))
    outs = out if isinstance(out, tuple) else (out,)
    for m, o in zip(group.merges, outs):
        ctx.vars[m.name + ":0"] = o


def _cond_ready(ctx, group):
    return _fq(group.pred_ref) in ctx.vars and all(
        _fq(s.input[0]) in ctx.vars for s in group.switches)


def _map_nodes_v1(ctx: _ImportCtx, nodes, skip=frozenset()):
    """Node walk for GraphDefs containing V1 control flow: loop frames and
    Switch/Merge conds are emitted as functional composites at the point
    their outer inputs are all available; their internal nodes are skipped
    from the plain walk."""
    from deeplearning4j_tpu.modelimport.tf_v1_control_flow import (
        analyze_conds, analyze_loops)

    try:
        loops = analyze_loops(nodes)
        loop_names = set().union(*(l.all_names for l in loops)) \
            if loops else set()
        conds = analyze_conds(nodes, loop_names)
    except ValueError as e:
        raise TFImportError(str(e)) from e
    member_loop = {}
    for l in loops:
        for nm in l.all_names:
            member_loop[nm] = l
    cond_by_merge = {}
    for c in conds:
        for m in c.merges:
            cond_by_merge[m.name] = c
    cond_skip = set().union(*(c.skip_names for c in conds)) \
        if conds else set()

    # V1 tf.cond pivot plumbing (the pred Switch + switch_t/switch_f/pred_id
    # Identities) is consumed only over CONTROL edges — sweep any leftover
    # Switch, and any Identity chained off a swept node, whose tensor
    # outputs have no live data consumer
    name_set = {n.name for n in nodes}
    data_consumers = {}
    for n in nodes:
        for ref in n.input:
            if not ref.startswith("^"):
                data_consumers.setdefault(ref.split(":")[0], set()) \
                    .add(n.name)
    by_name = {n.name: n for n in nodes}
    dead = set()
    changed = True
    while changed:
        changed = False
        for n in nodes:
            if n.name in dead or n.name in cond_skip:
                continue
            live = {c for c in data_consumers.get(n.name, set())
                    if c not in dead and c not in cond_skip
                    and c not in member_loop}
            if live:
                continue
            src = n.input[0].split(":")[0].lstrip("^") if n.input else None
            if n.op == "Switch" or (
                    n.op == "Identity" and src in dead) or (
                    n.op == "Identity" and src in name_set
                    and by_name[src].op == "Switch"
                    and n.name not in member_loop):
                if n.name not in member_loop and n.name not in cond_by_merge:
                    dead.add(n.name)
                    changed = True
    cond_skip |= dead

    emitted = set()
    plain = []

    def loop_ready(l):
        return all(_fq(e.input[0]) in ctx.vars
                   for e in l.enters + l.inv_enters)

    for node in nodes:
        l = member_loop.get(node.name)
        if l is not None:
            if id(l) not in emitted:
                # flush plain nodes mapped so far, then emit when the
                # outer inputs are all present (topo order ⇒ by the time
                # any Merge appears, Enter inputs were walked)
                _map_nodes(ctx, plain, skip=skip)
                plain = []
                if loop_ready(l):
                    _emit_v1_loop(ctx, l)
                    emitted.add(id(l))
            continue
        c = cond_by_merge.get(node.name)
        if c is not None:
            if id(c) not in emitted:
                _map_nodes(ctx, plain, skip=skip)
                plain = []
                if _cond_ready(ctx, c):
                    _emit_v1_cond(ctx, c)
                    emitted.add(id(c))
            continue
        if node.name in cond_skip:
            continue
        plain.append(node)
    _map_nodes(ctx, plain, skip=skip)
    # hash-ordered node lists can place a region's outer producers AFTER
    # every member node, so the in-walk readiness checks all miss; retry
    # pending regions now that the final flush mapped everything else,
    # looping until a pass makes no progress (regions can unblock each
    # other — a cond feeding a loop's Enter)
    progress = True
    while progress:
        progress = False
        for l in loops:
            if id(l) not in emitted and loop_ready(l):
                _emit_v1_loop(ctx, l)
                emitted.add(id(l))
                progress = True
        for c in conds:
            if id(c) not in emitted and _cond_ready(ctx, c):
                _emit_v1_cond(ctx, c)
                emitted.add(id(c))
                progress = True
    missing = [l.frame for l in loops if id(l) not in emitted] \
        + [c.merges[0].name for c in conds if id(c) not in emitted]
    if missing:
        raise TFImportError(f"V1 control-flow regions never became "
                            f"emittable (inputs unmapped): {missing}")


def _map_nodes_auto(ctx: _ImportCtx, nodes, skip=frozenset()):
    """Plain walk, upgraded to the V1 control-flow walk when the node list
    itself contains Switch/Merge regions (cond-in-loop recursion)."""
    from deeplearning4j_tpu.modelimport.tf_v1_control_flow import (
        has_v1_control_flow)
    if has_v1_control_flow(nodes):
        _map_nodes_v1(ctx, nodes, skip=skip)
    else:
        _map_nodes(ctx, nodes, skip=skip)


def _fdef_builder(fdef, library):
    """FunctionDef → a control-flow body builder fn(sub_sd, *args)."""
    def build(sub_sd, *args):
        ctx = _ImportCtx(sub_sd, library=library)
        for i, arg in enumerate(fdef.signature.input_arg):
            ctx.vars[f"{arg.name}:0"] = args[i]
        _map_nodes(ctx, fdef.node_def)
        outs = [ctx.vars[_fq(fdef.ret[oarg.name])]
                for oarg in fdef.signature.output_arg]
        return outs if len(outs) > 1 else outs[0]
    return build


_FUNC_WRAPPER = re.compile(r"(^|.*/)Func/.*/(input|output)/_\d+$")


def _elide_func_wrappers(nodes):
    """Drop the pass-through Identity nodes TF's function INLINER inserts
    (``Func/<scope>/input/_k`` / ``output/_k``) when control flow is lowered
    (``lower_control_flow=True``), rewiring consumers to the wrapped tensor.
    The V1 frame analyzer partitions nodes by Enter/Exit frames; these
    wrappers sit OUTSIDE the frames while referencing tensors inside them,
    which otherwise breaks the partition (round-3 finding)."""
    # a wrapper is a pass-through when its one DATA input is first and any
    # remaining inputs are control edges — which this importer drops
    # globally by design (functional executor; ordering comes from the
    # topo walk, see the control-edge skip in _map_nodes)
    subst = {n.name: n.input[0] for n in nodes
             if n.op == "Identity" and _FUNC_WRAPPER.match(n.name)
             and n.input and not n.input[0].startswith("^")
             and all(r.startswith("^") for r in n.input[1:])}
    if not subst:
        return nodes

    def resolve(ref):
        ctrl = ref.startswith("^")
        base = ref.lstrip("^").split(":")[0]
        suffix = None
        seen = set()
        while base in subst:
            if base in seen:
                raise TFImportError(
                    f"cyclic Func-wrapper chain at {base!r}")
            seen.add(base)
            nxt = subst[base]
            base = nxt.lstrip("^").split(":")[0]
            suffix = nxt.split(":", 1)[1] if ":" in nxt else None
        if not seen:
            return ref
        if ctrl:
            return "^" + base
        return base + (":" + suffix if suffix else "")

    out = []
    for n in nodes:
        if n.name in subst:
            continue
        new_inputs = [resolve(ref) for ref in n.input]
        if new_inputs != list(n.input):
            # copy before rewiring: the caller's GraphDef stays untouched
            copied = type(n)()
            copied.CopyFrom(n)
            del copied.input[:]
            copied.input.extend(new_inputs)
            n = copied
        out.append(n)
    return out


class TFGraphMapper:
    """ref: TFGraphMapper#importGraph — GraphDef → SameDiff."""

    @staticmethod
    def import_graph(graph_def, ignore_nodes=()) -> SameDiff:
        gd = _as_graph_def(graph_def)
        sd = SameDiff.create()
        library = {f.signature.name: f
                   for f in getattr(gd, "library", ()).function} \
            if gd.HasField("library") else {}
        ctx = _ImportCtx(sd, library=library)
        from deeplearning4j_tpu.modelimport.tf_v1_control_flow import (
            has_v1_control_flow)
        nodes = _elide_func_wrappers(list(gd.node))
        if has_v1_control_flow(nodes):
            _map_nodes_v1(ctx, nodes, skip=set(ignore_nodes))
        else:
            _map_nodes(ctx, nodes, skip=set(ignore_nodes))
        return sd

    importGraph = import_graph


def _as_graph_def(graph_def):
    if hasattr(graph_def, "node"):
        return graph_def
    try:
        from tensorflow.core.framework import graph_pb2
    except ImportError as e:
        raise TFImportError(
            "TF GraphDef parsing needs the tensorflow protos "
            "(pip tensorflow)") from e
    gd = graph_pb2.GraphDef()
    if isinstance(graph_def, (str, bytes)) and not isinstance(graph_def, bytes):
        with open(graph_def, "rb") as f:
            gd.ParseFromString(f.read())
    else:
        gd.ParseFromString(graph_def)
    return gd


def _register_tail_rules():
    """Round-3 long-tail sweep: the last common-GraphDef ops a probe of
    ~140 frequently-exported op types found missing."""

    @mapping_rule("AddN")
    def _addn(ctx, node, inputs, attrs):
        if len(inputs) == 1:               # N=1 (grappler/gradient forms):
            return ctx.sd._op("Identity", inputs[0])   # rename-safe
        acc = inputs[0]
        for x in inputs[1:]:
            acc = ctx.sd._op("Add", acc, x)
        return acc

    @mapping_rule("Div")
    def _div(ctx, node, inputs, attrs):
        # TF Div: plain division on floats (x/0 = ±inf), truncation toward
        # zero on integers — pick by operand dtype
        import numpy as np
        if np.issubdtype(np.dtype(inputs[0].dtype), np.integer):
            return ctx.sd._op("truncatediv", *inputs)
        return ctx.sd._op("RealDiv", *inputs)

    @mapping_rule("DivNoNan")
    def _div_no_nan(ctx, node, inputs, attrs):
        return ctx.sd._op("divide_no_nan", *inputs)

    @mapping_rule("IdentityN")
    def _identity_n(ctx, node, inputs, attrs):
        if len(inputs) == 1:
            # single output rides the normal rename path — emit a real op
            # so renaming cannot strip the PRODUCER's (or a placeholder's)
            # name
            return ctx.sd._op("Identity", inputs[0])
        # multi-output: alias the inputs directly (consumed as node:i refs,
        # never renamed); creating named Identity ops here could steal the
        # bare name "Identity" from a later graph-output node
        return list(inputs)

    @mapping_rule("Invert")
    def _invert(ctx, node, inputs, attrs):
        return ctx.sd._op("bitwise_not", inputs[0])

    @mapping_rule("RandomStandardNormal", "RandomUniform")
    def _tf_random(ctx, node, inputs, attrs):
        import numpy as np
        dims = ctx.const_value(node.input[0])   # raises if not foldable
        shape = tuple(int(d) for d in np.asarray(dims).reshape(-1))
        s1 = int(attrs.get("seed", 0))
        s2 = int(attrs.get("seed2", 0))
        # TF draws from the PAIR (graph seed, per-op seed): mix both so
        # ops sharing a graph-level seed still differ
        seed = (hash((s1, s2)) & 0x7FFFFFFF) if (s1 or s2) else 0
        if not seed:
            # one compiled program = one baked key: an unseeded TF random
            # draws FRESH values per session.run, but here the draw is
            # fixed at import time. Make that loud, and derive a
            # per-import seed so separate imports at least differ.
            import warnings
            from deeplearning4j_tpu.ndarray import random as _rng
            import jax as _jax
            seed = int(_jax.random.randint(_rng.next_key(), (), 0,
                                           2 ** 31 - 1))
            warnings.warn(
                f"{node.op} {node.name!r} has no seed: under whole-graph "
                f"jit the draw is fixed per import (TF would redraw per "
                f"run); set the seed attr for reproducibility",
                stacklevel=2)
        op = ("random_normal_gen" if node.op == "RandomStandardNormal"
              else "random_uniform_gen")
        out = ctx.sd._op(op, shape=shape, seed=seed)
        dt = _dtype_of(int(attrs.get("dtype", 1)))
        if str(dt) != "float32":
            out = ctx.sd._op("Cast", out, dtype=dt)
        return out

    @mapping_rule("DynamicStitch", "ParallelDynamicStitch")
    def _dynamic_stitch(ctx, node, inputs, attrs):
        # TF contract: merged.shape[0] = max(indices) + 1, duplicates
        # resolved last-wins. A static output shape therefore needs the
        # indices to be constant-foldable (they are in the partition/
        # stitch patterns TF emits); the merge then compiles to ONE gather
        # with a host-computed source plan — no scatter ordering hazards.
        import numpy as np
        n = int(attrs.get("N", len(inputs) // 2))
        data = inputs[n:]
        try:
            idx_vals = [np.asarray(ctx.const_value(r)).reshape(-1)
                        for r in node.input[:n]]
        except TFImportError:
            raise TFImportError(
                f"{node.op} {node.name!r}: indices must be "
                "constant-foldable — the output row count max(indices)+1 "
                "must be static under the whole-graph jit")
        # element shape = data rank minus the indices rank (indices may
        # be scalar, 1-D, or higher — TF flattens index-major)
        idx_raw = [np.asarray(ctx.const_value(r))
                   for r in node.input[:n]]
        elem = tuple(int(d) for d in
                     (data[0].shape or ())[idx_raw[0].ndim:])
        flat_data = ctx.sd._op(
            "concat", *[ctx.sd._op("Reshape", d, shape=(-1,) + elem)
                        for d in data], axis=0) if n > 1 else \
            ctx.sd._op("Reshape", data[0], shape=(-1,) + elem)
        all_idx = np.concatenate(idx_vals)
        rows = int(all_idx.max()) + 1 if all_idx.size else 0
        src = np.zeros(rows, np.int64)
        for flat_pos, out_row in enumerate(all_idx):   # last write wins
            src[int(out_row)] = flat_pos
        return ctx.sd._op("gather", flat_data,
                          ctx.sd.constant(src), axis=0)

    @mapping_rule("DynamicPartition")
    def _dynamic_partition(ctx, node, inputs, attrs):
        raise TFImportError(
            "DynamicPartition has data-dependent output shapes, which the "
            "whole-graph-jit executor cannot represent; restructure with "
            "masks/Where-free selects (the eager registry op "
            "'dynamic_partition' covers host-side use)")

    @mapping_rule("Where")
    def _where_tf(ctx, node, inputs, attrs):
        raise TFImportError(
            "TF Where (coordinate list) has a data-dependent output shape; "
            "under whole-graph jit use Select/SelectV2 masks instead "
            "(eager: ops registry 'nonzero_coords')")

    @mapping_rule("TensorListFromTensor", "TensorListStack",
                  "TensorListReserve", "TensorListGetItem",
                  "TensorListSetItem")
    def _tensor_list(ctx, node, inputs, attrs, _op=None):
        raise TFImportError(
            f"{node.op}: TensorList (TensorArray v2) ops are unsupported "
            "— restructure the loop to accumulate into a fixed-shape "
            "tensor (e.g. tensor_scatter_nd_update at the loop index), "
            "which the counted-While lowering trains through")


_register_tail_rules()
