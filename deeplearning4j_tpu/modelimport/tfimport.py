"""TensorFlow GraphDef import into the SameDiff-equivalent graph engine.

Reference: ``nd4j/samediff-import/samediff-import-tensorflow`` (Kotlin
``OpMappingRegistry``/``ImportGraph``) and the older
``org.nd4j.imports.graphmapper.tf.TFGraphMapper`` (SURVEY J8).

Same architecture as the reference: a per-op mapping-rule registry walks the
GraphDef topologically, turning each node into graph-engine ops. Structural
inputs (axes, shapes, perms, paddings) are constant-folded at import time —
the reference does the same through its "input frameworks" attribute
resolution. The imported graph then executes as ONE jitted XLA program
(where the reference interprets op-by-op through the JNI executioner).

Protobuf parsing uses the tensorflow pip package's generated proto classes
only (no session/runtime); import fails with a clear message without it.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

_RULES: Dict[str, Callable] = {}


def mapping_rule(*op_types):
    """ref: OpMappingRegistry rule registration."""
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


class TFImportError(ValueError):
    pass


# ------------------------------------------------------------------ attrs
def _parse_attrs(node) -> dict:
    out = {}
    for k, v in node.attr.items():
        field = v.WhichOneof("value")
        if field == "b":
            out[k] = v.b
        elif field == "i":
            out[k] = int(v.i)
        elif field == "f":
            out[k] = float(v.f)
        elif field == "s":
            out[k] = v.s.decode("utf-8", "ignore")
        elif field == "type":
            out[k] = int(v.type)
        elif field == "shape":
            out[k] = [d.size for d in v.shape.dim]
        elif field == "list":
            lv = v.list
            if lv.i:
                out[k] = [int(x) for x in lv.i]
            elif lv.f:
                out[k] = [float(x) for x in lv.f]
            elif lv.s:
                out[k] = [x.decode() for x in lv.s]
            elif lv.b:
                out[k] = list(lv.b)
            else:
                out[k] = []
        elif field == "tensor":
            out[k] = v.tensor
    return out


_TF_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
              5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
              14: np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.float32,
              19: np.float16}


def _dtype_of(enum: int):
    try:
        import ml_dtypes
        if enum == 14:
            return np.dtype(ml_dtypes.bfloat16)
    except ImportError:
        pass
    if enum in _TF_DTYPES:
        return np.dtype(_TF_DTYPES[enum])
    raise TFImportError(f"Unsupported TF dtype enum {enum}")


def _tensor_to_ndarray(tensor_proto) -> np.ndarray:
    """TensorProto → numpy without the TF runtime."""
    dtype = _dtype_of(int(tensor_proto.dtype))
    shape = [d.size for d in tensor_proto.tensor_shape.dim]
    if tensor_proto.tensor_content:
        return np.frombuffer(tensor_proto.tensor_content,
                             dtype=dtype).reshape(shape).copy()
    for field in ("float_val", "double_val", "int_val", "int64_val",
                  "bool_val", "half_val"):
        vals = list(getattr(tensor_proto, field, []))
        if vals:
            arr = np.asarray(vals, dtype=dtype)
            n = int(np.prod(shape)) if shape else 1
            if arr.size == 1 and n > 1:
                arr = np.full(shape, arr[0], dtype=dtype)
            return arr.reshape(shape)
    return np.zeros(shape, dtype=dtype)


# ------------------------------------------------------------------ mapper
class _ImportCtx:
    def __init__(self, sd: SameDiff):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}     # tf tensor name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}   # tf node name -> numpy

    def const_value(self, ref: str) -> np.ndarray:
        name = ref.split(":")[0]
        if name not in self.consts:
            raise TFImportError(
                f"op input {ref!r} must be a constant for import "
                f"(structural argument)")
        return self.consts[name]


def _pool_args(attrs):
    k = attrs.get("ksize", [1, 1, 1, 1])
    s = attrs.get("strides", [1, 1, 1, 1])
    if attrs.get("data_format", "NHWC") != "NHWC":
        raise TFImportError("only NHWC supported")
    return tuple(k[1:3]), tuple(s[1:3]), attrs.get("padding", "VALID")


def _register_default_rules():
    E = lambda ctx, name, *a, **kw: ctx.sd._op(name, *a, **kw)

    @mapping_rule("Placeholder", "PlaceholderWithDefault")
    def _ph(ctx, node, inputs, attrs):
        shape = attrs.get("shape")
        shape = tuple(None if d in (-1, 0) and i == 0 else (None if d == -1 else d)
                      for i, d in enumerate(shape or ())) or None
        dt = _dtype_of(attrs.get("dtype", 1))
        return ctx.sd.placeholder(node.name, shape, dt)

    @mapping_rule("Const")
    def _const(ctx, node, inputs, attrs):
        arr = _tensor_to_ndarray(attrs["value"])
        ctx.consts[node.name] = arr
        return ctx.sd.constant(arr, name=node.name)

    @mapping_rule("Identity", "StopGradient", "PreventGradient", "Snapshot")
    def _ident(ctx, node, inputs, attrs):
        # emit a real identity op so the TF node name stays addressable as a
        # graph output (XLA elides it at compile time)
        return ctx.sd._op("Identity", inputs[0])

    # elementwise binaries/unaries ride the registry's TF aliases directly
    _PASSTHRU = [
        "Add", "AddV2", "Sub", "Mul", "RealDiv", "Maximum", "Minimum",
        "SquaredDifference", "Pow", "Neg", "FloorDiv", "FloorMod",
        "Relu", "Relu6", "Elu", "Selu", "Sigmoid", "Tanh", "Softplus",
        "Softsign", "Gelu",
    ]
    for op in _PASSTHRU:
        @mapping_rule(op)
        def _ew(ctx, node, inputs, attrs, _op=op):
            alias = {"AddV2": "Add"}.get(_op, _op)
            return ctx.sd._op(alias, *inputs)

    for op, fn in [("Sqrt", "sqrt"), ("Rsqrt", "rsqrt"), ("Exp", "exp"),
                   ("Log", "log"), ("Abs", "abs"), ("Square", "square"),
                   ("Sign", "sign"), ("Floor", "floor"), ("Ceil", "ceil"),
                   ("Round", "round"), ("Erf", "erf")]:
        @mapping_rule(op)
        def _un(ctx, node, inputs, attrs, _fn=fn):
            return ctx.sd._op(_fn, inputs[0])

    @mapping_rule("LeakyRelu")
    def _leaky(ctx, node, inputs, attrs):
        return ctx.sd._op("LeakyRelu", inputs[0],
                          alpha=attrs.get("alpha", 0.2))

    @mapping_rule("MatMul", "BatchMatMul", "BatchMatMulV2")
    def _mm(ctx, node, inputs, attrs):
        return ctx.sd._op("MatMul", inputs[0], inputs[1],
                          transpose_a=attrs.get("transpose_a",
                                                attrs.get("adj_x", False)),
                          transpose_b=attrs.get("transpose_b",
                                                attrs.get("adj_y", False)))

    @mapping_rule("BiasAdd")
    def _bias(ctx, node, inputs, attrs):
        if attrs.get("data_format", "NHWC") != "NHWC":
            raise TFImportError("BiasAdd: only NHWC supported")
        return ctx.sd._op("Add", inputs[0], inputs[1])

    @mapping_rule("Softmax", "LogSoftmax")
    def _sm(ctx, node, inputs, attrs):
        return ctx.sd._op(node.op, inputs[0])

    @mapping_rule("Mean", "Sum", "Max", "Min", "Prod")
    def _red(ctx, node, inputs, attrs):
        axis = ctx.const_value(node.input[1])
        axis = tuple(int(a) for a in np.atleast_1d(axis))
        return ctx.sd._op(node.op, inputs[0], axis=axis,
                          keepdims=attrs.get("keep_dims", False))

    @mapping_rule("ArgMax", "ArgMin")
    def _arg(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[1])) if len(node.input) > 1 else -1
        return ctx.sd._op(node.op, inputs[0], axis=axis)

    @mapping_rule("Reshape")
    def _reshape(ctx, node, inputs, attrs):
        shape = [int(s) for s in ctx.const_value(node.input[1])]
        return ctx.sd._op("Reshape", inputs[0], shape=shape)

    @mapping_rule("Transpose")
    def _transpose(ctx, node, inputs, attrs):
        perm = [int(p) for p in ctx.const_value(node.input[1])]
        return ctx.sd._op("Transpose", inputs[0], perm=perm)

    @mapping_rule("Squeeze")
    def _squeeze(ctx, node, inputs, attrs):
        dims = attrs.get("squeeze_dims") or None
        return ctx.sd._op("Squeeze", inputs[0],
                          axis=list(dims) if dims else None)

    @mapping_rule("ExpandDims")
    def _expand(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[1]))
        return ctx.sd._op("ExpandDims", inputs[0], axis=axis)

    @mapping_rule("ConcatV2", "Concat")
    def _concat(ctx, node, inputs, attrs):
        axis = int(ctx.const_value(node.input[-1]))
        return ctx.sd._op("Concat", *inputs[:-1], axis=axis)

    @mapping_rule("Pack")
    def _pack(ctx, node, inputs, attrs):
        return ctx.sd._op("Stack", *inputs, axis=attrs.get("axis", 0))

    @mapping_rule("Pad", "PadV2")
    def _pad(ctx, node, inputs, attrs):
        pads = [[int(v) for v in row]
                for row in ctx.const_value(node.input[1])]
        return ctx.sd._op("Pad", inputs[0], paddings=pads)

    @mapping_rule("Cast")
    def _cast(ctx, node, inputs, attrs):
        return ctx.sd._op("Cast", inputs[0],
                          dtype=_dtype_of(attrs["DstT"]).name)

    @mapping_rule("Conv2D")
    def _conv(ctx, node, inputs, attrs):
        if attrs.get("data_format", "NHWC") != "NHWC":
            raise TFImportError("Conv2D: only NHWC supported")
        strides = tuple(attrs.get("strides", [1, 1, 1, 1])[1:3])
        dil = tuple(attrs.get("dilations", [1, 1, 1, 1])[1:3])
        return ctx.sd._op("conv2d", inputs[0], inputs[1],
                          strides=strides, padding=attrs.get("padding", "SAME"),
                          dilation=dil)

    @mapping_rule("DepthwiseConv2dNative")
    def _dwconv(ctx, node, inputs, attrs):
        strides = tuple(attrs.get("strides", [1, 1, 1, 1])[1:3])
        return ctx.sd._op("DepthwiseConv2dNative", inputs[0], inputs[1],
                          strides=strides,
                          padding=attrs.get("padding", "SAME"))

    @mapping_rule("MaxPool", "MaxPoolV2")
    def _maxpool(ctx, node, inputs, attrs):
        k, s, p = _pool_args(attrs)
        return ctx.sd._op("MaxPool", inputs[0], kernel=k, strides=s, padding=p)

    @mapping_rule("AvgPool")
    def _avgpool(ctx, node, inputs, attrs):
        k, s, p = _pool_args(attrs)
        return ctx.sd._op("AvgPool", inputs[0], kernel=k, strides=s, padding=p)

    @mapping_rule("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
    def _fbn(ctx, node, inputs, attrs):
        if attrs.get("is_training", True) and len(node.input) >= 5:
            # inference import of a graph exported in training mode still
            # carries moving stats as inputs 3/4 — use them
            pass
        x, scale, offset, mean, var = inputs[:5]
        return ctx.sd._op("batchnorm", x, mean, var, scale, offset,
                          epsilon=attrs.get("epsilon", 1e-3))

    @mapping_rule("StridedSlice")
    def _ss(ctx, node, inputs, attrs):
        begin = [int(v) for v in ctx.const_value(node.input[1])]
        end = [int(v) for v in ctx.const_value(node.input[2])]
        strides = [int(v) for v in ctx.const_value(node.input[3])]
        for m in ("ellipsis_mask", "new_axis_mask"):
            if attrs.get(m, 0):
                raise TFImportError(f"StridedSlice {m} unsupported")
        bm = attrs.get("begin_mask", 0)
        em = attrs.get("end_mask", 0)
        sm = attrs.get("shrink_axis_mask", 0)
        begin = [None if bm & (1 << i) else b for i, b in enumerate(begin)]
        end = [None if em & (1 << i) else e for i, e in enumerate(end)]
        for i in range(len(begin)):
            if sm & (1 << i):
                # TF shrink: take exactly the element at begin[i] (stride is
                # irrelevant). begin=-1 must map to end=None, not end=0.
                b = begin[i] if begin[i] is not None else 0
                begin[i] = b
                end[i] = b + 1 if b != -1 else None
                strides[i] = 1
        out = ctx.sd._op("StridedSlice", inputs[0], begin=begin, end=end,
                         strides=strides)
        shrink = [i for i in range(len(begin)) if sm & (1 << i)]
        if shrink:
            out = ctx.sd._op("Squeeze", out, axis=shrink)
        return out


_register_default_rules()


class TFGraphMapper:
    """ref: TFGraphMapper#importGraph — GraphDef → SameDiff."""

    @staticmethod
    def import_graph(graph_def, ignore_nodes=()) -> SameDiff:
        gd = _as_graph_def(graph_def)
        sd = SameDiff.create()
        ctx = _ImportCtx(sd)
        skip = set(ignore_nodes)
        for node in gd.node:
            if node.name in skip or node.op == "NoOp":
                continue
            rule = _RULES.get(node.op)
            if rule is None:
                raise TFImportError(
                    f"No mapping rule for TF op {node.op!r} (node "
                    f"{node.name!r}); register one with "
                    f"@tfimport.mapping_rule({node.op!r})")
            inputs = []
            for ref in node.input:
                if ref.startswith("^"):      # control edge — execution order
                    continue                 # is given by topo order already
                key = ref if ":" in ref else ref + ":0"
                if key not in ctx.vars:
                    raise TFImportError(
                        f"node {node.name!r} consumes unknown tensor {ref!r} "
                        f"(GraphDef not topologically ordered?)")
                inputs.append(ctx.vars[key])
            attrs = _parse_attrs(node)
            out = rule(ctx, node, inputs, attrs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            for i, o in enumerate(outs):
                ctx.vars[f"{node.name}:{i}"] = o
            # canonical graph name: rename single-output ops to the tf name
            if len(outs) == 1 and outs[0].name != node.name \
                    and node.name not in ctx.sd._vars:
                outs[0].rename(node.name)
        return sd

    importGraph = import_graph


def _as_graph_def(graph_def):
    if hasattr(graph_def, "node"):
        return graph_def
    try:
        from tensorflow.core.framework import graph_pb2
    except ImportError as e:
        raise TFImportError(
            "TF GraphDef parsing needs the tensorflow protos "
            "(pip tensorflow)") from e
    gd = graph_pb2.GraphDef()
    if isinstance(graph_def, (str, bytes)) and not isinstance(graph_def, bytes):
        with open(graph_def, "rb") as f:
            gd.ParseFromString(f.read())
    else:
        gd.ParseFromString(graph_def)
    return gd
