"""Keras H5 model import.

Reference: ``org.deeplearning4j.nn.modelimport.keras.KerasModelImport`` +
``KerasModel/KerasSequentialModel/KerasLayer`` and the per-layer mapping
classes under ``...modelimport.keras.layers.**`` (SURVEY D12). The reference
reads H5 through JavaCPP's HDF5 (``Hdf5Archive``); here h5py plays that role.

Layout notes (why no weight transposition is needed anywhere): Keras and
this framework agree on Dense (in,out), Conv2D HWIO kernels, NHWC
activations, and LSTM gate order (i,f,c/g,o) — the reference needs NCHW and
gate reordering; we do not. BatchNorm moving statistics land in layer
*state*, not params.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.configuration import (MultiLayerConfiguration,
                                                      NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.graph_conf import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.optim.updaters import Adam


class InvalidKerasConfigurationException(ValueError):
    """ref: exceptions.InvalidKerasConfigurationException."""


class UnsupportedKerasConfigurationException(ValueError):
    """ref: exceptions.UnsupportedKerasConfigurationException."""


_ACTIVATION_MAP = {
    "linear": "identity", "relu": "relu", "relu6": "relu6",
    "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "elu": "elu", "selu": "selu", "gelu": "gelu", "softplus": "softplus",
    "softsign": "softsign", "swish": "swish", "silu": "swish",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
    "exponential": None, "mish": "mish",
}


def _map_activation(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):   # serialized Activation object
        name = name.get("config", {}).get("activation", "linear")
    mapped = _ACTIVATION_MAP.get(str(name))
    if mapped is None and str(name) not in _ACTIVATION_MAP:
        raise UnsupportedKerasConfigurationException(
            f"Unsupported Keras activation {name!r}")
    return mapped or "identity"


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _padding(cfg) -> object:
    return "same" if cfg.get("padding", "valid") == "same" else 0


def _as_seq(v):
    return v if isinstance(v, (list, tuple)) else (v,)


class _H5Weights:
    """Per-layer weight lookup that tolerates the nested group layouts of
    Keras 2 (`layer/layer/kernel:0`) and Keras 3 (`layer/model/layer/kernel`)."""

    def __init__(self, h5file):
        # full group path (relative to the top-level layer) → array, so
        # nested submodels with several sub-layers can never collide
        self.by_layer: Dict[str, Dict[str, np.ndarray]] = {}
        root = h5file["model_weights"] if "model_weights" in h5file else h5file

        def walk(group, top, prefix=""):
            for k in group:
                item = group[k]
                name = k.split(":")[0]
                if hasattr(item, "shape"):
                    self.by_layer.setdefault(top, {})[prefix + name] = \
                        np.asarray(item)
                else:
                    walk(item, top, prefix=prefix + name + "/")

        for top in root:
            if hasattr(root[top], "keys"):
                walk(root[top], top)

    def get(self, layer_name: str,
            allow_ambiguous_leaves: bool = False) -> Dict[str, np.ndarray]:
        """Weights for one layer, keyed by leaf name ('kernel', 'bias', …)
        where unambiguous; full paths are always present. Ambiguous leaf
        names (nested submodels with several sub-layers) raise rather than
        silently loading the last-walked weight — unless the caller handles
        full paths itself (``allow_ambiguous_leaves``, e.g. the
        Bidirectional loader filters on forward_/backward_ prefixes)."""
        by_path = self.by_layer.get(layer_name, {})
        out: Dict[str, np.ndarray] = dict(by_path)
        leaves: Dict[str, list] = {}
        for path in by_path:
            leaves.setdefault(path.rsplit("/", 1)[-1], []).append(path)
        for leaf, paths in leaves.items():
            if leaf in out:      # a top-level dataset already owns this name
                continue
            if len(paths) > 1:
                if allow_ambiguous_leaves:
                    continue     # full paths remain available
                raise UnsupportedKerasConfigurationException(
                    f"Ambiguous weight name {leaf!r} in layer "
                    f"{layer_name!r}: {sorted(paths)} — nested submodel "
                    f"layouts must be addressed by full path")
            out[leaf] = by_path[paths[0]]
        return out


# ------------------------------------------------------------ layer mapping
# ---- custom/lambda layer registries (ref: KerasLayer.registerCustomLayer
# and KerasLayerUtils.registerLambdaLayer) -------------------------------
_CUSTOM_LAYERS: Dict[str, "object"] = {}
# single source of truth for lambda bodies: layers.LAMBDA_REGISTRY
_LAMBDA_LAYERS = L.LAMBDA_REGISTRY


def register_custom_layer(class_name: str, builder):
    """ref: ``KerasLayer.registerCustomLayer(name, clazz)``. ``builder`` is
    ``fn(config_dict) -> Layer``; consulted for unknown class_names."""
    _CUSTOM_LAYERS[class_name] = builder


def register_lambda_layer(layer_name: str, fn, output_type_fn=None):
    """ref: ``KerasLayerUtils.registerLambdaLayer``. ``fn`` is a
    jax-traceable ``fn(x) -> y`` bound to the Keras Lambda layer's NAME
    (lambda bodies cannot be deserialized from H5). ``output_type_fn``
    (InputType -> InputType) must be given for shape-CHANGING lambdas so
    downstream layers infer n_in correctly."""
    L.LAMBDA_REGISTRY[layer_name] = (fn, output_type_fn)


def _map_layer(cls: str, cfg: dict):
    """Keras layer config dict → (our Layer | '__flatten__' | None).

    Returning None means "structural no-op at runtime" (InputLayer etc.).
    """
    act = _map_activation(cfg.get("activation"))
    use_bias = cfg.get("use_bias", True)
    name = cfg.get("name")

    if cls == "InputLayer":
        return None
    if cls == "Flatten":
        # explicit row-major flatten (ref: KerasFlatten → preprocessor);
        # NHWC order matches Keras so Dense kernels line up
        return L.FlattenLayer(name=name)
    if cls == "Dense":
        return L.DenseLayer(name=name, n_out=cfg["units"], activation=act,
                            has_bias=use_bias)
    if cls == "Dropout":
        # Keras rate = drop prob; our dropout field = retain prob (ref parity)
        return L.DropoutLayer(name=name, dropout=1.0 - cfg["rate"])
    if cls in ("GaussianNoise", "GaussianDropout", "AlphaDropout"):
        from deeplearning4j_tpu.nn.conf.dropout import (AlphaDropout,
                                                        GaussianDropout,
                                                        GaussianNoise)
        obj = {"GaussianNoise": lambda: GaussianNoise(
                   float(cfg.get("stddev", 0.1))),
               "GaussianDropout": lambda: GaussianDropout(
                   float(cfg.get("rate", 0.5))),
               "AlphaDropout": lambda: AlphaDropout(
                   1.0 - float(cfg.get("rate", 0.05)))}[cls]()
        return L.DropoutLayer(name=name, dropout=obj)
    if cls == "Activation":
        return L.ActivationLayer(name=name, activation=act)
    if cls == "Reshape":
        return L.ReshapeLayer(name=name,
                              target_shape=tuple(cfg["target_shape"]))
    if cls == "Permute":
        return L.PermuteLayer(name=name, dims=tuple(cfg["dims"]))
    if cls == "RepeatVector":
        return L.RepeatVectorLayer(name=name, n=int(cfg["n"]))
    if cls in ("SpatialDropout2D", "SpatialDropout1D"):
        # channel-wise dropout (ref: KerasSpatialDropout → SpatialDropout)
        return L.SpatialDropoutLayer(name=name, dropout=1.0 - cfg["rate"])
    if cls == "Conv2D" or cls == "Convolution2D":
        return L.ConvolutionLayer(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "Conv2DTranspose":
        op = cfg.get("output_padding")
        return L.Deconvolution2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            output_padding=_pair(op) if op is not None else None,
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "SeparableConv2D":
        return L.SeparableConvolution2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "SeparableConv1D":
        # __post_init__ normalizes list/tuple kernel/stride/dilation to int;
        # "same"/"causal" pass through as strings (the layer left-pads for
        # causal), anything else is valid = 0
        pad = cfg.get("padding", "valid")
        return L.SeparableConvolution1D(
            name=name, n_out=cfg["filters"],
            kernel_size=cfg["kernel_size"],
            stride=cfg.get("strides", 1),
            dilation=cfg.get("dilation_rate", 1),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            padding=pad if pad in ("same", "causal") else 0,
            activation=act, has_bias=use_bias)
    if cls == "Conv3DTranspose":
        op3 = cfg.get("output_padding")
        d3 = cfg.get("dilation_rate", 1)
        return L.Deconvolution3D(
            name=name, n_out=cfg["filters"],
            kernel_size=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            dilation=tuple(d3) if not isinstance(d3, int) else (d3,) * 3,
            output_padding=tuple(op3) if op3 is not None else None,
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "ConvLSTM2D":
        if cfg.get("go_backwards") or cfg.get("stateful"):
            raise UnsupportedKerasConfigurationException(
                "ConvLSTM2D: go_backwards/stateful unsupported")
        if any(int(d) != 1 for d in _as_seq(cfg.get("dilation_rate", 1))):
            raise UnsupportedKerasConfigurationException(
                "ConvLSTM2D: dilation_rate != 1 unsupported")
        return L.ConvLSTM2D(
            name=name, n_out=cfg["filters"],
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            padding=cfg.get("padding", "valid"),   # string: same|valid
            activation=_map_activation(cfg.get("activation", "tanh")),
            recurrent_activation=cfg.get("recurrent_activation", "sigmoid"),
            return_sequences=bool(cfg.get("return_sequences", False)),
            has_bias=use_bias)
    if cls in ("MaxPooling2D", "MaxPool2D"):
        return L.SubsamplingLayer(
            name=name, pooling_type="max",
            kernel_size=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            padding=_padding(cfg))
    if cls in ("AveragePooling2D", "AvgPool2D"):
        return L.SubsamplingLayer(
            name=name, pooling_type="avg",
            kernel_size=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            padding=_padding(cfg))
    if cls in ("GlobalMaxPooling2D", "GlobalMaxPooling1D",
               "GlobalMaxPooling3D"):
        return L.GlobalPoolingLayer(name=name, pooling_type="max")
    if cls in ("GlobalAveragePooling2D", "GlobalAveragePooling1D",
               "GlobalAveragePooling3D"):
        return L.GlobalPoolingLayer(name=name, pooling_type="avg")
    if cls == "BatchNormalization":
        return L.BatchNormalization(name=name,
                                    decay=cfg.get("momentum", 0.99),
                                    eps=cfg.get("epsilon", 1e-3))
    if cls == "ZeroPadding2D":
        p = cfg.get("padding", 1)
        if isinstance(p, int):
            pads = (p, p, p, p)
        else:
            (t, b), (l, r) = [_pair(q) for q in p]
            pads = (t, b, l, r)
        return L.ZeroPaddingLayer(name=name, padding=pads)
    if cls == "Cropping2D":
        c = cfg.get("cropping", 0)
        if isinstance(c, int):
            crops = (c, c, c, c)
        else:
            (t, b), (l, r) = [_pair(q) for q in c]
            crops = (t, b, l, r)
        return L.Cropping2D(name=name, cropping=crops)
    if cls == "UpSampling2D":
        return L.Upsampling2D(name=name, size=_pair(cfg.get("size", 2)),
                              interpolation=cfg.get("interpolation",
                                                    "nearest"))
    # ---- tranche-2 layer mappings (ref KerasDepthwiseConvolution2D,
    # KerasPReLU, KerasThresholdedReLU, KerasMasking, KerasLocallyConnected,
    # the 1D/3D structural family — deeplearning4j-modelimport layers.*)
    if cls == "DepthwiseConv2D":
        return L.DepthwiseConvolution2D(
            name=name, kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            depth_multiplier=cfg.get("depth_multiplier", 1),
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "PReLU":
        shared = cfg.get("shared_axes")
        if shared:
            raise UnsupportedKerasConfigurationException(
                "PReLU shared_axes unsupported — full-shape alpha only")
        return L.PReLULayer(name=name)
    if cls == "ThresholdedReLU":
        theta = float(cfg.get("theta", 1.0))
        return L.LambdaLayer(
            name=name or "thresholded_relu",
            fn=lambda x, _t=theta: jnp.where(x > _t, x, 0.0))
    if cls == "Masking":
        # fused with the FOLLOWING layer by the sequential walk (Keras
        # masking semantics = derive mask from mask_value rows and hand it
        # to the next recurrent layer) — MaskZeroLayer carries both steps
        return ("__masking__", float(cfg.get("mask_value", 0.0)), name)
    if cls in ("LocallyConnected2D", "LocallyConnected1D"):
        if _padding(cfg) not in (0, (0, 0), "valid", "VALID"):
            raise UnsupportedKerasConfigurationException(
                f"{cls}: only 'valid' padding")
        if int(cfg.get("implementation", 1)) not in (1, 2):
            # implementation=3 stores a scipy-sparse kernel whose
            # get_weights layout is backend-dependent — still refused
            raise UnsupportedKerasConfigurationException(
                f"{cls}: implementation=3 (sparse) kernels are not "
                f"importable; re-save with implementation=1 or 2")
        if cls == "LocallyConnected2D":
            return L.LocallyConnected2D(
                name=name, n_out=cfg["filters"],
                kernel_size=_pair(cfg["kernel_size"]),
                stride=_pair(cfg.get("strides", 1)),
                activation=act, has_bias=use_bias)
        return L.LocallyConnected1D(
            name=name, n_out=cfg["filters"],
            kernel_size=int(cfg["kernel_size"][0]
                            if isinstance(cfg["kernel_size"],
                                          (list, tuple))
                            else cfg["kernel_size"]),
            stride=int(cfg.get("strides", [1])[0]
                       if isinstance(cfg.get("strides", 1), (list, tuple))
                       else cfg.get("strides", 1)),
            activation=act, has_bias=use_bias)
    if cls == "Cropping1D":
        return L.Cropping1D(name=name, cropping=_pair(
            cfg.get("cropping", 1)))
    if cls == "ZeroPadding1D":
        return L.ZeroPadding1DLayer(name=name, padding=_pair(
            cfg.get("padding", 1)))
    if cls == "UpSampling1D":
        return L.Upsampling1D(name=name, size=int(cfg.get("size", 2)))
    if cls == "Cropping3D":
        c = cfg.get("cropping", 0)
        if isinstance(c, int):
            crops = (c,) * 6
        else:
            crops = tuple(int(v) for pair in c for v in _pair(pair))
        return L.Cropping3D(name=name, cropping=crops)
    if cls == "ZeroPadding3D":
        p = cfg.get("padding", 1)
        if isinstance(p, int):
            pads = (p,) * 6
        else:
            pads = tuple(int(v) for pair in p for v in _pair(pair))
        return L.ZeroPadding3DLayer(name=name, padding=pads)
    if cls == "UpSampling3D":
        s = cfg.get("size", 2)
        return L.Upsampling3D(name=name, size=(s,) * 3
                              if isinstance(s, int) else tuple(s))
    if cls in ("MaxPooling1D", "AveragePooling1D"):
        pool = "max" if cls.startswith("Max") else "avg"
        ps = cfg.get("pool_size", 2)
        ps = int(ps[0] if isinstance(ps, (list, tuple)) else ps)
        st = cfg.get("strides") or ps
        st = int(st[0] if isinstance(st, (list, tuple)) else st)
        return L.Subsampling1DLayer(name=name, pooling_type=pool,
                                    kernel_size=ps, stride=st,
                                    padding=_padding(cfg))
    if cls in ("MaxPooling3D", "AveragePooling3D"):
        pool = "max" if cls.startswith("Max") else "avg"
        ps = cfg.get("pool_size", 2)
        ps = (ps,) * 3 if isinstance(ps, int) else tuple(ps)
        st = cfg.get("strides") or ps
        st = (st,) * 3 if isinstance(st, int) else tuple(st)
        return L.Subsampling3DLayer(name=name, pooling_type=pool,
                                    kernel_size=ps, stride=st,
                                    padding=_padding(cfg))
    if cls == "Embedding":
        return L.EmbeddingSequenceLayer(name=name, n_in=cfg["input_dim"],
                                        n_out=cfg["output_dim"])
    if cls in ("Conv1D", "Convolution1D"):
        pad = cfg.get("padding", "valid")
        pad = {"valid": 0, "same": "same", "causal": "causal"}[pad]
        ks = cfg["kernel_size"]
        return L.Convolution1DLayer(
            name=name, n_out=cfg["filters"],
            kernel_size=ks[0] if isinstance(ks, (list, tuple)) else ks,
            stride=(cfg.get("strides", [1]) or [1])[0]
            if isinstance(cfg.get("strides", 1), (list, tuple))
            else cfg.get("strides", 1),
            dilation=(cfg.get("dilation_rate", [1]) or [1])[0]
            if isinstance(cfg.get("dilation_rate", 1), (list, tuple))
            else cfg.get("dilation_rate", 1),
            padding=pad, activation=act, has_bias=use_bias)
    if cls in ("Conv3D", "Convolution3D"):
        return L.Convolution3D(
            name=name, n_out=cfg["filters"],
            kernel_size=tuple(cfg["kernel_size"]),
            stride=tuple(cfg.get("strides", (1, 1, 1))),
            dilation=tuple(cfg.get("dilation_rate", (1, 1, 1))),
            padding=_padding(cfg), activation=act, has_bias=use_bias)
    if cls == "LayerNormalization":
        # we normalize over the LAST dim. Keras 3 keeps axis=-1; Keras 2
        # (tf_keras) H5 configs carry the RESOLVED positive axis with no
        # per-layer build_config — defer the rank check to the layer's
        # shape-inference (LayerNormalization.set_n_in), where the input
        # rank is known.
        axis = cfg.get("axis", -1)
        axes = list(axis) if isinstance(axis, (list, tuple)) else [axis]
        if len(axes) != 1:
            raise UnsupportedKerasConfigurationException(
                f"LayerNormalization over multiple axes {axes} unsupported")
        return L.LayerNormalization(name=name, eps=cfg.get("epsilon", 1e-3),
                                    axis=int(axes[0]))
    if cls == "LeakyReLU":
        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return L.ActivationLayer(name=name,
                                 activation=f"leakyrelu:{alpha}")
    if cls == "ELU":
        return L.ActivationLayer(name=name, activation="elu")
    if cls == "ReLU":
        max_value = cfg.get("max_value")
        slope = cfg.get("negative_slope", 0.0) or 0.0
        if cfg.get("threshold", 0.0):
            raise UnsupportedKerasConfigurationException(
                "ReLU threshold != 0 is not supported")
        if slope:
            return L.ActivationLayer(name=name,
                                     activation=f"leakyrelu:{slope}")
        if max_value == 6.0:
            return L.ActivationLayer(name=name, activation="relu6")
        if max_value is not None:
            raise UnsupportedKerasConfigurationException(
                f"ReLU max_value={max_value} unsupported (only None/6.0)")
        return L.ActivationLayer(name=name, activation="relu")
    if cls == "Softmax":
        return L.ActivationLayer(name=name, activation="softmax")
    if cls == "TimeDistributed":
        # TimeDistributed(Dense) == our per-timestep dense on rnn input
        inner = cfg["layer"]
        mapped = _map_layer(inner["class_name"], inner["config"])
        if not isinstance(mapped, L.DenseLayer):
            raise UnsupportedKerasConfigurationException(
                "TimeDistributed only supported around Dense")
        mapped.name = name or mapped.name
        return mapped
    if cls == "Bidirectional":
        inner = cfg["layer"]
        mapped = _map_layer(inner["class_name"], inner["config"])
        wrapped = mapped
        if isinstance(mapped, L.LastTimeStep):
            wrapped = mapped._inner_layer
        mode = {"concat": "concat", "sum": "add", "ave": "average",
                "mul": "mul"}.get(cfg.get("merge_mode", "concat"), "concat")
        bi = L.Bidirectional.wrap(wrapped, mode=mode)
        bi.name = name
        if isinstance(mapped, L.LastTimeStep):
            return L.LastTimeStep.wrap(bi)
        return bi
    if cls in ("LSTM", "GRU", "SimpleRNN"):
        ctor = {"LSTM": L.LSTM, "GRU": L.GRU, "SimpleRNN": L.SimpleRnn}[cls]
        kw = {}
        if cls == "GRU":
            if not cfg.get("reset_after", True):
                raise UnsupportedKerasConfigurationException(
                    "GRU reset_after=False is not supported (candidate-gate "
                    "formulation differs); re-save with reset_after=True")
            kw["recurrent_bias"] = True
        lyr = ctor(name=name, n_out=cfg["units"],
                   activation=_map_activation(cfg.get("activation", "tanh")),
                   **kw)
        if not cfg.get("return_sequences", False):
            # wrapped, as the reference's KerasLSTM does with LastTimeStep
            return L.LastTimeStep.wrap(lyr)
        return lyr
    if cls == "Lambda":
        entry = _LAMBDA_LAYERS.get(name)
        if entry is None:
            raise UnsupportedKerasConfigurationException(
                f"Keras Lambda layer {name!r}: register its body with "
                f"keras.register_lambda_layer({name!r}, fn) before import "
                f"(lambda code cannot be read from H5)")
        fn, ot = entry
        return L.LambdaLayer(name=name, fn=fn, output_type_fn=ot)
    if cls in _CUSTOM_LAYERS:
        return _CUSTOM_LAYERS[cls](cfg)
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras layer type {cls!r} (register a builder with "
        f"keras.register_custom_layer({cls!r}, builder))")


def _load_weights_into(layer, w: Dict[str, np.ndarray], params: dict,
                       states: dict, lkey: str):
    """Copy Keras weights into our param/state trees for one layer."""
    import jax.numpy as jnp
    if not w:
        return
    def put(our, theirs):
        if theirs in w:
            params.setdefault(lkey, {})[our] = jnp.asarray(w[theirs])
    while isinstance(layer, (L.LastTimeStep, L.MaskZeroLayer)):
        layer._materialize()
        layer = layer._inner_layer   # params live under the wrapper's key
    if isinstance(layer, L.SeparableConvolution2D):
        put("dW", "depthwise_kernel")
        put("pW", "pointwise_kernel")
        put("b", "bias")
    elif isinstance(layer, L.SeparableConvolution1D):
        put("dW", "depthwise_kernel")
        if "pointwise_kernel" in w:          # keras (1, C*dm, F) → (C*dm, F)
            params.setdefault(lkey, {})["pW"] = jnp.asarray(
                np.asarray(w["pointwise_kernel"])[0])
        put("b", "bias")
    elif isinstance(layer, (L.Deconvolution2D, L.Deconvolution3D)):
        # keras Conv{2,3}DTranspose kernel is (*k, OUT, IN); ours (*k, IN, OUT)
        if "kernel" in w:
            kk = np.asarray(w["kernel"])
            perm = tuple(range(kk.ndim - 2)) + (kk.ndim - 1, kk.ndim - 2)
            params.setdefault(lkey, {})["W"] = jnp.asarray(
                kk.transpose(perm))
        put("b", "bias")
    elif isinstance(layer, L.ConvLSTM2D):
        put("W", "kernel")
        put("RW", "recurrent_kernel")
        put("b", "bias")
    elif isinstance(layer, L.SelfAttentionLayer):
        # keras MultiHeadAttention sublayer paths: query/key/value einsum
        # kernels (C, H, dh) + biases (H, dh); attention_output kernel
        # (H, dh, C_out) + bias (C_out,)
        hs = layer.n_heads * layer.head_size

        def find(path_suffix):
            for k, v in w.items():
                if k.endswith(path_suffix):
                    return np.asarray(v)
            return None

        for ours, theirs in (("Wq", "query/kernel"), ("Wk", "key/kernel"),
                             ("Wv", "value/kernel")):
            arr = find(theirs)
            if arr is not None:
                # einsum kernel (C, H, dh) — C is the SOURCE's feature dim
                # (differs per projection for cross attention)
                params.setdefault(lkey, {})[ours] = jnp.asarray(
                    arr.reshape(arr.shape[0], hs))
        arr = find("attention_output/kernel")
        if arr is not None:
            params.setdefault(lkey, {})["Wo"] = jnp.asarray(
                arr.reshape(hs, layer.n_out))
        if layer.qkv_bias:
            for ours, theirs in (("bq", "query/bias"), ("bk", "key/bias"),
                                 ("bv", "value/bias"),
                                 ("bo", "attention_output/bias")):
                arr = find(theirs)
                if arr is not None:
                    params.setdefault(lkey, {})[ours] = jnp.asarray(
                        arr.reshape(-1))
    elif isinstance(layer, L.DepthwiseConvolution2D):
        # Keras 2 names it depthwise_kernel; Keras 3 plain kernel
        put("dW", "depthwise_kernel")
        put("dW", "kernel")
        put("b", "bias")
    elif isinstance(layer, L.PReLULayer):
        put("alpha", "alpha")
    elif isinstance(layer, (L.LocallyConnected2D, L.LocallyConnected1D)):
        # Keras LC implementation=1 kernel: (positions, kh*kw*in, filters),
        # feature axis in (*k, C) order — exactly the layer's internal
        # patch layout, so a pure reshape onto the position grid suffices.
        # implementation=2 stores the FULL masked dense kernel
        # (in_spatial…, cin, out_spatial…, filters); the local weights are
        # its banded diagonal — extracted below (r5 closes that refusal).
        for pname in ("kernel", "bias"):
            arr = w.get(pname)
            if arr is None:
                continue
            arr = np.asarray(arr)
            our = "W" if pname == "kernel" else "b"
            tgt = layer.param_shapes()[our]
            if pname == "kernel" and isinstance(
                    layer, L.LocallyConnected2D) and arr.ndim == 6:
                oh, ow, fd, f = tgt
                kh, kw = layer.kernel_size
                sh, sw = layer.stride
                cin = fd // (kh * kw)
                out = np.empty((oh, ow, kh, kw, cin, f), arr.dtype)
                for dh in range(kh):
                    for dw in range(kw):
                        sub = arr[dh:dh + oh * sh:sh,
                                  dw:dw + ow * sw:sw]
                        out[:, :, dh, dw] = np.einsum("ijcijf->ijcf", sub)
                arr = out
            elif pname == "kernel" and isinstance(
                    layer, L.LocallyConnected1D) and arr.ndim == 4:
                ol, fd, f = tgt
                k, s = layer.kernel_size, layer.stride
                cin = fd // k
                out = np.empty((ol, k, cin, f), arr.dtype)
                for d in range(k):
                    out[:, d] = np.einsum("icif->icf",
                                          arr[d:d + ol * s:s])
                arr = out
            params.setdefault(lkey, {})[our] = jnp.asarray(
                np.reshape(arr, tgt))
    elif isinstance(layer, L.BatchNormalization):
        put("gamma", "gamma")
        put("beta", "beta")
        st = states.setdefault(lkey, {})
        if "moving_mean" in w:
            st["mean"] = jnp.asarray(w["moving_mean"])
        if "moving_variance" in w:
            st["var"] = jnp.asarray(w["moving_variance"])
    elif isinstance(layer, (L.LSTM, L.SimpleRnn)):
        put("W", "kernel")
        put("RW", "recurrent_kernel")
        put("b", "bias")
    elif isinstance(layer, L.GRU):
        # Keras gate order (z, r, h) -> ours (r, u=z, n); Keras default
        # reset_after=True carries a (2, 3u) bias: [input_bias, recurrent_bias]
        k, rk = w.get("kernel"), w.get("recurrent_kernel")
        if k is not None and rk is not None:
            u = k.shape[1] // 3

            def reorder(m):
                return np.concatenate([m[:, u:2 * u], m[:, :u], m[:, 2 * u:]],
                                      axis=1)
            params.setdefault(lkey, {})["W"] = jnp.asarray(reorder(k))
            params[lkey]["RW"] = jnp.asarray(reorder(rk))
            b = w.get("bias")
            if b is not None:
                def reorder_b(v):
                    return np.concatenate([v[u:2 * u], v[:u], v[2 * u:]])
                if b.ndim == 2:      # reset_after=True
                    params[lkey]["b"] = jnp.asarray(reorder_b(b[0]))
                    params[lkey]["bR"] = jnp.asarray(reorder_b(b[1]))
                else:
                    params[lkey]["b"] = jnp.asarray(reorder_b(b))
    elif isinstance(layer, (L.EmbeddingLayer, L.EmbeddingSequenceLayer)):
        put("W", "embeddings")
    elif isinstance(layer, L.LayerNormalization):
        put("gamma", "gamma")
        put("beta", "beta")
    elif isinstance(layer, L.Bidirectional):
        # Keras nests weights per direction; our params are flat
        # "f_<name>"/"b_<name>" keys (Bidirectional.param_shapes)
        layer._materialize()
        for ours_prefix, theirs_prefix in (("f_", "forward_"),
                                           ("b_", "backward_")):
            sub = {k.split("/")[-1]: v for k, v in w.items()
                   if k.startswith(theirs_prefix)
                   or f"/{theirs_prefix}" in k}
            if sub:
                inner_params = {}
                _load_weights_into(layer._fwd_layer, sub, inner_params,
                                   {}, "x")
                for pname, val in inner_params.get("x", {}).items():
                    params.setdefault(lkey, {})[ours_prefix + pname] = val
    else:
        put("W", "kernel")
        put("b", "bias")


def _input_type_from_config(cfg_layers: List[dict]) -> Optional[InputType]:
    """Infer InputType from the first layer's batch_shape/batch_input_shape."""
    for ld in cfg_layers:
        cfg = ld.get("config", {})
        shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
        if shape:
            dims = [d for d in shape[1:]]
            if len(dims) == 4:
                return InputType.convolutional3d(dims[0], dims[1], dims[2],
                                                 dims[3])
            if len(dims) == 3:
                return InputType.convolutional(dims[0], dims[1], dims[2])
            if len(dims) == 2:
                return InputType.recurrent(dims[1], dims[0])
            if len(dims) == 1:
                return InputType.feed_forward(dims[0])
    return None


class KerasModelImport:
    """ref: KerasModelImport#importKerasSequentialModelAndWeights /
    #importKerasModelAndWeights."""

    @staticmethod
    def import_keras_sequential_model_and_weights(h5_path: str,
                                                  enforce_training_config: bool = False):
        import h5py
        with h5py.File(h5_path, "r") as f:
            model_config = json.loads(f.attrs["model_config"])
            weights = _H5Weights(f)
            if model_config["class_name"] != "Sequential":
                raise InvalidKerasConfigurationException(
                    "not a Sequential model; use import_keras_model_and_weights")
            layer_dicts = model_config["config"]["layers"]
            input_type = _input_type_from_config(layer_dicts)

            b = (NeuralNetConfiguration.builder()
                 .updater(Adam(1e-3)).weight_init("xavier").list())
            mapped: List[tuple] = []   # (our layer, keras name)
            pending_mask = None        # (mask_value, name) from Masking
            for ld in layer_dicts:
                out = _map_layer(ld["class_name"], ld["config"])
                if out is None:
                    continue
                if isinstance(out, tuple) and out[0] == "__masking__":
                    pending_mask = (out[1], out[2])
                    continue
                for lyr in (out if isinstance(out, list) else [out]):
                    if pending_mask is not None:
                        mv, mname = pending_mask
                        pending_mask = None
                        lyr = L.MaskZeroLayer.wrap(lyr, mask_value=mv)
                        lyr.name = mname
                    mapped.append((lyr, ld["config"].get("name")))
            if pending_mask is not None:
                raise UnsupportedKerasConfigurationException(
                    "Masking as the FINAL layer has nothing to mask")
            # Keras graphs carry no loss head; make the net trainable by
            # promoting the final Dense to an OutputLayer with a loss
            # inferred from its activation (ref: KerasLoss mapping)
            if mapped and type(mapped[-1][0]) is L.DenseLayer:
                d = mapped[-1][0]
                loss = {"softmax": "mcxent", "sigmoid": "xent"}.get(
                    d.activation, "mse")
                mapped[-1] = (L.OutputLayer(
                    name=d.name, n_out=d.n_out, activation=d.activation,
                    has_bias=d.has_bias, loss_function=loss), mapped[-1][1])
            elif mapped and not hasattr(mapped[-1][0], "loss"):
                mapped.append((L.LossLayer(loss_function="mse"), None))
            for lyr, _ in mapped:
                b.layer(lyr)
            if input_type is not None:
                b.set_input_type(input_type)
            conf = b.build()

            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            net = MultiLayerNetwork(conf).init()
            for i, (lyr, kname) in enumerate(mapped):
                _load_weights_into(
                    lyr, weights.get(kname, allow_ambiguous_leaves=isinstance(
                        lyr, (L.Bidirectional, L.SelfAttentionLayer))),
                    net._params,
                                   net._states, str(i))
            net._opt_state = net._opt.init(net._params)
            return net

    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights

    @staticmethod
    def import_keras_model_and_weights(h5_path: str):
        """Functional-API model → ComputationGraph."""
        import h5py
        with h5py.File(h5_path, "r") as f:
            model_config = json.loads(f.attrs["model_config"])
            weights = _H5Weights(f)
            if model_config["class_name"] == "Sequential":
                return KerasModelImport.import_keras_sequential_model_and_weights(h5_path)
            cfg = model_config["config"]
            g = (NeuralNetConfiguration.builder()
                 .updater(Adam(1e-3)).weight_init("xavier").graph_builder())

            # keras node name → our vertex name (keras layer names are unique)
            input_names = []
            input_types = []
            name_of = {}
            mapped = {}
            for ld in cfg["layers"]:
                cls, lcfg = ld["class_name"], ld["config"]
                name = ld.get("name") or lcfg.get("name")
                inbound = _inbound_layer_names(ld.get("inbound_nodes"))
                if cls == "InputLayer":
                    input_names.append(name)
                    name_of[name] = name
                    shape = lcfg.get("batch_shape") or lcfg.get("batch_input_shape")
                    dims = list(shape[1:]) if shape else []
                    if len(dims) == 4:
                        input_types.append(InputType.convolutional3d(*dims))
                    elif len(dims) == 3:
                        input_types.append(InputType.convolutional(*dims))
                    elif len(dims) == 2:
                        input_types.append(InputType.recurrent(dims[1], dims[0]))
                    else:
                        input_types.append(InputType.feed_forward(dims[0] if dims else 0))
                    continue
                srcs = [name_of[s] for s in inbound if s in name_of]
                if cls == "Add":
                    g.add_vertex(name, ElementWiseVertex(op="add"), *srcs)
                elif cls in ("Concatenate", "Merge"):
                    g.add_vertex(name, MergeVertex(), *srcs)
                elif cls in ("Subtract",):
                    g.add_vertex(name, ElementWiseVertex(op="sub"), *srcs)
                elif cls in ("Multiply",):
                    g.add_vertex(name, ElementWiseVertex(op="prod"), *srcs)
                elif cls in ("Average",):
                    g.add_vertex(name, ElementWiseVertex(op="avg"), *srcs)
                elif cls in ("Maximum",):
                    g.add_vertex(name, ElementWiseVertex(op="max"), *srcs)
                elif cls in ("Minimum",):
                    g.add_vertex(name, ElementWiseVertex(op="min"), *srcs)
                elif cls == "Dot":
                    from deeplearning4j_tpu.nn.graph_conf import DotVertex
                    ax = lcfg.get("axes", -1)
                    g.add_vertex(name, DotVertex(
                        axes=tuple(ax) if isinstance(ax, list) else ax,
                        normalize=bool(lcfg.get("normalize", False))), *srcs)
                elif cls in ("Attention", "AdditiveAttention"):
                    from deeplearning4j_tpu.nn.graph_conf import (
                        AdditiveAttentionVertex, DotProductAttentionVertex)
                    if lcfg.get("use_scale") and cls == "Attention":
                        raise UnsupportedKerasConfigurationException(
                            "Attention(use_scale=True) carries a learned "
                            "scale — re-save with use_scale=False")
                    if cls == "AdditiveAttention" \
                            and lcfg.get("use_scale", True):
                        raise UnsupportedKerasConfigurationException(
                            "AdditiveAttention(use_scale=True) carries a "
                            "learned scale vector — re-save with "
                            "use_scale=False")
                    if lcfg.get("score_mode", "dot") not in ("dot",):
                        raise UnsupportedKerasConfigurationException(
                            f"Attention score_mode "
                            f"{lcfg.get('score_mode')!r} unsupported")
                    vcls = (DotProductAttentionVertex if cls == "Attention"
                            else AdditiveAttentionVertex)
                    g.add_vertex(name, vcls(
                        causal=bool(lcfg.get("causal", False))), *srcs)
                elif cls == "MultiHeadAttention":
                    if lcfg.get("value_dim") not in (None,
                                                     lcfg.get("key_dim")):
                        raise UnsupportedKerasConfigurationException(
                            "MultiHeadAttention: value_dim != key_dim "
                            "unsupported")
                    if lcfg.get("output_shape"):
                        raise UnsupportedKerasConfigurationException(
                            "MultiHeadAttention: custom output_shape "
                            "unsupported")
                    if len(set(srcs)) == 1:
                        lyr = L.SelfAttentionLayer(
                            name=name, n_heads=int(lcfg["num_heads"]),
                            head_size=int(lcfg["key_dim"]),
                            qkv_bias=bool(lcfg.get("use_bias", True)))
                        g.add_layer(name, lyr, srcs[0])
                    else:
                        # cross form: Keras call order (query, value[, key])
                        lyr = L.CrossAttentionLayer(
                            name=name, n_heads=int(lcfg["num_heads"]),
                            head_size=int(lcfg["key_dim"]),
                            qkv_bias=bool(lcfg.get("use_bias", True)))
                        g.add_layer(name, lyr, *srcs)
                    mapped[name] = lyr
                else:
                    out = _map_layer(cls, lcfg)
                    if out is None:
                        name_of[name] = srcs[0]
                        continue
                    if isinstance(out, tuple) and out[0] == "__masking__":
                        raise UnsupportedKerasConfigurationException(
                            "Masking in functional graphs unsupported — "
                            "wrap the consumer in MaskZeroLayer instead")
                    lyrs = out if isinstance(out, list) else [out]
                    prev = srcs
                    for j, lyr in enumerate(lyrs):
                        vname = name if j == 0 else f"{name}__{j}"
                        g.add_layer(vname, lyr, *prev)
                        prev = [vname]
                        if j == 0:
                            mapped[name] = lyr
                    name_of[name] = prev[0]
                    continue
                name_of[name] = name
            g.add_inputs(*input_names)
            g.set_input_types(*input_types)
            outputs = [name_of[o] for o in _output_names(cfg)]
            g.set_outputs(*outputs)
            conf = g.build()
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            net = ComputationGraph(conf).init()
            for kname, lyr in mapped.items():
                _load_weights_into(
                    lyr, weights.get(kname, allow_ambiguous_leaves=isinstance(
                        lyr, (L.Bidirectional, L.SelfAttentionLayer))),
                    net._params,
                                   net._states, kname)
            net._opt_state = net._opt.init(net._params)
            return net

    importKerasModelAndWeights = import_keras_model_and_weights


def _inbound_layer_names(inbound_nodes) -> List[str]:
    """Source layer names from inbound_nodes, across Keras 2
    (``[[["name", 0, 0, {}], ...]]``) and Keras 3
    (``[{"args": [{"config": {"keras_history": ["name", 0, 0]}}]}]``)."""
    names: List[str] = []

    def walk(obj):
        if isinstance(obj, dict):
            hist = obj.get("keras_history")
            if isinstance(hist, list) and hist and isinstance(hist[0], str):
                names.append(hist[0])
            for k, v in obj.items():
                if k != "keras_history":
                    walk(v)
        elif isinstance(obj, list):
            # keras2 node: ["layer_name", node_idx, tensor_idx, {kwargs}]
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int) and isinstance(obj[2], int)):
                names.append(obj[0])
                # call-KWARG tensors ride the 4th slot (Keras 2 saves
                # MultiHeadAttention's value/key as {"value": [name,0,0]})
                # — in insertion order, preserving (query, value[, key])
                if len(obj) >= 4 and isinstance(obj[3], dict):
                    for v in obj[3].values():
                        walk(v)
            else:
                for v in obj:
                    walk(v)

    walk(inbound_nodes or [])
    return names


def _output_names(cfg) -> List[str]:
    outs = cfg.get("output_layers", [])
    # flat single output ["name", 0, 0] vs list of such triples
    if (len(outs) >= 1 and isinstance(outs[0], str)):
        return [outs[0]]
    return [o[0] for o in outs if isinstance(o, (list, tuple)) and o]
