"""Model import (ref: deeplearning4j-modelimport + samediff-import,
SURVEY D12/J8)."""
from deeplearning4j_tpu.modelimport.keras import KerasModelImport

__all__ = ["KerasModelImport"]
