"""Shared importer helpers (ref: ``samediff-import-api`` — the layer both
the TF and ONNX importers build on)."""
from __future__ import annotations

from typing import Optional

import numpy as np


def fold_constant(sd, var) -> Optional[np.ndarray]:
    """Evaluate ``var`` if it depends only on constants; None otherwise.

    Eager ``_emit`` (no jit) — folding must not pay one XLA compile per
    structural argument on large imported graphs.
    """
    try:
        fn = sd._emit([var.name])
        return np.asarray(fn(sd._values, {}, 0)[0])
    except Exception:
        return None
