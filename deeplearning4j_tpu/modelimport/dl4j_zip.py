"""Reference-artifact compatibility: read/write actual DL4J model zips.

ref: ``org.deeplearning4j.util.ModelSerializer`` (SURVEY D9, §5.6). A DL4J
zip is NOT our native ``coefficients.npz`` container — it holds

- ``configuration.json``  — Jackson-serialized ``MultiLayerConfiguration``:
  polymorphic ``@class`` layer entries, ``activationFn``/``lossFn`` wrapper
  objects, camelCase fields (``nin``/``nout``/``kernelSize``…)
- ``coefficients.bin``    — the net's single FLAT param vector written by
  ``Nd4j.write(params, dos)``: two ND4J ``DataBuffer`` records (shape-info
  longs, then data), each ``UTF(allocationMode) · long(length) ·
  UTF(dataType) · big-endian elements`` (ref: ``BaseDataBuffer#write``)
- optionally ``updaterState.bin`` (same binary format) and
  ``normalizer.bin`` (NormalizerSerializer — not supported here; a loud
  error, not a silent skip)

Per-layer views into the flat vector follow the reference param-initializer
conventions this module encodes: Dense/Output W reshaped column-major
(``DefaultParamInitializer`` order 'f'); Conv W is (nOut, nIn, kH, kW)
row-major (``ConvolutionParamInitializer`` order 'c'), transposed to our
(kH, kW, nIn, nOut) layout; LSTM gates are stored [i, f, o, g]
(``LSTMParamInitializer``) and permuted to our [i, f, g, o] fused layout;
BatchNormalization packs [gamma, beta, mean, var]
(``BatchNormalizationParamInitializer``), with mean/var landing in the
running-stats state, not trainable params.

Caveat (also in MIGRATION.md): the binary header layout is implemented from
the upstream format description; real Java-written artifacts could not be
obtained in this zero-egress build, so conformance evidence is hand-built
fixture zips that follow the documented byte layout exactly. The header
parse is isolated in ``_read_databuffer`` for easy adjustment against a real
artifact.
"""
from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Optional

import numpy as np

# ----------------------------------------------------------- binary format

_DTYPE_NAMES = {"FLOAT": (np.dtype(">f4"), np.float32),
                "DOUBLE": (np.dtype(">f8"), np.float64),
                "HALF": (np.dtype(">f2"), np.float16),
                "LONG": (np.dtype(">i8"), np.int64),
                "INT": (np.dtype(">i4"), np.int32)}


def _read_utf(f) -> str:
    """java.io.DataInputStream#readUTF: u2 length + modified-UTF8 bytes."""
    (n,) = struct.unpack(">H", f.read(2))
    return f.read(n).decode("utf-8")


def _write_utf(f, s: str):
    data = s.encode("utf-8")
    f.write(struct.pack(">H", len(data)))
    f.write(data)


def _read_databuffer(f) -> np.ndarray:
    """One ND4J DataBuffer record (ref: BaseDataBuffer#write)."""
    _alloc_mode = _read_utf(f)               # e.g. MIXED_DATA_TYPES; unused
    (length,) = struct.unpack(">q", f.read(8))
    dtype_name = _read_utf(f)
    if dtype_name not in _DTYPE_NAMES:
        raise ValueError(f"unsupported ND4J DataBuffer dtype {dtype_name!r}")
    be_dtype, np_dtype = _DTYPE_NAMES[dtype_name]
    raw = f.read(length * be_dtype.itemsize)
    if len(raw) != length * be_dtype.itemsize:
        raise ValueError("truncated ND4J DataBuffer record")
    return np.frombuffer(raw, be_dtype).astype(np_dtype)


def _write_databuffer(f, arr: np.ndarray, dtype_name: str):
    be_dtype, _ = _DTYPE_NAMES[dtype_name]
    _write_utf(f, "MIXED_DATA_TYPES")
    f.write(struct.pack(">q", arr.size))
    _write_utf(f, dtype_name)
    f.write(np.ascontiguousarray(arr, be_dtype).tobytes())


def read_nd4j_array(data: bytes) -> np.ndarray:
    """``Nd4j.write``-format bytes → numpy array (shape-info + data)."""
    f = io.BytesIO(data)
    shape_info = _read_databuffer(f).astype(np.int64)
    values = _read_databuffer(f)
    rank = int(shape_info[0])
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[-1])) if shape_info[-1] in (99, 102) else "c"
    return values.reshape(shape, order="F" if order == "f" else "C")


def write_nd4j_array(arr: np.ndarray) -> bytes:
    """numpy array → ``Nd4j.write``-format bytes ('c' order, FLOAT data)."""
    arr = np.asarray(arr)
    rank = arr.ndim
    shape = list(arr.shape)
    strides = [int(np.prod(shape[i + 1:], dtype=np.int64))
               for i in range(rank)]
    # shape-info layout: rank, shape, stride, extras, elementWiseStride, order
    shape_info = np.asarray([rank] + shape + strides + [0, 1, ord("c")],
                            np.int64)
    f = io.BytesIO()
    _write_databuffer(f, shape_info, "LONG")
    _write_databuffer(f, arr.ravel(order="C").astype(np.float32), "FLOAT")
    return f.getvalue()


# ------------------------------------------------------------ JSON mapping

_ACT_FROM_CLASS = {
    "ActivationIdentity": "identity", "ActivationReLU": "relu",
    "ActivationTanH": "tanh", "ActivationSigmoid": "sigmoid",
    "ActivationSoftmax": "softmax", "ActivationLReLU": "leakyrelu",
    "ActivationELU": "elu", "ActivationGELU": "gelu",
    "ActivationSoftPlus": "softplus", "ActivationSwish": "swish",
    "ActivationHardSigmoid": "hardsigmoid", "ActivationHardTanH": "hardtanh",
    "ActivationCube": "cube", "ActivationRationalTanh": "rationaltanh",
}
_ACT_TO_CLASS = {v: k for k, v in _ACT_FROM_CLASS.items()}

_LOSS_FROM_CLASS = {
    "LossNegativeLogLikelihood": "negativeloglikelihood",
    "LossMCXENT": "mcxent", "LossMSE": "mse", "LossBinaryXENT": "binaryxent",
    "LossL1": "l1", "LossL2": "l2", "LossMAE": "mae",
}
_LOSS_TO_CLASS = {v: k for k, v in _LOSS_FROM_CLASS.items()}

_PKG = "org.deeplearning4j.nn.conf.layers."

# DL4J LSTM gate order [i, f, o, g] → our fused [i, f, g, o]
_LSTM_GATES_DL4J_TO_OURS = (0, 1, 3, 2)


def _act_name(layer_json: dict) -> str:
    fn = layer_json.get("activationFn")
    if isinstance(fn, dict):
        cls = fn.get("@class", "").rsplit(".", 1)[-1]
        if cls not in _ACT_FROM_CLASS:
            # loud, like unsupported layers — identity would be silent wrong math
            raise ValueError(f"unsupported DL4J activation {cls!r}")
        return _ACT_FROM_CLASS[cls]
    legacy = layer_json.get("activation")
    return legacy.lower() if isinstance(legacy, str) else "identity"


def _loss_name(layer_json: dict) -> str:
    fn = layer_json.get("lossFn")
    if isinstance(fn, dict):
        cls = fn.get("@class", "").rsplit(".", 1)[-1]
        if cls not in _LOSS_FROM_CLASS:
            raise ValueError(f"unsupported DL4J loss {cls!r}")
        return _LOSS_FROM_CLASS[cls]
    legacy = layer_json.get("lossFunction")
    return legacy.lower() if isinstance(legacy, str) else "mse"


def _layer_from_json(lj: dict):
    """One Jackson layer entry → our config-DSL layer instance."""
    from deeplearning4j_tpu.nn.conf import layers as L

    cls = lj.get("@class", "").rsplit(".", 1)[-1]
    act = _act_name(lj)
    nin = lj.get("nin")
    nout = lj.get("nout")
    common = dict(n_in=int(nin) if nin else None,
                  n_out=int(nout) if nout else None,
                  activation=act, name=lj.get("layerName"))

    if cls == "DenseLayer":
        return L.DenseLayer(**common)
    if cls == "OutputLayer":
        return L.OutputLayer(loss_function=_loss_name(lj), **common)
    if cls == "RnnOutputLayer":
        return L.RnnOutputLayer(loss_function=_loss_name(lj), **common)
    if cls == "CenterLossOutputLayer":
        return L.CenterLossOutputLayer(
            loss_function=_loss_name(lj), alpha=float(lj.get("alpha", 0.05)),
            lambda_=float(lj.get("lambda", 2e-4)), **common)
    if cls == "LossLayer":
        return L.LossLayer(loss_function=_loss_name(lj), activation=act,
                           name=lj.get("layerName"))
    if cls == "CnnLossLayer":
        return L.CnnLossLayer(loss_function=_loss_name(lj), activation=act,
                              name=lj.get("layerName"))
    if cls == "Yolo2OutputLayer":
        from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer
        boxes = lj.get("boundingBoxes")
        return Yolo2OutputLayer(
            boxes=tuple(tuple(float(v) for v in b) for b in boxes)
            if boxes else None,
            lambda_coord=float(lj.get("lambdaCoord", 5.0)),
            lambda_no_obj=float(lj.get("lambdaNoObj", 0.5)),
            name=lj.get("layerName"))
    def conv_kwargs():
        kw = dict(kernel_size=tuple(lj.get("kernelSize", (3, 3))),
                  stride=tuple(lj.get("stride", (1, 1))),
                  dilation=tuple(lj.get("dilation", (1, 1))))
        # ConvolutionMode.Same ⇒ DL4J ignores the padding field
        if str(lj.get("convolutionMode", "")).lower() == "same":
            kw["padding"] = "same"
        else:
            kw["padding"] = tuple(lj.get("padding", (0, 0)))
        if "hasBias" in lj:
            kw["has_bias"] = bool(lj["hasBias"])
        return kw

    if cls == "ConvolutionLayer":
        return L.ConvolutionLayer(**conv_kwargs(), **common)
    if cls == "SeparableConvolution2D":
        return L.SeparableConvolution2D(
            depth_multiplier=int(lj.get("depthMultiplier", 1)),
            **conv_kwargs(), **common)
    if cls == "DepthwiseConvolution2D":
        from deeplearning4j_tpu.nn.conf.layers2 import DepthwiseConvolution2D
        return DepthwiseConvolution2D(
            depth_multiplier=int(lj.get("depthMultiplier", 1)),
            **conv_kwargs(), **common)
    if cls == "Deconvolution2D":
        return L.Deconvolution2D(**conv_kwargs(), **common)
    if cls == "Upsampling2D":
        sz = lj.get("size", (2, 2))
        return L.Upsampling2D(size=tuple(sz) if not isinstance(sz, int)
                              else (sz, sz), name=lj.get("layerName"))
    if cls == "ZeroPaddingLayer":
        return L.ZeroPaddingLayer(padding=tuple(lj.get("padding",
                                                       (1, 1, 1, 1))),
                                  name=lj.get("layerName"))
    if cls == "Cropping2D":
        return L.Cropping2D(cropping=tuple(lj.get("cropping",
                                                  (0, 0, 0, 0))),
                            name=lj.get("layerName"))
    if cls == "GlobalPoolingLayer":
        pool = lj.get("poolingType", "MAX")
        pool = pool if isinstance(pool, str) \
            else pool.get("poolingType", "MAX")
        return L.GlobalPoolingLayer(pooling_type=pool.lower(),
                                    name=lj.get("layerName"))
    if cls == "LocalResponseNormalization":
        return L.LocalResponseNormalization(
            k=float(lj.get("k", 2.0)), n=int(lj.get("n", 5)),
            alpha=float(lj.get("alpha", 1e-4)),
            beta=float(lj.get("beta", 0.75)), name=lj.get("layerName"))
    if cls == "PReLULayer":
        from deeplearning4j_tpu.nn.conf.layers2 import PReLULayer
        ishape = lj.get("inputShape")
        return PReLULayer(
            n_in=common["n_in"],
            alpha_shape=tuple(ishape) if ishape else None,
            name=lj.get("layerName"))
    if cls == "LocallyConnected2D":
        from deeplearning4j_tpu.nn.conf.layers2 import LocallyConnected2D
        isz = lj.get("inputSize")
        return LocallyConnected2D(
            kernel_size=tuple(lj.get("kernelSize", (2, 2))),
            stride=tuple(lj.get("stride", (1, 1))),
            n_in=common["n_in"], n_out=common["n_out"],
            input_size=tuple(isz) if isz else None,
            has_bias=bool(lj.get("hasBias", True)),
            name=lj.get("layerName"))
    if cls == "SubsamplingLayer":
        pool = lj.get("poolingType", "MAX")
        pool = pool if isinstance(pool, str) else pool.get("poolingType", "MAX")
        same = str(lj.get("convolutionMode", "")).lower() == "same"
        return L.SubsamplingLayer(
            kernel_size=tuple(lj.get("kernelSize", (2, 2))),
            stride=tuple(lj.get("stride", (2, 2))),
            padding="same" if same else tuple(lj.get("padding", (0, 0))),
            pooling_type=pool.lower(), name=lj.get("layerName"))
    if cls == "BatchNormalization":
        return L.BatchNormalization(
            n_out=common["n_out"],
            eps=lj.get("eps", 1e-5), decay=lj.get("decay", 0.9),
            name=lj.get("layerName"))
    if cls in ("LSTM", "GravesLSTM"):
        klass = L.GravesLSTM if cls == "GravesLSTM" else L.LSTM
        return klass(forget_gate_bias_init=lj.get("forgetGateBiasInit", 1.0),
                     **common)
    if cls == "EmbeddingLayer":
        return L.EmbeddingLayer(**common)
    if cls == "ActivationLayer":
        return L.ActivationLayer(activation=act, name=lj.get("layerName"))
    if cls == "DropoutLayer":
        p = lj.get("iDropout", {})
        if not isinstance(p, dict):
            return L.DropoutLayer(dropout=0.5, name=lj.get("layerName"))
        scheme = str(p.get("@class", "")).rsplit(".", 1)[-1]
        if scheme in ("GaussianDropout", "GaussianNoise", "AlphaDropout"):
            from deeplearning4j_tpu.nn.conf.dropout import (AlphaDropout,
                                                            GaussianDropout,
                                                            GaussianNoise)
            obj = {"GaussianDropout": lambda: GaussianDropout(
                       float(p.get("rate", 0.5))),
                   "GaussianNoise": lambda: GaussianNoise(
                       float(p.get("stddev", 0.1))),
                   "AlphaDropout": lambda: AlphaDropout(
                       float(p.get("p", 0.95)))}[scheme]()
            return L.DropoutLayer(dropout=obj, name=lj.get("layerName"))
        # DL4J Dropout(p) and our Layer.dropout are BOTH retain probability
        return L.DropoutLayer(dropout=float(p.get("p", 0.5)),
                              name=lj.get("layerName"))
    raise ValueError(
        f"DL4J layer class {cls!r} is outside the supported compat subset "
        "(Dense/Conv/SeparableConv/DepthwiseConv/Deconv/Subsampling/"
        "Upsampling/ZeroPadding/Cropping/GlobalPooling/LRN/BatchNorm/LSTM/"
        "Output/RnnOutput/Embedding/Activation/Dropout/PReLU/"
        "LocallyConnected2D)")


def _input_type_from_json(itj: Optional[dict]):
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    if not itj:
        return None
    cls = itj.get("@class", "").rsplit("$", 1)[-1].rsplit(".", 1)[-1]
    if "ConvolutionalFlat" in cls:
        return InputType.convolutional_flat(itj["height"], itj["width"],
                                            itj["depth"])
    if "Convolutional" in cls:
        return InputType.convolutional(itj["height"], itj["width"],
                                       itj["channels"]
                                       if "channels" in itj else itj["depth"])
    if "Recurrent" in cls:
        return InputType.recurrent(itj["size"],
                                   itj.get("timeSeriesLength"))
    if "FeedForward" in cls:
        return InputType.feed_forward(itj["size"])
    return None


def _updater_from_json(confs) -> object:
    """iUpdater entry of the first layer conf → our updater instance
    (ref: org.nd4j.linalg.learning.config.*)."""
    from deeplearning4j_tpu.optim import updaters as U

    names = ("Adam", "AdamW", "Nesterovs", "Sgd", "RmsProp", "AdaGrad",
             "AdaDelta", "Nadam", "AMSGrad", "NoOp")
    table = {n: getattr(U, n) for n in names if hasattr(U, n)}
    for entry in confs:
        iu = entry.get("layer", {}).get("iUpdater") or entry.get("iUpdater")
        if isinstance(iu, dict):
            cls = iu.get("@class", "").rsplit(".", 1)[-1]
            ctor = table.get(cls)
            if ctor is None:
                raise ValueError(f"unsupported DL4J updater {cls!r}")
            lr = iu.get("learningRate", 1e-3)
            return ctor(lr)
    from deeplearning4j_tpu.optim.updaters import Adam
    return Adam(1e-3)


def config_from_dl4j_json(text: str):
    """Jackson MultiLayerConfiguration JSON → our MultiLayerConfiguration."""
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration

    j = json.loads(text)
    confs = j.get("confs", [])
    builder = NeuralNetConfiguration.builder()
    if confs:
        builder.seed(int(confs[0].get("seed", 0) or 0))
    builder = builder.updater(_updater_from_json(confs)).list()
    for entry in confs:
        builder.layer(_layer_from_json(entry.get("layer", {})))
    it = _input_type_from_json(j.get("inputType"))
    if it is not None:
        builder.set_input_type(it)
    bpt = j.get("backpropType", "Standard")
    if bpt == "TruncatedBPTT":
        from deeplearning4j_tpu.nn.conf.configuration import BackpropType
        builder.backprop_type(BackpropType.TruncatedBPTT)
        builder.t_bptt_length(int(j.get("tbpttFwdLength", 20)))
    return builder.build()


# --------------------------------------------------- flat-vector packing

def _layer_param_plan(layer, params):
    """[(pname, dl4j_numel, unpack_fn, pack_fn)] for one layer, in the
    reference's flat-vector order. unpack(flat_chunk) -> our array;
    pack(our_array) -> flat chunk."""
    import math

    kind = type(layer).__name__
    plan = []
    if not params:
        return plan

    if kind in ("DenseLayer", "OutputLayer", "RnnOutputLayer",
                "EmbeddingLayer", "EmbeddingSequenceLayer",
                "CenterLossOutputLayer"):
        nin, nout = params["W"].shape
        plan.append(("W", nin * nout,
                     lambda c, s=(nin, nout): c.reshape(s, order="F"),
                     lambda a: np.asarray(a).ravel(order="F")))
        if "b" in params:
            plan.append(("b", nout, lambda c: c, np.ravel))
        if kind == "CenterLossOutputLayer":
            # CenterLossParamInitializer: class centers (nClasses, nIn)
            # follow W/b in the flat vector
            plan.append(("centers", nout * nin,
                         lambda c, s=(nout, nin): c.reshape(s, order="C"),
                         lambda a: np.asarray(a).ravel(order="C")))
    elif kind in ("ConvolutionLayer",):
        # ConvolutionParamInitializer: BIAS occupies the first nOut elements
        # of the layer's params view, weights follow (unlike dense, which is
        # weights-first)
        kh, kw, cin, cout = params["W"].shape
        if "b" in params:
            plan.append(("b", cout, lambda c: c, np.ravel))
        plan.append(("W", kh * kw * cin * cout,
                     lambda c, s=(cout, cin, kh, kw):
                     c.reshape(s, order="C").transpose(2, 3, 1, 0),
                     lambda a: np.asarray(a).transpose(3, 2, 0, 1)
                     .ravel(order="C")))
    elif kind in ("LSTM", "GravesLSTM"):
        nin, four_h = params["W"].shape
        h = four_h // 4
        perm = _LSTM_GATES_DL4J_TO_OURS
        graves = kind == "GravesLSTM"

        def unpack_gates(c, rows):
            m = c.reshape((rows, 4 * h), order="F").reshape(rows, 4, h,
                                                            order="C")
            # DL4J gate blocks [i,f,o,g] → ours [i,f,g,o]
            m = m[:, perm, :]
            return m.reshape(rows, 4 * h)

        def pack_gates(a, rows):
            m = np.asarray(a).reshape(rows, 4, h)
            inv = np.argsort(perm)
            m = m[:, inv, :]
            return m.reshape((rows, 4 * h)).ravel(order="F")

        plan.append(("W", nin * 4 * h,
                     lambda c, r=nin: unpack_gates(c, r),
                     lambda a, r=nin: pack_gates(a, r)))
        if graves:
            # GravesLSTMParamInitializer: RW is (nOut, 4·nOut + 3) — the
            # last three columns are the peephole weights [wFF, wOO, wGG].
            # Mapping caveat (documented): DL4J's third peephole feeds the
            # block-input gate; our GravesLSTM's third peephole (pI) feeds
            # the input gate — approximate parity, isolated here.
            rw_cols = 4 * h + 3

            def unpack_rw_graves(c):
                m = c.reshape((h, rw_cols), order="F")
                return {"RW": unpack_gates(m[:, :4 * h].ravel(order="F"), h),
                        "pF": m[:, 4 * h].copy(),
                        "pO": m[:, 4 * h + 1].copy(),
                        "pI": m[:, 4 * h + 2].copy()}

            def pack_rw_graves(d):
                m = np.zeros((h, rw_cols), np.float32)
                m[:, :4 * h] = np.asarray(
                    pack_gates(d["RW"], h)).reshape((h, 4 * h), order="F")
                m[:, 4 * h] = np.asarray(d["pF"])
                m[:, 4 * h + 1] = np.asarray(d["pO"])
                m[:, 4 * h + 2] = np.asarray(d["pI"])
                return m.ravel(order="F")

            plan.append(("__multi_RW+pF+pO+pI", h * rw_cols,
                         unpack_rw_graves, pack_rw_graves))
        else:
            plan.append(("RW", h * 4 * h,
                         lambda c, r=h: unpack_gates(c, r),
                         lambda a, r=h: pack_gates(a, r)))

        def unpack_b(c):
            m = c.reshape(1, 4, h)[:, perm, :]
            return m.reshape(4 * h)

        def pack_b(a):
            m = np.asarray(a).reshape(1, 4, h)[:, np.argsort(perm), :]
            return m.reshape(4 * h)

        plan.append(("b", 4 * h, unpack_b, pack_b))
    elif kind == "BatchNormalization":
        n = params["gamma"].shape[0]
        plan.append(("gamma", n, lambda c: c, np.ravel))
        plan.append(("beta", n, lambda c: c, np.ravel))
        # running stats ride the flat vector in the reference
        plan.append(("__state_mean", n, lambda c: c, np.ravel))
        plan.append(("__state_var", n, lambda c: c, np.ravel))
    elif kind == "Deconvolution2D":
        # DeconvolutionParamInitializer: bias-first like conv; weights are
        # (inDepth, outDepth, kH, kW) — input-channels leading, the
        # transpose of the conv layout
        kh, kw, cin, cout = params["W"].shape
        if "b" in params:
            plan.append(("b", cout, lambda c: c, np.ravel))
        plan.append(("W", kh * kw * cin * cout,
                     lambda c, s=(cin, cout, kh, kw):
                     c.reshape(s, order="C").transpose(2, 3, 0, 1),
                     lambda a: np.asarray(a).transpose(2, 3, 0, 1)
                     .ravel(order="C")))
    elif kind in ("SeparableConvolution2D", "DepthwiseConvolution2D"):
        # SeparableConvolutionParamInitializer: bias, depthwise, pointwise.
        # Depthwise weights (depthMultiplier, nIn, kH, kW); pointwise
        # (nOut, nIn·dm, 1, 1) — layouts reconstructed from the upstream
        # initializers (same caveat as the module docstring)
        kh, kw, cin, dm = params["dW"].shape

        def unpack_dw(c, s=(dm, cin, kh, kw)):
            return c.reshape(s, order="C").transpose(2, 3, 1, 0)

        def pack_dw(a):
            return np.asarray(a).transpose(3, 2, 0, 1).ravel(order="C")

        if kind == "SeparableConvolution2D":
            _, _, cmid, cout = params["pW"].shape
            if "b" in params:
                plan.append(("b", cout, lambda c: c, np.ravel))
            plan.append(("dW", kh * kw * cin * dm, unpack_dw, pack_dw))
            plan.append(("pW", cmid * cout,
                         lambda c, s=(cout, cmid, 1, 1):
                         c.reshape(s, order="C").transpose(2, 3, 1, 0),
                         lambda a: np.asarray(a).transpose(3, 2, 0, 1)
                         .ravel(order="C")))
        else:
            if "b" in params:
                plan.append(("b", cin * dm, lambda c: c, np.ravel))
            plan.append(("dW", kh * kw * cin * dm, unpack_dw, pack_dw))
    elif kind == "PReLULayer":
        a = params["alpha"]
        if a.ndim == 3:
            # ours (H, W, C) ↔ DL4J's NCHW feature shape (C, H, W)
            h, w, ch = a.shape
            plan.append(("alpha", h * w * ch,
                         lambda c, s=(ch, h, w):
                         c.reshape(s, order="C").transpose(1, 2, 0),
                         lambda x: np.asarray(x).transpose(2, 0, 1)
                         .ravel(order="C")))
        else:
            plan.append(("alpha", int(np.prod(a.shape)),
                         lambda c, s=a.shape: c.reshape(s),
                         lambda x: np.asarray(x).ravel()))
    elif kind == "LocallyConnected2D":
        # SameDiff-layer params: W (outH·outW, kH·kW·nIn, nOut) C-order.
        # Bias mapped per-position (our Keras-layout (oh, ow, nOut)) —
        # documented assumption; a real artifact with a shared (1, nOut)
        # bias fails the chunk-size check LOUDLY rather than mis-mapping
        oh, ow, fd, nout = params["W"].shape
        plan.append(("W", oh * ow * fd * nout,
                     lambda c, s=(oh, ow, fd, nout):
                     c.reshape(s, order="C"),
                     lambda a: np.asarray(a).ravel(order="C")))
        if "b" in params:
            plan.append(("b", oh * ow * nout,
                         lambda c, s=(oh, ow, nout): c.reshape(s, order="C"),
                         lambda a: np.asarray(a).ravel(order="C")))
    else:
        raise ValueError(f"no DL4J flat-param plan for layer {kind}")
    return plan


def _flat_unpack_layer(model, key, layer, flat, idx, where: str) -> int:
    """Consume one layer's DL4J flat-vector chunk into
    ``model._params[key]`` / ``model._states[key]``. Returns the new idx.
    Shared by the MLN and CG walks — the packing rules must never fork."""
    import jax.numpy as jnp

    params = model._params.get(key, {})
    for pname, numel, unpack, _ in _layer_param_plan(layer, params):
        chunk = flat[idx:idx + numel]
        if chunk.size != numel:
            raise ValueError(
                f"coefficients.bin exhausted at {where} ({pname}): "
                f"need {numel}, have {chunk.size}")
        idx += numel
        val = unpack(chunk)
        if pname.startswith("__multi_"):
            for sub, arr in val.items():
                model._params[key][sub] = jnp.asarray(
                    np.asarray(arr, np.float32))
        elif pname.startswith("__state_"):
            sname = pname[len("__state_"):]
            model._states.setdefault(key, {})
            model._states[key][sname] = jnp.asarray(val)
        else:
            model._params[key][pname] = jnp.asarray(
                np.asarray(val, np.float32))
    return idx


def _flat_pack_layer(model, key, layer) -> list:
    """One layer's params (+state rows) as DL4J-ordered flat chunks."""
    params = model._params.get(key, {})
    state = model._states.get(key, {}) if hasattr(model, "_states") else {}
    chunks = []
    for pname, numel, _, pack in _layer_param_plan(layer, params):
        if pname.startswith("__multi_"):
            src = {sub: np.asarray(params[sub])
                   for sub in pname[len("__multi_"):].split("+")}
        elif pname.startswith("__state_"):
            src = state.get(pname[len("__state_"):],
                            np.zeros(numel, np.float32))
        else:
            src = np.asarray(params[pname])
        chunks.append(np.asarray(pack(src), np.float32))
    return chunks


def params_from_flat(net, flat: np.ndarray):
    """Distribute a DL4J flat coefficient vector into the net's params/state
    (in place). Returns the number of consumed elements."""
    idx = 0
    for li, layer in enumerate(net.conf.layers):
        idx = _flat_unpack_layer(net, str(li), layer, flat, idx,
                                 f"layer {li}")
    return idx


def params_to_flat(net) -> np.ndarray:
    """The net's params (+BN stats) as a DL4J-ordered flat vector."""
    chunks = []
    for li, layer in enumerate(net.conf.layers):
        chunks.extend(_flat_pack_layer(net, str(li), layer))
    return (np.concatenate(chunks) if chunks
            else np.zeros((0,), np.float32))


# ------------------------------------------------------------- zip surface

# layer classes living in subpackages of conf.layers in the reference
_LAYER_SUBPKG = {"Yolo2OutputLayer": "objdetect.",
                 "Cropping2D": "convolutional.",
                 "Cropping1D": "convolutional.",
                 "Cropping3D": "convolutional."}


def _layer_to_json(layer, li: int) -> dict:
    kind = type(layer).__name__
    out = {"@class": _PKG + _LAYER_SUBPKG.get(kind, "") + kind,
           "layerName": getattr(layer, "name", None) or f"layer{li}"}
    act = getattr(layer, "activation", None)
    if act:
        out["activationFn"] = {
            "@class": "org.nd4j.linalg.activations.impl."
                      + _ACT_TO_CLASS.get(act, "ActivationIdentity")}
    for ours, theirs in (("n_in", "nin"), ("n_out", "nout")):
        v = getattr(layer, ours, None)
        if v is not None:
            out[theirs] = int(v)
    for ours, theirs in (("kernel_size", "kernelSize"), ("stride", "stride"),
                         ("padding", "padding"), ("dilation", "dilation")):
        v = getattr(layer, ours, None)
        if ours == "padding" and isinstance(v, str):
            # ConvolutionMode.Same: DL4J ignores the padding field
            out["convolutionMode"] = "Same"
            out["padding"] = [0, 0]
            continue
        if v is not None:
            out[theirs] = list(v) if isinstance(v, (tuple, list)) else [v, v]
    hb = getattr(layer, "has_bias", None)
    if hb is not None and kind not in ("SubsamplingLayer",):
        out["hasBias"] = bool(hb)
    dm = getattr(layer, "depth_multiplier", None)
    if dm is not None:
        out["depthMultiplier"] = int(dm)
    if kind == "Upsampling2D":
        if getattr(layer, "interpolation", "nearest") != "nearest":
            raise ValueError(
                "DL4J Upsampling2D is nearest-neighbor only — "
                f"interpolation={layer.interpolation!r} has no "
                "reference-zip representation (keep the native format "
                "for this model)")
        out["size"] = list(layer.size)
    if kind == "Cropping2D":
        out["cropping"] = list(layer.cropping)
    if kind == "GlobalPoolingLayer":
        out["poolingType"] = layer.pooling_type.upper()
    if kind == "LocalResponseNormalization":
        out.update(k=float(layer.k), n=int(layer.n),
                   alpha=float(layer.alpha), beta=float(layer.beta))
    if kind == "PReLULayer":
        if getattr(layer, "alpha_shape", None):
            out["inputShape"] = list(layer.alpha_shape)
    if kind == "LocallyConnected2D":
        if getattr(layer, "input_size", None):
            out["inputSize"] = list(layer.input_size)
    loss = getattr(layer, "loss_function", None)
    if loss:
        out["lossFn"] = {"@class": "org.nd4j.linalg.lossfunctions.impl."
                         + _LOSS_TO_CLASS.get(loss,
                                              "LossNegativeLogLikelihood")}
    pool = getattr(layer, "pooling_type", None)
    if pool and kind == "SubsamplingLayer":
        out["poolingType"] = pool.upper()
    if kind == "BatchNormalization":
        out["eps"] = getattr(layer, "eps", 1e-5)
        out["decay"] = getattr(layer, "decay", 0.9)
    if kind in ("LSTM", "GravesLSTM"):
        out["forgetGateBiasInit"] = getattr(layer, "forget_gate_bias_init",
                                            1.0)
    if kind == "DropoutLayer":
        out["iDropout"] = _idropout_to_json(
            getattr(layer, "dropout", 0.5))
    if kind == "CenterLossOutputLayer":
        out["alpha"] = float(layer.alpha)
        out["lambda"] = float(layer.lambda_)
    if kind == "Yolo2OutputLayer":
        if getattr(layer, "boxes", None):
            out["boundingBoxes"] = [list(b) for b in layer.boxes]
        out["lambdaCoord"] = float(layer.lambda_coord)
        out["lambdaNoObj"] = float(layer.lambda_no_obj)
    return out


def _idropout_to_json(d) -> dict:
    """Our dropout field (float retain-prob or IDropout object) → the
    reference's Jackson conf.dropout classes."""
    from deeplearning4j_tpu.nn.conf.dropout import (AlphaDropout, Dropout,
                                                    GaussianDropout,
                                                    GaussianNoise, IDropout)
    base = "org.deeplearning4j.nn.conf.dropout."
    if isinstance(d, IDropout):
        if isinstance(d, Dropout):
            return {"@class": base + "Dropout", "p": float(d.p)}
        if isinstance(d, GaussianDropout):
            return {"@class": base + "GaussianDropout",
                    "rate": float(d.rate)}
        if isinstance(d, GaussianNoise):
            return {"@class": base + "GaussianNoise",
                    "stddev": float(d.stddev)}
        if isinstance(d, AlphaDropout):
            return {"@class": base + "AlphaDropout", "p": float(d.p)}
        raise ValueError(
            f"no DL4J-zip mapping for dropout scheme {type(d).__name__}")
    return {"@class": base + "Dropout", "p": float(d or 0.5)}


def _input_type_to_json(it) -> Optional[dict]:
    if it is None:
        return None
    base = "org.deeplearning4j.nn.conf.inputs.InputType$InputType"
    kind = getattr(it, "kind", None)
    if kind == "cnn_flat":
        return {"@class": base + "ConvolutionalFlat", "height": it.height,
                "width": it.width, "depth": it.channels}
    if kind == "cnn":
        return {"@class": base + "Convolutional", "height": it.height,
                "width": it.width, "channels": it.channels}
    if kind == "rnn":
        return {"@class": base + "Recurrent", "size": it.size,
                "timeSeriesLength": it.timeseries_length}
    return {"@class": base + "FeedForward", "size": it.size}


def _iupdater_to_json(conf) -> Optional[dict]:
    """Shared Jackson iUpdater entry for the MLN and CG writers."""
    upd = getattr(conf, "updater", None)
    if upd is None:
        return None
    return {"@class": "org.nd4j.linalg.learning.config."
            + type(upd).__name__,
            "learningRate": float(getattr(upd, "learning_rate",
                                          getattr(upd, "lr", 1e-3)))}


def _is_tbptt(conf) -> bool:
    bpt = getattr(conf, "backprop_type", None)
    return bool(bpt) and "runcated" in str(bpt)   # TruncatedBPTT / truncated


def config_to_dl4j_json(conf) -> str:
    iupdater = _iupdater_to_json(conf)
    confs = []
    for li, layer in enumerate(conf.layers):
        lj = _layer_to_json(layer, li)
        if iupdater is not None:
            lj["iUpdater"] = iupdater
        confs.append({
            "cacheMode": "NONE", "dataType": "FLOAT",
            "epochCount": 0, "iterationCount": 0,
            "layer": lj,
            "miniBatch": True, "minimize": True,
            "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
            "seed": conf.seed or 0,
        })
    out = {"backpropType": ("TruncatedBPTT" if _is_tbptt(conf)
                            else "Standard"),
           "confs": confs}
    it = _input_type_to_json(getattr(conf, "input_type", None))
    if it:
        out["inputType"] = it
    return json.dumps(out, indent=2)


def restore_multi_layer_network(path):
    """ref: ModelSerializer#restoreMultiLayerNetwork over a REAL DL4J zip
    (configuration.json + coefficients.bin)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J model zip: no configuration.json")
        conf = config_from_dl4j_json(
            zf.read("configuration.json").decode("utf-8"))
        net = MultiLayerNetwork(conf)
        net.init()
        if "coefficients.bin" in names:
            flat = read_nd4j_array(zf.read("coefficients.bin")).ravel()
            used = params_from_flat(net, flat.astype(np.float32))
            if used != flat.size:
                raise ValueError(
                    f"coefficients.bin has {flat.size} values but the "
                    f"architecture consumes {used} — layer plan mismatch")
        if "updaterState.bin" in names:
            # mapping the reference's flat updater-state vector onto optax
            # state trees is not implemented; resuming starts with FRESH
            # optimizer state — warn, don't silently pretend it was kept
            import logging
            logging.getLogger(__name__).warning(
                "updaterState.bin present but not restored — optimizer "
                "moments start fresh (config updater/lr ARE restored)")
        if "normalizer.bin" in names:
            raise ValueError(
                "normalizer.bin (Java NormalizerSerializer format) is not "
                "supported — strip it or re-fit a normalizer")
    return net


def write_model(net, path):
    """Write OUR net as a reference-schema DL4J zip (configuration.json +
    coefficients.bin) that ``restore_multi_layer_network`` /
    ``restore_computation_graph`` — and, per the documented format, the
    reference's ModelSerializer — can read. Dispatches on net type like
    ``ModelSerializer.writeModel`` does."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    is_cg = isinstance(net, ComputationGraph)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json",
                    cg_config_to_dl4j_json(net.conf) if is_cg
                    else config_to_dl4j_json(net.conf))
        zf.writestr("coefficients.bin",
                    write_nd4j_array(cg_params_to_flat(net) if is_cg
                                     else params_to_flat(net)))


# ----------------------------------------------- ComputationGraph surface
#
# ref: ModelSerializer#restoreComputationGraph — the zip layout is identical
# to the MLN one, but configuration.json is a Jackson
# ComputationGraphConfiguration: networkInputs/networkOutputs, a
# ``vertices`` map of polymorphic @class graph-vertex entries (LayerVertex
# wraps a full NeuralNetConfiguration under "layerConf"), and a
# ``vertexInputs`` map. The flat coefficient vector concatenates the LAYER
# vertices' params in topological order (ComputationGraph#params walks
# topologicalSortOrder); the per-layer packing reuses the MLN plans above.

_VERTEX_PKG = "org.deeplearning4j.nn.conf.graph."

# our ElementWiseVertex op spellings → the reference's Op enum constants
_EW_OP_TO_DL4J = {"add": "Add", "subtract": "Subtract", "sub": "Subtract",
                  "product": "Product", "prod": "Product", "mul": "Product",
                  "average": "Average", "avg": "Average", "max": "Max"}
_EW_OP_FROM_DL4J = {"Add": "add", "Subtract": "subtract",
                    "Product": "product", "Average": "average", "Max": "max"}


def _vertex_to_json(v) -> dict:
    from deeplearning4j_tpu.nn import graph_conf as G

    kind = type(v).__name__
    rnn = kind in ("LastTimeStepVertex", "DuplicateToTimeSeriesVertex",
                   "ReverseTimeSeriesVertex")
    out = {"@class": _VERTEX_PKG + ("rnn." if rnn else "") + kind}
    if isinstance(v, G.ElementWiseVertex):
        op = _EW_OP_TO_DL4J.get(v.op.lower())
        if op is None:
            raise ValueError(f"ElementWiseVertex op {v.op!r} has no DL4J "
                             f"Op enum constant")
        out["op"] = op
    elif isinstance(v, G.SubsetVertex):
        out["from"] = int(v.from_idx)
        out["to"] = int(v.to_idx)
    elif isinstance(v, G.ScaleVertex):
        out["scaleFactor"] = float(v.scale)
    elif isinstance(v, G.ShiftVertex):
        out["shiftValue"] = float(v.shift)
    elif isinstance(v, G.UnstackVertex):
        out["from"] = int(v.from_idx)
        out["stackSize"] = int(v.stack_size)
    elif isinstance(v, G.L2NormalizeVertex):
        out["eps"] = float(v.eps)
    elif isinstance(v, G.ReshapeVertex):
        # reference newShape INCLUDES the minibatch dim (-1); ours is
        # non-batch dims only
        out["newShape"] = [-1] + [int(s) for s in v.shape]
    elif isinstance(v, (G.MergeVertex, G.StackVertex, G.LastTimeStepVertex,
                        G.DuplicateToTimeSeriesVertex,
                        G.ReverseTimeSeriesVertex)):
        pass
    else:
        raise ValueError(
            f"vertex {kind!r} has no DL4J-zip JSON mapping (LambdaVertex "
            f"and Preprocessor/Pool-helper vertices are outside the compat "
            f"subset)")
    return out


def _vertex_from_json(vj: dict):
    from deeplearning4j_tpu.nn import graph_conf as G

    cls = vj.get("@class", "").rsplit(".", 1)[-1]
    if cls == "MergeVertex":
        return G.MergeVertex()
    if cls == "ElementWiseVertex":
        op = _EW_OP_FROM_DL4J.get(str(vj.get("op", "Add")))
        if op is None:
            raise ValueError(f"unknown ElementWiseVertex op "
                             f"{vj.get('op')!r}")
        return G.ElementWiseVertex(op=op)
    if cls == "SubsetVertex":
        return G.SubsetVertex(from_idx=int(vj["from"]), to_idx=int(vj["to"]))
    if cls == "ScaleVertex":
        return G.ScaleVertex(scale=float(vj.get("scaleFactor", 1.0)))
    if cls == "ShiftVertex":
        return G.ShiftVertex(shift=float(vj.get("shiftValue", 0.0)))
    if cls == "StackVertex":
        return G.StackVertex()
    if cls == "UnstackVertex":
        return G.UnstackVertex(from_idx=int(vj.get("from", 0)),
                               stack_size=int(vj.get("stackSize", 1)))
    if cls == "L2NormalizeVertex":
        return G.L2NormalizeVertex(eps=float(vj.get("eps", 1e-8)))
    if cls == "ReshapeVertex":
        # reference newShape includes the minibatch dim; strip it for our
        # non-batch-dims-only vertex (a concrete leading extent cannot be
        # honored batch-independently — refuse rather than mis-shape)
        ns = [int(s) for s in vj.get("newShape", ())]
        if ns and ns[0] not in (-1, 0):
            raise ValueError(
                f"ReshapeVertex newShape {ns} pins the minibatch dim to "
                f"{ns[0]}; only batch-preserving (-1 leading) reshapes are "
                f"supported")
        return G.ReshapeVertex(shape=tuple(ns[1:]))
    if cls == "LastTimeStepVertex":
        return G.LastTimeStepVertex()
    if cls == "DuplicateToTimeSeriesVertex":
        return G.DuplicateToTimeSeriesVertex()
    if cls == "ReverseTimeSeriesVertex":
        return G.ReverseTimeSeriesVertex()
    raise ValueError(
        f"DL4J graph vertex class {cls!r} is outside the supported compat "
        f"subset (see _vertex_from_json for the implemented set)")


def cg_config_to_dl4j_json(conf) -> str:
    """Our ComputationGraphConfiguration → Jackson CG-configuration JSON."""
    iupdater = _iupdater_to_json(conf)
    from deeplearning4j_tpu.nn import graph_conf as G

    vertices, vertex_inputs = {}, {}
    for li, name in enumerate(conf.topo_order):
        node = conf.nodes[name]
        vertex_inputs[name] = list(node.inputs)
        if node.layer is not None:
            lj = _layer_to_json(node.layer, li)
            lj["layerName"] = name
            if iupdater is not None:
                lj["iUpdater"] = iupdater
            vertices[name] = {
                "@class": _VERTEX_PKG + "LayerVertex",
                "layerConf": {"layer": lj, "seed": conf.seed or 0,
                              "dataType": "FLOAT"}}
        else:
            vj = _vertex_to_json(node.vertex)
            if isinstance(node.vertex, G.DuplicateToTimeSeriesVertex):
                # reference shape: ONE graph input (the vector); the
                # time-series reference rides the 'inputName' field
                if len(node.inputs) != 2:
                    raise ValueError(
                        f"DuplicateToTimeSeriesVertex {name!r} needs "
                        f"[vector, series] inputs, got {node.inputs}")
                vertex_inputs[name] = [node.inputs[0]]
                vj["inputName"] = node.inputs[1]
            vertices[name] = vj
    out = {"networkInputs": list(conf.network_inputs),
           "networkOutputs": list(conf.network_outputs),
           "vertices": vertices,
           "vertexInputs": vertex_inputs,
           "backpropType": ("TruncatedBPTT" if _is_tbptt(conf)
                            else "Standard")}
    if _is_tbptt(conf):
        out["tbpttFwdLength"] = int(conf.tbptt_fwd_length)
        out["tbpttBackLength"] = int(conf.tbptt_bwd_length)
    its = [_input_type_to_json(it) for it in (conf.input_types or [])]
    if any(its):
        out["networkInputTypes"] = its
    return json.dumps(out, indent=2)


def cg_config_from_dl4j_json(text: str):
    """Jackson ComputationGraphConfiguration JSON → our CG configuration
    (via the GraphBuilder DSL, which recomputes topo order and shapes)."""
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration

    j = json.loads(text)
    if "vertices" not in j:
        raise ValueError("not a ComputationGraph configuration "
                         "(no 'vertices' key — use "
                         "restore_multi_layer_network for MLN zips)")
    vertices = j["vertices"]
    vertex_inputs = j.get("vertexInputs", {})
    layer_confs = [{"layer": vj.get("layerConf", {}).get("layer", {})}
                   for vj in vertices.values()
                   if vj.get("@class", "").endswith("LayerVertex")]
    builder = NeuralNetConfiguration.builder()
    seed = None
    for vj in vertices.values():
        lc = vj.get("layerConf")
        if lc and lc.get("seed") is not None:
            seed = int(lc["seed"])
            break
    if seed is not None:
        builder.seed(seed)
    gb = (builder.updater(_updater_from_json(layer_confs))
          .graph_builder()
          .add_inputs(*j.get("networkInputs", [])))
    for name, vj in vertices.items():
        inputs = list(vertex_inputs.get(name, []))
        if vj.get("@class", "").endswith("LayerVertex"):
            layer = _layer_from_json(vj.get("layerConf", {}).get("layer", {}))
            gb.add_layer(name, layer, *inputs)
        else:
            v = _vertex_from_json(vj)
            if vj.get("@class", "").endswith(
                    "DuplicateToTimeSeriesVertex"):
                # the reference names its series reference via 'inputName';
                # our vertex takes it as a second graph input
                ref_name = vj.get("inputName")
                if not ref_name:
                    raise ValueError(
                        f"DuplicateToTimeSeriesVertex {name!r} is missing "
                        f"the required 'inputName' field")
                inputs.append(ref_name)
            gb.add_vertex(name, v, *inputs)
    gb.set_outputs(*j.get("networkOutputs", []))
    its = [_input_type_from_json(it)
           for it in j.get("networkInputTypes", j.get("inputTypes", []))]
    if its and all(it is not None for it in its):
        gb.set_input_types(*its)
    if j.get("backpropType") == "TruncatedBPTT":
        gb.backprop_type("truncated_bptt")
        gb.t_bptt_length(int(j.get("tbpttFwdLength", 20)),
                         int(j.get("tbpttBackLength", 20)))
    return gb.build()


def _cg_layer_nodes(conf):
    """Layer vertices in topological order — the reference's flat-vector
    walk (ComputationGraph#params over topologicalSortOrder)."""
    return [(name, conf.nodes[name].layer) for name in conf.topo_order
            if conf.nodes[name].layer is not None]


def cg_params_from_flat(g, flat: np.ndarray) -> int:
    """Distribute a DL4J CG flat coefficient vector into the graph's
    params/state (in place). Returns consumed element count.

    Order assumption (ADVICE r4): the reference flattens params over
    ``topologicalSortOrder()``, whose tie-break follows vertex indices =
    Jackson map insertion order. Our ``_toposort`` breaks ties by the
    same insertion order (config_from json preserves it), so the walks
    agree whenever the artifact's vertices map is in creation order —
    true for reference-serialized configs. A mismatch between two
    order-ambiguous vertices with *identical* param plans would be
    silent; with different plans the per-chunk size checks fail loudly.
    Unverifiable further without a real artifact (empty reference
    mount)."""
    idx = 0
    for name, layer in _cg_layer_nodes(g.conf):
        idx = _flat_unpack_layer(g, name, layer, flat, idx,
                                 f"vertex {name!r}")
    return idx


def cg_params_to_flat(g) -> np.ndarray:
    """The graph's params (+BN stats) as a DL4J-ordered flat vector."""
    chunks = []
    for name, layer in _cg_layer_nodes(g.conf):
        chunks.extend(_flat_pack_layer(g, name, layer))
    return (np.concatenate(chunks) if chunks
            else np.zeros((0,), np.float32))


def restore_computation_graph(path):
    """ref: ModelSerializer#restoreComputationGraph over a REAL DL4J zip
    (configuration.json with a vertices map + coefficients.bin)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J model zip: no configuration.json")
        conf = cg_config_from_dl4j_json(
            zf.read("configuration.json").decode("utf-8"))
        g = ComputationGraph(conf)
        g.init()
        if "coefficients.bin" in names:
            flat = read_nd4j_array(zf.read("coefficients.bin")).ravel()
            used = cg_params_from_flat(g, flat.astype(np.float32))
            if used != flat.size:
                raise ValueError(
                    f"coefficients.bin has {flat.size} values but the "
                    f"architecture consumes {used} — vertex plan mismatch")
        if "updaterState.bin" in names:
            import logging
            logging.getLogger(__name__).warning(
                "updaterState.bin present but not restored — optimizer "
                "moments start fresh (config updater/lr ARE restored)")
        if "normalizer.bin" in names:
            raise ValueError(
                "normalizer.bin (Java NormalizerSerializer format) is not "
                "supported — strip it or re-fit a normalizer")
    return g
