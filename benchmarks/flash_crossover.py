"""Device-timed flash-vs-XLA attention crossover sweep (run on a live
TPU window; feeds FLASH_MIN_SEQ in models/transformer.py and the
benchmarks/RESULTS.md table)."""
import sys, tempfile
import jax, jax.numpy as jnp, numpy as np

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from device_timing import measure_device_step
from deeplearning4j_tpu.kernels.flash_attention import flash_attention
from deeplearning4j_tpu.parallel.ring import _plain_attention

D = 64

def time_fn(f, args, tag):
    try:
        g = jax.jit(jax.value_and_grad(lambda *a: f(*a).astype(jnp.float32).sum()))
        out = g(*args); jax.block_until_ready(out)
        def window():
            r = None
            for _ in range(6):
                r = g(*args)
            float(r[0])
        r = measure_device_step(window, "jit_", logdir=tempfile.mkdtemp(prefix="ft_"))
        ms = r["median_s"] * 1e3 if r else float("nan")
        print(f"{tag}: {ms:.3f} ms", flush=True)
    except Exception as e:
        print(f"{tag}: FAIL {type(e).__name__}", flush=True)

import itertools
cases = [(8, 512), (8, 2048), (2, 8192)]
for B, T in cases:
    H = 8
    q = jax.random.normal(jax.random.key(0), (B, H, T, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, H, T, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, T, D), jnp.bfloat16)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    time_fn(lambda a, b, c: _plain_attention(a, b, c, causal=True),
            (qt, kt, vt), f"B={B} T={T} XLA")
    for bq, bk in [(128, 128), (256, 512), (512, 512), (512, 1024)]:
        if bq > T or bk > T: continue
        time_fn(lambda a, b, c, bq=bq, bk=bk: flash_attention(
            a, b, c, causal=True, block_q=bq, block_k=bk),
            (q, k, v), f"B={B} T={T} flash bq={bq} bk={bk}")
    print(flush=True)
