"""GPipe bubble-fraction measurement (VERDICT r3 #10: PP efficiency must be
evidenced, not asserted).

Theory: with S stages and M micro-batches, the GPipe schedule idles each
device for (S-1) of (M+S-1) ticks — bubble = (S-1)/(M+S-1), so throughput
at fixed global batch should scale ∝ (M+S-1)⁻¹·M ticks of useful work.
This harness measures a pipelined train step at fixed GLOBAL batch while
sweeping M, reports per-step wall time, implied utilisation vs the best
rung, and the theoretical bubble — one JSON line per M.

Run (virtual mesh):  python benchmarks/pipeline_bubble.py
     (on TPU pass --tpu and set stages to the real chip count)
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu", action="store_true")
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--micro", type=int, nargs="*", default=[4, 8, 16, 32])
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--global-batch", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--iters", type=int, default=4)
    args = ap.parse_args()

    if not args.tpu:
        from deeplearning4j_tpu.utils import force_cpu_devices
        force_cpu_devices(max(8, args.stages))
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, STAGE_AXIS

    S = args.stages
    mesh = MeshSpec({STAGE_AXIS: S}).build(jax.devices()[:S])
    print(f"# platform={jax.devices()[0].platform} stages={S}",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    toks_np = rng.integers(0, 1024, (args.global_batch, args.seq))
    rows = []
    for M in args.micro:
        if args.global_batch % M:
            continue
        cfg = TransformerConfig(
            vocab_size=1024, n_layers=args.layers,
            n_heads=4, d_model=args.d_model, max_len=args.seq,
            pipeline_stages=S, microbatches=M)
        model = TransformerLM(cfg, mesh)
        params = model.init_params(jax.random.key(0))
        params = jax.device_put(params, model.param_shardings(mesh))
        opt = optax.adamw(1e-3)
        opt_state = jax.jit(opt.init)(params)
        step = model.make_train_step(opt)
        toks = jnp.asarray(toks_np, jnp.int32)
        tgts = jnp.roll(toks, -1, axis=1)
        # XLA's own memory accounting for the compiled step: temp bytes =
        # live activations/workspace. Validates the O(M/S)-microbatch queue
        # claim with compiler numbers rather than arithmetic.
        temp_mib = None
        try:
            ma = step.lower(params, opt_state, toks,
                            tgts).compile().memory_analysis()
            if ma is not None:
                temp_mib = round(ma.temp_size_in_bytes / 2**20, 1)
        except Exception as e:
            print(f"# memory_analysis unavailable: {e!r}", file=sys.stderr)
        p, s, loss = step(params, opt_state, toks, tgts)   # compile+warm
        float(loss)
        runs = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            for _ in range(args.iters):
                p, s, loss = step(p, s, toks, tgts)
            float(loss)                                    # value-fetch sync
            runs.append((time.perf_counter() - t0) / args.iters)
        step_s = statistics.median(runs)
        rows.append((M, step_s))
        print(json.dumps({
            "metric": "gpipe_step_seconds", "microbatches": M,
            "stages": S, "global_batch": args.global_batch,
            "step_s": round(step_s, 4),
            "bubble_theory": round((S - 1) / (M + S - 1), 4),
            "tokens_per_sec": round(args.global_batch * args.seq / step_s,
                                    1),
            "xla_temp_mib": temp_mib,
        }), flush=True)
    if len(rows) >= 2:
        # utilisation vs the best rung: the measured analog of 1-bubble
        best = min(s for _, s in rows)
        print(json.dumps({
            "metric": "gpipe_bubble_summary",
            "per_microbatch_utilisation": {
                str(m): round(best / s, 3) for m, s in rows},
            "expected_utilisation_ratio": {
                str(m): round((1 - (S - 1) / (m + S - 1))
                              / max(1 - (S - 1) / (mm + S - 1)
                                    for mm, _ in rows), 3)
                for m, _ in rows for mm, _ in [max(rows, key=lambda r: r[0])]
            },
        }), flush=True)


if __name__ == "__main__":
    main()
