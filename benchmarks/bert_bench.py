"""BERT-base fine-tune samples/sec: TF-imported SameDiff vs HF FlaxBert.

BASELINE.md north-star row 2: "SameDiff TF-import BERT-base fine-tune
(samples/sec) >=70% of JAX/Flax reference". The numerator is the literal
reference workflow (ref: SURVEY J8 ``TFGraphMapper.importGraph`` on bert.pb
+ ``SameDiff#fit``): freeze a TF BERT-base, import it, promote the encoder
weights to variables, attach a [CLS] classifier head, and fine-tune through
``sd.fit``. The denominator is ``transformers.FlaxBertModel`` — an actual
JAX/Flax BERT — with the same head, optimizer (Adam 2e-5), batch, dtype
(f32: the imported graph's dtype), trainable set (everything), and per-step
loss-value fetch.

Both sides are measured INTERLEAVED (A,B,A,B...). On TPU the printed
value/vs_baseline come from DEVICE-side XPlane timing whenever the trace
parses (BASELINE round-3 protocol); ``timing_source`` records which path won.

Run: python benchmarks/bert_bench.py [--smoke]   (--smoke: tiny CPU config)
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import resolve_platform  # noqa: E402 — shared TPU probe


def build_frozen_bert(batch, seq, layers, hidden, heads, intermediate,
                      vocab):
    """Freeze a deterministic TF BERT at the bench shape; returns graph_def."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    from transformers import BertConfig, TFBertModel

    cfg = BertConfig(num_hidden_layers=layers, hidden_size=hidden,
                     num_attention_heads=heads,
                     intermediate_size=intermediate, vocab_size=vocab,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    model = TFBertModel(cfg)

    @tf.function
    def f(input_ids, attention_mask):
        return model(input_ids=input_ids,
                     attention_mask=attention_mask).last_hidden_state

    frozen = convert_variables_to_constants_v2(f.get_concrete_function(
        tf.TensorSpec((batch, seq), tf.int32, name="input_ids"),
        tf.TensorSpec((batch, seq), tf.int32, name="attention_mask")))
    return frozen.graph.as_graph_def()


def measure_ours(gd, hidden, batch, seq, vocab, iters, lr):
    """TF-import + promote + head + sd.fit window closure (per-step sync)."""
    import numpy as np

    from deeplearning4j_tpu.data.dataset import MultiDataSet
    from deeplearning4j_tpu.modelimport.tfimport import TFGraphMapper

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tests.bert_helpers import (attach_classifier_head,
                                    promote_weight_constants)

    t0 = time.perf_counter()
    sd = TFGraphMapper.import_graph(gd)
    promoted = promote_weight_constants(sd, min_size=512)
    attach_classifier_head(sd, gd, hidden_size=hidden, lr=lr)
    print(f"[bert-bench] import+head: {time.perf_counter() - t0:.1f}s, "
          f"{promoted} tensors promoted", file=sys.stderr, flush=True)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq)).astype(np.int32)
    mask = np.ones((batch, seq), np.int32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch)]
    ds = MultiDataSet([ids, mask], [y])

    t0 = time.perf_counter()
    sd.fit([ds], epochs=1)                 # warm/compile
    print(f"[bert-bench] ours warmup (compile+run): "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr, flush=True)

    def window():
        t0 = time.perf_counter()
        sd.fit([ds] * iters, epochs=1)     # fit float()s the loss per batch
        return batch * iters / (time.perf_counter() - t0)

    return window


def measure_flax(batch, seq, layers, hidden, heads, intermediate, vocab,
                 iters, lr):
    """HF FlaxBertModel + [CLS] head + Adam — the JAX/Flax denominator."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from transformers import BertConfig, FlaxBertModel

    cfg = BertConfig(num_hidden_layers=layers, hidden_size=hidden,
                     num_attention_heads=heads,
                     intermediate_size=intermediate, vocab_size=vocab,
                     hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    t0 = time.perf_counter()
    model = FlaxBertModel(cfg, seed=0)
    print(f"[bert-bench] flax init: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr, flush=True)

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    mask = jnp.ones((batch, seq), jnp.int32)
    y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch)])

    params = {"bert": model.params,
              "head_w": jnp.zeros((hidden, 2), jnp.float32),
              "head_b": jnp.zeros((2,), jnp.float32)}
    opt = optax.adam(lr)
    opt_state = jax.jit(opt.init)(params)

    def loss_fn(p, ids, mask, y):
        out = model(input_ids=ids, attention_mask=mask,
                    params=p["bert"]).last_hidden_state
        logits = out[:, 0] @ p["head_w"] + p["head_b"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(y * logp, axis=-1))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def flax_step(p, s, ids, mask, y):
        loss, g = jax.value_and_grad(loss_fn)(p, ids, mask, y)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    state = (params, opt_state)
    t0 = time.perf_counter()
    p, s, loss = flax_step(*state, ids, mask, y)
    float(loss)
    state = (p, s)
    print(f"[bert-bench] flax warmup (compile+run): "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr, flush=True)

    def window():
        nonlocal state
        p, s = state
        t0 = time.perf_counter()
        for _ in range(iters):
            p, s, loss = flax_step(p, s, ids, mask, y)
            float(loss)                    # per-step fetch, matching sd.fit
        state = (p, s)
        return batch * iters / (time.perf_counter() - t0)

    return window


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config (CI/dev)")
    args = ap.parse_args()

    platform, err = resolve_platform(force_cpu=args.smoke)
    if platform is None or platform == "cpu":
        if err:
            print(f"[bert-bench] accelerator unavailable: {err}",
                  file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if platform is None or platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    print(f"[bert-bench] platform={platform}", file=sys.stderr, flush=True)

    if args.smoke or not on_tpu:
        # 2L/h64 mini-BERT: exercises the full freeze->import->fit path
        layers, hidden, heads, inter, vocab = 2, 64, 2, 128, 1000
        batches, seq, iters, repeats, lr = [2], 16, 2, 2, 5e-3
    else:
        # the real thing: BERT-base 12L/h768/12A/i3072/V30522, f32
        # (the imported graph's dtype), classic fine-tune shape s128
        layers, hidden, heads, inter, vocab = 12, 768, 12, 3072, 30522
        batches, seq, iters, repeats, lr = [32, 16], 128, 10, 3, 2e-5
    batch_env = os.environ.get("BENCH_BERT_BATCH")
    if batch_env:
        batches = [int(batch_env)]

    ours = flax_w = None
    last_err = None
    for batch in batches:                  # OOM ladder (TPU HBM is 16 GB)
        try:
            gd = build_frozen_bert(batch, seq, layers, hidden, heads, inter,
                                   vocab)
            ours = measure_ours(gd, hidden, batch, seq, vocab, iters, lr)
            flax_w = measure_flax(batch, seq, layers, hidden, heads, inter,
                                  vocab, iters, lr)
            break
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e) and "Out of memory" \
                    not in str(e):
                raise
            last_err = str(e)[:300]
            print(f"[bert-bench] batch={batch} OOM — stepping down",
                  file=sys.stderr)
            ours = flax_w = None
    if ours is None:
        raise RuntimeError(f"all batch rungs OOMed: {last_err}")

    ours_runs, flax_runs = [], []
    for i in range(repeats):
        print(f"[bert-bench] timed window {i + 1}/{repeats}",
              file=sys.stderr, flush=True)
        ours_runs.append(ours())
        flax_runs.append(flax_w())
    ours_sps = statistics.median(ours_runs)
    flax_sps = statistics.median(flax_runs)

    # device-side timing (BASELINE round-3 protocol): ours jits samediff's
    # `step` -> "jit_step"; the denominator jits `flax_step` -> distinct name
    ours_dev = flax_dev = None
    can_parse = True
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401
    except Exception:
        can_parse = False
    if on_tpu and can_parse:
        from device_timing import measure_device_step
        r = measure_device_step(lambda: ours(), "jit_step")
        if r:
            ours_dev = batch / r["median_s"]
        r = measure_device_step(lambda: flax_w(), "jit_flax_step")
        if r:
            flax_dev = batch / r["median_s"]
        if ours_dev and flax_dev:
            ours_sps, flax_sps = ours_dev, flax_dev

    print(json.dumps({
        "metric": "bert_base_tfimport_finetune_samples_per_sec",
        "value": round(ours_sps, 2),
        "unit": "samples/sec",
        "vs_baseline": round(ours_sps / flax_sps, 3),
        "flax_samples_per_sec": round(flax_sps, 2),
        "timing_source": "device_trace" if (on_tpu and ours_dev and flax_dev)
                         else "host_value_fetch",
        "platform": platform,
        "config": {"layers": layers, "hidden": hidden, "seq": seq,
                   "batch": batch, "dtype": "float32"},
    }))


if __name__ == "__main__":
    main()
