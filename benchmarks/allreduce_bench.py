"""Gradient-sync allreduce bandwidth (BASELINE.md row 3: "measure XLA
collective over ICI; record GB/s vs theoretical").

The reference's gradient-sharing transport (Aeron UDP mesh + threshold
codec, SURVEY P3/J13) is replaced by GSPMD-emitted dense allreduce; this
microbench measures that path directly: a psum over the ``data`` axis of a
parameter-sized f32 buffer, device-timed (XPlane) when possible.

On a real multi-chip slice the number is ICI bandwidth; on the virtual CPU
mesh it validates the harness (numbers are host-memory-bound and labeled as
such). Algorithmic bytes for a ring allreduce: 2·(n-1)/n · size per chip.

Run: python benchmarks/allreduce_bench.py [--devices N] [--mb SIZE_MB]

``--compressed-ab`` adds the ISSUE 7 dense-vs-compressed exchange A/B:
the dense f32 psum against the error-feedback threshold exchange
(encode to an int8 sign mask + per-bucket scale, psum the signs, decode
— the exact in-graph pipeline of ShardedTrainer's compressed step).
Repeats are INTERLEAVED (dense, compressed, dense, ...) and scored
min-of-N: this box drifts ±40%, and back-to-back blocks hand whichever
mode runs second a systematic advantage. Results are archived under
``benchmarks/ab/allreduce_compress_ab.json``.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench import resolve_platform  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))


def _compressed_ab(mesh, n, elems, repeats=7):
    """Interleaved min-of-N dense-vs-compressed exchange timing on the
    built mesh. Returns the result dict (archived by the caller)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    try:
        from jax import shard_map
    except ImportError:  # pre-0.6 jax spells it jax.experimental.shard_map
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import compression as comp

    rng = np.random.default_rng(0)
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((n, elems)) * 1e-3, jnp.float32),
        NamedSharding(mesh, P("data")))
    thr = 1e-3
    wdt = comp.wire_dtype(n)

    @jax.jit
    def dense(x):
        f = shard_map(lambda s: jax.lax.psum(s, "data"), mesh=mesh,
                      in_specs=P("data", None), out_specs=P("data", None))
        return f(x.reshape(n, 1, elems)).reshape(n, elems)

    @jax.jit
    def compressed(x):
        def body(s):
            # the trainer's own exchange pipeline — shared fn, so this
            # A/B measures exactly what the compressed step runs
            dec, _, _, _ = comp.exchange_bucket(s.reshape(-1), thr,
                                                "data", n)
            return dec.reshape(s.shape)
        f = shard_map(body, mesh=mesh, in_specs=P("data", None),
                      out_specs=P("data", None))
        return f(x.reshape(n, 1, elems)).reshape(n, elems)

    for fn in (dense, compressed):               # warm/compile both first
        jax.block_until_ready(fn(x))

    iters = 5
    times = {"dense": [], "compressed": []}
    for _ in range(repeats):                     # interleaved, never blocked
        for name, fn in (("dense", dense), ("compressed", compressed)):
            t0 = time.perf_counter()
            o = x
            for _ in range(iters):
                o = fn(o)
            jax.block_until_ready(o)
            times[name].append((time.perf_counter() - t0) / iters)

    dense_s = min(times["dense"])
    comp_s = min(times["compressed"])
    size = elems * 4
    payload = elems * jnp.dtype(wdt).itemsize + 8
    return {
        "metric": "allreduce_compress_ab",
        "devices": n,
        "buffer_mb": round(size / (1 << 20), 2),
        "threshold": thr,
        "dense_wire_bytes": size,
        "compressed_wire_bytes": int(payload),
        "wire_ratio": round(size / payload, 2),
        "dense_min_s": round(dense_s, 6),
        "compressed_min_s": round(comp_s, 6),
        "speedup_vs_dense": round(dense_s / comp_s, 3),
        "repeats": repeats,
        "schedule": "interleaved min-of-N (this box drifts +-40%; "
                    "back-to-back blocks bias the second mode)",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=0,
                    help="virtual device count when not on TPU (default 8)")
    ap.add_argument("--mb", type=float, default=64.0,
                    help="buffer size in MiB (default 64 ≈ a 16M-param f32 "
                         "gradient shard)")
    ap.add_argument("--compressed-ab", action="store_true",
                    help="also run the dense-vs-compressed exchange A/B "
                         "and archive it under benchmarks/ab/")
    args = ap.parse_args()

    platform, err = resolve_platform()
    if platform is None or platform == "cpu":
        if err:
            print(f"[allreduce] accelerator unavailable: {err}",
                  file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if platform is None or platform == "cpu":
        from deeplearning4j_tpu.utils import force_cpu_devices
        force_cpu_devices(args.devices or 8)

    import jax.numpy as jnp
    import numpy as np
    try:
        from jax import shard_map
    except ImportError:  # pre-0.6 jax spells it jax.experimental.shard_map
        from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = len(devs)
    platform = devs[0].platform
    if n < 2:
        print(json.dumps({
            "metric": "allreduce_busbw_gbps", "value": None,
            "unit": "GB/s", "vs_baseline": None, "platform": platform,
            "note": f"single {platform} device — allreduce needs >=2; run "
                    f"on a slice or with virtual devices"}))
        return

    mesh = Mesh(np.array(devs), ("data",))
    elems = int(args.mb * (1 << 20) // 4)
    x = jax.device_put(
        jnp.arange(elems * n, dtype=jnp.float32).reshape(n, elems),
        NamedSharding(mesh, P("data")))

    @jax.jit
    def allreduce(x):
        f = shard_map(lambda s: jax.lax.psum(s, "data"), mesh=mesh,
                      in_specs=P("data", None), out_specs=P("data", None))
        return f(x.reshape(n, 1, elems)).reshape(n, elems)

    out = allreduce(x)
    jax.block_until_ready(out)           # warm/compile

    iters, runs = 5, []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = allreduce(out)
        float(out[0, 0])                 # value fetch = sync
        runs.append((time.perf_counter() - t0) / iters)
    sec = statistics.median(runs)

    size = elems * 4
    # ring-allreduce bus bandwidth convention: 2(n-1)/n · size / time
    busbw = 2 * (n - 1) / n * size / sec / 1e9
    out_json = {
        "metric": "allreduce_busbw_gbps",
        "value": round(busbw, 2),
        "unit": "GB/s",
        "vs_baseline": None,             # v5e ICI theoretical filled on HW
        "platform": platform,
        "devices": n,
        "buffer_mb": args.mb,
        "sec_per_allreduce": round(sec, 6),
        "note": ("host-memory-bound virtual mesh (harness validation)"
                 if platform == "cpu" else
                 "ICI path; compare to v5e 1.6 TB/s ICI per chip"),
    }
    print(json.dumps(out_json))

    if args.compressed_ab:
        ab = _compressed_ab(mesh, n, elems)
        ab["platform"] = platform
        if platform == "cpu":
            ab["note"] = ("virtual CPU mesh: encode/decode compute and the "
                          "psum are host-memory-bound, so the time ratio "
                          "is NOT an interconnect signal — the wire-bytes "
                          "ratio is the durable number; device A/B lands "
                          "next TPU window")
        path = os.path.join(HERE, "ab", "allreduce_compress_ab.json")
        with open(path, "w") as f:
            json.dump(ab, f, indent=1)
        print(json.dumps(ab))


if __name__ == "__main__":
    main()
