"""Async hot-path guard (acceptance tool for the async-execution PR).

A/B-measures the effect of the async runtime (device prefetch + deferred
loss fetch + multi-in-flight bucketed serving) against the fully
synchronous behavior (``DL4J_TPU_ASYNC=0``):

- **training** — lenet (and a small self-attention "transformer" net) fit
  loop over a DataSetIterator with host-side ETL cost: wall clock per step
  and the ``data_wait`` share of the step-time decomposition, both read
  from the PR-1 metrics registry. Acceptance: async reduces the data_wait
  share and improves wall clock ≥5% on the lenet loop (or documented
  parity with an explanation in benchmarks/RESULTS.md).
- **serving** — ParallelInference at ~0.3 batch occupancy: padded-compute
  waste (1 - mean examples/padded-size) under power-of-two shape buckets
  vs pad-to-``batch_limit``.

Each mode runs in a fresh subprocess: the serving pipeline threads and the
bucket-executable caches are chosen at instance construction, so flipping
the switch in-process would measure a hybrid.

Run: python benchmarks/async_overlap.py [--steps N] [--batch B]
     [--model lenet|transformer|all] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_TRAIN_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

model, steps, batch, etl_ms = (sys.argv[1], int(sys.argv[2]),
                               int(sys.argv[3]), float(sys.argv[4]))

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.observability import global_registry

rng = np.random.RandomState(0)
if model == "lenet":
    from deeplearning4j_tpu.models import zoo
    net = zoo.LeNet().init_model()
    x = rng.rand(steps * batch, 28 * 28).astype("f4")
    y = np.eye(10, dtype="f4")[rng.randint(0, 10, steps * batch)]
else:  # small self-attention net — the transformer-shaped fit loop
    from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf import layers as L
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optim.updaters import Adam
    T, C = 32, 32
    conf = (NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(1e-3)).list()
            .layer(L.SelfAttentionLayer(n_out=C, n_heads=4))
            .layer(L.DenseLayer(n_out=64, activation="relu"))
            .layer(L.GlobalPoolingLayer(pooling_type="avg"))
            .layer(L.OutputLayer(n_out=8, activation="softmax",
                                 loss_function="mcxent"))
            .set_input_type(InputType.recurrent(C, T)).build())
    net = MultiLayerNetwork(conf).init()
    x = rng.rand(steps * batch, T, C).astype("f4")
    y = np.eye(8, dtype="f4")[rng.randint(0, 8, steps * batch)]


class EtlIterator(DataSetIterator):
    '''Host-side ETL with a fixed per-batch cost (models the I/O + decode
    stage of a real input pipeline; a sleep so the cost does not compete
    with the device step for CPU on small CI boxes).'''

    def __init__(self, x, y, batch, etl_seconds):
        self.x, self.y, self.bs, self.etl = x, y, batch, etl_seconds
        self._pos = 0

    def has_next(self):
        return self._pos + self.bs <= self.x.shape[0]

    def next(self):
        i = self._pos
        self._pos += self.bs
        if self.etl:
            time.sleep(self.etl)
        xb = (self.x[i:i + self.bs] - 0.5) * 2.0   # the "decode" work
        return DataSet(xb, self.y[i:i + self.bs])

    def reset(self):
        self._pos = 0

    def batch(self):
        return self.bs


warm = EtlIterator(x[: 2 * batch], y[: 2 * batch], batch, 0.0)
net.fit(warm)                       # compile + warm caches outside window
net.score()

it = EtlIterator(x, y, batch, etl_ms / 1e3)
t0 = time.perf_counter()
net.fit(it)
net.score()                         # flush any deferred loss fetch
wall = time.perf_counter() - t0

reg = global_registry()
phase = reg.get("dl4j_training_phase_seconds")
step = reg.get("dl4j_training_step_seconds")
kind = type(net).__name__
dw = phase.labels(model=kind, phase="data_wait")
st = step.labels(model=kind)
print(json.dumps({
    "seconds_per_step": wall / steps,
    "data_wait_share": dw.sum / max(st.sum, 1e-12),
    "async": os.environ.get("DL4J_TPU_ASYNC", "1"),
}))
"""

_SERVE_WORKER = r"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

batch_limit, req_size, n_req = (int(sys.argv[1]), int(sys.argv[2]),
                                int(sys.argv[3]))

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import global_registry
from deeplearning4j_tpu.optim.updaters import Adam
from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                   ParallelInference)

conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-3)).list()
        .layer(L.DenseLayer(n_in=16, n_out=32, activation="relu"))
        .layer(L.OutputLayer(n_in=32, n_out=4, activation="softmax",
                             loss_function="mcxent")).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.RandomState(0)

pi = (ParallelInference.Builder(net)
      .inference_mode(InferenceMode.BATCHED)
      .batch_limit(batch_limit).build())
try:
    # sequential requests: each forms its own window of ``req_size``
    # examples -> occupancy req_size / batch_limit
    for _ in range(n_req):
        out = pi.output(rng.rand(req_size, 16).astype("f4"))
        assert out.shape[0] == req_size
finally:
    pi.shutdown()

fill = global_registry().get("dl4j_inference_bucket_fill")
mean_fill = fill.sum / max(fill.count, 1)
print(json.dumps({
    "occupancy": req_size / batch_limit,
    "padded_waste": 1.0 - mean_fill,
    "distinct_padded_shapes": len(pi._seen_buckets),
    "async": os.environ.get("DL4J_TPU_ASYNC", "1"),
}))
"""


def _run(worker: str, args, async_mode: str) -> dict:
    env = dict(os.environ, DL4J_TPU_ASYNC=async_mode)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", worker] + [str(a) for a in args],
        capture_output=True, text=True, env=env, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_train(model: str, steps: int, batch: int, etl_ms: float,
              repeats: int) -> dict:
    # interleaved A/B pairs with a min-estimator (same protocol as
    # obs_overhead.py): host warmup noise cannot masquerade as a win
    offs, ons = [], []
    for _ in range(repeats):
        offs.append(_run(_TRAIN_WORKER, [model, steps, batch, etl_ms], "0"))
        ons.append(_run(_TRAIN_WORKER, [model, steps, batch, etl_ms], "1"))
    off = min(offs, key=lambda r: r["seconds_per_step"])
    on = min(ons, key=lambda r: r["seconds_per_step"])
    speedup = (off["seconds_per_step"] - on["seconds_per_step"]) \
        / off["seconds_per_step"] * 100.0
    return {"model": model,
            "sync_seconds_per_step": off["seconds_per_step"],
            "async_seconds_per_step": on["seconds_per_step"],
            "wall_clock_improvement_percent": speedup,
            "sync_data_wait_share": off["data_wait_share"],
            "async_data_wait_share": on["data_wait_share"]}


def run_serving(batch_limit: int, occupancy: float, n_req: int) -> dict:
    req = max(1, round(batch_limit * occupancy))
    off = _run(_SERVE_WORKER, [batch_limit, req, n_req], "0")
    on = _run(_SERVE_WORKER, [batch_limit, req, n_req], "1")
    return {"batch_limit": batch_limit, "request_size": req,
            "occupancy": on["occupancy"],
            "sync_padded_waste": off["padded_waste"],
            "async_padded_waste": on["padded_waste"],
            "async_distinct_padded_shapes": on["distinct_padded_shapes"]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--etl-ms", type=float, default=25.0,
                    help="host ETL cost per batch the prefetch can hide; "
                         "keep it a visible share of the step (on a CPU "
                         "box the 'device' step competes for the same "
                         "cores, so a tiny ETL leaves nothing to overlap)")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--model", choices=("lenet", "transformer", "all"),
                    default="lenet")
    ap.add_argument("--occupancy", type=float, default=0.3)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    models = ("lenet", "transformer") if args.model == "all" \
        else (args.model,)
    result = {"train": [run_train(m, args.steps, args.batch, args.etl_ms,
                                  args.repeats) for m in models],
              "serving": run_serving(32, args.occupancy, args.requests)}
    if args.json:
        print(json.dumps(result, indent=2))
        return result
    for tr in result["train"]:
        print(f"{tr['model']} fit loop, {args.steps} steps, "
              f"batch={args.batch}, etl={args.etl_ms}ms:")
        print(f"  sync  (DL4J_TPU_ASYNC=0): "
              f"{tr['sync_seconds_per_step'] * 1e3:8.3f} ms/step, "
              f"data_wait share {tr['sync_data_wait_share']:.3f}")
        print(f"  async (default):          "
              f"{tr['async_seconds_per_step'] * 1e3:8.3f} ms/step, "
              f"data_wait share {tr['async_data_wait_share']:.3f}")
        print(f"  wall-clock improvement: "
              f"{tr['wall_clock_improvement_percent']:+.1f}%  "
              f"(acceptance bar: >= 5% on lenet)")
    sv = result["serving"]
    print(f"serving at occupancy {sv['occupancy']:.2f} "
          f"(requests of {sv['request_size']}, batch_limit "
          f"{sv['batch_limit']}):")
    print(f"  padded-compute waste  sync pad-to-limit: "
          f"{sv['sync_padded_waste']:.3f}   async buckets: "
          f"{sv['async_padded_waste']:.3f}   "
          f"({sv['async_distinct_padded_shapes']} compiled shape(s))")
    return result


if __name__ == "__main__":
    main()
