#!/usr/bin/env python
"""HTTP front-door load generator: heavy-tailed traffic, SLO grading,
and the kill/respawn drill.

Drives the REAL wire surface (``serving/frontdoor.py``) with a seeded
open-loop load: Poisson arrivals at ``--qps`` with a heavy-tailed
request mix — mostly cheap classifies, a Pareto-tailed minority of
multi-token generations, a slice of SSE streams — because production
traffic is never uniform and the tail is what kills SLOs. Grades the
run with the SLO machinery (p50/p99 per route, goodput, shed/error
ratios via the PR-3 ``_grade``) and emits ONE JSON line
(``metric: http_serve``) the driver archives as ``SERVE_r*.json`` for
``tools/bench_diff.py``'s sustained-only trajectory.

Two modes:

- **in-process** (default): one worker in this process; the classify
  goodput is also measured DIRECT (in-process ``router.output``)
  interleaved A/B-style, so ``vs_direct`` is the HTTP overhead ratio —
  host-load drift divides out, which is the only host-timed series
  worth gating on (the bench_diff discipline).
- ``--workers N``: spawns a real ``tools/serve.py`` fleet (separate
  processes + proxy + shared store) and drives it over the proxy.
  ``--kill-drill`` additionally SIGKILLs one worker mid-load and
  asserts the acceptance properties: **zero failed requests on the
  survivors** (proxy failover), and the **respawned worker rejoins the
  same rollout stage** from the shared store.

Every run also pins streaming correctness: for one seeded prompt the
SSE token sequence must equal the non-streamed result exactly, and the
first-token latency must beat the full-sequence latency by a real
margin (the reason per-token streaming exists).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

TYPED_CODES = (429, 503, 504)


# ------------------------------------------------------------ HTTP client
def _post(addr: str, path: str, doc: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        addr + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(addr: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(addr + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _sse_generate(addr: str, doc: dict, timeout: float = 60.0):
    """POST a streaming generate; returns (tokens, first_token_s,
    total_s, done_payload)."""
    req = urllib.request.Request(
        addr + "/v1/generate",
        data=json.dumps(dict(doc, stream=True)).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    toks, first_at, done = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ev = None
        for line in r:
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                ev = line[7:]
            elif line.startswith("data: "):
                data = json.loads(line[6:])
                if ev == "token":
                    if first_at is None:
                        first_at = time.perf_counter() - t0
                    toks.append(data["token"])
                elif ev == "done":
                    done = data
                elif ev == "error":
                    raise RuntimeError(f"stream error: {data}")
    return toks, first_at, time.perf_counter() - t0, done


# ------------------------------------------------------------- load model
class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.lat = {"classify": [], "generate": [], "stream": []}
        self.ok = 0
        self.typed = 0
        self.failed = 0
        self.conn_retries = 0
        self.failures = []

    def add(self, route: str, dt: float, outcome: str, detail=None):
        with self.lock:
            if outcome == "ok":
                self.ok += 1
                self.lat[route].append(dt)
            elif outcome == "typed":
                self.typed += 1
            else:
                self.failed += 1
                if len(self.failures) < 16:
                    self.failures.append(detail)


def _quantile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(q * len(xs)))
    return xs[i]


def run_load(addr: str, rng, qps: float, duration_s: float,
             max_new_cap: int = 24, prompt_len: int = 7,
             stats: "_Stats" = None) -> "_Stats":
    """Open-loop seeded load against ``addr`` for ``duration_s``:
    Poisson arrivals, 70/20/10 classify/generate/stream mix, generation
    lengths Pareto-tailed (clipped at ``max_new_cap``) — the heavy tail
    that makes continuous batching and shedding earn their keep."""
    stats = stats or _Stats()
    threads = []
    t_end = time.monotonic() + duration_s

    def one(kind: str, n_new: int, seed: int, x):
        t0 = time.perf_counter()
        attempts = 0
        while True:
            attempts += 1
            try:
                if kind == "classify":
                    _post(addr, "/v1/classify",
                          {"inputs": [x], "request_key": seed})
                elif kind == "generate":
                    _post(addr, "/v1/generate",
                          {"prompt": [1 + seed % 50] * prompt_len,
                           "max_new_tokens": n_new, "request_key": seed})
                else:
                    _sse_generate(addr, {
                        "prompt": [1 + seed % 50] * prompt_len,
                        "max_new_tokens": n_new, "request_key": seed})
                stats.add(kind, time.perf_counter() - t0, "ok")
                return
            except urllib.error.HTTPError as e:
                stats.add(kind, 0.0,
                          "typed" if e.code in TYPED_CODES else "failed",
                          detail=f"{kind}: HTTP {e.code}")
                return
            except Exception as e:
                # connection-level death (a SIGKILLed worker's in-flight
                # request, a reset mid-stream): standard client behavior
                # is ONE retry — it must land on a survivor through the
                # proxy's failover, which is exactly the property the
                # drill grades. Retries are counted, never hidden.
                if attempts <= 1:
                    with stats.lock:
                        stats.conn_retries += 1
                    continue
                stats.add(kind, 0.0, "failed", detail=f"{kind}: {e!r}")
                return

    i = 0
    while time.monotonic() < t_end:
        # Poisson arrivals; the request mix and tail are drawn from the
        # SAME seeded rng, so two runs issue identical traffic
        gap = rng.expovariate(qps) if qps > 0 else 0.0
        time.sleep(min(gap, 1.0))
        u = rng.random()
        kind = ("classify" if u < 0.7 else
                "generate" if u < 0.9 else "stream")
        # Pareto tail (alpha 1.5) clipped to the cache budget
        n_new = min(max_new_cap, max(2, int(2 * rng.paretovariate(1.5))))
        # all randomness drawn HERE (one thread, one seeded rng): two
        # runs with the same seed issue identical traffic
        x = [round(rng.uniform(0, 1), 6) for _ in range(4)]
        t = threading.Thread(target=one, args=(kind, n_new, i, x),
                             daemon=True)
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=60.0)
    return stats


def check_streaming(addr: str, prompt, n_new: int) -> dict:
    """The streaming acceptance pins: byte-identical tokens and a real
    first-token win."""
    doc = {"prompt": list(prompt), "max_new_tokens": n_new}
    _, plain = _post(addr, "/v1/generate", doc)
    t0 = time.perf_counter()
    _post(addr, "/v1/generate", doc)      # timed non-stream run
    full_s = time.perf_counter() - t0
    toks, first_s, total_s, done = _sse_generate(addr, doc)
    return {
        "matches": toks == plain["tokens"] and done["tokens"] == toks,
        "n_tokens": len(toks),
        "first_token_ms": round(first_s * 1e3, 3) if first_s else None,
        "full_ms": round(full_s * 1e3, 3),
        "stream_total_ms": round(total_s * 1e3, 3),
        "first_token_speedup": (round(full_s / first_s, 3)
                                if first_s and first_s > 0 else None),
    }


# ----------------------------------------------------------- in-process AB
def run_inproc(args, rng) -> dict:
    """One in-process worker; interleaved HTTP-vs-direct classify
    windows give the drift-immune ``vs_direct`` ratio."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import serve as _serve

    reg, router, gen_router = _serve._build_demo(args.slots, True)
    from deeplearning4j_tpu.serving import FrontDoor
    fd = FrontDoor(router, gen_router, port=0,
                   max_inflight=args.max_inflight).start()
    addr = fd.get_address()
    try:
        stream = check_streaming(addr, [3, 1, 4, 1, 5, 9, 2], 12)
        stats = run_load(addr, rng, args.qps, args.duration_s, stats=None)
        # interleaved A/B: paired HTTP / direct windows, median of
        # per-pair ratios (bench.py's paired_window_median discipline)
        ratios = []
        x = np.asarray([[0.1, 0.2, 0.3, 0.4]], "f4")
        for pair in range(5):
            t0 = time.perf_counter()
            for i in range(16):
                _post(addr, "/v1/classify",
                      {"inputs": x.tolist(), "request_key": (pair, i)})
            http_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for i in range(16):
                router.output(x, request_key=(pair, i))
            direct_s = time.perf_counter() - t0
            if http_s > 0:
                ratios.append(direct_s / http_s)
        vs_direct = statistics.median(ratios) if ratios else None
        return _record(args, stats, stream, vs_direct=vs_direct,
                       workers=1, kill_drill=None)
    finally:
        fd.stop()
        reg.shutdown()


# --------------------------------------------------------------- fleet mode
def _fleet_store(state_dir):
    from deeplearning4j_tpu.serving.shared_state import SharedStore
    return SharedStore(state_dir)


def run_fleet(args, rng) -> dict:
    """Spawn a real tools/serve.py fleet, drive it over the proxy, and
    (``--kill-drill``) SIGKILL + respawn one worker mid-load."""
    state_dir = args.state_dir or f"/tmp/dl4j-http-load-{os.getpid()}"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", str(args.workers), "--port", "0",
         "--state-dir", state_dir, "--slots", str(args.slots)],
        stdout=subprocess.PIPE, text=True)
    store = _fleet_store(state_dir)
    try:
        # read until the FLEET line (workers' announce lines may share
        # the stream on older serve.py builds — never drive a worker
        # directly: the drill's "survivors lose nothing" property is
        # about the proxy)
        fleet = None
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        # wait until the fleet answers
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)
        stream = check_streaming(addr, [3, 1, 4, 1, 5, 9, 2], 12)
        # canary v2 with a fast shared policy: the fleet must advance it
        # to FULL on aggregated windows while under load
        _post(addr, "/admin/rollout", {
            "lane": "scoring", "candidate": "v2",
            "policy": {"window_seconds": max(0.5, args.duration_s / 10),
                       "window_min_requests": 4, "healthy_windows": 1,
                       "canary_fraction": 0.3, "ramp_fractions": [0.6]}})
        stats = _Stats()
        load = threading.Thread(
            target=run_load,
            args=(addr, rng, args.qps, args.duration_s),
            kwargs={"stats": stats}, daemon=True)
        load.start()
        kill_drill = None
        if args.kill_drill:
            kill_drill = _kill_drill(store, addr, args)
        load.join(timeout=args.duration_s + 120)
        doc = store.read()
        lane = (doc.get("lanes") or {}).get("scoring") or {}
        ro = lane.get("rollout") or {}
        rollout = {"final_stage": ro.get("stage"),
                   "primary": lane.get("primary"),
                   "history": [
                       {k: e.get(k) for k in ("lane", "from", "to")}
                       for e in doc.get("history", [])]}
        return _record(args, stats, stream, vs_direct=None,
                       workers=args.workers, kill_drill=kill_drill,
                       rollout=rollout)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _kill_drill(store, addr: str, args) -> dict:
    """SIGKILL one non-leader worker mid-load; wait for the parent to
    respawn it; report the rejoin evidence. The zero-failed-on-survivors
    assertion lands in the final record (stats.failed)."""
    time.sleep(max(1.0, args.duration_s * 0.3))
    doc = store.read()
    workers = doc.get("workers") or {}
    victims = sorted(workers)[1:] or sorted(workers)  # spare the leader
    victim = victims[-1]
    old_pid = int(workers[victim]["pid"])
    stage_before = (((doc.get("lanes") or {}).get("scoring") or {})
                    .get("rollout") or {}).get("stage")
    os.kill(old_pid, signal.SIGKILL)
    killed_at = time.time()
    respawned = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        rec = (store.read().get("workers") or {}).get(victim) or {}
        if (int(rec.get("pid", old_pid)) != old_pid
                and float(rec.get("heartbeat", 0)) > killed_at):
            respawned = rec
            break
        time.sleep(0.5)
    doc = store.read()
    stage_after = (((doc.get("lanes") or {}).get("scoring") or {})
                   .get("rollout") or {}).get("stage")
    rejoined_view = None
    if respawned and respawned.get("port"):
        try:
            _, snap = _get(f"http://127.0.0.1:{respawned['port']}",
                           "/debug/frontdoor")
            sh = snap.get("shared") or {}
            rollout = ((sh.get("lanes") or {}).get("scoring")
                       or {}).get("rollout") or {}
            rejoined_view = rollout.get("stage")
        except Exception as e:
            rejoined_view = f"unreachable: {e!r}"
    return {
        "victim": victim,
        "old_pid": old_pid,
        "respawned": bool(respawned),
        "respawned_pid": int(respawned["pid"]) if respawned else None,
        "stage_at_kill": stage_before,
        "stage_after_respawn": stage_after,
        "respawned_worker_sees_stage": rejoined_view,
        # the stage the respawned worker reports must be the fleet's —
        # "rejoins the same rollout stage"
        "rejoined_same_stage": (rejoined_view == stage_after
                                if respawned else False),
    }


# ----------------------------------------------------------------- record
def _record(args, stats: "_Stats", stream: dict, vs_direct, workers,
            kill_drill, rollout=None) -> dict:
    from deeplearning4j_tpu.observability.slo import _grade
    total = stats.ok + stats.typed + stats.failed
    all_lat = [v for xs in stats.lat.values() for v in xs]
    p50 = _quantile(all_lat, 0.50)
    p99 = _quantile(all_lat, 0.99)
    goodput = stats.ok / args.duration_s if args.duration_s > 0 else None
    shed_ratio = stats.typed / total if total else 0.0
    error_ratio = stats.failed / total if total else 0.0
    slo = {
        "p99": _grade(p99 or 0.0, args.p99_degraded_s, args.p99_failing_s),
        "error_ratio": _grade(error_ratio, 0.01, 0.05),
        "shed_ratio": _grade(shed_ratio, 0.2, 0.5),
    }
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    return {
        "metric": "http_serve",
        "platform": platform,
        "value": goodput,
        "unit": "ok_requests_per_s",
        "goodput": goodput,
        "vs_direct": vs_direct,
        "ratio_method": "paired_window_median" if vs_direct else None,
        "requests": total,
        "ok": stats.ok,
        "typed": stats.typed,
        "failed": stats.failed,
        "conn_retries": stats.conn_retries,
        "failures": stats.failures,
        "p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "shed_ratio": round(shed_ratio, 4),
        "error_ratio": round(error_ratio, 4),
        "slo": slo,
        "stream": stream,
        "rollout": rollout,
        "kill_drill": kill_drill,
        "workers": workers,
        "qps": args.qps,
        "duration_s": args.duration_s,
        "seed": args.seed,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qps", type=float, default=20.0)
    ap.add_argument("--duration-s", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workers", type=int, default=0,
                    help="0 = in-process single worker; N = real fleet "
                         "via tools/serve.py")
    ap.add_argument("--kill-drill", action="store_true",
                    help="SIGKILL one worker mid-load (needs "
                         "--workers >= 2)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-inflight", type=int, default=64)
    ap.add_argument("--state-dir", default=None)
    ap.add_argument("--p99-degraded-s", type=float, default=2.0)
    ap.add_argument("--p99-failing-s", type=float, default=10.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.kill_drill and args.workers < 2:
        ap.error("--kill-drill needs --workers >= 2")
    import random
    rng = random.Random(args.seed)
    rec = (run_fleet(args, rng) if args.workers
           else run_inproc(args, rng))
    line = json.dumps(rec)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    ok = (rec["failed"] == 0 and rec["stream"]["matches"]
          and (rec["kill_drill"] is None
               or (rec["kill_drill"]["respawned"]
                   and rec["kill_drill"]["rejoined_same_stage"])))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
