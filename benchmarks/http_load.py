#!/usr/bin/env python
"""HTTP front-door load generator: heavy-tailed traffic, SLO grading,
and the kill/respawn drill.

Drives the REAL wire surface (``serving/frontdoor.py``) with a seeded
open-loop load: Poisson arrivals at ``--qps`` with a heavy-tailed
request mix — mostly cheap classifies, a Pareto-tailed minority of
multi-token generations, a slice of SSE streams — because production
traffic is never uniform and the tail is what kills SLOs. Grades the
run with the SLO machinery (p50/p99 per route, goodput, shed/error
ratios via the PR-3 ``_grade``) and emits ONE JSON line
(``metric: http_serve``) the driver archives as ``SERVE_r*.json`` for
``tools/bench_diff.py``'s sustained-only trajectory.

Two modes:

- **in-process** (default): one worker in this process; the classify
  goodput is also measured DIRECT (in-process ``router.output``)
  interleaved A/B-style, so ``vs_direct`` is the HTTP overhead ratio —
  host-load drift divides out, which is the only host-timed series
  worth gating on (the bench_diff discipline).
- ``--workers N``: spawns a real ``tools/serve.py`` fleet (separate
  processes + proxy + shared store) and drives it over the proxy.
  ``--kill-drill`` additionally SIGKILLs one worker mid-load and
  asserts the acceptance properties: **zero failed requests on the
  survivors** (proxy failover), and the **respawned worker rejoins the
  same rollout stage** from the shared store.
- ``--tenants "a:2,b:1"``: the multi-tenant QoS flooding drill
  (in-process): the named weighted victim tenants run the SAME seeded
  load in two phases — alone (baseline), then alongside one flooding
  tenant at ``--flood-factor`` x its request-rate quota. Emits ONE
  JSON line (``metric: qos_drill``) the driver archives as
  ``QOS_r*.json``: per-victim goodput/p99 ratios (same-run, so host
  drift divides out), flooder shed counts, and the acceptance verdicts
  (victim goodput >= 90% of baseline, p99 within 2x, flooder shed at
  the door with Retry-After).

- ``--session-failover``: the graded exactly-once streaming drill
  (archives ``SESS_r*.json``): a 2-worker fleet under
  ``generation.step`` crash + ``generation.adopt`` faults, one worker
  SIGKILLed with every SSE stream mid-flight — 100% of streams must
  complete via survivor session adoption with gapless/duplicate-free
  ``id:`` sequences and greedy tokens byte-identical to an undisturbed
  in-process run (resume latency reported, never gated).

Every run also pins streaming correctness: for one seeded prompt the
SSE token sequence must equal the non-streamed result exactly, and the
first-token latency must beat the full-sequence latency by a real
margin (the reason per-token streaming exists).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import statistics
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

TYPED_CODES = (429, 503, 504)


# ------------------------------------------------------------ HTTP client
def _post(addr: str, path: str, doc: dict, timeout: float = 30.0,
          tenant: str = None, idem_key: str = None):
    headers = {"Content-Type": "application/json"}
    if tenant is not None:
        headers["X-Dl4j-Tenant"] = tenant
    if idem_key is not None:
        headers["X-Dl4j-Idempotency-Key"] = idem_key
    req = urllib.request.Request(
        addr + path, data=json.dumps(doc).encode(), headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(addr: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(addr + path, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _sse_generate(addr: str, doc: dict, timeout: float = 60.0,
                  idem_key: str = None):
    """POST a streaming generate; returns (tokens, first_token_s,
    total_s, done_payload)."""
    headers = {"Content-Type": "application/json"}
    if idem_key is not None:
        headers["X-Dl4j-Idempotency-Key"] = idem_key
    req = urllib.request.Request(
        addr + "/v1/generate",
        data=json.dumps(dict(doc, stream=True)).encode(),
        headers=headers)
    t0 = time.perf_counter()
    toks, first_at, done = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ev = None
        for line in r:
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                ev = line[7:]
            elif line.startswith("data: "):
                data = json.loads(line[6:])
                if ev == "token":
                    if first_at is None:
                        first_at = time.perf_counter() - t0
                    toks.append(data["token"])
                elif ev == "done":
                    done = data
                elif ev == "error":
                    raise RuntimeError(f"stream error: {data}")
    return toks, first_at, time.perf_counter() - t0, done


# ------------------------------------------------------------- load model
class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.lat = {"classify": [], "generate": [], "stream": []}
        self.ok = 0
        self.typed = 0
        self.failed = 0
        self.conn_retries = 0
        self.failures = []

    def add(self, route: str, dt: float, outcome: str, detail=None):
        with self.lock:
            if outcome == "ok":
                self.ok += 1
                self.lat[route].append(dt)
            elif outcome == "typed":
                self.typed += 1
            else:
                self.failed += 1
                if len(self.failures) < 16:
                    self.failures.append(detail)


def _quantile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(q * len(xs)))
    return xs[i]


def run_load(addr: str, rng, qps: float, duration_s: float,
             max_new_cap: int = 24, prompt_len: int = 7,
             stats: "_Stats" = None) -> "_Stats":
    """Open-loop seeded load against ``addr`` for ``duration_s``:
    Poisson arrivals, 70/20/10 classify/generate/stream mix, generation
    lengths Pareto-tailed (clipped at ``max_new_cap``) — the heavy tail
    that makes continuous batching and shedding earn their keep."""
    stats = stats or _Stats()
    threads = []
    t_end = time.monotonic() + duration_s

    def one(kind: str, n_new: int, seed: int, x):
        t0 = time.perf_counter()
        attempts = 0
        while True:
            attempts += 1
            try:
                if kind == "classify":
                    _post(addr, "/v1/classify",
                          {"inputs": [x], "request_key": seed})
                elif kind == "generate":
                    _post(addr, "/v1/generate",
                          {"prompt": [1 + seed % 50] * prompt_len,
                           "max_new_tokens": n_new, "request_key": seed})
                else:
                    _sse_generate(addr, {
                        "prompt": [1 + seed % 50] * prompt_len,
                        "max_new_tokens": n_new, "request_key": seed})
                stats.add(kind, time.perf_counter() - t0, "ok")
                return
            except urllib.error.HTTPError as e:
                stats.add(kind, 0.0,
                          "typed" if e.code in TYPED_CODES else "failed",
                          detail=f"{kind}: HTTP {e.code}")
                return
            except Exception as e:
                # connection-level death (a SIGKILLed worker's in-flight
                # request, a reset mid-stream): standard client behavior
                # is ONE retry — it must land on a survivor through the
                # proxy's failover, which is exactly the property the
                # drill grades. Retries are counted, never hidden.
                if attempts <= 1:
                    with stats.lock:
                        stats.conn_retries += 1
                    continue
                stats.add(kind, 0.0, "failed", detail=f"{kind}: {e!r}")
                return

    i = 0
    while time.monotonic() < t_end:
        # Poisson arrivals; the request mix and tail are drawn from the
        # SAME seeded rng, so two runs issue identical traffic
        gap = rng.expovariate(qps) if qps > 0 else 0.0
        time.sleep(min(gap, 1.0))
        u = rng.random()
        kind = ("classify" if u < 0.7 else
                "generate" if u < 0.9 else "stream")
        # Pareto tail (alpha 1.5) clipped to the cache budget
        n_new = min(max_new_cap, max(2, int(2 * rng.paretovariate(1.5))))
        # all randomness drawn HERE (one thread, one seeded rng): two
        # runs with the same seed issue identical traffic
        x = [round(rng.uniform(0, 1), 6) for _ in range(4)]
        t = threading.Thread(target=one, args=(kind, n_new, i, x),
                             daemon=True)
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=60.0)
    return stats


def check_streaming(addr: str, prompt, n_new: int) -> dict:
    """The streaming acceptance pins: byte-identical tokens and a real
    first-token win."""
    doc = {"prompt": list(prompt), "max_new_tokens": n_new}
    _, plain = _post(addr, "/v1/generate", doc)
    t0 = time.perf_counter()
    _post(addr, "/v1/generate", doc)      # timed non-stream run
    full_s = time.perf_counter() - t0
    toks, first_s, total_s, done = _sse_generate(addr, doc)
    return {
        "matches": toks == plain["tokens"] and done["tokens"] == toks,
        "n_tokens": len(toks),
        "first_token_ms": round(first_s * 1e3, 3) if first_s else None,
        "full_ms": round(full_s * 1e3, 3),
        "stream_total_ms": round(total_s * 1e3, 3),
        "first_token_speedup": (round(full_s / first_s, 3)
                                if first_s and first_s > 0 else None),
    }


# ----------------------------------------------------------- in-process AB
def run_inproc(args, rng) -> dict:
    """One in-process worker; interleaved HTTP-vs-direct classify
    windows give the drift-immune ``vs_direct`` ratio."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import serve as _serve

    reg, router, gen_router = _serve._build_demo(args.slots, True)
    from deeplearning4j_tpu.serving import FrontDoor
    fd = FrontDoor(router, gen_router, port=0,
                   max_inflight=args.max_inflight).start()
    addr = fd.get_address()
    try:
        stream = check_streaming(addr, [3, 1, 4, 1, 5, 9, 2], 12)
        stats = run_load(addr, rng, args.qps, args.duration_s, stats=None)
        # interleaved A/B: paired HTTP / direct windows, median of
        # per-pair ratios (bench.py's paired_window_median discipline)
        ratios = []
        x = np.asarray([[0.1, 0.2, 0.3, 0.4]], "f4")
        for pair in range(5):
            t0 = time.perf_counter()
            for i in range(16):
                _post(addr, "/v1/classify",
                      {"inputs": x.tolist(), "request_key": (pair, i)})
            http_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            for i in range(16):
                router.output(x, request_key=(pair, i))
            direct_s = time.perf_counter() - t0
            if http_s > 0:
                ratios.append(direct_s / http_s)
        vs_direct = statistics.median(ratios) if ratios else None
        return _record(args, stats, stream, vs_direct=vs_direct,
                       workers=1, kill_drill=None)
    finally:
        fd.stop()
        reg.shutdown()


# ----------------------------------------------------------- QoS drill mode
def _parse_tenants(spec: str):
    """``name:weight,name:weight`` → ordered (name, weight) list."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        out.append((name.strip(), float(w) if w else 1.0))
    if not out:
        raise ValueError(f"no tenants in spec {spec!r}")
    return out


def _tenant_load(addr: str, seed: int, tenant: str, qps: float,
                 duration_s: float, stats: "_Stats"):
    """One tenant's open-loop seeded classify stream (its own rng, so
    the SAME traffic is issued in the baseline and flood phases)."""
    import random
    rng = random.Random(seed)
    threads = []
    t_end = time.monotonic() + duration_s

    def one(x, key):
        t0 = time.perf_counter()
        try:
            _post(addr, "/v1/classify",
                  {"inputs": [x], "request_key": key}, tenant=tenant)
            stats.add("classify", time.perf_counter() - t0, "ok")
        except urllib.error.HTTPError as e:
            stats.add("classify", 0.0,
                      "typed" if e.code in TYPED_CODES else "failed",
                      detail=f"{tenant}: HTTP {e.code}")
        except Exception as e:
            stats.add("classify", 0.0, "failed",
                      detail=f"{tenant}: {e!r}")

    i = 0
    while time.monotonic() < t_end:
        time.sleep(min(rng.expovariate(qps) if qps > 0 else 0.0, 1.0))
        x = [round(rng.uniform(0, 1), 6) for _ in range(4)]
        t = threading.Thread(target=one, args=(x, (tenant, i)),
                             daemon=True)
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=60.0)


def run_qos_drill(args, rng) -> dict:
    """The multi-tenant flooding drill (in-process worker): N weighted
    victim tenants at a steady per-tenant QPS, one flooding tenant at
    ``--flood-factor`` x its request-rate quota. Two phases with the
    SAME seeded victim traffic — (A) victims alone (the no-flood
    baseline), (B) victims + flooder — so each victim's goodput/p99
    ratio is a same-run interleaved comparison and host drift divides
    out. Acceptance: every victim's goodput holds >= 90% of its
    baseline and its p99 stays within 2x, while the flooder is shed
    (429 + Retry-After) at the door."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import serve as _serve

    from deeplearning4j_tpu.resilience import qos
    from deeplearning4j_tpu.serving import FrontDoor

    victims = _parse_tenants(args.tenants)
    flooder = args.flooder
    treg = qos.global_tenants()
    policies = {name: qos.TenantPolicy(name, weight=w)
                for name, w in victims}
    policies[flooder] = qos.TenantPolicy(
        flooder, weight=1.0, request_rate=args.flooder_quota_qps,
        request_burst=max(2.0, args.flooder_quota_qps))
    treg.configure(policies)
    reg, router, gen_router = _serve._build_demo(args.slots, False)
    fd = FrontDoor(router, gen_router, port=0,
                   max_inflight=args.max_inflight).start()
    addr = fd.get_address()
    phase_s = args.duration_s / 2

    def run_phase(phase: str, with_flood: bool):
        stats = {name: _Stats() for name, _ in victims}
        threads = [threading.Thread(
            target=_tenant_load,
            args=(addr, args.seed + 1000 * k, name, args.victim_qps,
                  phase_s, stats[name]), daemon=True)
            for k, (name, _) in enumerate(victims)]
        flood_stats = _Stats()
        if with_flood:
            threads.append(threading.Thread(
                target=_tenant_load,
                args=(addr, args.seed + 777, flooder,
                      args.flood_factor * args.flooder_quota_qps,
                      phase_s, flood_stats), daemon=True))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=phase_s + 120)
        return stats, flood_stats

    try:
        baseline, _ = run_phase("baseline", with_flood=False)
        flood, flood_stats = run_phase("flood", with_flood=True)
    finally:
        fd.stop()
        reg.shutdown()

    per_tenant = {}
    goodput_ratios, p99_ratios = [], []
    for name, w in victims:
        b, f = baseline[name], flood[name]
        b_good = b.ok / phase_s
        f_good = f.ok / phase_s
        b_p99 = _quantile(b.lat["classify"], 0.99)
        f_p99 = _quantile(f.lat["classify"], 0.99)
        g_ratio = (f_good / b_good) if b_good else None
        p_ratio = (f_p99 / b_p99) if b_p99 and f_p99 else None
        if g_ratio is not None:
            goodput_ratios.append(g_ratio)
        if p_ratio is not None:
            p99_ratios.append(p_ratio)
        per_tenant[name] = {
            "weight": w,
            "baseline_goodput": round(b_good, 3),
            "flood_goodput": round(f_good, 3),
            "goodput_ratio": (round(g_ratio, 4)
                              if g_ratio is not None else None),
            "baseline_p99_ms": (round(b_p99 * 1e3, 3) if b_p99 else None),
            "flood_p99_ms": (round(f_p99 * 1e3, 3) if f_p99 else None),
            "p99_ratio": (round(p_ratio, 4)
                          if p_ratio is not None else None),
            "typed": f.typed, "failed": f.failed,
        }
    victim_goodput_ratio = min(goodput_ratios) if goodput_ratios else None
    victim_p99_ratio = max(p99_ratios) if p99_ratios else None
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    snap = treg.snapshot()["tenants"].get(flooder, {})
    return {
        "metric": "qos_drill",
        "platform": platform,
        "value": victim_goodput_ratio,
        "unit": "victim_goodput_ratio",
        "ratio_method": "same_run_baseline_vs_flood",
        "victim_goodput_ratio": victim_goodput_ratio,
        "victim_p99_ratio": victim_p99_ratio,
        "victims": per_tenant,
        "flooder": flooder,
        "flooder_quota_qps": args.flooder_quota_qps,
        "flood_factor": args.flood_factor,
        "flooder_sent": (flood_stats.ok + flood_stats.typed
                         + flood_stats.failed),
        "flooder_ok": flood_stats.ok,
        "flooder_shed": flood_stats.typed,
        "flooder_failed": flood_stats.failed,
        "flooder_shed_counter": snap.get("shed"),
        "goodput_holds": (victim_goodput_ratio is not None
                          and victim_goodput_ratio >= 0.9),
        "p99_holds": (victim_p99_ratio is not None
                      and victim_p99_ratio <= 2.0),
        "victim_qps": args.victim_qps,
        "duration_s": args.duration_s,
        "seed": args.seed,
    }


# --------------------------------------------------------------- fleet mode
def _fleet_store(state_dir):
    from deeplearning4j_tpu.serving.shared_state import SharedStore
    return SharedStore(state_dir)


def run_fleet(args, rng) -> dict:
    """Spawn a real tools/serve.py fleet, drive it over the proxy, and
    (``--kill-drill``) SIGKILL + respawn one worker mid-load."""
    state_dir = args.state_dir or f"/tmp/dl4j-http-load-{os.getpid()}"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", str(args.workers), "--port", "0",
         "--state-dir", state_dir, "--slots", str(args.slots)],
        stdout=subprocess.PIPE, text=True)
    store = _fleet_store(state_dir)
    try:
        # read until the FLEET line (workers' announce lines may share
        # the stream on older serve.py builds — never drive a worker
        # directly: the drill's "survivors lose nothing" property is
        # about the proxy)
        fleet = None
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        # wait until the fleet answers
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)
        stream = check_streaming(addr, [3, 1, 4, 1, 5, 9, 2], 12)
        # canary v2 with a fast shared policy: the fleet must advance it
        # to FULL on aggregated windows while under load
        _post(addr, "/admin/rollout", {
            "lane": "scoring", "candidate": "v2",
            "policy": {"window_seconds": max(0.5, args.duration_s / 10),
                       "window_min_requests": 4, "healthy_windows": 1,
                       "canary_fraction": 0.3, "ramp_fractions": [0.6]}})
        stats = _Stats()
        load = threading.Thread(
            target=run_load,
            args=(addr, rng, args.qps, args.duration_s),
            kwargs={"stats": stats}, daemon=True)
        load.start()
        kill_drill = None
        if args.kill_drill:
            kill_drill = _kill_drill(store, addr, args)
        load.join(timeout=args.duration_s + 120)
        doc = store.read()
        lane = (doc.get("lanes") or {}).get("scoring") or {}
        ro = lane.get("rollout") or {}
        rollout = {"final_stage": ro.get("stage"),
                   "primary": lane.get("primary"),
                   "history": [
                       {k: e.get(k) for k in ("lane", "from", "to")}
                       for e in doc.get("history", [])]}
        return _record(args, stats, stream, vs_direct=None,
                       workers=args.workers, kill_drill=kill_drill,
                       rollout=rollout)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _kill_drill(store, addr: str, args) -> dict:
    """SIGKILL one non-leader worker mid-load; wait for the parent to
    respawn it; report the rejoin evidence. The zero-failed-on-survivors
    assertion lands in the final record (stats.failed)."""
    time.sleep(max(1.0, args.duration_s * 0.3))
    doc = store.read()
    workers = doc.get("workers") or {}
    victims = sorted(workers)[1:] or sorted(workers)  # spare the leader
    victim = victims[-1]
    old_pid = int(workers[victim]["pid"])
    stage_before = (((doc.get("lanes") or {}).get("scoring") or {})
                    .get("rollout") or {}).get("stage")
    os.kill(old_pid, signal.SIGKILL)
    killed_at = time.time()
    respawned = None
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        rec = (store.read().get("workers") or {}).get(victim) or {}
        if (int(rec.get("pid", old_pid)) != old_pid
                and float(rec.get("heartbeat", 0)) > killed_at):
            respawned = rec
            break
        time.sleep(0.5)
    doc = store.read()
    stage_after = (((doc.get("lanes") or {}).get("scoring") or {})
                   .get("rollout") or {}).get("stage")
    rejoined_view = None
    if respawned and respawned.get("port"):
        try:
            _, snap = _get(f"http://127.0.0.1:{respawned['port']}",
                           "/debug/frontdoor")
            sh = snap.get("shared") or {}
            rollout = ((sh.get("lanes") or {}).get("scoring")
                       or {}).get("rollout") or {}
            rejoined_view = rollout.get("stage")
        except Exception as e:
            rejoined_view = f"unreachable: {e!r}"
    return {
        "victim": victim,
        "old_pid": old_pid,
        "respawned": bool(respawned),
        "respawned_pid": int(respawned["pid"]) if respawned else None,
        "stage_at_kill": stage_before,
        "stage_after_respawn": stage_after,
        "respawned_worker_sees_stage": rejoined_view,
        # the stage the respawned worker reports must be the fleet's —
        # "rejoins the same rollout stage"
        "rejoined_same_stage": (rejoined_view == stage_after
                                if respawned else False),
    }


# ----------------------------------------------------------- fleet chaos
_STAGE_RANK = {"canary": 1, "ramp": 2, "full": 3}


def _chaos_load(addr: str, rng, qps: float, duration_s: float,
                stats: "_Stats", prompt_len: int = 7,
                max_new_cap: int = 16):
    """The fleet-chaos load: like :func:`run_load` but EVERY request
    carries a unique idempotency key and a connection-level death gets
    ONE retry **with the same key** — through the proxy's failover the
    retry lands on a survivor and the worker-side journal guarantees it
    replays rather than re-executes. The drill audits exactly that."""
    threads = []
    t_end = time.monotonic() + duration_s

    def one(kind: str, n_new: int, seed: int, x):
        key = f"fc-{seed}"
        t0 = time.perf_counter()
        attempts = 0
        while True:
            attempts += 1
            try:
                if kind == "classify":
                    _post(addr, "/v1/classify",
                          {"inputs": [x], "request_key": seed},
                          timeout=30.0, idem_key=key)
                else:
                    _post(addr, "/v1/generate",
                          {"prompt": [1 + seed % 50] * prompt_len,
                           "max_new_tokens": n_new, "request_key": seed},
                          timeout=30.0, idem_key=key)
                stats.add(kind, time.perf_counter() - t0, "ok")
                return
            except urllib.error.HTTPError as e:
                stats.add(kind, 0.0,
                          "typed" if e.code in TYPED_CODES else "failed",
                          detail=f"{kind}: HTTP {e.code}")
                return
            except Exception as e:
                if attempts <= 1:
                    with stats.lock:
                        stats.conn_retries += 1
                    continue
                stats.add(kind, 0.0, "failed", detail=f"{kind}: {e!r}")
                return

    i = 0
    while time.monotonic() < t_end:
        gap = rng.expovariate(qps) if qps > 0 else 0.0
        time.sleep(min(gap, 1.0))
        u = rng.random()
        kind = "classify" if u < 0.7 else "generate"
        n_new = min(max_new_cap, max(2, int(2 * rng.paretovariate(1.5))))
        x = [round(rng.uniform(0, 1), 6) for _ in range(4)]
        t = threading.Thread(target=one, args=(kind, n_new, i, x),
                             daemon=True)
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=90.0)


class _StageSampler:
    """Polls the shared store: the rollout stage sequence (must never
    move backward) and the leader (worker, term) sequence (terms must be
    strictly monotonic, and every history event's term non-decreasing)."""

    def __init__(self, store):
        self._store = store
        self.stages = []
        self.leaders = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                doc = self._store.read()
            except Exception:
                self._stop.wait(0.2)
                continue
            lane = (doc.get("lanes") or {}).get("scoring") or {}
            stage = (lane.get("rollout") or {}).get("stage")
            if stage is not None and (not self.stages
                                      or self.stages[-1] != stage):
                self.stages.append(stage)
            led = doc.get("leader") or {}
            cur = (led.get("worker"), int(led.get("term", 0)))
            if led and (not self.leaders or self.leaders[-1] != cur):
                self.leaders.append(cur)
            self._stop.wait(0.2)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    def stage_regressed(self) -> bool:
        ranks = [_STAGE_RANK.get(s) for s in self.stages]
        if "rolled_back" in self.stages:
            return True          # nothing in this drill should roll back
        ranks = [r for r in ranks if r is not None]
        return any(b < a for a, b in zip(ranks, ranks[1:]))

    def terms_monotonic(self) -> bool:
        """STRICTLY increasing across leadership changes: two leaders
        sharing one term (the exact fence failure this drill exists to
        catch) must fail, so ``>=`` would be wrong here. A corruption
        rebuild's ``{"worker": None}`` carry-forward record is term
        CONTINUITY (no one leads), not a transition — filtered out."""
        seq = []
        for w, t in self.leaders:
            if w is None:
                continue
            if not seq or seq[-1] != (w, t):
                seq.append((w, t))
        terms = [t for _, t in seq]
        return all(b > a for a, b in zip(terms, terms[1:]))


def run_fleet_chaos(args, rng) -> dict:
    """The graded fleet chaos drill: a 3-worker fleet under seeded load
    while the drill (1) SIGSTOPs the LEADER past the worker TTL then
    SIGCONTs it — the lease must move with a term bump, the woken
    ex-leader must demote at write time, and no stale-term write may
    land; (2) SIGKILLs a non-leader worker mid-stream — the proxy fails
    over with the idempotency key, the parent respawns it; (3) corrupts
    the store document once — it must be quarantined and rebuilt from
    the workers' mirrors; (4) injects store.read/store.write faults in
    every worker for the whole run. Graded: goodput >= 90%, ZERO
    duplicate executions (audited via the per-worker idempotency
    journals), leader terms strictly monotonic, rollout stage never
    regresses."""
    state_dir = args.state_dir or f"/tmp/dl4j-fleet-chaos-{os.getpid()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the whole run breathes injected store faults (seeded per process)
    env["DL4J_TPU_FAULTS"] = args.fleet_faults
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", "3", "--port", "0", "--state-dir", state_dir,
         "--slots", str(args.slots)],
        stdout=subprocess.PIPE, text=True, env=env)
    store = _fleet_store(state_dir)
    sampler = None
    try:
        fleet = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)
        # shared canary under load: its stage trajectory is one of the
        # graded invariants (forward-only). Retried: the workers run
        # with store faults armed, so the admin write itself may eat an
        # injected fault (500) a beat or two
        for _ in range(8):
            try:
                code, _body = _post(addr, "/admin/rollout", {
                    "lane": "scoring", "candidate": "v2",
                    "policy": {
                        "window_seconds": max(0.5, args.duration_s / 12),
                        "window_min_requests": 4, "healthy_windows": 1,
                        "canary_fraction": 0.3,
                        "ramp_fractions": [0.6]}})
                if code == 200:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        sampler = _StageSampler(store)
        stats = _Stats()
        load = threading.Thread(
            target=_chaos_load,
            args=(addr, rng, args.qps, args.duration_s, stats),
            daemon=True)
        load.start()

        chaos: dict = {"corruptions": 0}

        def run_chaos():
            d = args.duration_s
            # --- SIGSTOP the leader past TTL, then SIGCONT
            time.sleep(d * 0.2)
            doc = store.read()
            leader = ((doc.get("leader") or {}).get("worker")
                      or (min(doc.get("workers") or {"w0": 0})))
            pid = int(((doc.get("workers") or {}).get(leader) or {})
                      .get("pid", 0))
            chaos["paused_leader"] = leader
            if pid:
                os.kill(pid, signal.SIGSTOP)
                time.sleep(args.pause_s)
                os.kill(pid, signal.SIGCONT)
                chaos["pause_s"] = args.pause_s
            # --- SIGKILL a non-leader worker MID-STREAM: pin several
            # long SSE generations in flight first (round-robin puts
            # some on the victim); their connection-level deaths retry
            # with the SAME idempotency key through the proxy
            time.sleep(d * 0.15)

            def one_stream(k: int):
                key = f"fcs-{k}"
                t0 = time.perf_counter()
                for attempt in (1, 2):
                    try:
                        _, _, _, done = _sse_generate(
                            addr, {"prompt": [1 + k, 2, 3],
                                   "max_new_tokens": 40,
                                   "request_key": ("fcs", k)},
                            timeout=60.0, idem_key=key)
                        if done is None:
                            # killed mid-stream: connection-close SSE
                            # framing makes a dead worker look like a
                            # clean (truncated) end — no terminal event
                            # = a connection-level death, retry by key
                            raise OSError("stream truncated (no done "
                                          "event)")
                        stats.add("stream",
                                  time.perf_counter() - t0, "ok")
                        return
                    except urllib.error.HTTPError as e:
                        stats.add("stream", 0.0,
                                  "typed" if e.code in TYPED_CODES
                                  else "failed",
                                  detail=f"stream: HTTP {e.code}")
                        return
                    except Exception as e:
                        if attempt == 1:
                            with stats.lock:
                                stats.conn_retries += 1
                            continue
                        stats.add("stream", 0.0, "failed",
                                  detail=f"stream: {e!r}")
                        return

            streamers = [threading.Thread(target=one_stream, args=(k,),
                                          daemon=True)
                         for k in range(6)]
            for t in streamers:
                t.start()
            time.sleep(0.15)         # streams are mid-flight NOW
            doc = store.read()
            leader = (doc.get("leader") or {}).get("worker")
            victims = [w for w in sorted(doc.get("workers") or {})
                       if w != leader and w != chaos.get("paused_leader")]
            victim = (victims or [w for w in sorted(
                doc.get("workers") or {}) if w != leader])[-1]
            vpid = int(doc["workers"][victim]["pid"])
            chaos["killed_worker"] = victim
            chaos["killed_pid"] = vpid
            os.kill(vpid, signal.SIGKILL)
            for t in streamers:
                t.join(timeout=60.0)
            # --- corrupt the store document once (disk fault); retry
            # the scribble until a reader actually quarantined it (an
            # in-flight atomic writer may immediately replace garbage
            # that nobody ever read)
            time.sleep(d * 0.2)
            state_file = os.path.join(state_dir, "state.json")
            for _ in range(4):
                try:
                    with open(state_file, "w") as f:
                        f.write('{"rev": "garbage", "workers": [')
                except OSError:
                    break
                time.sleep(1.0)
                quarantined = [fn for fn in os.listdir(state_dir)
                               if fn.startswith("state.json.corrupt.")]
                if quarantined:
                    chaos["corruptions"] = len(quarantined)
                    break

        chaos_thread = threading.Thread(target=run_chaos, daemon=True)
        chaos_thread.start()
        load.join(timeout=args.duration_s + 180)
        chaos_thread.join(timeout=60)
        # settle: wait for the parent's respawn of the killed worker to
        # register (its demo deploys may still be warming when the load
        # window closes)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                rec_w = ((store.read().get("workers") or {})
                         .get(chaos.get("killed_worker")) or {})
            except Exception:
                rec_w = {}
            if (rec_w.get("port")
                    and int(rec_w.get("pid", 0)) != chaos.get("killed_pid")
                    and time.time() - float(rec_w.get("heartbeat", 0))
                    <= 3.0):
                break
            time.sleep(0.5)
        sampler.stop()
        # ---------------------------------------------------- the audit
        doc = store.read()
        _killed_rec = ((doc.get("workers") or {})
                       .get(chaos.get("killed_worker")) or {})
        respawned = bool(
            _killed_rec.get("port")
            and int(_killed_rec.get("pid", 0)) != chaos.get("killed_pid"))
        duplicate_execs = 0
        demotions = 0
        replays = 0
        rebuilds = 0
        per_worker = {}
        audited_all = True
        executed_on: dict = {}       # key -> live workers that executed it
        for w, rec in sorted((doc.get("workers") or {}).items()):
            port = rec.get("port")
            if not port:
                continue
            # the workers run with store.read faults armed — a single
            # fetch can 500 on an injected blip; retry before giving
            # up, and an UNAUDITED worker fails the verdict (its
            # journal could hide the duplicate the drill exists to
            # catch — 'unreachable' must never grade green)
            fl = err = None
            for _ in range(6):
                try:
                    _, fl = _get(f"http://127.0.0.1:{port}",
                                 "/debug/fleet", timeout=10.0)
                    break
                except Exception as e:
                    err = e
                    time.sleep(0.5)
            if fl is None:
                per_worker[w] = f"unreachable: {err!r}"
                audited_all = False
                continue
            idem = fl.get("idempotency") or {}
            duplicate_execs += int(idem.get("duplicate_executions", 0))
            replays += int(idem.get("replays", 0))
            for key, e in (idem.get("entries") or {}).items():
                if int(e.get("executions", 0)) > 0:
                    executed_on.setdefault(key, set()).add(w)
            for d_ in fl.get("frontdoors") or ():
                fence = ((d_.get("shared") or {}).get("fence") or {})
                demotions += int(fence.get("demotions", 0))
                rebuilds += int(fence.get("rebuilds", 0))
            per_worker[w] = {
                "journal_size": idem.get("size"),
                "duplicate_executions": idem.get(
                    "duplicate_executions"),
                "replays": idem.get("replays"),
            }
        # cross-worker half of the audit: one key executed in TWO live
        # journals is a duplicate the per-worker counts cannot see (the
        # killed worker's pre-death execution died with its journal and
        # is correctly not counted — nothing it charged survives)
        cross_dups = sum(len(ws) - 1 for ws in executed_on.values()
                         if len(ws) > 1)
        duplicate_execs += cross_dups
        history = doc.get("history") or []
        hist_terms = [e.get("term") for e in history
                      if e.get("term") is not None]
        terms_monotonic = (
            sampler.terms_monotonic()
            and all(b >= a for a, b in zip(hist_terms, hist_terms[1:])))
        stage_regressed = sampler.stage_regressed()
        total = stats.ok + stats.typed + stats.failed
        goodput_ratio = (stats.ok / total) if total else None
        all_lat = [v for xs in stats.lat.values() for v in xs]
        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        lane = (doc.get("lanes") or {}).get("scoring") or {}
        rec = {
            "metric": "fleet_chaos",
            "platform": platform,
            "value": goodput_ratio,
            "unit": "goodput_ratio",
            "goodput_ratio": (round(goodput_ratio, 4)
                              if goodput_ratio is not None else None),
            "requests": total,
            "ok": stats.ok,
            "typed": stats.typed,
            "failed": stats.failed,
            "conn_retries": stats.conn_retries,
            "failures": stats.failures,
            "p50_ms": (round(_quantile(all_lat, 0.5) * 1e3, 3)
                       if all_lat else None),
            "p99_ms": (round(_quantile(all_lat, 0.99) * 1e3, 3)
                       if all_lat else None),
            "duplicate_executions": duplicate_execs,
            "cross_worker_duplicates": cross_dups,
            "double_charges": duplicate_execs,
            "idempotent_replays": replays,
            "terms_monotonic": terms_monotonic,
            "leader_sequence": sampler.leaders,
            "history_terms": hist_terms,
            "demotions": demotions,
            "stage_regressed": stage_regressed,
            "stage_sequence": sampler.stages,
            "final_stage": (lane.get("rollout") or {}).get("stage"),
            "final_primary": lane.get("primary"),
            "corruptions": chaos.get("corruptions", 0),
            "rebuilds": rebuilds,
            "proxy": doc.get("proxy"),
            "paused_leader": chaos.get("paused_leader"),
            "pause_s": chaos.get("pause_s"),
            "killed_worker": chaos.get("killed_worker"),
            "respawned": respawned,
            "per_worker": per_worker,
            "fleet_faults": args.fleet_faults,
            "workers": 3,
            "qps": args.qps,
            "duration_s": args.duration_s,
            "seed": args.seed,
        }
        rec["audited_all_workers"] = audited_all
        rec["ok_verdict"] = bool(
            goodput_ratio is not None and goodput_ratio >= 0.90
            and duplicate_execs == 0 and audited_all
            and terms_monotonic and not stage_regressed
            and chaos.get("corruptions", 0) >= 1
            and demotions >= 1 and respawned)
        return rec
    finally:
        if sampler is not None:
            sampler.stop()
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def run_fleet_obs(args, rng) -> dict:
    """The graded fleet observability drill (archives OBSFLEET_r*.json):
    a 2-worker fleet behind the splice proxy with the fleet admin plane
    up.  Phase 1 issues classify requests carrying caller-supplied
    ``X-Dl4j-Trace-Id`` headers and checks the SAME id comes back on
    every response, and that the proxy's recent ``proxy_request`` spans
    carry a sent id (one trace id across proxy and worker).  Phase 2
    times ``/metrics/fleet`` scrapes (scrape p99, reported never gated)
    and checks every live worker appears as a ``worker="..."`` label
    (federation completeness).  Phase 3 SIGKILLs one worker: traced
    idempotent requests must keep echoing their ids through the
    failover replay, and ``/metrics/fleet`` must keep answering 200
    with partial data — never a 500 because one worker died.  Graded:
    trace coverage >= 0.95, federation completeness == 1.0, the
    single-trace check, and the partial scrape staying 200."""
    state_dir = args.state_dir or f"/tmp/dl4j-fleet-obs-{os.getpid()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FLEET_OBS", None)      # the drill grades the ON path
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", "2", "--port", "0", "--state-dir", state_dir,
         "--slots", str(args.slots), "--no-respawn"],
        stdout=subprocess.PIPE, text=True, env=env)
    store = _fleet_store(state_dir)
    try:
        fleet = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        admin = fleet.get("admin_address")
        if not admin:
            raise RuntimeError("fleet announce carried no admin_address "
                               "(is DL4J_TPU_FLEET_OBS off?)")
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)

        sent_ids: set = set()
        echoed = [0]
        attempted = [0]

        def traced_post(i: int, idem_key: str = None) -> bool:
            """One classify through the proxy with a caller-supplied
            trace id; True iff the response (ANY status — typed errors
            must carry the header too) echoed the SAME id back."""
            tid = f"{0xA0000000 + i:016x}"
            sent_ids.add(tid)
            attempted[0] += 1
            headers = {"Content-Type": "application/json",
                       "X-Dl4j-Trace-Id": tid}
            if idem_key is not None:
                headers["X-Dl4j-Idempotency-Key"] = idem_key
            req = urllib.request.Request(
                addr + "/v1/classify",
                data=json.dumps({
                    "inputs": [[round(rng.uniform(0, 1), 6)
                                for _ in range(4)]],
                    "request_key": i}).encode(),
                headers=headers)
            for attempt in (1, 2):
                try:
                    with urllib.request.urlopen(req, timeout=30.0) as r:
                        r.read()
                        got = r.headers.get("X-Dl4j-Trace-Id")
                    break
                except urllib.error.HTTPError as e:
                    got = e.headers.get("X-Dl4j-Trace-Id")
                    e.read()
                    break
                except Exception:
                    # connection-level death (the SIGKILLed worker):
                    # one retry — the replay must ride the proxy's
                    # failover AND still echo the id
                    if attempt == 2:
                        return False
            ok = got == tid
            if ok:
                echoed[0] += 1
            return ok

        # ---- phase 1: traced steady load + timed federation scrapes
        for i in range(args.obs_requests):
            traced_post(i)
        live = sorted(w for w, r in (store.read().get("workers")
                                     or {}).items()
                      if r.get("port")
                      and time.time() - float(r.get("heartbeat", 0))
                      <= 3.0)
        scrape_s = []
        completeness = 0.0
        label_re = re.compile(r'worker="([^"]+)"')
        for _ in range(max(8, args.obs_scrapes)):
            t0 = time.perf_counter()
            with urllib.request.urlopen(admin + "/metrics/fleet",
                                        timeout=10.0) as r:
                text = r.read().decode()
            scrape_s.append(time.perf_counter() - t0)
            seen = set(label_re.findall(text))
            if live:
                completeness = max(
                    completeness,
                    len([w for w in live if w in seen]) / len(live))
            time.sleep(0.05)
        # spans land in the ring on exit, AFTER the response bytes —
        # give the proxy a beat before reading its recent spans
        time.sleep(0.3)
        single_trace_ok = False
        try:
            _, dbg = _get(admin, "/debug/proxy", timeout=10.0)
            for sp in dbg.get("recent_proxy_spans") or ():
                if (sp.get("trace_id") in sent_ids
                        and (sp.get("attrs") or {}).get("worker")):
                    single_trace_ok = True
                    break
        except Exception:
            pass

        # ---- phase 3: SIGKILL one worker; traced replays + partial scrape
        doc = store.read()
        leader = (doc.get("leader") or {}).get("worker")
        victims = [w for w in sorted(doc.get("workers") or {})
                   if w != leader] or sorted(doc.get("workers") or {})
        victim = victims[-1]
        vpid = int(doc["workers"][victim]["pid"])
        survivors = [w for w in live if w != victim]
        os.kill(vpid, signal.SIGKILL)
        partial_codes = []
        scrape_errors_seen = False
        survivor_always = True
        t_end = time.monotonic() + 3.0
        while time.monotonic() < t_end:
            try:
                with urllib.request.urlopen(admin + "/metrics/fleet",
                                            timeout=10.0) as r:
                    text = r.read().decode()
                    partial_codes.append(r.status)
            except urllib.error.HTTPError as e:
                partial_codes.append(e.code)
                e.read()
                text = ""
            if "dl4j_fleet_scrape_errors_total" in text:
                scrape_errors_seen = True
            seen = set(label_re.findall(text))
            if survivors and not all(w in seen for w in survivors):
                survivor_always = False
            time.sleep(0.2)
        for i in range(args.obs_requests, args.obs_requests + 10):
            traced_post(i, idem_key=f"obs-{i}")
        partial_scrape_ok = bool(
            partial_codes and all(c == 200 for c in partial_codes)
            and survivor_always)
        trace_coverage = (echoed[0] / attempted[0]) if attempted[0] else 0.0
        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        rec = {
            "metric": "obsfleet_drill",
            "platform": platform,
            "value": round(trace_coverage, 4),
            "unit": "trace_coverage",
            "trace_coverage": round(trace_coverage, 4),
            "federation_completeness": round(completeness, 4),
            "scrape_p50_ms": (round(_quantile(scrape_s, 0.5) * 1e3, 3)
                              if scrape_s else None),
            "scrape_p99_ms": (round(_quantile(scrape_s, 0.99) * 1e3, 3)
                              if scrape_s else None),
            "single_trace_ok": single_trace_ok,
            "partial_scrape_ok": partial_scrape_ok,
            "partial_scrape_codes": partial_codes,
            "scrape_errors_seen": scrape_errors_seen,
            "traced_requests": attempted[0],
            "echoed": echoed[0],
            "live_workers": live,
            "killed_worker": victim,
            "workers": 2,
            "seed": args.seed,
        }
        rec["ok_verdict"] = bool(
            trace_coverage >= 0.95 and completeness == 1.0
            and partial_scrape_ok and single_trace_ok)
        return rec
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def run_trace_intel(args, rng) -> dict:
    """The graded trace-intelligence drill (archives TRACEQ_r*.json):
    a 2-worker fleet behind the splice proxy, trace store on with head
    sampling at 0.1 and the tail rule at p90.  Phase 1 sends boring
    classify traffic (the head-sample volume bound) and short generates
    (warming the per-endpoint tail windows).  Phase 2 sends requests
    that MUST be retained: bad-input 400s and tiny-deadline 504s (error
    rule) under caller-supplied trace ids, then long generates that
    overshoot the warmed p90 (latency-tail rule).  Each expected id is
    then assembled through the proxy admin's ``/debug/trace/<id>`` and
    must stitch proxy + worker spans into one waterfall (retention
    coverage and assembly completeness, both gated).  Phase 3 SIGKILLs
    one worker: fresh error requests ride the failover and must still
    retain + assemble from the survivor, old ids must answer 200
    (partial) or 404 — never a 5xx — and the boring head-sampled volume
    must stay bounded.  Assembly latency p99 is reported, never gated
    (host weather)."""
    state_dir = args.state_dir or f"/tmp/dl4j-trace-intel-{os.getpid()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_TRACE_SAMPLE="0.1", DL4J_TPU_TRACE_TAIL_Q="0.9")
    env.pop("DL4J_TPU_FLEET_OBS", None)     # the drill grades the ON path
    env.pop("DL4J_TPU_TRACE_STORE", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", "2", "--port", "0", "--state-dir", state_dir,
         "--slots", str(args.slots), "--no-respawn"],
        stdout=subprocess.PIPE, text=True, env=env)
    store = _fleet_store(state_dir)
    try:
        fleet = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        admin = fleet.get("admin_address")
        if not admin:
            raise RuntimeError("fleet announce carried no admin_address "
                               "(is DL4J_TPU_FLEET_OBS off?)")
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)

        def traced(path: str, doc: dict, tid: str, idem_key=None):
            """POST with a caller-supplied trace id; returns the HTTP
            status (connection death retries once — the failover path
            must still produce a retained trace)."""
            headers = {"Content-Type": "application/json",
                       "X-Dl4j-Trace-Id": tid}
            if idem_key is not None:
                headers["X-Dl4j-Idempotency-Key"] = idem_key
            req = urllib.request.Request(
                addr + path, data=json.dumps(doc).encode(),
                headers=headers)
            for attempt in (1, 2):
                try:
                    with urllib.request.urlopen(req, timeout=60.0) as r:
                        r.read()
                        return r.status
                except urllib.error.HTTPError as e:
                    e.read()
                    return e.code
                except Exception:
                    if attempt == 2:
                        return None
            return None

        assemble_s = []

        def assemble(tid: str):
            """GET the assembled waterfall through the proxy admin;
            returns (status, doc-or-None), timing every call."""
            t0 = time.perf_counter()
            try:
                code, doc = _get(admin, f"/debug/trace/{tid}",
                                 timeout=10.0)
            except urllib.error.HTTPError as e:
                code, doc = e.code, None
                e.read()
            assemble_s.append(time.perf_counter() - t0)
            return code, doc

        def stitched(doc) -> bool:
            """Does the assembled doc carry the proxy hop AND a serving
            worker's spans under one trace?"""
            if not doc:
                return False
            names = {s.get("name") for s in doc.get("waterfall") or ()}
            workers = {s.get("worker") for s in doc.get("waterfall") or ()}
            return ("proxy_request" in names and "http_request" in names
                    and len(workers) >= 2)

        # ---- phase 1: boring traffic (head bound) + tail-window warmup
        boring_ids = [f"{0xC0000000 + i:016x}" for i in range(40)]
        for i, tid in enumerate(boring_ids):
            traced("/v1/classify", {
                "inputs": [[round(rng.uniform(0, 1), 6)
                            for _ in range(4)]],
                "request_key": i}, tid)
        for i in range(40):          # short generates warm BOTH workers'
            traced("/v1/generate",   # /v1/generate tail windows past the
                   {"prompt": [1 + i % 50, 2, 3],   # 16-sample minimum
                    "max_new_tokens": 2, "request_key": 1000 + i},
                   f"{0xD0000000 + i:016x}")

        # ---- phase 2: requests the retention rules MUST keep
        error_ids = []
        for i in range(6):           # in-span 400s: bad input
            tid = f"{0xA0000000 + i:016x}"
            error_ids.append(tid)
            traced("/v1/classify", {"oops": 1, "request_key": 2000 + i},
                   tid)
        for i in range(6, 12):       # in-span 504s: unmeetable deadline
            tid = f"{0xA0000000 + i:016x}"
            error_ids.append(tid)
            traced("/v1/classify", {
                "inputs": [[0.1, 0.2, 0.3, 0.4]],
                "deadline_ms": 0.001, "request_key": 2000 + i}, tid)
        tail_ids = []
        for i in range(4):           # long generates overshoot the p90
            tid = f"{0xB0000000 + i:016x}"
            tail_ids.append(tid)
            traced("/v1/generate",
                   {"prompt": [1 + i, 2, 3], "max_new_tokens": 16,
                    "request_key": 3000 + i}, tid)
        time.sleep(0.3)              # spans land after response bytes

        # ---- retention + assembly over every expected id
        expected = error_ids + tail_ids
        retained_ok = assembled_ok = 0
        for tid in expected:
            code, doc = assemble(tid)
            if code == 200 and doc:
                retained_ok += 1
                if stitched(doc):
                    assembled_ok += 1
        retention_coverage = retained_ok / len(expected)
        assembly_completeness = (assembled_ok / retained_ok
                                 if retained_ok else 0.0)
        chrome_ok = False
        try:
            code, cdoc = _get(
                admin, f"/debug/trace/{expected[0]}?format=chrome",
                timeout=10.0)
            events = (cdoc.get("traceEvents")
                      if isinstance(cdoc, dict) else cdoc)
            chrome_ok = code == 200 and bool(events)
        except Exception:
            pass
        reasons_seen = set()
        try:
            code, rec_doc = _get(admin, "/debug/trace/recent?limit=200",
                                 timeout=10.0)
            for t in rec_doc.get("traces") or ():
                reasons_seen.add(t.get("reason"))
        except Exception:
            pass

        # ---- phase 3: SIGKILL one worker; retention must survive
        doc = store.read()
        leader = (doc.get("leader") or {}).get("worker")
        live = sorted(w for w, r in (doc.get("workers") or {}).items()
                      if r.get("port")
                      and time.time() - float(r.get("heartbeat", 0))
                      <= 3.0)
        victims = [w for w in sorted(doc.get("workers") or {})
                   if w != leader] or sorted(doc.get("workers") or {})
        victim = victims[-1]
        vpid = int(doc["workers"][victim]["pid"])
        os.kill(vpid, signal.SIGKILL)
        postkill_ids = []
        for i in range(6):           # fresh errors must ride failover
            tid = f"{0xE0000000 + i:016x}"
            postkill_ids.append(tid)
            traced("/v1/classify", {"oops": 1, "request_key": 4000 + i},
                   tid, idem_key=f"traceq-{i}")
        time.sleep(0.3)
        postkill_ok = 0
        for tid in postkill_ids:
            code, adoc = assemble(tid)
            if code == 200 and adoc:
                postkill_ok += 1
        postkill_coverage = postkill_ok / len(postkill_ids)
        # old ids: partial (200) or gone with the dead store (404) —
        # a dead worker must NEVER turn assembly into a 5xx
        partial_never_5xx = True
        for tid in expected[:6]:
            code, _doc2 = assemble(tid)
            if code >= 500:
                partial_never_5xx = False

        # ---- head-sampled volume stays bounded
        boring_retained = 0
        for tid in boring_ids:
            code, _doc3 = assemble(tid)
            if code == 200:
                boring_retained += 1
        head_fraction = boring_retained / len(boring_ids)
        head_bounded = head_fraction <= 0.5

        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        rec = {
            "metric": "traceq_drill",
            "platform": platform,
            "value": round(retention_coverage, 4),
            "unit": "retention_coverage",
            "retention_coverage": round(retention_coverage, 4),
            "assembly_completeness": round(assembly_completeness, 4),
            "assembly_p50_ms": (round(_quantile(assemble_s, 0.5) * 1e3, 3)
                                if assemble_s else None),
            "assembly_p99_ms": (round(_quantile(assemble_s, 0.99) * 1e3, 3)
                                if assemble_s else None),
            "postkill_coverage": round(postkill_coverage, 4),
            "partial_never_5xx": partial_never_5xx,
            "chrome_export_ok": chrome_ok,
            "reasons_seen": sorted(r for r in reasons_seen if r),
            "head_sample_fraction": round(head_fraction, 4),
            "head_bounded": head_bounded,
            "error_requests": len(error_ids),
            "tail_requests": len(tail_ids),
            "postkill_requests": len(postkill_ids),
            "live_workers": live,
            "killed_worker": victim,
            "workers": 2,
            "seed": args.seed,
        }
        rec["ok_verdict"] = bool(
            retention_coverage == 1.0 and assembly_completeness == 1.0
            and postkill_coverage == 1.0 and partial_never_5xx
            and head_bounded and chrome_ok
            and {"error", "latency_tail"} <= reasons_seen)
        return rec
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def run_watchtower(args, rng) -> dict:
    """The graded watchtower drill (archives WATCH_r*.json): a 2-worker
    fleet with the detector windows drill-scaled (fast 3 s / slow 10 s,
    hold 0.5 s, clear 3 s).  Phase 1 sends clean classify traffic long
    enough to warm every detector baseline and asserts ZERO firing
    alerts and zero alert-opened incidents (the false-positive gate).
    Phase 2 injects a mid-run regression — a sustained burst of
    unmeetable-deadline 504s — and polls ``/debug/alerts`` until the
    error-burn page fires (detection latency, gated against the
    ``--detect-budget-s`` window); the firing page must close the loop
    into EXACTLY ONE ``alert:``-reason incident (two detectors or two
    workers paging inside the cooldown coalesce) with the offending
    retained traces pinned as evidence.  Phase 3 stops the burst and
    polls until the alert walks firing → resolved (flap damping exits
    cleanly after recovery)."""
    state_dir = args.state_dir or f"/tmp/dl4j-watchtower-{os.getpid()}"
    pm_dir = os.path.join(state_dir, "postmortem")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_POSTMORTEM_DIR=pm_dir,
               DL4J_TPU_WATCHTOWER_INTERVAL_S="0.2",
               DL4J_TPU_TIMESERIES_INTERVAL_S="0.2",
               DL4J_TPU_WATCHTOWER_FAST_S="3.0",
               DL4J_TPU_WATCHTOWER_SLOW_S="10.0",
               DL4J_TPU_WATCHTOWER_HOLD_S="0.5",
               DL4J_TPU_WATCHTOWER_CLEAR_S="3.0",
               DL4J_TPU_WATCHTOWER_COOLDOWN_S="120.0",
               DL4J_TPU_FLEET_HEALTH_INTERVAL_S="0.5")
    env.pop("DL4J_TPU_WATCHTOWER", None)    # the drill grades the ON path
    env.pop("DL4J_TPU_FLEET_OBS", None)
    env.pop("DL4J_TPU_TRACE_STORE", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", "2", "--port", "0", "--state-dir", state_dir,
         "--slots", str(args.slots), "--no-respawn"],
        stdout=subprocess.PIPE, text=True, env=env)
    try:
        fleet = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        admin = fleet.get("admin_address")
        if not admin:
            raise RuntimeError("fleet announce carried no admin_address "
                               "(is DL4J_TPU_FLEET_OBS off?)")
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)

        def classify(i: int, bad_deadline: bool = False):
            doc = {"inputs": [[round(rng.uniform(0, 1), 6)
                               for _ in range(4)]],
                   "request_key": i}
            if bad_deadline:
                doc["deadline_ms"] = 0.001      # unmeetable: in-span 504
            req = urllib.request.Request(
                addr + "/v1/classify", data=json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30.0) as r:
                    r.read()
                    return r.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code
            except Exception:
                return None

        def alerts_view():
            """The fleet alert rollup through the proxy admin (never a
            500); polling a worker's own /debug/alerts through the
            splice drives its beat too."""
            try:
                _get(addr, "/debug/alerts", timeout=5.0)     # beat a worker
                code, doc = _get(admin, "/debug/alerts", timeout=5.0)
                return doc if code == 200 else {}
            except Exception:
                return {}

        def firing_rules(view: dict):
            rules = set()
            for a in (view.get("watchtower") or {}).get("firing") or ():
                rules.add(a.get("rule"))
            for _wid, rec in (view.get("workers") or {}).items():
                for a in rec.get("firing") or ():
                    rules.add(a.get("rule"))
            for a in (view.get("fleet") or {}).get("firing") or ():
                rules.add(a.get("rule"))
            return rules - {None}

        def alert_incidents(view: dict):
            return [i for i in view.get("incidents") or ()
                    if str(i.get("reason", "")).startswith("alert:")]

        # ---- phase 1: clean baseline — warm every detector, zero alerts
        baseline_s = 10.0
        base_false = set()
        t0 = time.monotonic()
        i = 0
        while time.monotonic() - t0 < baseline_s:
            classify(i)
            i += 1
            if i % 10 == 0:
                base_false |= firing_rules(alerts_view())
            time.sleep(0.05)
        view = alerts_view()
        base_false |= firing_rules(view)
        baseline_incidents = len(alert_incidents(view))
        fp_free = not base_false and baseline_incidents == 0

        # ---- phase 2: regression — sustained 504 burst; detect + page
        detect_budget_s = args.detect_budget_s
        burst_t0 = time.monotonic()
        detect_s = None
        fired = set()
        j = 0
        while time.monotonic() - burst_t0 < detect_budget_s:
            classify(10_000 + j, bad_deadline=True)
            j += 1
            if j % 5 == 0:
                fired = firing_rules(alerts_view())
                if "watch_http_error_burn" in fired:
                    detect_s = time.monotonic() - burst_t0
                    break
            time.sleep(0.02)
        detected = detect_s is not None

        # keep the burst alive briefly so the capture fan-out completes,
        # then grade the incident ledger: EXACTLY ONE alert incident,
        # with pinned trace evidence attached
        incidents = []
        fan_deadline = time.monotonic() + 10.0
        while time.monotonic() < fan_deadline:
            classify(20_000 + j, bad_deadline=True)
            j += 1
            incidents = alert_incidents(alerts_view())
            if incidents and len((incidents[0].get("captured") or {})) >= 2:
                break
            time.sleep(0.2)
        single_incident = len(incidents) == 1
        traces_attached = bool(incidents
                               and incidents[0].get("trace_ids"))
        captured_workers = sorted((incidents[0].get("captured") or {})
                                  if incidents else ())

        # ---- phase 3: recovery — the page must resolve, not flap
        resolved = False
        recover_t0 = time.monotonic()
        k = 0
        while time.monotonic() - recover_t0 < 30.0:
            classify(30_000 + k)
            k += 1
            if k % 5 == 0:
                view = alerts_view()
                still = firing_rules(view)
                if "watch_http_error_burn" not in still:
                    res = set()
                    for _wid, rec in (view.get("workers") or {}).items():
                        for a in rec.get("resolved") or ():
                            res.add(a.get("rule"))
                    for a in ((view.get("watchtower") or {})
                              .get("resolved") or ()):
                        res.add(a.get("rule"))
                    if "watch_http_error_burn" in res:
                        resolved = True
                        break
            time.sleep(0.05)
        final_incidents = alert_incidents(alerts_view())

        try:
            import jax
            platform = jax.default_backend()
        except Exception:
            platform = "unknown"
        rec = {
            "metric": "watch_drill",
            "platform": platform,
            "value": round(detect_s, 3) if detected else None,
            "unit": "detect_latency_s",
            "detected": detected,
            "detect_latency_s": (round(detect_s, 3) if detected
                                 else None),
            "detect_budget_s": detect_budget_s,
            "fp_free": fp_free,
            "baseline_false_rules": sorted(base_false),
            "fired_rules": sorted(fired),
            "single_incident": single_incident,
            "alert_incidents": len(final_incidents),
            "traces_attached": traces_attached,
            "trace_ids": ((final_incidents[0].get("trace_ids") or [])[:8]
                          if final_incidents else []),
            "captured_workers": captured_workers,
            "resolved": resolved,
            "baseline_requests": i,
            "burst_requests": j,
            "recovery_requests": k,
            "workers": 2,
            "seed": args.seed,
        }
        rec["ok_verdict"] = bool(detected and fp_free and single_incident
                                 and traces_attached and resolved)
        return rec
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


# ----------------------------------------------------------------- record
def _record(args, stats: "_Stats", stream: dict, vs_direct, workers,
            kill_drill, rollout=None) -> dict:
    from deeplearning4j_tpu.observability.slo import _grade
    total = stats.ok + stats.typed + stats.failed
    all_lat = [v for xs in stats.lat.values() for v in xs]
    p50 = _quantile(all_lat, 0.50)
    p99 = _quantile(all_lat, 0.99)
    goodput = stats.ok / args.duration_s if args.duration_s > 0 else None
    shed_ratio = stats.typed / total if total else 0.0
    error_ratio = stats.failed / total if total else 0.0
    slo = {
        "p99": _grade(p99 or 0.0, args.p99_degraded_s, args.p99_failing_s),
        "error_ratio": _grade(error_ratio, 0.01, 0.05),
        "shed_ratio": _grade(shed_ratio, 0.2, 0.5),
    }
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    return {
        "metric": "http_serve",
        "platform": platform,
        "value": goodput,
        "unit": "ok_requests_per_s",
        "goodput": goodput,
        "vs_direct": vs_direct,
        "ratio_method": "paired_window_median" if vs_direct else None,
        "requests": total,
        "ok": stats.ok,
        "typed": stats.typed,
        "failed": stats.failed,
        "conn_retries": stats.conn_retries,
        "failures": stats.failures,
        "p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "shed_ratio": round(shed_ratio, 4),
        "error_ratio": round(error_ratio, 4),
        "slo": slo,
        "stream": stream,
        "rollout": rollout,
        "kill_drill": kill_drill,
        "workers": workers,
        "qps": args.qps,
        "duration_s": args.duration_s,
        "seed": args.seed,
    }


# ------------------------------------------------- session failover drill
class _SseCollector(threading.Thread):
    """One raw-socket SSE stream against the proxy: records every
    ``id:`` line, token, and terminal event with receive timestamps —
    the audit trail for the zero-duplicate/zero-missing assertion."""

    def __init__(self, host: str, port: int, prompt, n_new: int):
        super().__init__(daemon=True)
        self.prompt, self.n_new = list(prompt), n_new
        self._addr = (host, port)
        self.ids, self.toks, self.at = [], [], []
        self.done = None
        self.error = None
        self.exc = None

    def run(self):
        try:
            body = json.dumps({"prompt": self.prompt,
                               "max_new_tokens": self.n_new,
                               "stream": True}).encode()
            s = socket.create_connection(self._addr, timeout=180)
            s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Type: application/json\r\n"
                      b"Content-Length: " + str(len(body)).encode()
                      + b"\r\nConnection: close\r\n\r\n" + body)
            s.settimeout(180)
            buf, ev, cur_id = b"", None, None
            while True:
                try:
                    data = s.recv(65536)
                except OSError as e:
                    self.exc = e
                    break
                if not data:
                    break
                buf += data
                while b"\n" in buf:
                    ln, _, buf = buf.partition(b"\n")
                    ln = ln.strip()
                    if ln.startswith(b"id:"):
                        cur_id = int(ln[3:].strip())
                    elif ln.startswith(b"event:"):
                        ev = ln.split(b":", 1)[1].strip().decode()
                    elif ln.startswith(b"data:"):
                        d = json.loads(ln[5:].strip())
                        if ev == "token":
                            self.ids.append(cur_id)
                            self.toks.append(d["token"])
                            self.at.append(time.monotonic())
                        elif ev == "done":
                            self.done = d
                        elif ev == "error":
                            self.error = d
            s.close()
        except Exception as e:
            self.exc = e


def _session_baselines(prompts, n_new: int, slots: int):
    """The undisturbed greedy token sequences, computed IN-PROCESS on
    the same demo engine the fleet deploys (same config, same seed, no
    faults) — what every chaos-run stream must match byte-for-byte."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.generation import DecodeEngine
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.parallel.generation import GenerationPipeline
    cfg = TransformerConfig(vocab_size=61, n_layers=2, n_heads=2,
                            d_model=32, max_len=64)
    model = TransformerLM(cfg)
    engine = DecodeEngine(model, model.init_params(jax.random.key(0)),
                          max_len=48)
    gp = GenerationPipeline(engine, slots=slots, max_new_tokens=n_new)
    try:
        return [[int(t) for t in
                 gp.generate(np.asarray(p, np.int32),
                             max_new_tokens=n_new)]
                for p in prompts]
    finally:
        gp.shutdown()


def run_session_failover(args, rng) -> dict:
    """The graded exactly-once streaming drill (archives SESS_r*.json):
    a 2-worker fleet under chaos — per-step decode latency, seeded
    ``generation.step`` crashes (in-place resume), armed
    ``generation.adopt`` faults (the adoption retry path) — then one
    worker SIGKILLed with every stream mid-flight.  Every SSE stream
    must still complete through the proxy's mid-stream failover with a
    gapless, duplicate-free ``id:`` sequence and greedy tokens
    byte-identical to the undisturbed in-process baseline.  Resume
    latency (kill → first survivor token) is reported, never gated."""
    n_streams = max(8, args.workers * 4)
    n_new = 16
    prompts = [[rng.randrange(1, 61) for _ in range(rng.randrange(4, 8))]
               for _ in range(n_streams)]
    baselines = _session_baselines(prompts, n_new, args.slots)

    state_dir = args.state_dir or f"/tmp/dl4j-sess-drill-{os.getpid()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_SESSIONS", None)       # the drill grades the ON path
    env["DL4J_TPU_SESSION_JOURNAL_STEPS"] = "1"
    env["DL4J_TPU_FAULTS"] = args.session_faults
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "tools", "serve.py"),
         "--workers", "2", "--port", "0", "--state-dir", state_dir,
         "--slots", str(max(args.slots, n_streams)), "--no-respawn"],
        stdout=subprocess.PIPE, text=True, env=env)
    store = _fleet_store(state_dir)
    try:
        fleet = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("tools/serve.py exited before "
                                   "announcing the fleet")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and "fleet" in doc:
                fleet = doc
                break
        if fleet is None:
            raise RuntimeError("fleet announce line never arrived")
        addr = fleet["address"]
        host, port = addr.split("//")[1].split(":")
        port = int(port)
        deadline = time.monotonic() + 60
        while True:
            try:
                _get(addr, "/debug/frontdoor", timeout=5.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError("fleet never answered")
                time.sleep(0.5)

        workers = store.read().get("workers") or {}
        victim = sorted(workers)[-1]            # spare the leader
        victim_pid = int(workers[victim]["pid"])

        streams = [_SseCollector(host, port, p, n_new) for p in prompts]
        for st in streams:
            st.start()
            time.sleep(0.05)
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if all(len(st.ids) >= 2 for st in streams):
                break
            time.sleep(0.05)
        inflight_at_kill = [len(st.ids) for st in streams]
        os.kill(victim_pid, signal.SIGKILL)
        killed_at = time.monotonic()
        for st in streams:
            st.join(timeout=300)

        complete = seq_exact = match = 0
        resume_lat = []
        failures = []
        for i, (st, base) in enumerate(zip(streams, baselines)):
            gapless = st.ids == list(range(len(st.ids)))
            ok_done = st.done is not None
            ok_match = st.toks == base
            complete += ok_done
            seq_exact += gapless
            match += ok_match
            if not (gapless and ok_done and ok_match):
                failures.append({
                    "stream": i, "n": len(st.ids), "gapless": gapless,
                    "done": ok_done, "match": ok_match,
                    "error": st.error, "exc": repr(st.exc)})
            post = [t for t in st.at if t > killed_at]
            if inflight_at_kill[i] < n_new and post:
                resume_lat.append(post[0] - killed_at)
        sessions = {}
        try:
            sessions = _get(addr, "/debug/sessions", timeout=10.0)[1]
        except Exception:
            pass
        frac = complete / max(1, n_streams)
        rec = {
            "metric": "sess_failover",
            "platform": "cpu",
            "value": round(frac, 4),
            "unit": "completion_fraction",
            "sess_completion": round(frac, 4),
            "sess_seq_exact": seq_exact / max(1, n_streams),
            "sess_greedy_match": match / max(1, n_streams),
            "sess_streams": n_streams,
            "inflight_at_kill": inflight_at_kill,
            "resume_latency_ms": (round(max(resume_lat) * 1e3, 1)
                                  if resume_lat else None),
            "resume_latency_ms_all": [round(t * 1e3, 1)
                                      for t in sorted(resume_lat)],
            "resumed_streams": len(resume_lat),
            "survivor_sessions": len(sessions.get("sessions") or []),
            "survivor_worker": sessions.get("worker"),
            "killed_worker": victim,
            "failures": failures,
            "session_faults": args.session_faults,
            "workers": 2,
            "seed": args.seed,
            "audited_all_streams": len(streams) == n_streams,
            "ok_verdict": (frac == 1.0 and seq_exact == n_streams
                           and match == n_streams),
        }
        return rec
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qps", type=float, default=20.0)
    ap.add_argument("--duration-s", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workers", type=int, default=0,
                    help="0 = in-process single worker; N = real fleet "
                         "via tools/serve.py")
    ap.add_argument("--kill-drill", action="store_true",
                    help="SIGKILL one worker mid-load (needs "
                         "--workers >= 2)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-inflight", type=int, default=64)
    ap.add_argument("--state-dir", default=None)
    ap.add_argument("--p99-degraded-s", type=float, default=2.0)
    ap.add_argument("--p99-failing-s", type=float, default=10.0)
    ap.add_argument("--tenants", default=None,
                    help="QoS flooding drill: victim tenants as "
                         "'name:weight,name:weight' (in-process mode; "
                         "archives QOS_r*.json)")
    ap.add_argument("--flooder", default="flood",
                    help="flooding tenant name (QoS drill)")
    ap.add_argument("--flooder-quota-qps", type=float, default=4.0,
                    help="the flooder's request-rate quota; it floods "
                         "at --flood-factor x this")
    ap.add_argument("--flood-factor", type=float, default=10.0)
    ap.add_argument("--victim-qps", type=float, default=6.0,
                    help="per-victim steady request rate (QoS drill)")
    ap.add_argument("--fleet-chaos", action="store_true",
                    help="the graded 3-worker chaos drill: SIGSTOP the "
                         "leader past TTL, SIGKILL a worker mid-stream, "
                         "corrupt the store doc once, store faults "
                         "throughout; archives FLEET_r*.json")
    ap.add_argument("--pause-s", type=float, default=4.5,
                    help="fleet-chaos leader SIGSTOP duration (must "
                         "exceed the 3 s worker TTL)")
    ap.add_argument("--fleet-faults",
                    default="store.read:error:0.02,store.write:error:0.02",
                    help="DL4J_TPU_FAULTS spec injected into every "
                         "fleet-chaos worker")
    ap.add_argument("--fleet-obs", action="store_true",
                    help="the graded 2-worker observability drill: "
                         "caller-supplied trace ids end-to-end, timed "
                         "/metrics/fleet scrapes, SIGKILL one worker "
                         "and check partial federation + traced "
                         "failover replays; archives OBSFLEET_r*.json")
    ap.add_argument("--obs-requests", type=int, default=40,
                    help="traced requests in the fleet-obs drill's "
                         "steady phase")
    ap.add_argument("--obs-scrapes", type=int, default=20,
                    help="timed /metrics/fleet scrapes (fleet-obs)")
    ap.add_argument("--trace-intel", action="store_true",
                    help="the graded 2-worker trace-intelligence "
                         "drill: error/tail/head retention rules, "
                         "cross-worker waterfall assembly through the "
                         "proxy admin, SIGKILL one worker and check "
                         "survivor retention + partial assembly; "
                         "archives TRACEQ_r*.json")
    ap.add_argument("--watchtower", action="store_true",
                    help="the graded 2-worker watchtower drill: clean "
                         "baseline must stay alert-free, a mid-run 504 "
                         "burst must page the error-burn detector within "
                         "the detection budget and close the loop into "
                         "exactly one trace-attached incident, and the "
                         "alert must resolve after recovery; archives "
                         "WATCH_r*.json")
    ap.add_argument("--detect-budget-s", type=float, default=15.0,
                    help="--watchtower: seconds the burn-rate page may "
                         "take to fire after the regression starts")
    ap.add_argument("--session-failover", action="store_true",
                    help="the graded exactly-once streaming drill: a "
                         "2-worker fleet under generation.step crash + "
                         "generation.adopt faults, one worker SIGKILLed "
                         "with every SSE stream mid-flight — 100%% must "
                         "complete via survivor adoption with gapless "
                         "ids and greedy tokens byte-identical to an "
                         "undisturbed run; archives SESS_r*.json")
    ap.add_argument("--session-faults",
                    default="generation.step:latency:1.0,"
                            "generation.step:crash:0.02:2,"
                            "generation.adopt:error:0.5:2",
                    help="DL4J_TPU_FAULTS spec injected into every "
                         "--session-failover worker")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.kill_drill and args.workers < 2:
        ap.error("--kill-drill needs --workers >= 2")
    import random
    rng = random.Random(args.seed)
    if args.session_failover:
        rec = run_session_failover(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if rec.get("ok_verdict") else 1
    if args.watchtower:
        rec = run_watchtower(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if rec.get("ok_verdict") else 1
    if args.trace_intel:
        rec = run_trace_intel(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if rec.get("ok_verdict") else 1
    if args.fleet_obs:
        rec = run_fleet_obs(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if rec.get("ok_verdict") else 1
    if args.fleet_chaos:
        rec = run_fleet_chaos(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0 if rec.get("ok_verdict") else 1
    if args.tenants:
        rec = run_qos_drill(args, rng)
        line = json.dumps(rec)
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        ok = (rec["goodput_holds"] and rec["p99_holds"]
              and rec["flooder_shed"] > 0
              and all(v["failed"] == 0 for v in rec["victims"].values()))
        return 0 if ok else 1
    rec = (run_fleet(args, rng) if args.workers
           else run_inproc(args, rng))
    line = json.dumps(rec)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    ok = (rec["failed"] == 0 and rec["stream"]["matches"]
          and (rec["kill_drill"] is None
               or (rec["kill_drill"]["respawned"]
                   and rec["kill_drill"]["rejoined_same_stage"])))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
