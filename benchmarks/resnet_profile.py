"""Device-side op-level diff of our zoo ResNet-50 step vs the flax twin.

The round-5 captures put ours at 0.895x flax (device-traced). This script
hunts the missing 10%: one traced window per side, then the "XLA Ops"
kernel aggregation per side, printed as (op, total_ms, count) tables plus
the module-level step times. Run on a live TPU window only.

Usage: python benchmarks/resnet_profile.py [--batch 32] [--iters 6]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def trace_side(label, window, match, top=30):
    import statistics

    import jax

    from device_timing import module_times, op_times

    logdir = tempfile.mkdtemp(prefix=f"rn_prof_{label}_")
    with jax.profiler.trace(logdir):
        window()
    times = module_times(logdir)
    step_ms = None
    for base, durs in times.items():
        if base.startswith(match):
            step_ms = statistics.median(durs) * 1e3
    rows = op_times(logdir, top=100000)
    print(f"\n=== {label}: module {match} median {step_ms and round(step_ms,3)} ms ===")
    total = sum(r[1] for r in rows)
    for name, tot, cnt in rows[:top]:
        print(f"  {tot*1e3:9.3f} ms  x{cnt:<4d} {name[:110]}")
    print(f"  (ALL-op total {total*1e3:.1f} ms across the window, {len(rows)} distinct)")
    # category sums: where does the step time live?
    cats = {}
    for name, tot, cnt in rows:
        # classify by the RESULT name only (before '='): matching the whole
        # HLO line binned every convert_reduce fusion reading a convolution
        # operand as "conv" and zeroed the reduce bucket (review finding)
        head = name.split("=")[0].lstrip("%")
        if head.startswith("convolution") or head.startswith("conv_"):
            c = "conv"
        elif "select_and_scatter" in head:
            c = "maxpool_bwd"
        elif "reduce" in head:
            c = "reduce_fusion"
        elif head.startswith("copy"):
            c = "copy"
        elif "fusion" in head:
            c = "other_fusion"
        else:
            c = "other"
        a = cats.setdefault(c, [0.0, 0])
        a[0] += tot
        a[1] += cnt
    for c, (tot, cnt) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        print(f"  [{c:>14}] {tot*1e3:9.2f} ms  x{cnt}")
    # prefix histogram inside the noisy buckets: the op-count diff between
    # sides lives in thousands of tiny kernels, not the top-30
    pref = {}
    for name, tot, cnt in rows:
        head = name.split("=")[0].lstrip("%").strip()
        base = head.split(".")[0]
        a = pref.setdefault(base, [0.0, 0])
        a[0] += tot
        a[1] += cnt
    print("  -- by op prefix (top 25 by time) --")
    for b, (tot, cnt) in sorted(pref.items(), key=lambda kv: -kv[1][0])[:25]:
        print(f"  {tot*1e3:9.2f} ms x{cnt:<6d} {b}")
    import shutil
    shutil.rmtree(logdir, ignore_errors=True)
    return step_ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--iters", type=int, default=6)
    args = ap.parse_args()

    from resnet_bench import measure_flax, measure_ours

    img_hw, classes, dtype = (224, 224), 1000, "bfloat16"
    ours = measure_ours(img_hw, classes, args.batch, args.iters, 0.1, dtype=dtype)
    ours_ms = trace_side("ours", ours, "jit__train_step")
    flax_w = measure_flax(img_hw, classes, args.batch, args.iters, 0.1, dtype=dtype)
    flax_ms = trace_side("flax", flax_w, "jit_step")
    if ours_ms and flax_ms:
        print(f"\nstep ms: ours {ours_ms:.3f} vs flax {flax_ms:.3f} "
              f"-> ratio {flax_ms/ours_ms:.3f}x")


if __name__ == "__main__":
    main()
