#!/usr/bin/env python
"""Decode-path A/Bs: KV cache vs. naive recompute, continuous vs.
static, paged vs. dense cache, int8 vs. f32 storage, speculative vs.
plain decode.

Five questions, each answered with the RESULTS.md noisy-box protocol
(interleaved repeats, per-repeat rotating arm order, min-estimator per
arm — raw single samples on this ±40%-drift box are weather):

1. ``--kv-ab`` — tokens/s of KV-cache incremental decode
   (``DecodeEngine.generate``: one prefill + one O(T) step per token)
   vs. the naive full-recompute loop (``naive_generate``: one full
   O(T²)-attention forward over the fixed-padded sequence per token).
   Both greedy, both one compiled executable per arm, same prompt, same
   emitted tokens (asserted). The acceptance bar is ≥5× at 256 decoded
   tokens on the flagship CPU-smoke config.

2. ``--cb-ab`` — goodput (completed tokens/s over the whole workload)
   of continuous batching (``GenerationPipeline``: requests join/leave
   the slot batch at step boundaries) vs. static windowed batching (the
   same engine, but a window of ``slots`` requests decodes until its
   LONGEST member finishes before any new request is admitted) under
   mixed-length requests arriving on a seeded Poisson process. Same
   arrival schedule, same prompts, same budgets in both arms.

3. ``--paged-ab`` — max sustained concurrent slots AND goodput at a
   FIXED HBM budget: dense worst-case reservation vs. a page pool of
   the same bytes backing ``slot_factor`` x the slots (admission by
   actual cached tokens). Bar: >= 2x the concurrency.

4. ``--quant-ab`` — int8 per-page KV storage vs. f32 pages: tokens/s
   interleaved, the deploy-time numerics-gate record, and the
   resident-bytes-per-page ratio (the durable number on any host).

5. ``--spec-ab`` — draft-accelerated speculative decode vs. plain:
   tokens/s interleaved + accept rate, greedy tokens byte-identical
   asserted. Bar: >= 1.3x tokens/s.

JSON archives to ``benchmarks/ab/decode_ab.json`` (never the repo
root — the driver's ``DECODE_r*.json`` copies are what
``tools/bench_diff.py`` grades across rounds, sustained-only).
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
from typing import Dict, List

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from deeplearning4j_tpu.models.generation import (DecodeEngine,  # noqa: E402
                                                  naive_generate)
from deeplearning4j_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                                   TransformerLM)
from deeplearning4j_tpu.parallel.generation import GenerationPipeline  # noqa: E402

AB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ab")


def flagship_cpu_config(max_len: int) -> TransformerConfig:
    """The bench.py CPU-smoke flagship shape (vocab 1024, 2L, d128,
    fused qkv), with the cache length this A/B needs."""
    import jax.numpy as jnp
    return TransformerConfig(vocab_size=1024, n_layers=2, n_heads=4,
                             d_model=128, max_len=max_len,
                             dtype=jnp.float32, fused_qkv=True)


def _interleaved_best(modes: List[str], repeats: int, run_one) -> Dict:
    """The rotating-order interleaved protocol (obs_overhead.py), with
    the estimator flipped for RATE metrics: obs_overhead's min-of-N is
    min SECONDS per step (the least-interfered window); for tokens/s
    the same estimator is the MAX sample. In-process because both arms
    share the compiled engine deliberately — compiles must not land in
    a measured window (arms are warmed before the first repeat)."""
    samples = {m: [] for m in modes}
    order = list(modes)
    for r in range(repeats):
        for m in order[r % len(order):] + order[:r % len(order)]:
            samples[m].append(run_one(m))
    return {m: max(v) for m, v in samples.items()}


# ------------------------------------------------------------------ kv A/B
def kv_ab(decode_tokens: int, prompt_len: int, repeats: int,
          naive_tokens: int, as_json: bool) -> dict:
    max_len = prompt_len + decode_tokens
    cfg = flagship_cpu_config(max_len)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    engine = DecodeEngine(model, params, max_len=max_len)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(np.int32)

    # correctness first: both paths emit the same greedy continuation
    kv_out = engine.generate(prompt, min(32, decode_tokens))
    nv_out = naive_generate(model, params, prompt, min(32, decode_tokens),
                            pad_to=max_len)
    assert np.array_equal(kv_out, nv_out), \
        "KV-cache decode diverged from the full-forward continuation"

    def run_kv() -> float:
        t0 = time.perf_counter()
        engine.generate(prompt, decode_tokens)
        return decode_tokens / (time.perf_counter() - t0)

    def run_naive() -> float:
        # the naive arm's per-token cost is CONSTANT (every step re-runs
        # the same fixed-padded forward), so a shorter run measures the
        # same tokens/s rate — full 256-token naive runs would spend
        # minutes re-proving a constant on this box
        n = min(naive_tokens, decode_tokens)
        t0 = time.perf_counter()
        naive_generate(model, params, prompt, n, pad_to=max_len)
        return n / (time.perf_counter() - t0)

    best = _interleaved_best(["kv", "naive"], repeats,
                             lambda m: run_kv() if m == "kv" else run_naive())
    ratio = best["kv"] / best["naive"]
    result = {
        "metric": "decode_kv_cache",
        "platform": jax.default_backend(),
        "value": best["kv"],
        "kv_tokens_per_s": best["kv"],
        "naive_tokens_per_s": best["naive"],
        "vs_naive": ratio,
        "decode_tokens": decode_tokens,
        "prompt_len": prompt_len,
        "naive_tokens_measured": min(naive_tokens, decode_tokens),
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
        "config": {"n_layers": cfg.n_layers, "d_model": cfg.d_model,
                   "vocab": cfg.vocab_size, "max_len": max_len},
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"KV-cache decode A/B ({decode_tokens} tokens, prompt "
              f"{prompt_len}, best of {repeats} rotating repeats)")
        print(f"  kv cache : {best['kv']:9.1f} tokens/s")
        print(f"  naive    : {best['naive']:9.1f} tokens/s "
              f"(full recompute, {min(naive_tokens, decode_tokens)} "
              "tokens measured)")
        print(f"  speedup  : {ratio:.2f}x  (bar: >= 5x)")
    return result


# ------------------------------------------------------------------ cb A/B
def _workload(n_requests: int, slots: int, seed: int):
    """Seeded mixed-length Poisson workload shared by both arms:
    heavy-tailed output budgets (mostly short chats, a long tail of
    long generations — the production LLM length distribution), so a
    static window genuinely strands slots behind its longest member."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in rng.integers(4, 24, n_requests)]
    budgets = [int(rng.integers(48, 80)) if rng.random() < 0.25
               else int(rng.integers(6, 16)) for _ in range(n_requests)]
    # Poisson arrivals tuned so the offered load keeps ~slots streams busy
    gaps = rng.exponential(scale=0.01, size=n_requests)
    arrivals = np.cumsum(gaps)
    return prompts, budgets, arrivals


def _static_windowed(engine: DecodeEngine, slots: int, prompts, budgets,
                     arrivals):
    """The pre-continuous-batching baseline: admit up to ``slots``
    arrived requests, decode the window until EVERY member finished,
    then admit the next window (the whole window waits on its longest
    member — exactly the slot waste continuous batching removes).
    Returns (goodput tokens/s, per-request latencies)."""
    t_start = time.perf_counter()
    done_tokens = 0
    latencies = []
    i = 0
    while i < len(prompts):
        # wait for at least one arrival, then take whatever has arrived
        now = time.perf_counter() - t_start
        if arrivals[i] > now:
            time.sleep(arrivals[i] - now)
        now = time.perf_counter() - t_start
        window = [j for j in range(i, min(i + slots, len(prompts)))
                  if arrivals[j] <= now] or [i]
        i = window[-1] + 1
        cache = engine.new_cache(slots)
        toks = np.zeros((slots,), np.int32)
        pos = np.zeros((slots,), np.int32)
        remaining = {}
        for s, j in enumerate(window):
            first, _l, kv, t = engine.prefill(prompts[j][None], step=0)
            cache = engine.insert_slot(cache, kv, s)
            toks[s] = int(np.asarray(first)[0])
            pos[s] = t
            remaining[s] = budgets[j] - 1
            done_tokens += 1
        step = 0
        while any(r > 0 for r in remaining.values()):
            nxt, _l, cache = engine.decode(cache, toks, pos, step)
            nxt = np.asarray(nxt)
            for s, j in enumerate(window):
                if remaining[s] > 0:
                    remaining[s] -= 1
                    done_tokens += 1
                    if remaining[s] == 0:
                        latencies.append(time.perf_counter() - t_start
                                         - arrivals[j])
            toks, pos, step = nxt, pos + 1, step + 1
    return done_tokens / (time.perf_counter() - t_start), latencies


def _continuous(engine: DecodeEngine, slots: int, prompts, budgets,
                arrivals):
    """The same workload through GenerationPipeline (requests join/leave
    at step boundaries). Returns (goodput, per-request latencies)."""
    gp = GenerationPipeline(engine, slots=slots,
                            queue_limit=max(64, len(prompts)))
    results: "queue.Queue" = queue.Queue()
    t_start = time.perf_counter()

    def one(j, t_arr):
        try:
            out = gp.generate(prompts[j], max_new_tokens=budgets[j])
            results.put((len(out), time.perf_counter() - t_arr))
        except Exception:
            results.put((0, 0.0))

    threads = []
    for j in range(len(prompts)):
        now = time.perf_counter() - t_start
        if arrivals[j] > now:
            time.sleep(arrivals[j] - now)
        th = threading.Thread(target=one, args=(j, time.perf_counter()),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120)
    pairs = [results.get() for _ in range(results.qsize())]
    goodput = sum(n for n, _ in pairs) / (time.perf_counter() - t_start)
    gp.shutdown()
    return goodput, [lat for n, lat in pairs if n]


def cb_ab(n_requests: int, slots: int, repeats: int, as_json: bool) -> dict:
    cfg = flagship_cpu_config(128)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    engine = DecodeEngine(model, params, max_len=128)
    prompts, budgets, arrivals = _workload(n_requests, slots, seed=7)
    occupancy: List[float] = []
    lat_p50: Dict[str, float] = {}

    # AOT-warm every executable both arms will hit — the SAME recipe a
    # production deploy runs (DecodeEngine.warm), so the rotating
    # windows measure decode, never compilation
    engine.warm(slots)

    def run_one(mode: str) -> float:
        if mode == "static":
            goodput, lats = _static_windowed(engine, slots, prompts,
                                             budgets, arrivals)
            lat_p50["static"] = float(np.median(lats)) if lats else 0.0
            return goodput
        from deeplearning4j_tpu.observability import global_registry
        inst = global_registry().get("dl4j_decode_slot_occupancy_ratio")
        before = (inst.sum, inst.count) if inst is not None else (0.0, 0)
        goodput, lats = _continuous(engine, slots, prompts, budgets,
                                    arrivals)
        lat_p50["continuous"] = float(np.median(lats)) if lats else 0.0
        inst = global_registry().get("dl4j_decode_slot_occupancy_ratio")
        if inst is not None and inst.count > before[1]:
            occupancy.append((inst.sum - before[0])
                             / (inst.count - before[1]))
        return goodput

    best = _interleaved_best(["continuous", "static"], repeats, run_one)
    ratio = best["continuous"] / best["static"]
    result = {
        "metric": "decode_continuous_batching",
        "platform": jax.default_backend(),
        "value": best["continuous"],
        "continuous_tokens_per_s": best["continuous"],
        "static_tokens_per_s": best["static"],
        "vs_static": ratio,
        "slot_occupancy": [round(o, 4) for o in occupancy],
        "latency_p50_s": {k: round(v, 4) for k, v in lat_p50.items()},
        "n_requests": n_requests,
        "slots": slots,
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"continuous-batching A/B ({n_requests} mixed-length "
              f"requests, {slots} slots, best of {repeats} rotating "
              "repeats)")
        print(f"  continuous: {best['continuous']:9.1f} tokens/s goodput")
        print(f"  static    : {best['static']:9.1f} tokens/s goodput")
        print(f"  ratio     : {ratio:.2f}x  (bar: > 1x)")
        if lat_p50:
            print(f"  p50 request latency: continuous "
                  f"{lat_p50.get('continuous', 0) * 1e3:.0f} ms vs static "
                  f"{lat_p50.get('static', 0) * 1e3:.0f} ms")
        if occupancy:
            print(f"  mean slot occupancy (continuous): "
                  f"{occupancy[-1]:.3f}")
    return result


# ------------------------------------------------------- paged-cache A/B
def paged_ab(n_requests: int, dense_slots: int, slot_factor: int,
             repeats: int, as_json: bool) -> dict:
    """Max sustained concurrent slots AND goodput at a FIXED HBM budget,
    paged vs dense. The budget is what ``dense_slots`` worst-case dense
    slots cost (slots x max_len rows); the paged arm spends exactly the
    same bytes as a page pool but runs ``slot_factor`` x the slots —
    admission is bounded by ACTUAL cached tokens, and the workload's
    streams use ~1/4 of max_len each, so the pool sustains what the
    dense worst-case reservation never could."""
    max_len = 128
    cfg = flagship_cpu_config(max_len)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    page = 32
    dense_eng = DecodeEngine(model, params, max_len=max_len, page_tokens=0)
    paged_eng = DecodeEngine(model, params, max_len=max_len,
                             page_tokens=page)
    budget_pages = dense_slots * paged_eng.pages_per_slot
    budget_bytes = budget_pages * paged_eng.page_bytes()
    paged_slots = dense_slots * slot_factor
    # short streams: ~max_len/4 actual rows per request, the regime the
    # worst-case reservation wastes 4x on
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in rng.integers(6, 14, n_requests)]
    budgets = [int(b) for b in rng.integers(12, 22, n_requests)]
    arrivals = np.cumsum(rng.exponential(scale=0.004, size=n_requests))
    dense_eng.warm(dense_slots)
    paged_eng.warm(paged_slots)
    peak = {"dense": 0, "paged": 0}

    def run_one(mode: str) -> float:
        if mode == "dense":
            gp = GenerationPipeline(dense_eng, slots=dense_slots,
                                    queue_limit=max(64, n_requests))
        else:
            gp = GenerationPipeline(paged_eng, slots=paged_slots,
                                    queue_limit=max(64, n_requests),
                                    cache_pages=budget_pages)
        results: "queue.Queue" = queue.Queue()
        stop = threading.Event()

        def sample_peak():
            while not stop.is_set():
                peak[mode] = max(peak[mode], gp._n_active())
                time.sleep(0.002)

        sampler = threading.Thread(target=sample_peak, daemon=True)
        sampler.start()
        t_start = time.perf_counter()

        def one(j, t_arr):
            try:
                out = gp.generate(prompts[j], max_new_tokens=budgets[j])
                results.put(len(out))
            except Exception:
                results.put(0)

        threads = []
        for j in range(n_requests):
            now = time.perf_counter() - t_start
            if arrivals[j] > now:
                time.sleep(arrivals[j] - now)
            th = threading.Thread(target=one,
                                  args=(j, time.perf_counter()),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=120)
        done = sum(results.get() for _ in range(results.qsize()))
        goodput = done / (time.perf_counter() - t_start)
        stop.set()
        sampler.join(timeout=1)
        gp.shutdown()
        return goodput

    best = _interleaved_best(["paged", "dense"], repeats, run_one)
    ratio = best["paged"] / best["dense"]
    result = {
        "metric": "decode_paged_cache",
        "platform": jax.default_backend(),
        "value": best["paged"],
        "paged_tokens_per_s": best["paged"],
        "dense_tokens_per_s": best["dense"],
        "vs_dense_cache": ratio,
        "hbm_budget_bytes": budget_bytes,
        "page_tokens": page,
        "max_slots_dense": peak["dense"],
        "max_slots_paged": peak["paged"],
        "slot_ratio": (peak["paged"] / peak["dense"]
                       if peak["dense"] else None),
        "dense_slot_cap": dense_slots,
        "paged_slot_cap": paged_slots,
        "n_requests": n_requests,
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"paged-vs-dense KV cache A/B at a fixed "
              f"{budget_bytes / 1e6:.1f} MB HBM budget "
              f"({n_requests} short streams, best of {repeats})")
        print(f"  dense : {best['dense']:9.1f} tokens/s, peak "
              f"{peak['dense']} concurrent slots (cap {dense_slots} — "
              "worst-case reservation)")
        print(f"  paged : {best['paged']:9.1f} tokens/s, peak "
              f"{peak['paged']} concurrent slots (cap {paged_slots}, "
              "same bytes)")
        print(f"  goodput ratio {ratio:.2f}x, concurrency ratio "
              f"{result['slot_ratio']:.1f}x (bar: >= 2x)")
    return result


# ------------------------------------------------------- int8-quant A/B
def quant_ab(decode_tokens: int, prompt_len: int, repeats: int,
             as_json: bool) -> dict:
    """int8-quantized vs f32 paged cache: tokens/s (interleaved) and the
    numerics-gate record. The durable number on ANY host is the
    resident-bytes ratio — int8 k/v + per-row scale vs f32 rows; the
    tokens/s ratio only moves where decode is HBM-bound (a real chip),
    so it is reported, never a bar."""
    max_len = prompt_len + decode_tokens
    cfg = flagship_cpu_config(max_len)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    page = 32
    f32_eng = DecodeEngine(model, params, max_len=max_len,
                           page_tokens=page)
    q_eng = DecodeEngine(model, params, max_len=max_len, page_tokens=page,
                         kv_quant=True)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(np.int32)
    f32_eng.warm(1)
    q_eng.warm(1)
    gate = dict(q_eng.quant_gate or {})
    quant_live = bool(q_eng.kv_quant)

    def run(eng) -> float:
        t0 = time.perf_counter()
        eng.generate(prompt, decode_tokens)
        return decode_tokens / (time.perf_counter() - t0)

    best = _interleaved_best(
        ["int8", "f32"], repeats,
        lambda m: run(q_eng if m == "int8" else f32_eng))
    result = {
        "metric": "decode_kv_quant",
        "platform": jax.default_backend(),
        "value": best["int8"],
        "int8_tokens_per_s": best["int8"],
        "f32_tokens_per_s": best["f32"],
        "vs_f32": best["int8"] / best["f32"],
        "quant_live": quant_live,
        "gate": gate,
        "page_bytes_int8": q_eng.page_bytes() if quant_live else None,
        "page_bytes_f32": f32_eng.page_bytes(),
        "bytes_ratio": ((q_eng.page_bytes() / f32_eng.page_bytes())
                        if quant_live else None),
        "decode_tokens": decode_tokens,
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"int8-vs-f32 KV cache A/B ({decode_tokens} tokens, "
              f"best of {repeats})")
        print(f"  int8 : {best['int8']:9.1f} tokens/s "
              f"(gate max |logit diff| {gate.get('max_abs_logit_diff', 0):.2e}"
              f" <= tol {gate.get('tol')}, "
              f"{'LIVE' if quant_live else 'FELL BACK TO f32'})")
        print(f"  f32  : {best['f32']:9.1f} tokens/s")
        if quant_live:
            print(f"  resident bytes/page: {q_eng.page_bytes()} vs "
                  f"{f32_eng.page_bytes()} "
                  f"({f32_eng.page_bytes() / q_eng.page_bytes():.2f}x "
                  "more tokens per byte)")
    return result


# ------------------------------------------------------ spec-decode A/B
def spec_ab(decode_tokens: int, prompt_len: int, spec_k: int,
            draft_layers: int, repeats: int, as_json: bool) -> dict:
    """Speculative vs plain decode on the flagship shape: the draft is a
    ``draft_layers``-layer truncation of the target sharing its
    embeddings (at 0.02 init scale the blocks barely perturb the
    logits, so even the 0-layer embedding-only draft agrees with the
    target often — the synthetic stand-in for a distilled production
    draft; the measured accept rate IS reported, it is a property of
    this config, not a claim about real drafts). Greedy mode, so the
    emitted tokens are asserted BYTE-IDENTICAL to plain decode; accept
    rate and tokens/s are the measurements. On this dispatch-bound box
    the win comes from round shape — ONE fused k-step propose + ONE
    windowed verify replace up to k single-token dispatches — which is
    also the shape of the win on a real chip, where the verify's W-row
    matmuls batch where plain decode runs GEMVs."""
    max_len = prompt_len + decode_tokens
    cfg = flagship_cpu_config(max_len)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    import dataclasses as _dc
    dcfg = _dc.replace(cfg, n_layers=draft_layers)
    draft_model = TransformerLM(dcfg)
    draft_params = {"tok_emb": params["tok_emb"],
                    "pos_emb": params["pos_emb"], "ln_f": params["ln_f"],
                    "blocks": [params["blocks"][i]
                               for i in range(draft_layers)]}
    page = 32
    draft = DecodeEngine(draft_model, draft_params, max_len=max_len,
                         page_tokens=0)
    plain_eng = DecodeEngine(model, params, max_len=max_len,
                             page_tokens=page)
    spec_eng = DecodeEngine(model, params, max_len=max_len,
                            page_tokens=page, draft=draft, spec_k=spec_k)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(np.int32)
    plain_eng.warm(1)
    spec_eng.warm(1)
    # correctness first: greedy speculative decode must emit EXACTLY the
    # plain continuation (the accept loop's contract)
    ref = plain_eng.generate(prompt, decode_tokens)
    out = spec_eng.generate(prompt, decode_tokens)
    assert np.array_equal(ref, out), \
        "speculative greedy decode diverged from plain decode"

    def run(eng) -> float:
        t0 = time.perf_counter()
        eng.generate(prompt, decode_tokens)
        return decode_tokens / (time.perf_counter() - t0)

    spec_eng.spec_stats = {"rounds": 0, "proposed": 0, "accepted": 0}
    best = _interleaved_best(
        ["spec", "plain"], repeats,
        lambda m: run(spec_eng if m == "spec" else plain_eng))
    accept = spec_eng.spec_accept_ratio()
    result = {
        "metric": "decode_speculative",
        "platform": jax.default_backend(),
        "value": best["spec"],
        "spec_tokens_per_s": best["spec"],
        "plain_tokens_per_s": best["plain"],
        "vs_no_spec": best["spec"] / best["plain"],
        "spec_accept_ratio": accept,
        "spec_k": spec_k,
        "draft_layers": draft_layers,
        "greedy_identical": True,
        "decode_tokens": decode_tokens,
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"speculative-vs-plain decode A/B ({decode_tokens} tokens, "
              f"k={spec_k}, best of {repeats}; greedy tokens identical "
              "asserted)")
        print(f"  spec  : {best['spec']:9.1f} tokens/s "
              f"(accept ratio {accept:.3f})")
        print(f"  plain : {best['plain']:9.1f} tokens/s")
        print(f"  speedup {best['spec'] / best['plain']:.2f}x "
              "(bar: >= 1.3x)")
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv-ab", action="store_true",
                    help="KV-cache decode vs naive full recompute")
    ap.add_argument("--cb-ab", action="store_true",
                    help="continuous vs static windowed batching")
    ap.add_argument("--paged-ab", action="store_true",
                    help="paged vs dense cache at a fixed HBM budget")
    ap.add_argument("--quant-ab", action="store_true",
                    help="int8 vs f32 KV storage")
    ap.add_argument("--spec-ab", action="store_true",
                    help="speculative vs plain decode")
    ap.add_argument("--decode-tokens", type=int, default=256)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--naive-tokens", type=int, default=64,
                    help="tokens the naive arm measures per window (its "
                         "per-token cost is constant; see docstring)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--dense-slots", type=int, default=2,
                    help="paged A/B: dense slots whose worst-case bytes "
                         "set the fixed HBM budget")
    ap.add_argument("--slot-factor", type=int, default=4,
                    help="paged A/B: paged slot cap as a multiple of the "
                         "dense cap (same bytes)")
    ap.add_argument("--spec-k", type=int, default=8)
    ap.add_argument("--draft-layers", type=int, default=0,
                    help="spec A/B: target layers the draft keeps (0 = "
                         "embedding-only draft)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    chosen = any((args.kv_ab, args.cb_ab, args.paged_ab, args.quant_ab,
                  args.spec_ab))
    results = {}
    if args.kv_ab or not chosen:
        results["kv"] = kv_ab(args.decode_tokens, args.prompt_len,
                              args.repeats, args.naive_tokens, args.json)
    if args.cb_ab or not chosen:
        results["cb"] = cb_ab(args.requests, args.slots, args.repeats,
                              args.json)
    if args.paged_ab or not chosen:
        results["paged"] = paged_ab(args.requests, args.dense_slots,
                                    args.slot_factor, args.repeats,
                                    args.json)
    if args.quant_ab or not chosen:
        results["quant"] = quant_ab(min(args.decode_tokens, 96),
                                    args.prompt_len, args.repeats,
                                    args.json)
    if args.spec_ab or not chosen:
        results["spec"] = spec_ab(min(args.decode_tokens, 96),
                                  args.prompt_len, args.spec_k,
                                  args.draft_layers, args.repeats,
                                  args.json)
    os.makedirs(AB_DIR, exist_ok=True)
    out = os.path.join(AB_DIR, "decode_ab.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"archived -> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
