#!/usr/bin/env python
"""Decode-path A/Bs: KV cache vs. naive recompute, continuous vs. static.

Two questions, each answered with the RESULTS.md noisy-box protocol
(interleaved repeats, per-repeat rotating arm order, min-estimator per
arm — raw single samples on this ±40%-drift box are weather):

1. ``--kv-ab`` — tokens/s of KV-cache incremental decode
   (``DecodeEngine.generate``: one prefill + one O(T) step per token)
   vs. the naive full-recompute loop (``naive_generate``: one full
   O(T²)-attention forward over the fixed-padded sequence per token).
   Both greedy, both one compiled executable per arm, same prompt, same
   emitted tokens (asserted). The acceptance bar is ≥5× at 256 decoded
   tokens on the flagship CPU-smoke config.

2. ``--cb-ab`` — goodput (completed tokens/s over the whole workload)
   of continuous batching (``GenerationPipeline``: requests join/leave
   the slot batch at step boundaries) vs. static windowed batching (the
   same engine, but a window of ``slots`` requests decodes until its
   LONGEST member finishes before any new request is admitted) under
   mixed-length requests arriving on a seeded Poisson process. Same
   arrival schedule, same prompts, same budgets in both arms.

JSON archives to ``benchmarks/ab/decode_ab.json`` (never the repo
root — the driver's ``DECODE_r*.json`` copies are what
``tools/bench_diff.py`` grades across rounds, sustained-only).
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
from typing import Dict, List

import numpy as np

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

from deeplearning4j_tpu.models.generation import (DecodeEngine,  # noqa: E402
                                                  naive_generate)
from deeplearning4j_tpu.models.transformer import (TransformerConfig,  # noqa: E402
                                                   TransformerLM)
from deeplearning4j_tpu.parallel.generation import GenerationPipeline  # noqa: E402

AB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ab")


def flagship_cpu_config(max_len: int) -> TransformerConfig:
    """The bench.py CPU-smoke flagship shape (vocab 1024, 2L, d128,
    fused qkv), with the cache length this A/B needs."""
    import jax.numpy as jnp
    return TransformerConfig(vocab_size=1024, n_layers=2, n_heads=4,
                             d_model=128, max_len=max_len,
                             dtype=jnp.float32, fused_qkv=True)


def _interleaved_best(modes: List[str], repeats: int, run_one) -> Dict:
    """The rotating-order interleaved protocol (obs_overhead.py), with
    the estimator flipped for RATE metrics: obs_overhead's min-of-N is
    min SECONDS per step (the least-interfered window); for tokens/s
    the same estimator is the MAX sample. In-process because both arms
    share the compiled engine deliberately — compiles must not land in
    a measured window (arms are warmed before the first repeat)."""
    samples = {m: [] for m in modes}
    order = list(modes)
    for r in range(repeats):
        for m in order[r % len(order):] + order[:r % len(order)]:
            samples[m].append(run_one(m))
    return {m: max(v) for m, v in samples.items()}


# ------------------------------------------------------------------ kv A/B
def kv_ab(decode_tokens: int, prompt_len: int, repeats: int,
          naive_tokens: int, as_json: bool) -> dict:
    max_len = prompt_len + decode_tokens
    cfg = flagship_cpu_config(max_len)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    engine = DecodeEngine(model, params, max_len=max_len)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (1, prompt_len)).astype(np.int32)

    # correctness first: both paths emit the same greedy continuation
    kv_out = engine.generate(prompt, min(32, decode_tokens))
    nv_out = naive_generate(model, params, prompt, min(32, decode_tokens),
                            pad_to=max_len)
    assert np.array_equal(kv_out, nv_out), \
        "KV-cache decode diverged from the full-forward continuation"

    def run_kv() -> float:
        t0 = time.perf_counter()
        engine.generate(prompt, decode_tokens)
        return decode_tokens / (time.perf_counter() - t0)

    def run_naive() -> float:
        # the naive arm's per-token cost is CONSTANT (every step re-runs
        # the same fixed-padded forward), so a shorter run measures the
        # same tokens/s rate — full 256-token naive runs would spend
        # minutes re-proving a constant on this box
        n = min(naive_tokens, decode_tokens)
        t0 = time.perf_counter()
        naive_generate(model, params, prompt, n, pad_to=max_len)
        return n / (time.perf_counter() - t0)

    best = _interleaved_best(["kv", "naive"], repeats,
                             lambda m: run_kv() if m == "kv" else run_naive())
    ratio = best["kv"] / best["naive"]
    result = {
        "metric": "decode_kv_cache",
        "platform": jax.default_backend(),
        "value": best["kv"],
        "kv_tokens_per_s": best["kv"],
        "naive_tokens_per_s": best["naive"],
        "vs_naive": ratio,
        "decode_tokens": decode_tokens,
        "prompt_len": prompt_len,
        "naive_tokens_measured": min(naive_tokens, decode_tokens),
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
        "config": {"n_layers": cfg.n_layers, "d_model": cfg.d_model,
                   "vocab": cfg.vocab_size, "max_len": max_len},
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"KV-cache decode A/B ({decode_tokens} tokens, prompt "
              f"{prompt_len}, best of {repeats} rotating repeats)")
        print(f"  kv cache : {best['kv']:9.1f} tokens/s")
        print(f"  naive    : {best['naive']:9.1f} tokens/s "
              f"(full recompute, {min(naive_tokens, decode_tokens)} "
              "tokens measured)")
        print(f"  speedup  : {ratio:.2f}x  (bar: >= 5x)")
    return result


# ------------------------------------------------------------------ cb A/B
def _workload(n_requests: int, slots: int, seed: int):
    """Seeded mixed-length Poisson workload shared by both arms:
    heavy-tailed output budgets (mostly short chats, a long tail of
    long generations — the production LLM length distribution), so a
    static window genuinely strands slots behind its longest member."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, 1024, (int(n),)).astype(np.int32)
               for n in rng.integers(4, 24, n_requests)]
    budgets = [int(rng.integers(48, 80)) if rng.random() < 0.25
               else int(rng.integers(6, 16)) for _ in range(n_requests)]
    # Poisson arrivals tuned so the offered load keeps ~slots streams busy
    gaps = rng.exponential(scale=0.01, size=n_requests)
    arrivals = np.cumsum(gaps)
    return prompts, budgets, arrivals


def _static_windowed(engine: DecodeEngine, slots: int, prompts, budgets,
                     arrivals):
    """The pre-continuous-batching baseline: admit up to ``slots``
    arrived requests, decode the window until EVERY member finished,
    then admit the next window (the whole window waits on its longest
    member — exactly the slot waste continuous batching removes).
    Returns (goodput tokens/s, per-request latencies)."""
    t_start = time.perf_counter()
    done_tokens = 0
    latencies = []
    i = 0
    while i < len(prompts):
        # wait for at least one arrival, then take whatever has arrived
        now = time.perf_counter() - t_start
        if arrivals[i] > now:
            time.sleep(arrivals[i] - now)
        now = time.perf_counter() - t_start
        window = [j for j in range(i, min(i + slots, len(prompts)))
                  if arrivals[j] <= now] or [i]
        i = window[-1] + 1
        cache = engine.new_cache(slots)
        toks = np.zeros((slots,), np.int32)
        pos = np.zeros((slots,), np.int32)
        remaining = {}
        for s, j in enumerate(window):
            first, _l, kv, t = engine.prefill(prompts[j][None], step=0)
            cache = engine.insert_slot(cache, kv, s)
            toks[s] = int(np.asarray(first)[0])
            pos[s] = t
            remaining[s] = budgets[j] - 1
            done_tokens += 1
        step = 0
        while any(r > 0 for r in remaining.values()):
            nxt, _l, cache = engine.decode(cache, toks, pos, step)
            nxt = np.asarray(nxt)
            for s, j in enumerate(window):
                if remaining[s] > 0:
                    remaining[s] -= 1
                    done_tokens += 1
                    if remaining[s] == 0:
                        latencies.append(time.perf_counter() - t_start
                                         - arrivals[j])
            toks, pos, step = nxt, pos + 1, step + 1
    return done_tokens / (time.perf_counter() - t_start), latencies


def _continuous(engine: DecodeEngine, slots: int, prompts, budgets,
                arrivals):
    """The same workload through GenerationPipeline (requests join/leave
    at step boundaries). Returns (goodput, per-request latencies)."""
    gp = GenerationPipeline(engine, slots=slots,
                            queue_limit=max(64, len(prompts)))
    results: "queue.Queue" = queue.Queue()
    t_start = time.perf_counter()

    def one(j, t_arr):
        try:
            out = gp.generate(prompts[j], max_new_tokens=budgets[j])
            results.put((len(out), time.perf_counter() - t_arr))
        except Exception:
            results.put((0, 0.0))

    threads = []
    for j in range(len(prompts)):
        now = time.perf_counter() - t_start
        if arrivals[j] > now:
            time.sleep(arrivals[j] - now)
        th = threading.Thread(target=one, args=(j, time.perf_counter()),
                              daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=120)
    pairs = [results.get() for _ in range(results.qsize())]
    goodput = sum(n for n, _ in pairs) / (time.perf_counter() - t_start)
    gp.shutdown()
    return goodput, [lat for n, lat in pairs if n]


def cb_ab(n_requests: int, slots: int, repeats: int, as_json: bool) -> dict:
    cfg = flagship_cpu_config(128)
    model = TransformerLM(cfg)
    params = model.init_params(jax.random.key(0))
    engine = DecodeEngine(model, params, max_len=128)
    prompts, budgets, arrivals = _workload(n_requests, slots, seed=7)
    occupancy: List[float] = []
    lat_p50: Dict[str, float] = {}

    # AOT-warm every executable both arms will hit — the SAME recipe a
    # production deploy runs (DecodeEngine.warm), so the rotating
    # windows measure decode, never compilation
    engine.warm(slots)

    def run_one(mode: str) -> float:
        if mode == "static":
            goodput, lats = _static_windowed(engine, slots, prompts,
                                             budgets, arrivals)
            lat_p50["static"] = float(np.median(lats)) if lats else 0.0
            return goodput
        from deeplearning4j_tpu.observability import global_registry
        inst = global_registry().get("dl4j_decode_slot_occupancy_ratio")
        before = (inst.sum, inst.count) if inst is not None else (0.0, 0)
        goodput, lats = _continuous(engine, slots, prompts, budgets,
                                    arrivals)
        lat_p50["continuous"] = float(np.median(lats)) if lats else 0.0
        inst = global_registry().get("dl4j_decode_slot_occupancy_ratio")
        if inst is not None and inst.count > before[1]:
            occupancy.append((inst.sum - before[0])
                             / (inst.count - before[1]))
        return goodput

    best = _interleaved_best(["continuous", "static"], repeats, run_one)
    ratio = best["continuous"] / best["static"]
    result = {
        "metric": "decode_continuous_batching",
        "platform": jax.default_backend(),
        "value": best["continuous"],
        "continuous_tokens_per_s": best["continuous"],
        "static_tokens_per_s": best["static"],
        "vs_static": ratio,
        "slot_occupancy": [round(o, 4) for o in occupancy],
        "latency_p50_s": {k: round(v, 4) for k, v in lat_p50.items()},
        "n_requests": n_requests,
        "slots": slots,
        "repeats": repeats,
        "ratio_method": "interleaved_rotating_best",
    }
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(f"continuous-batching A/B ({n_requests} mixed-length "
              f"requests, {slots} slots, best of {repeats} rotating "
              "repeats)")
        print(f"  continuous: {best['continuous']:9.1f} tokens/s goodput")
        print(f"  static    : {best['static']:9.1f} tokens/s goodput")
        print(f"  ratio     : {ratio:.2f}x  (bar: > 1x)")
        if lat_p50:
            print(f"  p50 request latency: continuous "
                  f"{lat_p50.get('continuous', 0) * 1e3:.0f} ms vs static "
                  f"{lat_p50.get('static', 0) * 1e3:.0f} ms")
        if occupancy:
            print(f"  mean slot occupancy (continuous): "
                  f"{occupancy[-1]:.3f}")
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kv-ab", action="store_true",
                    help="KV-cache decode vs naive full recompute")
    ap.add_argument("--cb-ab", action="store_true",
                    help="continuous vs static windowed batching")
    ap.add_argument("--decode-tokens", type=int, default=256)
    ap.add_argument("--prompt-len", type=int, default=32)
    ap.add_argument("--naive-tokens", type=int, default=64,
                    help="tokens the naive arm measures per window (its "
                         "per-token cost is constant; see docstring)")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    results = {}
    if args.kv_ab or not args.cb_ab:
        results["kv"] = kv_ab(args.decode_tokens, args.prompt_len,
                              args.repeats, args.naive_tokens, args.json)
    if args.cb_ab or not args.kv_ab:
        results["cb"] = cb_ab(args.requests, args.slots, args.repeats,
                              args.json)
    os.makedirs(AB_DIR, exist_ok=True)
    out = os.path.join(AB_DIR, "decode_ab.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"archived -> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
