"""Ring-attention benchmark — the evidence harness for the SP claim
(VERDICT r1 weak #6: "compute/comm overlap is asserted in a docstring,
never measured").

Measures, per sequence length:
  1. wall time of ring attention on a ``seq``-sharded mesh vs plain (full
     T×T) attention on one device;
  2. peak-memory proxy: the largest live intermediate — ring never
     materialises the (T, T) score matrix, plain does;
  3. correctness cross-check at small T.

Run modes:
  python benchmarks/ring_attention_bench.py            # virtual 8-dev CPU mesh
  JAX_PLATFORMS=tpu python benchmarks/ring_attention_bench.py --tpu
     (on a multi-chip TPU slice the timings become the real SP scaling
      numbers; on one chip only the memory columns are meaningful)

Prints one JSON line per sequence length.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time


def _memory_worker(kind: str, T: int, P: int, heads: int, dim: int):
    """Measure peak device memory of ONE attention variant at global length
    ``T`` (VERDICT r3 #9: turn the "(T/P)^2 per chip" claim into telemetry).

    ``ring_chip`` runs exactly one ring participant's workload on the local
    device: resident q shard (T/P), one in-flight K/V block (T/P), and the
    online-softmax accumulators, looping P block-update steps (the ppermute
    is replaced by identity — same memory profile, no second chip needed).
    ``plain`` materialises the full (B, H, T, T) score matrix. Each variant
    runs in its own subprocess because peak_bytes_in_use is monotonic.
    Prints one JSON line."""
    import jax

    if os.environ.get("DL4J_RING_MEM_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from deeplearning4j_tpu.parallel.ring import (_block_attn_update,
                                                  _plain_attention)

    dev = jax.devices()[0]
    dtype = jnp.bfloat16 if dev.platform != "cpu" else jnp.float32
    rng = np.random.default_rng(0)
    out = {"kind": kind, "seq": T, "devices": P, "heads": heads, "dim": dim,
           "platform": dev.platform, "dtype": str(dtype.__name__)}
    try:
        if kind == "ring_chip":
            tl = T // P
            q = jnp.asarray(rng.normal(size=(1, tl, heads, dim)), dtype)
            k = jnp.asarray(rng.normal(size=(1, tl, heads, dim)), dtype)
            v = jnp.asarray(rng.normal(size=(1, tl, heads, dim)), dtype)
            scale = 1.0 / np.sqrt(dim)

            def local(q, k, v):
                m0 = jnp.full((1, heads, tl), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((1, heads, tl), jnp.float32)
                o0 = jnp.zeros((1, tl, heads, dim), jnp.float32)

                def body(i, carry):
                    k_blk, v_blk, m, l, o = carry
                    m, l, o = _block_attn_update(
                        q, k_blk, v_blk, m, l, o, 0, i * tl, False, scale)
                    return k_blk, v_blk, m, l, o

                _, _, m, l, o = lax.fori_loop(0, P, body, (k, v, m0, l0, o0))
                return (o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
                        ).astype(q.dtype)

            r = jax.block_until_ready(jax.jit(local)(q, k, v))
        else:
            q = jnp.asarray(rng.normal(size=(1, T, heads, dim)), dtype)
            k = jnp.asarray(rng.normal(size=(1, T, heads, dim)), dtype)
            v = jnp.asarray(rng.normal(size=(1, T, heads, dim)), dtype)
            r = jax.block_until_ready(jax.jit(
                lambda a, b, c: _plain_attention(a, b, c, causal=False)
            )(q, k, v))
        del r
        out["ok"] = True
    except Exception as e:
        msg = str(e)
        out["ok"] = False
        out["oom"] = ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                      or "out of memory" in msg)
        out["error"] = msg[:300]
    stats = dev.memory_stats() or {}
    out["peak_bytes_in_use"] = stats.get("peak_bytes_in_use")
    out["peak_mib"] = (round(stats["peak_bytes_in_use"] / 2**20, 1)
                       if stats.get("peak_bytes_in_use") else None)
    print(json.dumps(out), flush=True)


def run_memory_sweep(args):
    """Per-chip HBM telemetry: ring participant vs plain at each T, each in
    a fresh subprocess (monotonic peak counter; OOM must not kill the sweep).
    """
    for T in args.seqs:
        for kind in ("ring_chip", "plain"):
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--memory-worker", kind, str(T), str(args.devices),
                   str(args.heads), str(args.dim)]
            env = dict(os.environ)
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            # APPEND, never replace: the axon sitecustomize dir must stay
            env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
            if not args.tpu:
                # same convention as the timing matrix: CPU unless --tpu
                env["DL4J_RING_MEM_FORCE_CPU"] = "1"
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=600, env=env)
            except subprocess.TimeoutExpired:
                print(json.dumps({"kind": kind, "seq": T, "ok": False,
                                  "error": "timeout 600s"}))
                continue
            line = [ln for ln in (r.stdout or "").splitlines()
                    if ln.startswith("{")]
            if line:
                print(line[-1], flush=True)
            else:
                # a hard OOM can kill the process before the JSON prints —
                # that IS the boundary measurement; record it
                print(json.dumps({
                    "kind": kind, "seq": T, "ok": False,
                    "oom_process_killed": True, "rc": r.returncode,
                    "stderr_tail": (r.stderr or "")[-300:]}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu", action="store_true",
                    help="use the default (TPU) platform instead of forcing "
                         "a virtual CPU mesh")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--seqs", type=int, nargs="*",
                    default=[1024, 2048, 4096])
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--memory", action="store_true",
                    help="per-chip peak-HBM sweep (ring participant vs "
                         "plain) instead of the timing matrix")
    ap.add_argument("--memory-worker", nargs=5, metavar=("KIND", "T", "P",
                                                         "HEADS", "DIM"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.memory_worker:
        kind, T, P, heads, dim = args.memory_worker
        _memory_worker(kind, int(T), int(P), int(heads), int(dim))
        return
    if args.memory:
        run_memory_sweep(args)
        return

    if not args.tpu:
        from deeplearning4j_tpu.utils import force_cpu_devices
        force_cpu_devices(args.devices)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import MeshSpec
    from deeplearning4j_tpu.parallel.ring import ring_attention, _plain_attention

    n_dev = min(args.devices, len(jax.devices()))
    mesh = MeshSpec(axes={"seq": n_dev}).build(jax.devices()[:n_dev])
    print(f"# platform={jax.devices()[0].platform} devices={n_dev}",
          file=sys.stderr)

    for T in args.seqs:
        rng = np.random.default_rng(0)
        shape = (1, T, args.heads, args.dim)
        q = jnp.asarray(rng.normal(size=shape), jnp.float32)
        k = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v = jnp.asarray(rng.normal(size=shape), jnp.float32)
        qs = jax.device_put(q, NamedSharding(mesh, P(None, "seq")))
        ks = jax.device_put(k, NamedSharding(mesh, P(None, "seq")))
        vs = jax.device_put(v, NamedSharding(mesh, P(None, "seq")))

        ring = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh,
                                                      causal=True))
        plain = jax.jit(lambda a, b, c: _plain_attention(a, b, c,
                                                         causal=True))

        out_r = jax.block_until_ready(ring(qs, ks, vs))
        out_p = jax.block_until_ready(plain(q, k, v))
        max_err = float(jnp.max(jnp.abs(out_r - out_p)))

        def timed(fn, *xs):
            runs = []
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*xs))
                runs.append(time.perf_counter() - t0)
            return statistics.median(runs)

        t_ring = timed(ring, qs, ks, vs)
        t_plain = timed(plain, q, k, v)
        # peak-intermediate proxy (bytes): plain materialises B·H·T·T f32
        # scores; ring holds B·H·(T/P)·(T/P) per step
        score_plain = 4 * args.heads * T * T
        score_ring = 4 * args.heads * (T // n_dev) ** 2
        print(json.dumps({
            "seq": T, "devices": n_dev,
            "ring_ms": round(t_ring * 1e3, 2),
            "plain_ms": round(t_plain * 1e3, 2),
            "speedup": round(t_plain / t_ring, 3),
            "score_bytes_plain": score_plain,
            "score_bytes_ring_per_chip": score_ring,
            "score_mem_reduction": round(score_plain / score_ring, 1),
            "max_abs_err_vs_plain": max_err,
        }))


if __name__ == "__main__":
    main()
