"""Ring-attention benchmark — the evidence harness for the SP claim
(VERDICT r1 weak #6: "compute/comm overlap is asserted in a docstring,
never measured").

Measures, per sequence length:
  1. wall time of ring attention on a ``seq``-sharded mesh vs plain (full
     T×T) attention on one device;
  2. peak-memory proxy: the largest live intermediate — ring never
     materialises the (T, T) score matrix, plain does;
  3. correctness cross-check at small T.

Run modes:
  python benchmarks/ring_attention_bench.py            # virtual 8-dev CPU mesh
  JAX_PLATFORMS=tpu python benchmarks/ring_attention_bench.py --tpu
     (on a multi-chip TPU slice the timings become the real SP scaling
      numbers; on one chip only the memory columns are meaningful)

Prints one JSON line per sequence length.
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tpu", action="store_true",
                    help="use the default (TPU) platform instead of forcing "
                         "a virtual CPU mesh")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--seqs", type=int, nargs="*",
                    default=[1024, 2048, 4096])
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    if not args.tpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.devices)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.parallel import MeshSpec
    from deeplearning4j_tpu.parallel.ring import ring_attention, _plain_attention

    n_dev = min(args.devices, len(jax.devices()))
    mesh = MeshSpec(axes={"seq": n_dev}).build(jax.devices()[:n_dev])
    print(f"# platform={jax.devices()[0].platform} devices={n_dev}",
          file=sys.stderr)

    for T in args.seqs:
        rng = np.random.default_rng(0)
        shape = (1, T, args.heads, args.dim)
        q = jnp.asarray(rng.normal(size=shape), jnp.float32)
        k = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v = jnp.asarray(rng.normal(size=shape), jnp.float32)
        qs = jax.device_put(q, NamedSharding(mesh, P(None, "seq")))
        ks = jax.device_put(k, NamedSharding(mesh, P(None, "seq")))
        vs = jax.device_put(v, NamedSharding(mesh, P(None, "seq")))

        ring = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh,
                                                      causal=True))
        plain = jax.jit(lambda a, b, c: _plain_attention(a, b, c,
                                                         causal=True))

        out_r = jax.block_until_ready(ring(qs, ks, vs))
        out_p = jax.block_until_ready(plain(q, k, v))
        max_err = float(jnp.max(jnp.abs(out_r - out_p)))

        def timed(fn, *xs):
            runs = []
            for _ in range(args.repeats):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*xs))
                runs.append(time.perf_counter() - t0)
            return statistics.median(runs)

        t_ring = timed(ring, qs, ks, vs)
        t_plain = timed(plain, q, k, v)
        # peak-intermediate proxy (bytes): plain materialises B·H·T·T f32
        # scores; ring holds B·H·(T/P)·(T/P) per step
        score_plain = 4 * args.heads * T * T
        score_ring = 4 * args.heads * (T // n_dev) ** 2
        print(json.dumps({
            "seq": T, "devices": n_dev,
            "ring_ms": round(t_ring * 1e3, 2),
            "plain_ms": round(t_plain * 1e3, 2),
            "speedup": round(t_plain / t_ring, 3),
            "score_bytes_plain": score_plain,
            "score_bytes_ring_per_chip": score_ring,
            "score_mem_reduction": round(score_plain / score_ring, 1),
            "max_abs_err_vs_plain": max_err,
        }))


if __name__ == "__main__":
    main()
