"""Full-size zoo training steps on the real chip (BASELINE row: "VGG16 /
Darknet19 (zoo ComputationGraph) train end-to-end, v5e"; r3 weak #6: zoo
training evidence was toy-shaped — 224² steps had never executed on
hardware).

For each architecture: build at its REAL input resolution, run one warmup
(compile) train step + ``--steps`` timed steps at batch ``--batch``, print
one JSON line with the per-step wall time and the (finite) losses. Wedge
protection comes from the caller's timeout (tunnel_watcher_r4).

Run: python benchmarks/zoo_fullsize_step.py [--smoke]
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import resolve_platform  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CPU config")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--models", nargs="*",
                    default=["ResNet50", "VGG16", "Darknet19"])
    args = ap.parse_args()

    platform, err = resolve_platform(force_cpu=args.smoke)
    if platform is None or platform == "cpu":
        if err:
            print(f"[zoo-fullsize] accelerator unavailable: {err}",
                  file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if platform is None or platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"

    import numpy as np

    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.optim.updaters import Nesterovs

    side = 32 if (args.smoke or not on_tpu) else 224
    batch = 2 if (args.smoke or not on_tpu) else args.batch
    classes = 10 if (args.smoke or not on_tpu) else 1000
    dtype = "float32" if (args.smoke or not on_tpu) else "bfloat16"
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, side, side, 3)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]

    # BN-less VGG diverges from scratch at 1e-2 (He-init logits are large);
    # the reference trains it from pretrained weights — use a gentler lr
    lr_by_model = {"VGG16": 1e-3, "VGG19": 1e-3, "AlexNet": 1e-3}
    for name in args.models:
        t0 = time.perf_counter()
        m = net = None
        try:
            m = getattr(zoo, name)(num_classes=classes,
                                   input_shape=(side, side, 3),
                                   updater=Nesterovs(
                                       lr_by_model.get(name, 0.01),
                                       momentum=0.9),
                                   data_type=dtype)
            net = m.init_model()
            net.fit(x, y)                      # warmup = compile + step 1
            compile_s = time.perf_counter() - t0
            losses = [float(net.score())]
            t1 = time.perf_counter()
            for _ in range(args.steps):
                net.fit(x, y)
                losses.append(float(net.score()))
            step_s = (time.perf_counter() - t1) / args.steps
            print(json.dumps({
                "metric": "zoo_fullsize_train_step", "model": name,
                "platform": platform, "img": side, "batch": batch,
                "dtype": dtype, "compile_s": round(compile_s, 1),
                "step_s": round(step_s, 4),
                "images_per_sec": round(batch / step_s, 2),
                "losses": [round(l, 4) for l in losses],
                "finite": bool(np.all(np.isfinite(losses))),
            }), flush=True)
        except Exception as e:
            print(json.dumps({
                "metric": "zoo_fullsize_train_step", "model": name,
                "platform": platform, "error": str(e)[:300],
            }), flush=True)
        # free the model's buffers before the next architecture compiles
        m = net = None
        gc.collect()
        jax.clear_caches()


if __name__ == "__main__":
    main()
