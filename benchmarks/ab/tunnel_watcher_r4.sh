#!/bin/bash
# Round-4 tunnel watcher: polls the axon TPU tunnel; on a live window it
# captures, in judge-priority order (VERDICT r3 next-round #1/#3/#9):
#   1. resnet_bench.py    -> BENCH_r04_resnet.json   (north-star row 1)
#   2. bert_bench.py      -> BENCH_r04_bert.json     (north-star row 2)
#   3. bench.py flagship  -> BENCH_r04_live.json     (interleaved >=1.0 goal)
#   4. ring --memory      -> benchmarks/ring_memory_live.txt (HBM telemetry)
# Each capture is wedge-proof behind its own timeout; a window that dies
# mid-list costs only the remaining items (north-stars bank first).
# Exits after the flagship capture succeeds, or when the kill file appears.
cd /root/repo
LOG=benchmarks/tunnel_watcher.log
KILL=/tmp/stop_tunnel_watcher_r4
echo "[watcher-r4] started $(date -u +%H:%M:%S)" >> "$LOG"
while true; do
  [ -f "$KILL" ] && { echo "[watcher-r4] stopped" >> "$LOG"; exit 0; }
  if timeout 75 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu'" 2>/dev/null; then
    echo "[watcher-r4] TUNNEL LIVE $(date -u +%H:%M:%S) — capturing" >> "$LOG"

    if [ ! -f BENCH_r04_resnet.json ] || ! grep -q '"platform": "\(tpu\|axon\)"' BENCH_r04_resnet.json; then
      timeout 900 python benchmarks/resnet_bench.py > BENCH_r04_resnet.json.tmp 2>> "$LOG" \
        && grep -q '"platform": "\(tpu\|axon\)"' BENCH_r04_resnet.json.tmp \
        && mv BENCH_r04_resnet.json.tmp BENCH_r04_resnet.json \
        && echo "[watcher-r4] resnet done: $(cat BENCH_r04_resnet.json)" >> "$LOG"
    fi

    if [ ! -f BENCH_r04_bert.json ] || ! grep -q '"platform": "\(tpu\|axon\)"' BENCH_r04_bert.json; then
      timeout 1100 python benchmarks/bert_bench.py > BENCH_r04_bert.json.tmp 2>> "$LOG" \
        && grep -q '"platform": "\(tpu\|axon\)"' BENCH_r04_bert.json.tmp \
        && mv BENCH_r04_bert.json.tmp BENCH_r04_bert.json \
        && echo "[watcher-r4] bert done: $(cat BENCH_r04_bert.json)" >> "$LOG"
    fi

    if ! timeout 75 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu'" 2>/dev/null; then
      echo "[watcher-r4] window closed before flagship — resuming watch" >> "$LOG"
      sleep 180
      continue
    fi

    timeout 1700 python bench.py > BENCH_r04_live.json.tmp 2>> "$LOG" \
      && grep -q '"platform": "\(tpu\|axon\)"' BENCH_r04_live.json.tmp \
      && mv BENCH_r04_live.json.tmp BENCH_r04_live.json \
      && echo "[watcher-r4] flagship done: $(cat BENCH_r04_live.json)" >> "$LOG"

    timeout 900 python benchmarks/ring_attention_bench.py --tpu --memory \
      --seqs 8192 16384 32768 49152 --devices 8 --heads 8 --dim 128 \
      > benchmarks/ring_memory_live.txt 2>> "$LOG" \
      && echo "[watcher-r4] ring memory done" >> "$LOG"

    if [ ! -f benchmarks/zoo_fullsize_live.txt ] || ! grep -q '"finite": true' benchmarks/zoo_fullsize_live.txt; then
      timeout 1200 python benchmarks/zoo_fullsize_step.py \
        > benchmarks/zoo_fullsize_live.txt.tmp 2>> "$LOG" \
        && grep -q '"metric"' benchmarks/zoo_fullsize_live.txt.tmp \
        && mv benchmarks/zoo_fullsize_live.txt.tmp benchmarks/zoo_fullsize_live.txt \
        && echo "[watcher-r4] zoo fullsize done: $(cat benchmarks/zoo_fullsize_live.txt)" >> "$LOG"
    fi

    if [ -f BENCH_r04_live.json ] && [ -f BENCH_r04_resnet.json ] && [ -f BENCH_r04_bert.json ]; then
      echo "[watcher-r4] all captures complete $(date -u +%H:%M:%S)" >> "$LOG"
      exit 0
    fi
    echo "[watcher-r4] partial capture — resuming watch for the rest" >> "$LOG"
    sleep 180
  else
    sleep 180
  fi
done
