#!/bin/bash
# Tunnel watcher: polls the axon TPU tunnel; on the first live window it
# runs the queued hardware measurements and writes results into the repo
# (BENCH_r03_live.json + benchmarks/ logs). Safe to leave running — exits
# after one successful capture or when the kill file appears.
cd /root/repo
LOG=benchmarks/tunnel_watcher.log
echo "[watcher] started $(date -u +%H:%M:%S)" >> "$LOG"
while true; do
  [ -f /tmp/stop_tunnel_watcher ] && { echo "[watcher] stopped" >> "$LOG"; exit 0; }
  if timeout 75 python -c "import jax; d=jax.devices(); assert d[0].platform!='cpu'" 2>/dev/null; then
    echo "[watcher] TUNNEL LIVE $(date -u +%H:%M:%S) — capturing" >> "$LOG"
    # bench.py runs its TPU phases in its own timeout-wrapped subprocesses
    # (small config first to bank a number inside a short window)
    timeout 1700 python bench.py > BENCH_r03_live.json 2>> "$LOG" \
      && echo "[watcher] bench.py done: $(cat BENCH_r03_live.json)" >> "$LOG"
    # a real capture is a non-empty JSON whose platform is not cpu; an
    # empty file (outer-timeout kill) or CPU fallback must keep watching
    if ! grep -q '"platform": "tpu"\|"platform": "axon"' BENCH_r03_live.json 2>/dev/null; then
      echo "[watcher] no TPU capture (window closed?) — resuming watch" >> "$LOG"
      sleep 180
      continue
    fi
    timeout 600 python benchmarks/flash_crossover.py \
      > benchmarks/flash_crossover_live.txt 2>> "$LOG" \
      && echo "[watcher] crossover done" >> "$LOG"
    timeout 600 python benchmarks/ring_attention_bench.py --tpu \
      > benchmarks/ring_live.txt 2>> "$LOG" \
      && echo "[watcher] ring done" >> "$LOG"
    echo "[watcher] capture complete $(date -u +%H:%M:%S)" >> "$LOG"
    exit 0
  fi
  sleep 180
done
