"""MFU A/B ladder — unattended flagship-step optimization study for one
TPU window (VERDICT r4 #4: device-time fused-QKV and scan-layers, bf16
optimizer state, an XLA-flag rung, and a T=4096 rung where the flash
kernel engages; CPU-side prep so window time is pure measurement).

Each rung is ONE subprocess (fresh backend, wedge-proof behind a hard
timeout, env-delivered XLA flags) that device-times the flagship train
step via the XPlane trace (benchmarks/device_timing.py — host wall-clock
through the tunnel over-reports). One JSON line per rung is appended to
``benchmarks/ab/mfu_ladder_live.jsonl`` AS EACH RUNG FINISHES, so a dying
window keeps everything banked so far; the stdout summary at the end
carries vs-base ratios.

Run: ``python benchmarks/mfu_ladder.py`` (TPU; add ``--cpu-smoke`` for a
tiny-config correctness pass on CPU).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "ab", "mfu_ladder_live.jsonl")
RUNG_TIMEOUT_S = 600
V5E_PEAK_BF16 = 197e12

# (name, config-overrides, env-overrides) — base first so every later
# rung has its denominator banked even if the window dies early
RUNGS = [
    ("base_12L_d1024_T1024_b8", {}, {}),
    ("no_fused_qkv", {"fused_qkv": False}, {}),
    # plain scan_layers OOM'd the window (bf16 [12,8,1024,...] HLO temps:
    # the scan saves every layer's activations); remat bounds the live set
    # to one layer. The "dots" save policy keeps matmul outputs resident
    # so backward replays only the cheap ops instead of re-paying the MXU
    # — the two rungs A/B full-recompute vs save-dots under scan
    ("scan_layers", {"scan_layers": True, "remat": True}, {}),
    ("scan_layers_remat_dots",
     {"scan_layers": True, "remat": True, "remat_policy": "dots"}, {}),
    ("opt_state_bf16", {"opt_bf16": True}, {}),
    ("latency_hiding_scheduler", {},
     {"LIBTPU_INIT_ARGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}),
    ("T2048_b4", {"max_len": 2048, "batch": 4}, {}),
    ("T4096_b2_flash_auto", {"max_len": 4096, "batch": 2}, {}),
    ("T4096_b2_flash_off", {"max_len": 4096, "batch": 2, "flash": "0"}, {}),
]


def measure_rung(overrides: dict, smoke: bool) -> dict:
    """Runs INSIDE the subprocess: build the flagship config with the
    rung's overrides, device-time the train step."""
    import jax

    if smoke:
        # the container's sitecustomize re-sets JAX_PLATFORMS=axon at
        # interpreter startup — the env route alone cannot force CPU
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    sys.path.insert(0, HERE)
    sys.path.insert(0, os.path.dirname(HERE))
    from deeplearning4j_tpu.models import transformer as tmod
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)

    if overrides.get("flash") is not None:
        tmod.FLASH_ATTENTION = overrides["flash"] == "1"

    if smoke:
        cfg = TransformerConfig(
            vocab_size=512, n_layers=2, n_heads=4, d_model=128,
            max_len=128,
            dtype=jnp.float32, fused_qkv=overrides.get("fused_qkv", True),
            scan_layers=overrides.get("scan_layers", False),
            remat=overrides.get("remat", False),
            remat_policy=overrides.get("remat_policy"))
        batch = 2
        iters, repeats = 2, 1
    else:
        cfg = TransformerConfig(
            vocab_size=32768, n_layers=12, n_heads=16, d_model=1024,
            max_len=int(overrides.get("max_len", 1024)),
            dtype=jnp.bfloat16,
            fused_qkv=overrides.get("fused_qkv", True),
            scan_layers=overrides.get("scan_layers", False),
            remat=overrides.get("remat", False),
            remat_policy=overrides.get("remat_policy"))
        batch = int(overrides.get("batch", 8))
        iters, repeats = 10, 2

    model = TransformerLM(cfg, mesh=None)
    params = model.init_params(jax.random.key(0))
    if overrides.get("opt_bf16"):
        opt = optax.adamw(3e-4, mu_dtype=jnp.bfloat16)
    else:
        opt = optax.adamw(3e-4)
    opt_state = jax.jit(opt.init)(params)
    step = model.make_train_step(opt)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, cfg.max_len)),
                       jnp.int32)
    tgts = jnp.roll(toks, -1, axis=1)

    t0 = time.perf_counter()
    params, opt_state, loss = step(params, opt_state, toks, tgts)
    loss0 = float(loss)                       # value fetch = real sync
    compile_s = time.perf_counter() - t0

    def window():
        nonlocal params, opt_state
        lo = None
        for _ in range(iters):
            params, opt_state, lo = step(params, opt_state, toks, tgts)
        float(lo)

    n_tokens = batch * cfg.max_len
    host_tps = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        window()
        host_tps.append(n_tokens * iters / (time.perf_counter() - t0))

    device_step_s = None
    platform = jax.devices()[0].platform
    if platform != "cpu":
        try:
            from device_timing import measure_device_step
            r = measure_device_step(window, "jit_step")
            if r is not None:
                device_step_s = r["median_s"]
        except Exception as e:  # report, keep the host number
            print(f"[mfu] device trace failed: {e!r}", file=sys.stderr)

    tps = (n_tokens / device_step_s) if device_step_s else max(host_tps)
    n_params = sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))
    flops_tok = 6 * n_params + 6 * cfg.n_layers * cfg.max_len * cfg.d_model
    mfu = tps * flops_tok / V5E_PEAK_BF16 if platform != "cpu" else None
    return {
        "tokens_per_sec": round(tps, 1),
        "timing_source": "device_trace" if device_step_s else "host",
        "device_step_ms": round(device_step_s * 1e3, 3)
        if device_step_s else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "platform": platform,
        "compile_s": round(compile_s, 1),
        "loss": loss0,
        "n_params": n_params,
        "batch": batch,
        "seq": cfg.max_len,
        "flash_engaged": tmod._use_flash_attention(cfg.max_len),
    }


def main():
    smoke = "--cpu-smoke" in sys.argv
    if "--rung" in sys.argv:                      # subprocess entry
        i = sys.argv.index("--rung")
        overrides = json.loads(sys.argv[i + 1])
        out = measure_rung(overrides, smoke)
        print("RUNG_JSON:" + json.dumps(out), flush=True)
        return

    results = {}
    if os.path.exists(OUT):
        # resume: rungs banked by a previous (partial) window are reused,
        # not re-burned; error records do NOT count as done
        with open(OUT) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("rung") and not rec.get("error"):
                    results.setdefault(rec["rung"], rec)
    for name, overrides, env in RUNGS:
        if name in results:
            print(f"[mfu] {name}: banked "
                  f"{results[name].get('tokens_per_sec')}", file=sys.stderr)
            continue
        if smoke and name == "latency_hiding_scheduler":
            continue                              # flag is TPU-only
        child_env = dict(os.environ)
        child_env.update(env)
        if smoke:
            child_env["JAX_PLATFORMS"] = "cpu"
            overrides = {k: v for k, v in overrides.items()
                         if k not in ("max_len", "batch")}
        cmd = [sys.executable, os.path.abspath(__file__),
               "--rung", json.dumps(overrides)]
        if smoke:
            cmd.append("--cpu-smoke")
        t0 = time.time()
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=RUNG_TIMEOUT_S, env=child_env)
        except subprocess.TimeoutExpired:
            rec = {"rung": name, "error":
                   f"timeout after {RUNG_TIMEOUT_S}s"}
            results[name] = rec
            with open(OUT, "a") as f:
                f.write(json.dumps(rec) + "\n")
            continue
        rec = {"rung": name, "env": env, "wall_s": round(time.time() - t0, 1)}
        for line in (r.stdout or "").splitlines():
            if line.startswith("RUNG_JSON:"):
                rec.update(json.loads(line[len("RUNG_JSON:"):]))
                break
        else:
            rec["error"] = (r.stderr or r.stdout or "no output")[-800:]
        results[name] = rec
        with open(OUT, "a") as f:                 # bank immediately
            f.write(json.dumps(rec) + "\n")
        print(f"[mfu] {name}: "
              f"{rec.get('tokens_per_sec', rec.get('error'))}",
              file=sys.stderr, flush=True)

    base = results.get("base_12L_d1024_T1024_b8", {})
    base_tps = base.get("tokens_per_sec")
    summary = []
    for name, rec in results.items():
        row = {"rung": name,
               "tokens_per_sec": rec.get("tokens_per_sec"),
               "mfu": rec.get("mfu"),
               "timing_source": rec.get("timing_source"),
               "error": rec.get("error")}
        if base_tps and rec.get("tokens_per_sec") \
                and rec.get("seq") == base.get("seq"):
            row["vs_base"] = round(rec["tokens_per_sec"] / base_tps, 3)
        summary.append(row)
    print(json.dumps({"metric": "mfu_ladder", "rungs": summary}))


if __name__ == "__main__":
    main()
