"""Dump optimized HLO for our ResNet step vs flax's and diff the
standalone (non-fused) convert/copy/slice instructions — the small-kernel
tail the op profile shows ours paying ~0.3 ms/step more for.

Run: python benchmarks/resnet_hlo_diff.py  (TPU window; compile-only)
"""
from __future__ import annotations

import os
import re
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def entry_histogram(label, hlo_text):
    """Histogram opcode->count for instructions in ENTRY (top-level) only —
    those are the scheduled kernels; instructions inside fusion bodies are
    free (fused)."""
    in_entry = False
    hist = Counter()
    shapes = Counter()
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if line.startswith("}"):
                break
            m = re.match(r"\s+\S+ = (\S+?)\[", line)
            m2 = re.search(r"= (\S+)\[([^\]]*)\][^ ]* (\w[\w-]*)\(", line)
            if m2:
                dtype, shape, opcode = m2.groups()
                hist[opcode] += 1
                if opcode == "convert":
                    shapes[f"{dtype}[{shape}]"] += 1
    print(f"\n=== {label}: ENTRY opcode histogram (top 20) ===")
    for op, c in hist.most_common(20):
        print(f"  {c:5d}  {op}")
    if shapes:
        print("  -- standalone convert shapes (top 15) --")
        for s, c in shapes.most_common(15):
            print(f"  {c:5d}  {s}")
    return hist


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from resnet_bench import _flax_resnet50

    img_hw, classes, batch, dtype = (224, 224), 1000, 32, "bfloat16"

    # ours
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.optim.updaters import Nesterovs
    m = zoo.ResNet50(num_classes=classes, input_shape=img_hw + (3,),
                     updater=Nesterovs(0.1, momentum=0.9), data_type=dtype)
    net = m.init_model()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch,) + img_hw + (3,)).astype(np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, batch)]
    net.fit(x, y)   # compile path warm; we re-lower explicitly below
    import jax.numpy as jnp
    inputs = (jnp.asarray(x),)
    labels = (jnp.asarray(y),)
    # .lower on the jit object does not bind self — pass net explicitly
    # (static arg 0, hashable by id)
    lowered = net._train_step.lower(
        net, net._params, net._opt_state, net._states, inputs, labels,
        None, None, jax.random.key(0), None, frozenset())
    ours_txt = lowered.compile().as_text()
    entry_histogram("ours", ours_txt)

    # flax twin (same structure as resnet_bench.measure_flax)
    import functools
    import optax
    from deeplearning4j_tpu.nn._precision import _COMPUTE_DTYPES
    model = _flax_resnet50(classes, _COMPUTE_DTYPES.get(dtype, jnp.float32))
    xj = jnp.asarray(x, jnp.float32)
    yj = jax.nn.one_hot(jnp.asarray(rng.integers(0, classes, (batch,))),
                        classes)
    variables = model.init(jax.random.key(0), xj)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(0.1, momentum=0.9, nesterov=True)
    opt_state = jax.jit(opt.init)(params)

    def loss_fn(p, bs, x, y):
        logits, upd = model.apply({"params": p, "batch_stats": bs}, x,
                                  mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(y * logp, axis=-1)), upd["batch_stats"]

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, bs, s, x, y):
        (loss, bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p, bs, x, y)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), bs, s, loss

    flax_txt = step.lower(params, batch_stats, opt_state, xj, yj)\
        .compile().as_text()
    entry_histogram("flax", flax_txt)


if __name__ == "__main__":
    main()
