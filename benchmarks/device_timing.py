"""Device-side step timing via the JAX profiler's XPlane trace.

Host-side wall-clock through the remote-TPU (axon) tunnel is untrustworthy:
the relay can ack ``block_until_ready`` before the device finishes, which in
round 2 produced an impossible MFU of 8.4 (``benchmarks/RESULTS.md``). The
trace, by contrast, is recorded **on the device**: each execution of a jitted
module appears on the ``/device:TPU:*`` plane's "XLA Modules" line with a
picosecond duration measured by the TPU itself, and those durations ride back
inside the trace file — they cannot be faked by transport timing.

Protocol (BASELINE.md):
    run K warm steps under ``jax.profiler.trace`` → parse the xplane proto →
    median duration of the module whose name matches the jitted function →
    tokens/sec and MFU computed from device time.

Reference analog: the per-op wall-time aggregation of ``OpProfiler``
(`org.nd4j.linalg.profiler.OpProfiler`, SURVEY §5.1) — but measured by the
hardware instead of the host clock.
"""
from __future__ import annotations

import glob
import os
import statistics
import tempfile
from typing import Callable, Dict, List, Optional


def _load_xplane(logdir: str):
    """Parse every *.xplane.pb under ``logdir`` into XSpace protos.

    The xplane proto ships inside tensorflow (tsl); the import is deferred so
    the module stays usable (host-timing paths) when TF is absent.
    """
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # deferred: heavy

    spaces = []
    for f in glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True):
        sp = xplane_pb2.XSpace()
        with open(f, "rb") as fh:
            sp.ParseFromString(fh.read())
        spaces.append(sp)
    return spaces


def module_times(logdir: str, name_prefix: str = "jit_") -> Dict[str, List[float]]:
    """Durations (seconds) of every device-side XLA module execution,
    grouped by module name (fingerprint suffix stripped).

    Only device planes are read ("/device:TPU:*" etc.) — host planes carry
    dispatch time, which is exactly what we must NOT measure.
    """
    out: Dict[str, List[float]] = {}
    for space in _load_xplane(logdir):
        for plane in space.planes:
            if not plane.name.startswith("/device:"):
                continue
            if "CUSTOM" in plane.name:  # megascale/transport pseudo-planes
                continue
            meta = plane.event_metadata
            for line in plane.lines:
                if "module" not in line.name.lower():
                    continue
                for ev in line.events:
                    name = meta[ev.metadata_id].name
                    base = name.split("(")[0]  # strip (fingerprint)
                    if name_prefix and not base.startswith(name_prefix):
                        continue
                    out.setdefault(base, []).append(ev.duration_ps / 1e12)
    return out


def op_times(logdir: str, top: int = 25) -> List[tuple]:
    """Aggregate device-side per-op time: [(op_name, total_s, count)] sorted
    by total time. The "XLA Ops" line of the device plane — the kernel-level
    breakdown used to hunt regressions."""
    agg: Dict[str, List[float]] = {}
    for space in _load_xplane(logdir):
        for plane in space.planes:
            if not plane.name.startswith("/device:"):
                continue
            meta = plane.event_metadata
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    name = meta[ev.metadata_id].name
                    a = agg.setdefault(name, [0.0, 0])
                    a[0] += ev.duration_ps / 1e12
                    a[1] += 1
    rows = sorted(((k, v[0], v[1]) for k, v in agg.items()),
                  key=lambda r: -r[1])
    return rows[:top]


def measure_device_step(run_window: Callable[[], None],
                        match: str,
                        logdir: Optional[str] = None) -> Optional[dict]:
    """Run ``run_window`` (which must execute >=2 steps of the jitted fn and
    sync) under a profiler trace; return device-timing stats for the module
    whose name starts with ``match`` (e.g. "jit_train_step").

    Returns None when no matching device events were captured (CPU backend,
    or a backend whose PJRT plugin does not export device traces).
    """
    import jax

    own_dir = logdir is None
    logdir = logdir or tempfile.mkdtemp(prefix="dl4j_tpu_trace_")
    try:
        with jax.profiler.trace(logdir):
            run_window()
        try:
            times = module_times(logdir)
        except Exception as e:  # TF absent or proto drift — report, don't crash
            import sys
            print(f"[device_timing] trace parse failed: {e!r}", file=sys.stderr)
            return None
    finally:
        if own_dir:
            # trace files are multi-MB; don't accumulate them across runs
            import shutil
            shutil.rmtree(logdir, ignore_errors=True)
    for base, durs in times.items():
        if base.startswith(match) or base.startswith("jit_" + match):
            # first execution in the window may still include autotuning
            # noise; median over the window is the protocol number
            return {
                "module": base,
                "n": len(durs),
                "median_s": statistics.median(durs),
                "mean_s": statistics.fmean(durs),
                "min_s": min(durs),
                "logdir": None if own_dir else logdir,
            }
    return None
