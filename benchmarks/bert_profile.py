"""Device-side op-level diff of the TF-imported BERT step vs FlaxBert.

Same methodology as resnet_profile.py: one traced window per side,
module-level step time + per-op and per-prefix aggregation. Hunts the
residual imported-graph vs flax gap after the two-pass-variance peephole.

Run on a live TPU window: python benchmarks/bert_profile.py [--iters 4]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from resnet_profile import trace_side  # noqa: E402 — shared tracer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=4)
    args = ap.parse_args()

    from bert_bench import build_frozen_bert, measure_flax, measure_ours

    batch, seq, layers, hidden, heads, inter, vocab = \
        32, 128, 12, 768, 12, 3072, 30522
    gd = build_frozen_bert(batch, seq, layers, hidden, heads, inter, vocab)
    ours = measure_ours(gd, hidden, batch, seq, vocab, args.iters, 2e-5)
    ours_ms = trace_side("ours", ours, "jit__train", top=25)
    flax_w = measure_flax(batch, seq, layers, hidden, heads, inter, vocab,
                          args.iters, 2e-5)
    flax_ms = trace_side("flax", flax_w, "jit_flax_step", top=25)
    if ours_ms and flax_ms:
        print(f"\nstep ms: ours {ours_ms:.3f} vs flax {flax_ms:.3f} "
              f"-> ratio {flax_ms/ours_ms:.3f}x")


if __name__ == "__main__":
    main()
