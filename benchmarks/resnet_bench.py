"""ResNet-50 training throughput: our zoo ComputationGraph vs flax.linen.

BASELINE.md north-star row 1: "DL4J-zoo ResNet-50 train throughput
(images/sec/chip) ≥70% of JAX/Flax reference". Both sides run the same
optimizer (SGD+momentum), same batch/dtype, and are measured INTERLEAVED
(A,B,A,B…) with a per-window loss VALUE fetch as the sync point (bench.py's
anti-relay-artifact rule). Prints one JSON line.

Both sides sync per STEP (net.fit fetches its score scalar every batch, so
the flax denominator fetches its loss every step too).

On TPU the printed value/vs_baseline are overridden by DEVICE-side timing
(one traced window per side parsed from the XPlane, BASELINE round-3
protocol) whenever the trace parses; ``timing_source`` records which path
produced the numbers.

Run: python benchmarks/resnet_bench.py [--smoke]   (--smoke: tiny CPU config)
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import probe_accelerator  # noqa: E402 — shared TPU probe


def _flax_resnet50(num_classes, dtype):
    import flax.linen as fnn
    import jax.numpy as jnp

    class Bottleneck(fnn.Module):
        filters: int
        stride: int = 1
        project: bool = False

        @fnn.compact
        def __call__(self, x, train=True):
            f = self.filters
            r = x
            y = fnn.Conv(f, (1, 1), use_bias=False, dtype=dtype)(x)
            y = fnn.BatchNorm(use_running_average=not train, dtype=dtype)(y)
            y = fnn.relu(y)
            y = fnn.Conv(f, (3, 3), strides=(self.stride, self.stride),
                         padding="SAME", use_bias=False, dtype=dtype)(y)
            y = fnn.BatchNorm(use_running_average=not train, dtype=dtype)(y)
            y = fnn.relu(y)
            y = fnn.Conv(4 * f, (1, 1), use_bias=False, dtype=dtype)(y)
            y = fnn.BatchNorm(use_running_average=not train, dtype=dtype)(y)
            if self.project or self.stride != 1:
                r = fnn.Conv(4 * f, (1, 1),
                             strides=(self.stride, self.stride),
                             use_bias=False, dtype=dtype)(r)
                r = fnn.BatchNorm(use_running_average=not train,
                                  dtype=dtype)(r)
            return fnn.relu(y + r)

    class ResNet50(fnn.Module):
        @fnn.compact
        def __call__(self, x, train=True):
            x = fnn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                         use_bias=False, dtype=dtype)(x)
            x = fnn.BatchNorm(use_running_average=not train, dtype=dtype)(x)
            x = fnn.relu(x)
            x = fnn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
            for i, (f, n) in enumerate([(64, 3), (128, 4), (256, 6),
                                        (512, 3)]):
                for b in range(n):
                    x = Bottleneck(f, stride=(2 if b == 0 and i > 0 else 1),
                                   project=(b == 0))(x, train)
            x = x.mean(axis=(1, 2))
            return fnn.Dense(num_classes, dtype=jnp.float32)(x)

    return ResNet50()


def measure_flax(img_hw, num_classes, batch, iters, lr, dtype="float32"):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    # same alias canonicalization as our side (nn/_precision)
    from deeplearning4j_tpu.nn._precision import _COMPUTE_DTYPES
    model = _flax_resnet50(
        num_classes, _COMPUTE_DTYPES.get(dtype, jnp.float32))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch,) + img_hw + (3,)), jnp.float32)
    y = jax.nn.one_hot(
        jnp.asarray(rng.integers(0, num_classes, (batch,))), num_classes)
    variables = model.init(jax.random.key(0), x)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(lr, momentum=0.9, nesterov=True)  # = ours (Nesterovs)
    opt_state = jax.jit(opt.init)(params)

    def loss_fn(p, bs, x, y):
        logits, upd = model.apply({"params": p, "batch_stats": bs}, x,
                                  mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.sum(y * logp, axis=-1)), upd["batch_stats"]

    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(p, bs, s, x, y):
        (loss, bs), g = jax.value_and_grad(loss_fn, has_aux=True)(p, bs, x, y)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), bs, s, loss

    state = (params, batch_stats, opt_state)
    p, bs, s, loss = step(*state, x, y)
    float(loss)
    state = (p, bs, s)

    def window():
        nonlocal state
        p, bs, s = state
        t0 = time.perf_counter()
        for _ in range(iters):
            p, bs, s, loss = step(p, bs, s, x, y)
            float(loss)   # per-STEP fetch, matching net.fit's score sync
        state = (p, bs, s)
        return batch * iters / (time.perf_counter() - t0)

    return window


def measure_ours(img_hw, num_classes, batch, iters, lr, dtype="float32"):
    import numpy as np

    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.optim.updaters import Nesterovs

    m = zoo.ResNet50(num_classes=num_classes,
                     input_shape=img_hw + (3,),
                     updater=Nesterovs(lr, momentum=0.9),
                     data_type=dtype)
    net = m.init_model()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch,) + img_hw + (3,)).astype(np.float32)
    y = np.eye(num_classes, dtype=np.float32)[
        rng.integers(0, num_classes, batch)]
    net.fit(x, y)                         # warm/compile

    def window():
        t0 = time.perf_counter()
        for _ in range(iters):
            net.fit(x, y)                 # each fit syncs on float(loss)
        return batch * iters / (time.perf_counter() - t0)

    return window


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config (CI/dev)")
    args = ap.parse_args()

    from bench import resolve_platform
    platform, err = resolve_platform(force_cpu=args.smoke)
    if platform is None or platform == "cpu":
        if err:
            print(f"[resnet-bench] accelerator unavailable: {err}",
                  file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if platform is None or platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    print(f"[resnet-bench] platform={platform}", file=sys.stderr)

    if args.smoke or not on_tpu:
        img_hw, classes, batch, iters, repeats = (32, 32), 10, 4, 3, 2
        dtype = "float32"
    else:
        # bf16 compute on TPU (MXU rate); both sides use the same policy
        img_hw, classes, batch, iters, repeats = (224, 224), 1000, 32, 10, 3
        dtype = "bfloat16"

    ours = measure_ours(img_hw, classes, batch, iters, 0.1, dtype=dtype)
    flax_w = measure_flax(img_hw, classes, batch, iters, 0.1, dtype=dtype)

    ours_runs, flax_runs = [], []
    for _ in range(repeats):
        ours_runs.append(ours())
        flax_runs.append(flax_w())
    ours_ips = statistics.median(ours_runs)
    flax_ips = statistics.median(flax_runs)

    # device-side timing (BASELINE round-3 protocol): XPlane module
    # durations survive the relay's early acks; ours jits _train_step,
    # flax jits step — distinct module names
    ours_dev = flax_dev = None
    can_parse = True
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: F401
    except Exception:
        can_parse = False   # don't burn two traced TPU windows for nothing
    if on_tpu and can_parse:
        from device_timing import measure_device_step
        r = measure_device_step(lambda: ours(), "jit__train_step")
        if r:
            ours_dev = batch / r["median_s"]
        r = measure_device_step(lambda: flax_w(), "jit_step")
        if r:
            flax_dev = batch / r["median_s"]
        if ours_dev and flax_dev:
            ours_ips, flax_ips = ours_dev, flax_dev

    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(ours_ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ours_ips / flax_ips, 3),
        "flax_images_per_sec": round(flax_ips, 2),
        "timing_source": "device_trace" if (on_tpu and ours_dev and flax_dev)
                         else "host_value_fetch",
        "platform": platform,
        "config": {"img": list(img_hw), "classes": classes, "batch": batch,
                   "dtype": dtype},
    }))


if __name__ == "__main__":
    main()
