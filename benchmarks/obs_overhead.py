"""Instrumentation overhead guard (observability PR acceptance tool).

Measures the lenet train step in three modes, interleaved A/B/C with a
min-estimator:

- ``off``      — ``DL4J_TPU_METRICS=0`` (everything no-ops)
- ``no_trace`` — metrics on, ``DL4J_TPU_TRACE=0`` (spans + trace-context
  propagation disabled; isolates the causal-tracing cost)
- ``on``       — full default instrumentation

Acceptance bars: total overhead (on vs off) <5%; trace-id propagation
overhead (on vs no_trace) <2%.

Each mode runs in a fresh subprocess: the kill switches are applied at
instrument creation, so flipping them in-process after modules warmed up
would measure the wrong thing.

Run: python benchmarks/obs_overhead.py [--steps N] [--batch B] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.data.dataset import DataSet

steps = int(sys.argv[1])
batch = int(sys.argv[2])

net = zoo.LeNet().init_model()
rng = np.random.RandomState(0)
x = rng.rand(batch, 28 * 28).astype("f4")
y = np.eye(10, dtype="f4")[rng.randint(0, 10, batch)]
ds = DataSet(x, y)

net.fit(ds)                       # compile + warm caches outside the window
net.fit(ds)

t0 = time.perf_counter()
for _ in range(steps):
    net.fit(ds)
wall = time.perf_counter() - t0
print(json.dumps({"seconds_per_step": wall / steps,
                  "metrics": os.environ.get("DL4J_TPU_METRICS", "1")}))
"""


def _run(steps: int, batch: int, metrics: str, trace: str = "1") -> float:
    env = dict(os.environ, DL4J_TPU_METRICS=metrics, DL4J_TPU_TRACE=trace)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", _WORKER, str(steps), str(batch)],
        capture_output=True, text=True, env=env, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])["seconds_per_step"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved A/B/C process triples; min per mode "
                         "wins")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    # interleaved triples with a min-estimator: a lone run is dominated by
    # host warmup noise (the first subprocess routinely runs 1.5x slower
    # than steady state regardless of mode)
    offs, no_traces, ons = [], [], []
    for _ in range(args.repeats):
        offs.append(_run(args.steps, args.batch, "0"))
        no_traces.append(_run(args.steps, args.batch, "1", trace="0"))
        ons.append(_run(args.steps, args.batch, "1"))
    off, no_trace, on = min(offs), min(no_traces), min(ons)
    overhead = (on - off) / off * 100.0
    trace_overhead = (on - no_trace) / no_trace * 100.0
    result = {"lenet_step_seconds_uninstrumented": off,
              "lenet_step_seconds_metrics_only": no_trace,
              "lenet_step_seconds_instrumented": on,
              "overhead_percent": overhead,
              "trace_overhead_percent": trace_overhead,
              "steps": args.steps, "batch": args.batch}
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(f"lenet step, batch={args.batch}, {args.steps} steps/mode")
        print(f"  uninstrumented (DL4J_TPU_METRICS=0): {off * 1e3:8.3f} ms")
        print(f"  metrics only   (DL4J_TPU_TRACE=0):   "
              f"{no_trace * 1e3:8.3f} ms")
        print(f"  instrumented   (default):            {on * 1e3:8.3f} ms")
        print(f"  total overhead: {overhead:+.2f}%  (bar: < 5%)")
        print(f"  trace-context overhead: {trace_overhead:+.2f}%  "
              f"(bar: < 2%)")
    return overhead


if __name__ == "__main__":
    main()
