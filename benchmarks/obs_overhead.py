"""Instrumentation overhead guard (observability PR acceptance tool).

Measures the lenet train step with the observability substrate enabled
(default) vs disabled (``DL4J_TPU_METRICS=0``) and prints the overhead %.
The acceptance bar is <5% on CPU; future PRs adding instrumentation points
run this to keep the cost honest.

Each mode runs in a fresh subprocess: the kill switch is applied at
instrument creation, so flipping it in-process after modules warmed up
would measure the wrong thing.

Run: python benchmarks/obs_overhead.py [--steps N] [--batch B] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_WORKER = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.data.dataset import DataSet

steps = int(sys.argv[1])
batch = int(sys.argv[2])

net = zoo.LeNet().init_model()
rng = np.random.RandomState(0)
x = rng.rand(batch, 28 * 28).astype("f4")
y = np.eye(10, dtype="f4")[rng.randint(0, 10, batch)]
ds = DataSet(x, y)

net.fit(ds)                       # compile + warm caches outside the window
net.fit(ds)

t0 = time.perf_counter()
for _ in range(steps):
    net.fit(ds)
wall = time.perf_counter() - t0
print(json.dumps({"seconds_per_step": wall / steps,
                  "metrics": os.environ.get("DL4J_TPU_METRICS", "1")}))
"""


def _run(steps: int, batch: int, metrics: str) -> float:
    env = dict(os.environ, DL4J_TPU_METRICS=metrics)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", _WORKER, str(steps), str(batch)],
        capture_output=True, text=True, env=env, check=True)
    return json.loads(out.stdout.strip().splitlines()[-1])["seconds_per_step"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved A/B process pairs; min per mode wins")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    # interleaved A/B pairs with a min-estimator: a lone pair is dominated
    # by host warmup noise (the first subprocess routinely runs 1.5x slower
    # than steady state regardless of mode)
    offs, ons = [], []
    for _ in range(args.repeats):
        offs.append(_run(args.steps, args.batch, "0"))
        ons.append(_run(args.steps, args.batch, "1"))
    off, on = min(offs), min(ons)
    overhead = (on - off) / off * 100.0
    result = {"lenet_step_seconds_uninstrumented": off,
              "lenet_step_seconds_instrumented": on,
              "overhead_percent": overhead,
              "steps": args.steps, "batch": args.batch}
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(f"lenet step, batch={args.batch}, {args.steps} steps/mode")
        print(f"  uninstrumented (DL4J_TPU_METRICS=0): {off * 1e3:8.3f} ms")
        print(f"  instrumented   (default):            {on * 1e3:8.3f} ms")
        print(f"  overhead: {overhead:+.2f}%  (acceptance bar: < 5%)")
    return overhead


if __name__ == "__main__":
    main()
